"""Runtime configuration knobs (env vars).

The reference exposes runtime knobs as Java system properties and env vars
(SURVEY.md §5 "Config/flag system": `ai.rapids.cudf.spark.
rmmWatchdogPollingPeriod`, `ai.rapids.cudf.nvtx.enabled`,
`CUDA_INJECTION64_PATH`, `FAULT_INJECTOR_CONFIG_PATH`). The TPU engine's
equivalents, all read at use time (not import time) so tests can monkeypatch:

| env var | default | meaning |
|---|---|---|
| SPARK_RAPIDS_TPU_WATCHDOG_PERIOD_MS | 100 | arbiter deadlock-poll cadence |
| SPARK_RAPIDS_TPU_RETRY_LIMIT     | 500  | livelock cap before hard OOM   |
| SPARK_RAPIDS_TPU_TRACE           | 0    | profiler ranges (utils/tracing)|
| TPU_FAULT_INJECTOR_CONFIG_PATH   | —    | fault injector config (faultinj)|
| SPARK_RAPIDS_TPU_KERNELS         | —    | kernel-registry overrides, `op=name` pairs (e.g. `fused_select=xla,topk=pallas,groupby=scan`; ops/registry.py, docs/kernels.md) |
| SPARK_RAPIDS_TPU_ROW_CONVERSION_KERNEL | auto | auto/word/concat (legacy alias for `row_conversion=` in SPARK_RAPIDS_TPU_KERNELS) |
| SPARK_RAPIDS_TPU_GROUPBY_KERNEL  | auto | auto/scan/scatter (legacy alias for `groupby=` in SPARK_RAPIDS_TPU_KERNELS) |
| SPARK_RAPIDS_TPU_BREAKER_RETRY_BUDGET | 16 | fault retries allowed per plan attempt (runtime/health) |
| SPARK_RAPIDS_TPU_BREAKER_BACKOFF_BASE_MS | 10 | first-retry backoff (doubles per attempt, jittered) |
| SPARK_RAPIDS_TPU_BREAKER_BACKOFF_MAX_MS | 1000 | backoff ceiling |
| SPARK_RAPIDS_TPU_BREAKER_STICKY_THRESHOLD | 3 | same-op failures within the window that classify as sticky |
| SPARK_RAPIDS_TPU_BREAKER_STICKY_WINDOW_S | 60 | sticky-detection window |
| SPARK_RAPIDS_TPU_BREAKER_COOLDOWN_S | 30 | open→half_open self-arm delay (0 = only reset_device) |
| SPARK_RAPIDS_TPU_BREAKER_DEGRADE | cpu  | cpu (finish tripped plans on the CPU tier) / off |
| SPARK_RAPIDS_TPU_OPTIMIZER       | on   | rule-based plan optimizer (plan/optimizer.py): on/off |
| SPARK_RAPIDS_TPU_IO_PREFETCH     | 2    | streaming-scan prefetch depth (chunks decoded ahead); 0 = decode inline |
| SPARK_RAPIDS_TPU_IO_CHUNK_ROWS   | 0    | streaming-scan morsel row bound (0 = one chunk per row group) |
| SPARK_RAPIDS_TPU_BROADCAST_ROWS  | 8192 | distributed tier: estimated build-side rows at or below which exchange_planning picks a broadcast join over a shuffle |
| SPARK_RAPIDS_TPU_BROADCAST_BYTES | 64 MiB | distributed tier: certified build-side byte bound (analysis/footprint.py) above which exchange_planning refuses a broadcast even when the row heuristic qualifies — broadcast legality as a proven byte bound |
| SPARK_RAPIDS_TPU_CERT_BUDGET_BYTES | 0 | static resource certifier (analysis/footprint.py): device byte budget the admission gate compares certified per-operator residency hi-bounds against; 0 disables admission sizing |
| SPARK_RAPIDS_TPU_CERT_ADMISSION  | reject | what an over-budget certified plan does at admission: reject (raise ResourceAdmissionError naming the operator, before any compilation) / degrade (run on the CPU tier) |
| SPARK_RAPIDS_TPU_CERT_SEED       | on   | capped tier: tighten cold-run starting capacities to the certified hi-bound and ceiling the escalation ladder at it (active only with the stats store on — stats off stays byte-identical static) |
| SPARK_RAPIDS_TPU_DIST_SLACK      | 2.0  | distributed tier: initial per-bucket slack factor for hash/range exchanges (grows geometrically on overflow) |
| SPARK_RAPIDS_TPU_EXCHANGE_PACK   | on   | exchange transport packing (plan/transport.py, docs/distributed.md#transport): ship packed columnar wire planes across hash/broadcast/gather edges; "off" restores the byte-identical legacy per-column payload |
| SPARK_RAPIDS_TPU_EXCHANGE_CODECS | auto | codec families the transport layer may choose from: auto (for,dict,rle,bitpack), none (layout-only pass-through), or a comma subset |
| SPARK_RAPIDS_TPU_EXCHANGE_ASYNC  | off  | async exchange dispatch: an Exchange's pack+transfer runs on a worker thread and overlaps downstream compute until its consumer resolves it (overlap-ms on OperatorMetrics) |
| SPARK_RAPIDS_TPU_PLACEMENT       | off  | co-placement optimizer rule (plan/optimizer.py, docs/optimizer.md#placement): annotate cheap/small subtrees "host" and execute them on a worker thread overlapped with device execution of the sibling side; "off" keeps the single-backend walk byte-identical |
| SPARK_RAPIDS_TPU_PLACEMENT_BYTES | 1 MiB | cold-path placement threshold: a candidate subtree qualifies for host placement when its certified output-byte hi-bound is at or below this (warm fingerprints use backend-keyed observed wall instead) |
| SPARK_RAPIDS_TPU_VERIFY_PLANS    | 0    | static plan verifier gate (analysis/verifier.py): 1 verifies every plan pre-execution and every optimizer rule's output; on in tests (conftest), off in production |
| SPARK_RAPIDS_TPU_STATS           | on   | per-fingerprint operator-stats store (plan/stats.py, docs/adaptive.md): observed cardinalities drive join build sides / exchange modes, cap seeding, chunk sizing, and kernel tie-breaks; "off" restores fully static decisions |
| SPARK_RAPIDS_TPU_STATS_CAPACITY  | 256  | stats store LRU bound: per-(backend, fingerprint) plan entries retained (subtree/kernel tables scale off this) |
| SPARK_RAPIDS_TPU_STATS_PATH      | —    | optional JSONL persistence path for the stats store: records append per successful execution and load at first use, so observed stats survive the process |
| SPARK_RAPIDS_TPU_SERVING_WORKERS | 2    | serving layer (serving/scheduler.py, docs/serving.md): dispatcher worker threads — the device-side execution concurrency |
| SPARK_RAPIDS_TPU_SERVING_QUEUE_DEPTH | 64 | bounded admission queue: total plans queued across all sessions before submit blocks (or fast-rejects) |
| SPARK_RAPIDS_TPU_SERVING_QUOTA_BYTES | 256 MiB | default per-session device-memory quota the dispatcher admits certified footprints against (per-session override at open_session) |
| SPARK_RAPIDS_TPU_SERVING_DEFAULT_CHARGE_BYTES | 64 MiB | quota charge for plans the certifier could not bound (strings/unbound scans — footprint.quota_charge) |
| SPARK_RAPIDS_TPU_SERVING_STARVATION_MS | 2000 | fair-share aging bound: a queued plan waiting longer than this dispatches next regardless of lane/deficit — no session starves |
| SPARK_RAPIDS_TPU_SERVING_CACHE_ENTRIES | 64 | plan-result cache LRU bound (serving/cache.py); 0 disables the cache |
| SPARK_RAPIDS_TPU_SERVING_CACHE_BYTES | 256 MiB | plan-result cache RESIDENT-BYTES bound: cached result tables are live buffers no quota charges, so the cache evicts LRU past this and refuses any single result larger than it |
| SPARK_RAPIDS_TPU_SERVING_CACHE_TTL_S | 300 | plan-result cache entry time-to-live (seconds) |
| SPARK_RAPIDS_TPU_SERVING_OVER_QUOTA | reject | what a plan whose quota charge exceeds the session's remaining quota ceiling does: reject (typed ServingRejectedError naming session + operator, before compilation) / degrade (run on the CPU tier — the device quota does not bind there) / partial (offload enough certified subtrees to host threads that the DEVICE-placed remainder fits the quota, falling back to the CPU tier only when no split fits — docs/serving.md#partial-placement) |
| SPARK_RAPIDS_TPU_SERVING_BACKPRESSURE | block | submit() behavior at a full queue: block (wait for space) / reject (fast ServingRejectedError); per-submit override wins |
| SPARK_RAPIDS_TPU_SERVING_FEEDBACK | on | dispatch-fairness feedback loop (serving/scheduler.py): a session's WDRR credit grant scales down by its decayed cumulative wall-ms + retry cost, floored at a quarter of the configured weight; "off" restores pure weight-proportional credit |
| SPARK_RAPIDS_TPU_SERVING_FEEDBACK_HALFLIFE_S | 300 | half-life of the feedback cost decay — one bad hour fades instead of starving a tenant forever; <=0 disables decay (cost only accumulates) |
| SPARK_RAPIDS_TPU_FLEET_WORKERS | 1 | fleet serving tier (serving/fleet.py, docs/serving.md#fleet): executor workers behind the router; 1 (default) keeps the single-worker ServingScheduler path byte-identical |
| SPARK_RAPIDS_TPU_FLEET_RING_REPLICAS | 64 | consistent-hash ring virtual nodes per worker — higher spreads fingerprints more evenly at slightly more route cost |
| SPARK_RAPIDS_TPU_FLEET_SPILL_RATIO | 2.0 | load-aware spillover threshold: the routed worker sheds to the least-pressured replica when its pressure score exceeds ratio x (best score + 1); <=0 disables spillover |
| SPARK_RAPIDS_TPU_FLEET_RESPAWN | off | fleet self-healing (serving/fleet.py): when on, a killed/reaped/drained worker is replaced by a fresh one (new id, fresh isolated stack, warm-up gossip) until the fleet is back at its configured size; "off" keeps the legacy shrink-only failover |
| SPARK_RAPIDS_TPU_FLEET_RESPAWN_MAX | 16 | respawn budget: total replacement workers one fleet may spawn over its lifetime — a flapping environment must run out of budget, not respawn-storm |
| SPARK_RAPIDS_TPU_FLEET_RESPAWN_BACKOFF_MS | 100 | minimum delay between consecutive respawns, doubling per respawn in a flap streak (a quiet period of 16x the base resets the streak) |
| SPARK_RAPIDS_TPU_FLEET_QUARANTINE | reject | poison-fingerprint policy: a fingerprint whose executions tripped breakers on >=2 distinct workers is quarantined fleet-wide — "reject" fast-fails new submissions of it (typed ServingRejectedError), "degrade" pins them to the CPU tier |
| SPARK_RAPIDS_TPU_FLEET_HOT_REPLICAS | 1 | warm failover: frozen cache entries of HOT fingerprints replicate to this many secondary ring owners (0 disables replication) |
| SPARK_RAPIDS_TPU_FLEET_HOT_K | 8 | how many fingerprints (top-K by submissions seen at the router) count as HOT for replication (0 disables) |
| SPARK_RAPIDS_TPU_FLEET_SWEEP_MS | 0 | background health-sweep period: a fleet thread reaps stuck-open breakers and tops the fleet back up to size every this-many ms; 0 (default) disables the thread — kill/reap call sites still respawn inline |
| SPARK_RAPIDS_TPU_LOCKDEP         | 0    | runtime lock-order witness (runtime/lockdep.py, docs/analysis.md#concurrency-invariants): wrap engine locks, record held-set→acquired edges, raise on the first observed ordering cycle; armed by tests/conftest and the fleet chaos soak |

The SPARK_RAPIDS_TPU_BREAKER_* numeric knobs are snapshotted when a
`DeviceHealthMonitor` is constructed (one policy per monitor lifetime —
construct a new monitor/executor, or pass constructor overrides, to
re-tune); SPARK_RAPIDS_TPU_STATS_CAPACITY/_PATH likewise snapshot when a
`StatsStore` is constructed (plan/stats.reset_default_store re-reads);
everything else in the table is read at use time.
"""
from __future__ import annotations

import os


def _int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _float_env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def watchdog_period_s() -> float:
    """Deadlock-watchdog poll period (reference default: 100 ms,
    SparkResourceAdaptor.java:35-36)."""
    return _int_env("SPARK_RAPIDS_TPU_WATCHDOG_PERIOD_MS", 100) / 1000.0


def retry_limit() -> int:
    """Consecutive no-progress retries before a hard OOM (reference: 500,
    SparkResourceAdaptorJni.cpp:984-995)."""
    return _int_env("SPARK_RAPIDS_TPU_RETRY_LIMIT", 500)


def trace_enabled() -> bool:
    return os.environ.get("SPARK_RAPIDS_TPU_TRACE", "") == "1"


def row_conversion_kernel() -> str:
    """Row-conversion kernel selection: auto (default: u32 word kernel on
    TPU, byte-concat kernel on CPU — see ops/row_conversion.py), or force
    "word" / "concat". A typo must not silently fall back to auto — an A/B
    capture would attribute numbers to the wrong kernel."""
    v = os.environ.get("SPARK_RAPIDS_TPU_ROW_CONVERSION_KERNEL", "auto")
    if v not in ("auto", "word", "concat"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_ROW_CONVERSION_KERNEL={v!r}: expected "
            "auto, word, or concat")
    return v


def breaker_retry_budget() -> int:
    """Fault retries allowed per plan attempt, shared across every operator
    in the plan (runtime/health.py) — the no-retry-storm bound."""
    return _int_env("SPARK_RAPIDS_TPU_BREAKER_RETRY_BUDGET", 16)


def breaker_backoff_base_ms() -> float:
    """Backoff before the first retry; doubles per attempt with jitter.
    Float-valued: sub-millisecond pacing (e.g. 0.5) is valid."""
    return _float_env("SPARK_RAPIDS_TPU_BREAKER_BACKOFF_BASE_MS", 10.0)


def breaker_backoff_max_ms() -> float:
    return _float_env("SPARK_RAPIDS_TPU_BREAKER_BACKOFF_MAX_MS", 1000.0)


def breaker_sticky_threshold() -> int:
    """Failures of the SAME operator within the sticky window that escalate
    the classification from transient to sticky (breaker trip)."""
    return _int_env("SPARK_RAPIDS_TPU_BREAKER_STICKY_THRESHOLD", 3)


def breaker_sticky_window_s() -> float:
    return _float_env("SPARK_RAPIDS_TPU_BREAKER_STICKY_WINDOW_S", 60.0)


def breaker_cooldown_s() -> float:
    """Seconds an OPEN breaker waits before self-arming HALF_OPEN (the
    next admission then probes the device). Keeps quarantine from being
    permanent when the trip cause was transient (a pressure burst, a
    since-recovered device); 0 disables — only reset_device() re-arms."""
    return _float_env("SPARK_RAPIDS_TPU_BREAKER_COOLDOWN_S", 30.0)


def breaker_degrade() -> str:
    """Degradation policy when the breaker trips: "cpu" finishes the plan on
    the CPU backend tier, "off" propagates the failure (legacy behavior).
    Same strict-typo policy as the kernel selectors: a typo must not
    silently change failure-domain behavior."""
    v = os.environ.get("SPARK_RAPIDS_TPU_BREAKER_DEGRADE", "cpu")
    if v not in ("cpu", "off"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_BREAKER_DEGRADE={v!r}: expected cpu or off")
    return v


def optimizer_enabled() -> bool:
    """Rule-based plan optimizer (plan/optimizer.py), run inside
    PlanExecutor.execute() before tier dispatch. "on" (default) or "off";
    same strict-typo policy as the kernel selectors — a typo must not
    silently change which plan shape executes."""
    v = os.environ.get("SPARK_RAPIDS_TPU_OPTIMIZER", "on")
    if v not in ("on", "off"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_OPTIMIZER={v!r}: expected on or off")
    return v == "on"


def io_prefetch() -> int:
    """Streaming-scan prefetch depth (docs/io.md): how many decoded chunks
    a source-bound Scan's host decode thread may run ahead of execution —
    the double-buffer that overlaps host bitstream decode of chunk N+1
    with device execution of chunk N. 0 disables the thread entirely
    (decode happens inline on the executing thread)."""
    return max(0, _int_env("SPARK_RAPIDS_TPU_IO_PREFETCH", 2))


def io_chunk_rows() -> int:
    """Streaming-scan morsel row bound: decoded row groups larger than
    this split into <= this many rows per chunk, bounding the per-morsel
    working set independently of how the file was written. 0 (default)
    streams one chunk per row group. Returns 0 for "unbounded-by-rows";
    callers treat it as falsy."""
    return max(0, _int_env("SPARK_RAPIDS_TPU_IO_CHUNK_ROWS", 0))


def broadcast_rows() -> int:
    """Distributed tier (docs/distributed.md): the optimizer's
    exchange_planning rule replicates a join's build side (broadcast join,
    no shuffle of the probe side) when its estimated row count is at or
    below this — the row-count analogue of Spark's
    autoBroadcastJoinThreshold. Estimates come from bound tables or
    `est_rows` scan hints."""
    return _int_env("SPARK_RAPIDS_TPU_BROADCAST_ROWS", 8192)


def broadcast_bytes() -> int:
    """Distributed tier: the PROVEN byte bound broadcast-join legality
    requires (analysis/footprint.py, docs/analysis.md) — a build side
    whose certified hi-bound exceeds this never broadcasts, whatever the
    row estimate said (estimates are guesses; replicating a mis-estimated
    relation onto every peer is the failure mode this gate closes). Sides
    the certifier cannot bound (strings, unbound scans) fall back to the
    row heuristic alone. Default 64 MiB — roomy, the row threshold stays
    the cost heuristic; this is the legality ceiling."""
    return _int_env("SPARK_RAPIDS_TPU_BROADCAST_BYTES", 64 << 20)


def cert_budget_bytes() -> int:
    """Static-certifier admission budget (analysis/footprint.py): when
    non-zero, PlanExecutor.execute() compares every operator's certified
    residency hi-bound against this before any compilation and applies
    `cert_admission()`. 0 (default) disables admission sizing — the
    capped tier's escalation/OOM machinery remains the fallback."""
    return max(0, _int_env("SPARK_RAPIDS_TPU_CERT_BUDGET_BYTES", 0))


def cert_admission() -> str:
    """Over-budget policy for the certifier's admission gate: "reject"
    raises ResourceAdmissionError naming the offending operator (the
    serving-layer posture: fail fast, before compilation); "degrade"
    finishes the plan on the CPU tier (the device budget does not bind
    there). Same strict-typo policy as the kernel selectors."""
    v = os.environ.get("SPARK_RAPIDS_TPU_CERT_ADMISSION", "reject")
    if v not in ("reject", "degrade"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_CERT_ADMISSION={v!r}: expected reject or "
            "degrade")
    return v


def cert_seed() -> bool:
    """Capped tier: whether cold adaptive runs tighten starting
    capacities to the certified hi-bound and ceiling the escalation
    ladder at it (analysis/footprint.py, docs/adaptive.md). Only active
    when a stats store is (SPARK_RAPIDS_TPU_STATS=on or a scoped store)
    — with stats off the capped tier stays byte-identical static. Same
    strict-typo policy as the kernel selectors."""
    v = os.environ.get("SPARK_RAPIDS_TPU_CERT_SEED", "on")
    if v not in ("on", "off"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_CERT_SEED={v!r}: expected on or off")
    return v == "on"


def dist_slack() -> float:
    """Distributed tier: initial slack factor sizing the fixed-capacity
    exchange buckets (capacity = rows/peer x slack). Skew past the slack
    raises the overflow flag and the executor retries with geometrically
    grown slack (SplitAndRetry contract, parallel/autoretry.py)."""
    return _float_env("SPARK_RAPIDS_TPU_DIST_SLACK", 2.0)


def exchange_pack() -> bool:
    """Exchange transport packing (plan/transport.py, docs/distributed.md
    #transport): when on, hash/broadcast/gather exchange payloads ship as
    dense packed planes (coalesced word planes, bit-packed validity,
    cheap per-column encodings) and unpack on the receiving shard;
    metrics then split logical vs wire bytes per edge. "off" restores
    the byte-identical legacy payload layout (wire == logical). Same
    strict-typo policy as the kernel selectors — a typo must not
    silently change what a bench's wire numbers measured."""
    v = os.environ.get("SPARK_RAPIDS_TPU_EXCHANGE_PACK", "on")
    if v not in ("on", "off"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_EXCHANGE_PACK={v!r}: expected on or off")
    return v == "on"


def exchange_codecs() -> frozenset:
    """Codec families the exchange transport may choose from (selection
    per column stays by cheap inspection with strict pass-through):
    "auto" allows the full catalog (for, dict, rle, bitpack), "none"
    keeps the packed layout but no per-column encodings, a comma list
    restricts to a subset. Unknown names raise (strict-typo policy)."""
    from .plan.transport import resolve_codecs
    return resolve_codecs(
        os.environ.get("SPARK_RAPIDS_TPU_EXCHANGE_CODECS", "auto"))


def exchange_async() -> bool:
    """Async exchange dispatch (plan/distributed.py): when on, an
    Exchange node's pack+transfer runs on a worker thread and the plan
    walk continues — the transfer overlaps downstream operators' compute
    until the exchange's consumer resolves it (the PR 4 prefetch-thread
    shape applied to the exchange boundary; measured overlap-ms lands on
    the edge's OperatorMetrics). Off (default) keeps the fully
    synchronous walk — byte-identical behavior and fault attribution.
    Same strict-typo policy as the kernel selectors."""
    v = os.environ.get("SPARK_RAPIDS_TPU_EXCHANGE_ASYNC", "off")
    if v not in ("on", "off"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_EXCHANGE_ASYNC={v!r}: expected on or off")
    return v == "on"


def placement_enabled() -> bool:
    """Co-placement optimizer rule gate (plan/optimizer.py,
    docs/optimizer.md#placement): when on, the post-fixpoint placement
    pass may annotate small/cheap exclusive subtrees "host" and the
    executor runs them on a worker thread overlapped with device
    execution of the sibling side (the PendingRel async-resolve shape
    applied to a whole subtree; measured overlap-ms lands on the
    consuming operator's metrics). Off (default) keeps the
    single-backend walk byte-identical — no annotation, no thread.
    Same strict-typo policy as the kernel selectors."""
    v = os.environ.get("SPARK_RAPIDS_TPU_PLACEMENT", "off")
    if v not in ("on", "off"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_PLACEMENT={v!r}: expected on or off")
    return v == "on"


def placement_bytes() -> int:
    """Cold-path host-placement byte threshold: a candidate subtree with
    no observed wall on either backend qualifies for host placement only
    when its certified output-byte hi-bound (analysis/footprint.py) is
    at or below this. Warm fingerprints ignore it — backend-keyed
    observed wall decides instead (plan/stats.observed_wall)."""
    return max(1, _int_env("SPARK_RAPIDS_TPU_PLACEMENT_BYTES", 1 << 20))


def verify_plans() -> bool:
    """Static plan verifier gate (analysis/verifier.py, docs/analysis.md):
    when on, PlanExecutor.execute() verifies the (optimized) plan before
    any tier runs it, and the optimizer verifies every rule's output
    instead of only net-validating the pipeline's end state. Debug-mode:
    on in the test suite (tests/conftest.py), off by default in
    production. Same strict-typo policy as the kernel selectors — a typo
    must not silently disable a soundness gate."""
    v = os.environ.get("SPARK_RAPIDS_TPU_VERIFY_PLANS", "0")
    if v not in ("0", "1", "on", "off"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_VERIFY_PLANS={v!r}: expected 0, 1, on, "
            "or off")
    return v in ("1", "on")


def stats_enabled() -> bool:
    """Per-fingerprint operator-stats store gate (plan/stats.py,
    docs/adaptive.md): when on, every successful PlanResult records its
    observed rows/bytes/wall/caps/kernel timings and the optimizer,
    executor, and kernel registry consult them on the next execution of
    the same fingerprint. "off" restores byte-identical static decisions
    (the store is neither read nor written). Same strict-typo policy as
    the kernel selectors — a typo must not silently change whether runs
    self-tune. The test suite defaults this OFF (tests/conftest.py):
    cross-test fingerprint reuse would make cap-escalation and
    optimizer-report assertions order-dependent; tests/test_adaptive.py
    scopes explicit stores instead."""
    v = os.environ.get("SPARK_RAPIDS_TPU_STATS", "on")
    if v not in ("on", "off"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_STATS={v!r}: expected on or off")
    return v == "on"


def stats_capacity() -> int:
    """Stats store LRU bound: plan entries per (backend, fingerprint)
    retained before the least-recently-consulted evicts; the subtree-
    cardinality and kernel-timing side tables scale off this bound
    (plan/stats.py). Snapshotted when a StatsStore is constructed."""
    return max(1, _int_env("SPARK_RAPIDS_TPU_STATS_CAPACITY", 256))


def stats_path() -> str:
    """Optional JSONL persistence path for the stats store: when set,
    each successful execution appends one record and the process-default
    store replays the file at first use — observed caps/cardinalities
    survive restarts. Empty string (default) keeps the store
    in-memory-only. Snapshotted when a StatsStore is constructed."""
    return os.environ.get("SPARK_RAPIDS_TPU_STATS_PATH", "")


def serving_workers() -> int:
    """Serving dispatcher worker threads (serving/scheduler.py,
    docs/serving.md): how many admitted plans execute concurrently.
    Small by design — workers contend for one device; the queue, not the
    worker pool, absorbs traffic."""
    return max(1, _int_env("SPARK_RAPIDS_TPU_SERVING_WORKERS", 2))


def serving_queue_depth() -> int:
    """Bounded serving queue: total queued (not yet dispatched) plans
    across every session before submit() exerts backpressure. The bound
    is the backpressure signal — an unbounded queue hides overload until
    memory does the rejecting (StreamBox-HBM's bounded-pipeline
    discipline, PAPERS.md)."""
    return max(1, _int_env("SPARK_RAPIDS_TPU_SERVING_QUEUE_DEPTH", 64))


def serving_quota_bytes() -> int:
    """Default per-session device-memory quota (serving/scheduler.py):
    the sum of a session's in-flight certified charges
    (footprint.quota_charge) may not exceed this. Per-session override
    at `open_session(quota_bytes=...)`."""
    return max(1, _int_env("SPARK_RAPIDS_TPU_SERVING_QUOTA_BYTES",
                           256 << 20))


def serving_default_charge_bytes() -> int:
    """Quota charge for a plan the certifier could not bound (strings,
    unbound scans — footprint.quota_charge): a flat configurable amount,
    so unbounded plans neither ride the quota for free nor get rejected
    outright."""
    return max(1, _int_env(
        "SPARK_RAPIDS_TPU_SERVING_DEFAULT_CHARGE_BYTES", 64 << 20))


def serving_starvation_ms() -> float:
    """Fair-share aging bound (the starvation bound): a queued plan
    waiting longer than this dispatches next, regardless of priority
    lane or deficit state — weighted fairness may skew throughput but
    must never unbound any session's queue wait."""
    return max(0.0, _float_env("SPARK_RAPIDS_TPU_SERVING_STARVATION_MS",
                               2000.0))


def serving_cache_entries() -> int:
    """Plan-result cache LRU bound (serving/cache.py): completed results
    retained per scheduler, keyed by canonical plan fingerprint +
    input-data digest. 0 disables the cache entirely."""
    return max(0, _int_env("SPARK_RAPIDS_TPU_SERVING_CACHE_ENTRIES", 64))


def serving_cache_bytes() -> int:
    """Plan-result cache resident-bytes bound (serving/cache.py): cached
    tables are live device/host buffers that NO session quota charges
    (the quota covers in-flight execution, not retention), so the cache
    itself must bound what it pins — LRU eviction past this total, and a
    single result larger than it never caches at all (a one-entry cache
    that thrashes the whole budget serves nobody)."""
    return max(1, _int_env("SPARK_RAPIDS_TPU_SERVING_CACHE_BYTES",
                           256 << 20))


def serving_cache_ttl_s() -> float:
    """Plan-result cache time-to-live: entries older than this never
    serve (and evict on the next touch). <=0 means no TTL (LRU only)."""
    return _float_env("SPARK_RAPIDS_TPU_SERVING_CACHE_TTL_S", 300.0)


def serving_over_quota() -> str:
    """Policy when a plan's quota charge exceeds its session's quota
    ceiling: "reject" raises a typed ServingRejectedError naming the
    session and the operator that set the certified peak, BEFORE any
    compilation; "degrade" runs the plan on the CPU tier, where the
    device quota does not bind; "partial" offloads certified subtrees
    to co-placement host threads until the device-placed remainder fits
    the quota (charging only the device footprint), falling back to the
    CPU tier when no split fits (docs/serving.md#partial-placement).
    Same strict-typo policy as the kernel selectors."""
    v = os.environ.get("SPARK_RAPIDS_TPU_SERVING_OVER_QUOTA", "reject")
    if v not in ("reject", "degrade", "partial"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_SERVING_OVER_QUOTA={v!r}: expected reject, "
            "degrade, or partial")
    return v


def serving_backpressure() -> str:
    """submit() behavior at a full queue: "block" waits for space (the
    synchronous-caller posture), "reject" raises ServingRejectedError
    immediately (the load-shedding posture). The per-submit `block=`
    argument overrides. Same strict-typo policy as the kernel
    selectors."""
    v = os.environ.get("SPARK_RAPIDS_TPU_SERVING_BACKPRESSURE", "block")
    if v not in ("block", "reject"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_SERVING_BACKPRESSURE={v!r}: expected block "
            "or reject")
    return v


def serving_feedback() -> bool:
    """Dispatch-fairness feedback loop (serving/scheduler.py,
    docs/serving.md#fairness): when on, a session's WDRR credit grant
    scales down by its decayed cumulative wall-ms + retry cost — heavy
    recent consumers earn dispatch credit slower, bounded (floored at a
    quarter of the configured weight) so feedback skews but never
    starves. "off" restores pure weight-proportional credit. Same
    strict-typo policy as the kernel selectors."""
    v = os.environ.get("SPARK_RAPIDS_TPU_SERVING_FEEDBACK", "on")
    if v not in ("on", "off"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_SERVING_FEEDBACK={v!r}: expected on or off")
    return v == "on"


def serving_feedback_halflife_s() -> float:
    """Half-life (seconds) of the feedback cost decay: a session's
    accumulated wall/retry cost halves every this-many seconds of wall
    time, so one bad hour fades instead of permanently down-weighting
    the tenant. <=0 disables decay (cost only accumulates)."""
    return _float_env("SPARK_RAPIDS_TPU_SERVING_FEEDBACK_HALFLIFE_S",
                      300.0)


def fleet_workers() -> int:
    """Fleet serving tier (serving/fleet.py, docs/serving.md#fleet):
    executor workers the router fronts, each owning its own
    PlanExecutor + health monitor + stats store + result cache. The
    default 1 keeps serving on the single-worker ServingScheduler path
    (byte-identical to a fleet-less build)."""
    return max(1, _int_env("SPARK_RAPIDS_TPU_FLEET_WORKERS", 1))


def fleet_ring_replicas() -> int:
    """Consistent-hash ring virtual nodes per fleet worker
    (serving/router.py): more replicas spread plan fingerprints more
    evenly across workers and shrink the key range that moves on
    join/leave, at slightly higher route cost."""
    return max(1, _int_env("SPARK_RAPIDS_TPU_FLEET_RING_REPLICAS", 64))


def fleet_spill_ratio() -> float:
    """Load-aware spillover threshold (serving/fleet.py): the
    consistent-hash-routed worker sheds a new session to the
    least-pressured worker when its pressure score exceeds
    ratio x (best score + 1). Higher values prefer cache locality over
    load balance; <=0 disables spillover entirely."""
    return _float_env("SPARK_RAPIDS_TPU_FLEET_SPILL_RATIO", 2.0)


def fleet_respawn() -> bool:
    """Fleet self-healing gate (serving/fleet.py, docs/serving.md#fleet):
    when on, kill_worker/reap_unhealthy/drain_worker (and the background
    sweep, when armed) spawn a fresh replacement worker — new id, fresh
    isolated executor/health/stats/cache stack, warm-up gossip from the
    survivors — until the fleet is back at its configured size. Off
    (default) keeps the legacy shrink-only failover, which several
    regression tests pin. Same strict-typo policy as the kernel
    selectors — a typo must not silently change failure-domain
    behavior."""
    v = os.environ.get("SPARK_RAPIDS_TPU_FLEET_RESPAWN", "off")
    if v not in ("on", "off"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_FLEET_RESPAWN={v!r}: expected on or off")
    return v == "on"


def fleet_respawn_max() -> int:
    """Respawn budget: the total number of replacement workers one fleet
    may spawn over its lifetime. The bound is the respawn-storm guard —
    an environment that keeps killing replacements (a genuinely dead
    device, a poison plan the quarantine has not yet attributed) runs
    out of budget and degrades to shrink-only failover instead of
    spawning forever."""
    return max(0, _int_env("SPARK_RAPIDS_TPU_FLEET_RESPAWN_MAX", 16))


def fleet_respawn_backoff_ms() -> float:
    """Minimum delay between consecutive respawns, doubled per respawn
    while the fleet is flapping (a quiet period of 16x the base resets
    the streak). A respawn arriving inside the backoff window is
    deferred — the next kill/reap/sweep tick retries it."""
    return max(0.0, _float_env(
        "SPARK_RAPIDS_TPU_FLEET_RESPAWN_BACKOFF_MS", 100.0))


def fleet_quarantine() -> str:
    """Poison-fingerprint policy (serving/fleet.py): a fingerprint whose
    executions tripped breakers on >= 2 DISTINCT workers is quarantined
    fleet-wide — without this, auto-respawn is a crash amplifier (one
    bad plan kills every replacement in a loop). "reject" fast-fails new
    submissions of a quarantined fingerprint with a typed
    ServingRejectedError("quarantined"); "degrade" pins them to the CPU
    tier, where the device the plan keeps poisoning is not involved.
    Same strict-typo policy as SPARK_RAPIDS_TPU_SERVING_OVER_QUOTA."""
    v = os.environ.get("SPARK_RAPIDS_TPU_FLEET_QUARANTINE", "reject")
    if v not in ("reject", "degrade"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_FLEET_QUARANTINE={v!r}: expected reject "
            "or degrade")
    return v


def fleet_hot_replicas() -> int:
    """Warm failover (serving/fleet.py): HOT fingerprints' frozen cache
    entries replicate to this many secondary ring owners beyond the
    primary, so losing the home worker loses neither the cached result
    nor (with the stats gossip) the observed sizing. 0 disables
    replication — promotion alone still shares entries reactively."""
    return max(0, _int_env("SPARK_RAPIDS_TPU_FLEET_HOT_REPLICAS", 1))


def fleet_hot_k() -> int:
    """How many fingerprints count as HOT for replication: the top-K by
    submissions observed at the router. Small by design — replication
    multiplies resident cache bytes by (1 + replicas) for exactly the
    traffic where a cold rehome would hurt most. 0 disables."""
    return max(0, _int_env("SPARK_RAPIDS_TPU_FLEET_HOT_K", 8))


def fleet_sweep_ms() -> float:
    """Background health-sweep period (serving/fleet.py): when > 0 the
    fleet runs a daemon thread that, every this-many ms, reaps workers
    whose breaker is stuck OPEN with no cooldown and tops the fleet back
    up to its configured size (respawn knob permitting) — so a worker
    that dies while no kill/reap call site is active still gets
    replaced. 0 (default) disables the thread."""
    return max(0.0, _float_env("SPARK_RAPIDS_TPU_FLEET_SWEEP_MS", 0.0))


def faultinj_config_path() -> str:
    """Fault-injector config path (TPU_FAULT_INJECTOR_CONFIG_PATH — the
    reference's FAULT_INJECTOR_CONFIG_PATH analogue). Lives here so the
    hazard linter's env-reads-outside-config rule holds for faultinj.py
    too; empty string when unset."""
    return os.environ.get("TPU_FAULT_INJECTOR_CONFIG_PATH", "")


def kernel_overrides() -> dict:
    """Kernel-registry overrides (ops/registry.py, docs/kernels.md): the ONE
    backend-dispatch knob. Comma-separated `op=kernel` pairs, e.g.
    `SPARK_RAPIDS_TPU_KERNELS=fused_select=xla,topk=pallas,groupby=scan`.
    The legacy per-op vars (SPARK_RAPIDS_TPU_GROUPBY_KERNEL,
    SPARK_RAPIDS_TPU_ROW_CONVERSION_KERNEL) fold in as aliases for the
    `groupby`/`row_conversion` entries; an explicit SPARK_RAPIDS_TPU_KERNELS
    entry wins over its alias. Format errors raise here; unknown op/kernel
    NAMES raise in the registry, which owns the catalog — both directions of
    the strict-typo policy (a typo must not silently change which kernel an
    A/B capture measured). Signature-level declines are NOT errors: a forced
    kernel that cannot run a given signature falls back cleanly."""
    out = {}
    g = groupby_kernel()
    if g != "auto":
        out["groupby"] = g
    r = row_conversion_kernel()
    if r != "auto":
        out["row_conversion"] = r
    spec = os.environ.get("SPARK_RAPIDS_TPU_KERNELS", "")
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        op, sep, name = part.partition("=")
        op, name = op.strip(), name.strip()
        if not sep or not op or not name:
            raise ValueError(
                f"SPARK_RAPIDS_TPU_KERNELS: malformed entry {part!r} "
                "(expected op=kernel, e.g. fused_select=xla)")
        out[op] = name
    return out


def groupby_kernel() -> str:
    """Groupby aggregation kernel selection: auto (default: scan design on
    TPU where scatters are ~25x a cumsum, scatter/segment design on CPU
    where the scan design measured ~2x slower — see ops/aggregate.py), or
    force "scan" / "scatter". Same strict-typo policy as
    row_conversion_kernel."""
    v = os.environ.get("SPARK_RAPIDS_TPU_GROUPBY_KERNEL", "auto")
    if v not in ("auto", "scan", "scatter"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_GROUPBY_KERNEL={v!r}: expected auto, scan, "
            "or scatter")
    return v


def lockdep() -> bool:
    """Runtime lock-order witness gate (runtime/lockdep.py,
    docs/analysis.md#concurrency-invariants): SPARK_RAPIDS_TPU_LOCKDEP=1
    wraps every engine-constructed lock in a tracing proxy that records
    per-thread held-set -> acquired edges and raises LockOrderViolation
    on the first observed ordering cycle. Armed suite-wide by
    tests/conftest and in the fleet chaos soak; off (default) means zero
    overhead. Note the knob is latched where the witness is INSTALLED
    (conftest / chaos_soak read it once before importing the engine, so
    module-level locks get wrapped) — flipping it mid-process does not
    re-wrap existing locks."""
    return os.environ.get("SPARK_RAPIDS_TPU_LOCKDEP", "0") not in (
        "0", "", "off")
