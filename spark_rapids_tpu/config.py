"""Runtime configuration knobs (env vars).

The reference exposes runtime knobs as Java system properties and env vars
(SURVEY.md §5 "Config/flag system": `ai.rapids.cudf.spark.
rmmWatchdogPollingPeriod`, `ai.rapids.cudf.nvtx.enabled`,
`CUDA_INJECTION64_PATH`, `FAULT_INJECTOR_CONFIG_PATH`). The TPU engine's
equivalents, all read at use time (not import time) so tests can monkeypatch:

| env var | default | meaning |
|---|---|---|
| SPARK_RAPIDS_TPU_WATCHDOG_PERIOD_MS | 100 | arbiter deadlock-poll cadence |
| SPARK_RAPIDS_TPU_RETRY_LIMIT     | 500  | livelock cap before hard OOM   |
| SPARK_RAPIDS_TPU_TRACE           | 0    | profiler ranges (utils/tracing)|
| TPU_FAULT_INJECTOR_CONFIG_PATH   | —    | fault injector config (faultinj)|
| SPARK_RAPIDS_TPU_ROW_CONVERSION_KERNEL | auto | auto/word/concat (ops/row_conversion) |
| SPARK_RAPIDS_TPU_GROUPBY_KERNEL  | auto | auto/scan/scatter (ops/aggregate) |
"""
from __future__ import annotations

import os


def _int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def watchdog_period_s() -> float:
    """Deadlock-watchdog poll period (reference default: 100 ms,
    SparkResourceAdaptor.java:35-36)."""
    return _int_env("SPARK_RAPIDS_TPU_WATCHDOG_PERIOD_MS", 100) / 1000.0


def retry_limit() -> int:
    """Consecutive no-progress retries before a hard OOM (reference: 500,
    SparkResourceAdaptorJni.cpp:984-995)."""
    return _int_env("SPARK_RAPIDS_TPU_RETRY_LIMIT", 500)


def trace_enabled() -> bool:
    return os.environ.get("SPARK_RAPIDS_TPU_TRACE", "") == "1"


def row_conversion_kernel() -> str:
    """Row-conversion kernel selection: auto (default: u32 word kernel on
    TPU, byte-concat kernel on CPU — see ops/row_conversion.py), or force
    "word" / "concat". A typo must not silently fall back to auto — an A/B
    capture would attribute numbers to the wrong kernel."""
    v = os.environ.get("SPARK_RAPIDS_TPU_ROW_CONVERSION_KERNEL", "auto")
    if v not in ("auto", "word", "concat"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_ROW_CONVERSION_KERNEL={v!r}: expected "
            "auto, word, or concat")
    return v


def groupby_kernel() -> str:
    """Groupby aggregation kernel selection: auto (default: scan design on
    TPU where scatters are ~25x a cumsum, scatter/segment design on CPU
    where the scan design measured ~2x slower — see ops/aggregate.py), or
    force "scan" / "scatter". Same strict-typo policy as
    row_conversion_kernel."""
    v = os.environ.get("SPARK_RAPIDS_TPU_GROUPBY_KERNEL", "auto")
    if v not in ("auto", "scan", "scatter"):
        raise ValueError(
            f"SPARK_RAPIDS_TPU_GROUPBY_KERNEL={v!r}: expected auto, scan, "
            "or scatter")
    return v
