"""Task/memory arbitration for many framework threads sharing one TPU chip.

Python binding over the native core (native/resource_adaptor.cpp), playing
the role the Java RmmSpark/SparkResourceAdaptor pair plays in the reference
(/root/reference/src/main/java/com/nvidia/spark/rapids/jni/RmmSpark.java,
SparkResourceAdaptor.java; SURVEY.md §2.2). The externally observable
contract is the same:

- every thread doing device work registers as a *dedicated task thread*, a
  *pool thread* (serving several tasks), or a *shuffle thread* (top priority);
- allocations flow through the arbiter: failure under memory pressure blocks
  the thread, deadlocks escalate the lowest-priority thread to a RetryOOM
  rollback (BUFN), and a fully-wedged chip escalates the highest-priority
  task to SplitAndRetryOOM (split your batch and retry halves);
- a daemon watchdog polls for deadlocks every 100 ms
  (SparkResourceAdaptor.java:35-79);
- per-task retry metrics drain with get-and-reset semantics;
- OOM/exception injection hooks let tests force every path without real
  memory exhaustion.

The native core signals exceptional outcomes as status codes; this module
maps them onto the exception hierarchy (RetryOOM etc. — the reference's
GpuRetryOOM/GpuSplitAndRetryOOM/CpuRetryOOM/CpuSplitAndRetryOOM classes).
"""
from __future__ import annotations

import ctypes
import threading
import weakref
from typing import Iterable, Optional

from ..native.build import build

# ---- exception hierarchy (mirrors the reference's GpuOOM/OffHeapOOM tree) ---


class ArbiterOOM(MemoryError):
    """Base for all recoverable OOM signals raised by the arbiter."""


class RetryOOM(ArbiterOOM):
    """Device OOM: roll back to a spillable state, block until ready, retry."""


class SplitAndRetryOOM(ArbiterOOM):
    """Device OOM: additionally split the input and retry the halves."""


class CpuRetryOOM(ArbiterOOM):
    """Host off-heap OOM: roll back and retry."""


class CpuSplitAndRetryOOM(ArbiterOOM):
    """Host off-heap OOM: split the input and retry."""


class HardOOM(MemoryError):
    """Retry limit exceeded (livelock watchdog) — a real, fatal OOM."""


class InjectedException(RuntimeError):
    """Test-injected framework exception (forceFrameworkException)."""


class ThreadRemovedError(RuntimeError):
    """The thread was deregistered while blocked."""


_STATUS_TO_EXC = {
    1: RetryOOM,
    2: SplitAndRetryOOM,
    3: CpuRetryOOM,
    4: CpuSplitAndRetryOOM,
    5: InjectedException,
    6: ThreadRemovedError,
    7: HardOOM,
    8: ValueError,
}

# shutdown timed out with threads still parked on native state (not an
# exception: close() reacts by leaking the handle instead of destroying it)
SRA_BUSY = 9

# Thread states, numerically identical to RmmSparkThreadState.java:23-34.
STATE_UNKNOWN = -1
STATE_RUNNING = 0
STATE_ALLOC = 1
STATE_ALLOC_FREE = 2
STATE_BLOCKED = 3
STATE_BUFN_THROW = 4
STATE_BUFN_WAIT = 5
STATE_BUFN = 6
STATE_SPLIT_THROW = 7
STATE_REMOVE_THROW = 8

STATE_NAMES = {
    -1: "UNKNOWN", 0: "THREAD_RUNNING", 1: "THREAD_ALLOC", 2: "THREAD_ALLOC_FREE",
    3: "THREAD_BLOCKED", 4: "THREAD_BUFN_THROW", 5: "THREAD_BUFN_WAIT",
    6: "THREAD_BUFN", 7: "THREAD_SPLIT_THROW", 8: "THREAD_REMOVE_THROW",
}


class OomInjectionType:
    """Filter for injected OOMs (RmmSpark.OomInjectionType)."""
    CPU_OR_GPU = 0
    CPU = 1
    GPU = 2


def _load():
    lib = ctypes.CDLL(build("resource_adaptor"))
    L = ctypes.c_int64
    P = ctypes.c_void_p
    I = ctypes.c_int
    lib.sra_create.restype = P
    lib.sra_create.argtypes = [ctypes.c_char_p]
    lib.sra_destroy.argtypes = [P]
    lib.sra_last_error.restype = ctypes.c_char_p
    lib.sra_set_retry_limit.argtypes = [P, I]
    lib.sra_start_dedicated_task_thread.argtypes = [P, L, L, L]
    lib.sra_pool_thread_working_on_tasks.argtypes = [P, I, L, ctypes.POINTER(L), I, L]
    lib.sra_pool_thread_finished_for_tasks.argtypes = [P, L, ctypes.POINTER(L), I, L]
    lib.sra_remove_thread_association.argtypes = [P, L, L, L]
    lib.sra_task_done.argtypes = [P, L, L]
    lib.sra_all_done.argtypes = [P, L]
    lib.sra_set_pool_blocked.argtypes = [P, L, I]
    lib.sra_set_thread_blocked_hint.argtypes = [P, L, I]
    lib.sra_start_retry_block.argtypes = [P, L]
    lib.sra_end_retry_block.argtypes = [P, L]
    lib.sra_force_retry_oom.argtypes = [P, L, I, I, I]
    lib.sra_force_split_retry_oom.argtypes = [P, L, I, I, I]
    lib.sra_force_exception.argtypes = [P, L, I]
    lib.sra_pre_alloc.argtypes = [P, L, I, I, L, ctypes.POINTER(I)]
    lib.sra_post_alloc_success.argtypes = [P, L, I, I, L]
    lib.sra_post_alloc_failed.argtypes = [P, L, I, I, I, I, L, ctypes.POINTER(I)]
    lib.sra_dealloc.argtypes = [P, L, I, L]
    lib.sra_block_thread_until_ready.argtypes = [P, L, L]
    lib.sra_check_and_break_deadlocks.argtypes = [P, L]
    lib.sra_get_thread_state.argtypes = [P, L]
    for m in ("sra_get_and_reset_num_retry", "sra_get_and_reset_num_split_retry",
              "sra_get_and_reset_block_time_ns", "sra_get_and_reset_lost_time_ns"):
        getattr(lib, m).restype = L
        getattr(lib, m).argtypes = [P, L]
    return lib


_lib = None
_lib_lock = threading.Lock()


def _native():
    global _lib
    if _lib is None:
        with _lib_lock:
            if _lib is None:
                _lib = _load()
    return _lib


def current_thread_id() -> int:
    """OS thread id of the calling thread (the arbiter's thread identity)."""
    return threading.get_native_id()


def _watchdog_loop(arbiter_ref, stop: threading.Event, period_s: float):
    """Deadlock-watchdog body (daemon thread, 100 ms cadence — the Java
    watchdog in SparkResourceAdaptor.java:59-69)."""
    me = current_thread_id()
    while not stop.wait(period_s):
        arbiter = arbiter_ref()
        if arbiter is None or arbiter._closed:
            return
        arbiter._lib.sra_check_and_break_deadlocks(arbiter._h, me)
        del arbiter  # drop the strong ref before sleeping


class ResourceArbiter:
    """One arbiter per device (per process). Owns the native state machine and
    the deadlock watchdog daemon (100 ms cadence, like
    SparkResourceAdaptor.java:35-36)."""


    def __init__(self, log_loc: Optional[str] = None, watchdog: bool = True):
        self._lib = _native()
        self._h = self._lib.sra_create((log_loc or "").encode())
        if not self._h:
            raise ValueError(self._lib.sra_last_error().decode())
        from ..config import retry_limit
        self._lib.sra_set_retry_limit(self._h, retry_limit())
        self._closed = False
        # RLock: dealloc (called from weakref finalizers) guards on this
        # lock; a finalizer firing on the thread that is mid-close() must
        # not self-deadlock. The native handle is live until the final
        # destroy, so a reentrant dealloc during close is safe.
        self._close_lock = threading.RLock()
        self._watchdog_stop = threading.Event()
        self._watchdog = None
        if watchdog:
            from ..config import watchdog_period_s
            # weakref target: a bound-method target would root the arbiter
            # and keep __del__ from ever firing
            self._watchdog = threading.Thread(
                target=_watchdog_loop,
                args=(weakref.ref(self), self._watchdog_stop,
                      watchdog_period_s()),
                name="tpu-arbiter-watchdog", daemon=True)
            self._watchdog.start()

    # -- plumbing -------------------------------------------------------------
    def _check(self, code: int) -> None:
        if code == 0:
            return
        msg = self._lib.sra_last_error().decode()
        raise _STATUS_TO_EXC.get(code, RuntimeError)(msg)

    def close(self):
        with self._close_lock:
            if self._closed:
                return
            self._watchdog_stop.set()
            watchdog_live = False
            if self._watchdog is not None and self._watchdog is not threading.current_thread():
                self._watchdog.join(timeout=5)  # never destroy under its feet
                watchdog_live = self._watchdog.is_alive()
            rc = self._lib.sra_all_done(self._h, current_thread_id())
            self._closed = True
            # A straggler (SRA_BUSY: a registered thread never observed
            # REMOVE_THROW within the bounded wait; or a watchdog stalled past
            # the join timeout) may still be parked on native state —
            # destroying now would free memory under its feet, so leak the
            # handle instead. Same shutdown hazard the reference bounds with
            # its 1 s wait (SparkResourceAdaptorJni.cpp all_done :659-690);
            # we choose leak over use-after-free.
            if rc != SRA_BUSY and not watchdog_live:
                self._lib.sra_destroy(self._h)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- registration (RmmSpark.currentThreadIsDedicatedToTask etc.) ---------
    def current_thread_is_dedicated_to_task(self, task_id: int) -> None:
        tid = current_thread_id()
        self._check(self._lib.sra_start_dedicated_task_thread(self._h, tid, task_id, tid))

    def start_dedicated_task_thread(self, thread_id: int, task_id: int) -> None:
        self._check(self._lib.sra_start_dedicated_task_thread(
            self._h, thread_id, task_id, current_thread_id()))

    @staticmethod
    def _ids(task_ids: Iterable[int]):
        ids = list(task_ids)
        return (ctypes.c_int64 * len(ids))(*ids), len(ids)

    def shuffle_thread_working_on_tasks(self, task_ids: Iterable[int],
                                        thread_id: Optional[int] = None) -> None:
        arr, n = self._ids(task_ids)
        tid = thread_id if thread_id is not None else current_thread_id()
        self._check(self._lib.sra_pool_thread_working_on_tasks(
            self._h, 1, tid, arr, n, current_thread_id()))

    def pool_thread_working_on_tasks(self, task_ids: Iterable[int],
                                     thread_id: Optional[int] = None) -> None:
        arr, n = self._ids(task_ids)
        tid = thread_id if thread_id is not None else current_thread_id()
        self._check(self._lib.sra_pool_thread_working_on_tasks(
            self._h, 0, tid, arr, n, current_thread_id()))

    def pool_thread_finished_for_tasks(self, task_ids: Iterable[int],
                                       thread_id: Optional[int] = None) -> None:
        arr, n = self._ids(task_ids)
        tid = thread_id if thread_id is not None else current_thread_id()
        self._check(self._lib.sra_pool_thread_finished_for_tasks(
            self._h, tid, arr, n, current_thread_id()))

    def remove_dedicated_thread_association(self, thread_id: int, task_id: int) -> None:
        self._check(self._lib.sra_remove_thread_association(
            self._h, thread_id, task_id, current_thread_id()))

    def remove_current_dedicated_thread_association(self, task_id: int) -> None:
        self.remove_dedicated_thread_association(current_thread_id(), task_id)

    def task_done(self, task_id: int) -> None:
        self._check(self._lib.sra_task_done(self._h, task_id, current_thread_id()))

    # -- pool-wait bracketing (RmmSpark.submittingToPool/waitingOnPool) ------
    def submitting_to_pool(self, thread_id: Optional[int] = None) -> None:
        tid = thread_id if thread_id is not None else current_thread_id()
        self._check(self._lib.sra_set_pool_blocked(self._h, tid, 1))

    waiting_on_pool = submitting_to_pool

    def done_waiting_on_pool(self, thread_id: Optional[int] = None) -> None:
        tid = thread_id if thread_id is not None else current_thread_id()
        self._check(self._lib.sra_set_pool_blocked(self._h, tid, 0))

    def set_thread_blocked_hint(self, thread_id: int, blocked: bool) -> None:
        """Tell the deadlock detector a thread is parked in code it cannot
        see (the reference asks the JVM via ThreadStateRegistry.isThreadBlocked
        for this — SparkResourceAdaptorJni.cpp:1500-1502)."""
        self._check(self._lib.sra_set_thread_blocked_hint(self._h, thread_id, int(blocked)))

    # -- retry-block metrics bracketing --------------------------------------
    def start_retry_block(self, thread_id: Optional[int] = None) -> None:
        tid = thread_id if thread_id is not None else current_thread_id()
        self._check(self._lib.sra_start_retry_block(self._h, tid))

    def end_retry_block(self, thread_id: Optional[int] = None) -> None:
        tid = thread_id if thread_id is not None else current_thread_id()
        self._check(self._lib.sra_end_retry_block(self._h, tid))

    # -- injection (test hooks; RmmSpark.forceRetryOOM etc.) -----------------
    def force_retry_oom(self, thread_id: int, num_ooms: int = 1,
                        oom_filter: int = OomInjectionType.CPU_OR_GPU,
                        skip_count: int = 0) -> None:
        self._check(self._lib.sra_force_retry_oom(
            self._h, thread_id, num_ooms, oom_filter, skip_count))

    def force_split_and_retry_oom(self, thread_id: int, num_ooms: int = 1,
                                  oom_filter: int = OomInjectionType.CPU_OR_GPU,
                                  skip_count: int = 0) -> None:
        self._check(self._lib.sra_force_split_retry_oom(
            self._h, thread_id, num_ooms, oom_filter, skip_count))

    def force_framework_exception(self, thread_id: int, num_times: int = 1) -> None:
        self._check(self._lib.sra_force_exception(self._h, thread_id, num_times))

    def set_retry_limit(self, limit: int) -> None:
        self._lib.sra_set_retry_limit(self._h, limit)

    # -- allocation path ------------------------------------------------------
    def pre_alloc(self, is_cpu: bool = False, blocking: bool = True) -> bool:
        """Admission gate before reserving memory. Returns True when this is
        a recursive (spill-path) allocation. Raises the retry/split family."""
        tid = current_thread_id()
        rec = ctypes.c_int(0)
        self._check(self._lib.sra_pre_alloc(
            self._h, tid, int(is_cpu), int(blocking), tid, ctypes.byref(rec)))
        return bool(rec.value)

    def post_alloc_success(self, is_cpu: bool = False, was_recursive: bool = False) -> None:
        tid = current_thread_id()
        self._check(self._lib.sra_post_alloc_success(
            self._h, tid, int(is_cpu), int(was_recursive), tid))

    def post_alloc_failed(self, is_cpu: bool = False, was_oom: bool = True,
                          blocking: bool = True, was_recursive: bool = False) -> bool:
        """Returns True when the allocation should be retried."""
        tid = current_thread_id()
        retry = ctypes.c_int(0)
        self._check(self._lib.sra_post_alloc_failed(
            self._h, tid, int(is_cpu), int(was_oom), int(blocking), int(was_recursive),
            tid, ctypes.byref(retry)))
        return bool(retry.value)

    def dealloc(self, is_cpu: bool = False) -> None:
        tid = current_thread_id()
        # Admission reservations are released by weakref finalizers when op
        # outputs are collected — which can be *after* the session closed and
        # the native handle was destroyed. Gate on the close lock so a late
        # free is a no-op instead of a use-after-free.
        with self._close_lock:
            if self._closed:
                return
            self._check(self._lib.sra_dealloc(self._h, tid, int(is_cpu), tid))

    def block_thread_until_ready(self) -> None:
        """Called after catching RetryOOM, before retrying (the contract in
        RmmSpark.java:402-416): parks until the arbiter says go."""
        tid = current_thread_id()
        self._check(self._lib.sra_block_thread_until_ready(self._h, tid, tid))

    def check_and_break_deadlocks(self) -> None:
        self._check(self._lib.sra_check_and_break_deadlocks(self._h, current_thread_id()))

    # -- observability --------------------------------------------------------
    def get_state_of(self, thread_id: int) -> int:
        return self._lib.sra_get_thread_state(self._h, thread_id)

    def get_state_name_of(self, thread_id: int) -> str:
        return STATE_NAMES[self.get_state_of(thread_id)]

    def get_and_reset_num_retry_throw(self, task_id: int) -> int:
        return self._lib.sra_get_and_reset_num_retry(self._h, task_id)

    def get_and_reset_num_split_retry_throw(self, task_id: int) -> int:
        return self._lib.sra_get_and_reset_num_split_retry(self._h, task_id)

    def get_and_reset_block_time_ns(self, task_id: int) -> int:
        return self._lib.sra_get_and_reset_block_time_ns(self._h, task_id)

    def get_and_reset_computation_time_lost_ns(self, task_id: int) -> int:
        return self._lib.sra_get_and_reset_lost_time_ns(self._h, task_id)
