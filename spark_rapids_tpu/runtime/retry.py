"""The caller-side retry contract.

The reference documents the recovery protocol for plugin code
(RmmSpark.java:402-416): catch RetryOOM → make inputs spillable → block until
ready → retry; catch SplitAndRetryOOM → additionally split the input and
process halves. `with_retry` packages that protocol for TPU operator code.
"""
from __future__ import annotations

from collections import deque
from typing import Callable, Deque, List, Optional, Sequence, TypeVar

from .adaptor import (ResourceArbiter, RetryOOM, CpuRetryOOM,
                      SplitAndRetryOOM, CpuSplitAndRetryOOM)

T = TypeVar("T")
A = TypeVar("A")


def with_retry(arbiter: ResourceArbiter,
               attempt: Callable[[A], T],
               batch: A,
               split: Optional[Callable[[A], Sequence[A]]] = None,
               on_rollback: Optional[Callable[[], None]] = None) -> List[T]:
    """Run `attempt(batch)`, honoring the arbiter's retry/split protocol.

    Returns the list of results — one element normally, more if the input was
    split. `split` must return the pieces of its argument; when absent, a
    SplitAndRetryOOM is re-raised (nothing left to give back).
    `on_rollback` runs after a RetryOOM so callers can make state spillable.

    The work queue is a deque: split pieces push back onto the head with
    O(1) extendleft, so a deep split cascade (every piece splitting again)
    stays O(n) total instead of the O(n²) a list-head `work[0:1] = pieces`
    rewrite costs.
    """
    work: Deque[A] = deque([batch])
    out: List[T] = []

    def do_split(item: A) -> None:
        if split is None:
            raise
        pieces = list(split(item))
        if len(pieces) <= 1:
            raise
        work.popleft()
        work.extendleft(reversed(pieces))   # head-first, original order

    arbiter.start_retry_block()
    try:
        while work:
            item = work[0]
            try:
                out.append(attempt(item))
                work.popleft()
            except (RetryOOM, CpuRetryOOM):
                if on_rollback is not None:
                    on_rollback()
                # block-until-ready can itself answer with a split escalation
                # (BUFN_WAIT -> BUFN -> everyone wedged -> SPLIT_THROW)
                try:
                    arbiter.block_thread_until_ready()
                except (SplitAndRetryOOM, CpuSplitAndRetryOOM):
                    do_split(item)
            except (SplitAndRetryOOM, CpuSplitAndRetryOOM):
                do_split(item)
        return out
    finally:
        arbiter.end_retry_block()
