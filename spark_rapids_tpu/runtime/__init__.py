"""Host-side runtime: task/memory arbitration for a shared TPU device.

Native C++ state machine (native/resource_adaptor.cpp) + Python facade.
See SURVEY.md §2.2 — this is the reference's largest single component.
"""
from .adaptor import (ResourceArbiter, OomInjectionType, current_thread_id,
                      ArbiterOOM, RetryOOM, SplitAndRetryOOM, CpuRetryOOM,
                      CpuSplitAndRetryOOM, HardOOM, InjectedException,
                      ThreadRemovedError,
                      STATE_UNKNOWN, STATE_RUNNING, STATE_ALLOC,
                      STATE_ALLOC_FREE, STATE_BLOCKED, STATE_BUFN_THROW,
                      STATE_BUFN_WAIT, STATE_BUFN, STATE_SPLIT_THROW,
                      STATE_REMOVE_THROW, STATE_NAMES)
from .pool import (DeviceSession, MemoryBudget, MemoryEventHandler,
                   Reservation)
from .retry import with_retry
from .health import (DeviceHealthMonitor, CircuitBreaker, device_probe,
                     CLOSED, OPEN, HALF_OPEN, TRANSIENT, STICKY, FATAL)
from .admission import (set_active_session, get_active_session,
                        active_session, admitted_op, operand_nbytes)
from .spill import SpillPool, SpillableBuffer, SpillableTable

__all__ = [
    "set_active_session", "get_active_session", "active_session",
    "admitted_op", "operand_nbytes", "SpillPool", "SpillableBuffer",
    "SpillableTable",
    "ResourceArbiter", "OomInjectionType", "current_thread_id",
    "ArbiterOOM", "RetryOOM", "SplitAndRetryOOM", "CpuRetryOOM",
    "CpuSplitAndRetryOOM", "HardOOM", "InjectedException", "ThreadRemovedError",
    "MemoryBudget", "MemoryEventHandler", "DeviceSession", "Reservation",
    "with_retry",
    "DeviceHealthMonitor", "CircuitBreaker", "device_probe",
    "CLOSED", "OPEN", "HALF_OPEN", "TRANSIENT", "STICKY", "FATAL",
    "STATE_UNKNOWN", "STATE_RUNNING", "STATE_ALLOC", "STATE_ALLOC_FREE",
    "STATE_BLOCKED", "STATE_BUFN_THROW", "STATE_BUFN_WAIT", "STATE_BUFN",
    "STATE_SPLIT_THROW", "STATE_REMOVE_THROW", "STATE_NAMES",
]
