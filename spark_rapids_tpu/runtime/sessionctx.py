"""Serving-session identity context.

The serving layer (serving/scheduler.py, docs/serving.md) multiplexes N
tenant sessions over a small pool of dispatcher worker threads, and the
degraded CPU tier replays work on whatever thread hit the breaker — so
"which tenant does this work belong to" can no longer be answered by
thread identity. This module is the one place that question is asked:

- `session_scope(sid)` installs a session id for the dynamic extent on
  the CURRENT thread (re-entrant; the innermost scope wins). The serving
  dispatcher wraps every job execution in it.
- `current_session_id()` returns it (None outside any scope).
- `session_key()` is the budget/window key the health monitor uses
  (runtime/health.py): the explicit session id when set, else a
  thread-derived fallback — so unscoped callers keep the historical
  per-thread isolation, while scoped work is accounted to its TENANT
  even when several tenants share one worker thread (or one tenant
  spans several).

Kept deliberately tiny and dependency-free: runtime/health.py must be
importable without the serving package.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional

_ctx = threading.local()


def current_session_id() -> Optional[str]:
    """The innermost session id scoped on this thread, or None."""
    stack = getattr(_ctx, "stack", None)
    return stack[-1] if stack else None


def session_key() -> str:
    """Accounting key for per-session state (retry budgets, sticky
    windows): the scoped session id, falling back to thread identity so
    unscoped execution keeps per-thread isolation."""
    sid = current_session_id()
    return sid if sid is not None else f"thread:{threading.get_ident()}"


@contextlib.contextmanager
def session_scope(session_id: str) -> Iterator[str]:
    """Attribute the dynamic extent to `session_id` on this thread."""
    stack = getattr(_ctx, "stack", None)
    if stack is None:
        stack = _ctx.stack = []
    stack.append(str(session_id))
    try:
        yield session_id
    finally:
        stack.pop()
