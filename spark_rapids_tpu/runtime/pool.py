"""Reservation-based HBM/host-memory admission, arbitrated per task.

The reference wraps rmm's device allocator and catches the synchronous
cudaMalloc failure (`do_allocate` loop, SparkResourceAdaptorJni.cpp:1733-1754).
XLA dispatch is asynchronous, so the TPU-native design reserves budget
*before* dispatching work (SURVEY.md §7 step 4: "reservation-based admission
(acquire budget before dispatch) rather than catch-and-retry at malloc time")
while keeping the same observable retry contract: a reservation that doesn't
fit behaves exactly like a failed cudaMalloc — the thread blocks, retries
when memory frees, and escalates to RetryOOM/SplitAndRetryOOM on deadlock.

`MemoryBudget` is one budget (device HBM or host off-heap); tests use small
budgets the way the reference tests use `setupRmmForTestingWithLimits` and
`LimitingOffHeapAllocForTests` (RmmSparkTest.java) — no real exhaustion
needed.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

from .adaptor import ResourceArbiter, HardOOM


@dataclass
class Reservation:
    """A live memory reservation; free via MemoryBudget.release()."""
    nbytes: int
    is_cpu: bool
    _released: bool = False


class MemoryEventHandler:
    """Spill hook, the slot RmmEventHandlerResourceAdaptor fills in the
    reference's allocator chain (SparkResourceAdaptor → event-handler adaptor
    → pool; SURVEY.md §3.2 "child mr chain"). The plugin registers one whose
    on_alloc_failure makes buffers spillable/frees them and returns True to
    retry the allocation immediately — BEFORE the task-level blocking/retry
    state machine gets involved.

    Subclass and override; default is a no-op handler."""

    def on_alloc_failure(self, nbytes: int, retry_count: int) -> bool:
        """Called when a reservation doesn't fit. Return True if memory may
        have been freed (spilled) and the reservation should be retried
        immediately; False to fall through to the arbiter's blocking retry."""
        return False

    def on_allocated(self, total_used: int) -> None:
        """Called after a successful reservation with the new used total
        (the reference's alloc-threshold callback, coarse-grained)."""

    def on_deallocated(self, total_used: int) -> None:
        """Called after a release with the new used total."""


class MemoryBudget:
    """A byte budget for one memory space, fronted by the arbiter.

    acquire() runs the reference's do_allocate loop shape: pre_alloc (may
    block / raise retry-split) → try reserve → post_alloc_success, or
    post_alloc_failed → loop. release() mirrors do_deallocate: give the bytes
    back, then notify the arbiter so blocked threads wake.
    """

    def __init__(self, arbiter: ResourceArbiter, limit_bytes: int, is_cpu: bool = False,
                 event_handler: Optional[MemoryEventHandler] = None):
        self.arbiter = arbiter
        self.limit = int(limit_bytes)
        self.is_cpu = is_cpu
        self.event_handler = event_handler
        self._used = 0
        # RLock: releases run from weakref finalizers, which can fire via GC
        # on a thread that is already inside one of our critical sections; a
        # plain Lock would self-deadlock. The interleaving is benign — every
        # section is short arithmetic whose checks stay conservative when
        # _used shrinks mid-section.
        self._mu = threading.RLock()

    @property
    def used(self) -> int:
        with self._mu:
            return self._used

    @property
    def available(self) -> int:
        with self._mu:
            return self.limit - self._used

    def _try_reserve(self, nbytes: int) -> bool:
        with self._mu:
            if self._used + nbytes > self.limit:
                return False
            self._used += nbytes
            return True

    def acquire(self, nbytes: int) -> Reservation:
        """Blocking reservation: loops pre→reserve→post like the reference's
        do_allocate (SparkResourceAdaptorJni.cpp:1733-1754)."""
        nbytes = int(nbytes)
        # NB: a reservation larger than the whole budget still goes through
        # the state machine — the caller deserves its RetryOOM/SplitAndRetry
        # escalations (splitting may shrink the request until it fits); the
        # retry-limit watchdog bounds the livelock with a HardOOM, exactly
        # like the reference's 500-retry cap (SparkResourceAdaptorJni.cpp:984).
        while True:
            r = self._attempt(nbytes, blocking=True)
            if r is not None:
                return r

    def try_acquire(self, nbytes: int) -> Optional[Reservation]:
        """Non-blocking: one attempt; None on failure (the reference's
        tryAlloc path — LimitingOffHeapAllocForTests.java)."""
        return self._attempt(int(nbytes), blocking=False)

    def _attempt(self, nbytes: int, blocking: bool) -> Optional[Reservation]:
        recursive = self.arbiter.pre_alloc(is_cpu=self.is_cpu, blocking=blocking)
        ok = False
        try:
            ok = self._try_reserve(nbytes)
            if not ok and self.event_handler is not None:
                # spill loop: let the handler free memory and retry
                # immediately, before the task-level state machine blocks this
                # thread (the RmmEventHandlerResourceAdaptor contract:
                # onAllocFailure returns true -> retry the allocation)
                spill_retries = 0
                while not ok and self.event_handler.on_alloc_failure(
                        nbytes, spill_retries):
                    spill_retries += 1
                    ok = self._try_reserve(nbytes)
        except BaseException:
            # a raising handler must not leave this thread parked in the
            # arbiter's ALLOC state (every later pre_alloc would look
            # recursive and bypass blocking admission)
            if ok:
                with self._mu:
                    self._used -= nbytes
            self.arbiter.post_alloc_failed(
                is_cpu=self.is_cpu, was_oom=False, blocking=False,
                was_recursive=recursive)
            raise
        if ok:
            self.arbiter.post_alloc_success(is_cpu=self.is_cpu, was_recursive=recursive)
            r = Reservation(nbytes=nbytes, is_cpu=self.is_cpu)
            if self.event_handler is not None:
                try:
                    self.event_handler.on_allocated(self.used)
                except BaseException:
                    self.release(r)   # undo: the caller never sees r
                    raise
            return r
        retry = self.arbiter.post_alloc_failed(
            is_cpu=self.is_cpu, was_oom=True, blocking=blocking, was_recursive=recursive)
        if blocking and not retry:
            raise HardOOM(f"allocation of {nbytes} failed and retry is not possible")
        return None

    def resize(self, r: Reservation, nbytes: int) -> None:
        """Shrink (or best-effort grow) a live reservation to `nbytes`.

        The admission layer reserves a pre-dispatch working-set estimate and
        shrinks to the outputs' true bytes once they exist — the analogue of
        transient kernel scratch being freed at kernel end while the output
        allocation stays. Shrinking always succeeds and wakes blocked
        threads; growing takes only what fits (no blocking here: the grow
        path is advisory)."""
        nbytes = int(nbytes)
        with self._mu:
            if r._released:
                return
            delta = nbytes - r.nbytes
            if delta > 0 and self._used + delta > self.limit:
                return  # advisory grow did not fit; keep the old size
            self._used += delta
            r.nbytes = nbytes
        if delta < 0:
            self.arbiter.dealloc(is_cpu=self.is_cpu)
            if self.event_handler is not None:
                self.event_handler.on_deallocated(self.used)

    def release(self, r: Reservation) -> None:
        with self._mu:
            if r._released:
                return
            r._released = True
            self._used -= r.nbytes
        if r.nbytes > 0:
            self.arbiter.dealloc(is_cpu=self.is_cpu)
            if self.event_handler is not None:
                self.event_handler.on_deallocated(self.used)


class DeviceSession:
    """Process-wide pair of budgets (device HBM + host off-heap) and the
    arbiter that coordinates them — the TPU analogue of
    `Rmm.initialize + RmmSpark.setEventHandler` at executor startup
    (SURVEY.md §3.3)."""

    def __init__(self, device_limit_bytes: int, host_limit_bytes: int = 0,
                 log_loc: Optional[str] = None, watchdog: bool = True,
                 event_handler: Optional[MemoryEventHandler] = None):
        self.arbiter = ResourceArbiter(log_loc=log_loc, watchdog=watchdog)
        self.device = MemoryBudget(self.arbiter, device_limit_bytes,
                                   is_cpu=False, event_handler=event_handler)
        self.host = MemoryBudget(self.arbiter, host_limit_bytes, is_cpu=True)

    def close(self):
        self.arbiter.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
