"""Runtime lock-order witness — the dynamic half of the concurrency
soundness tier (docs/analysis.md#concurrency-invariants).

`install()` (armed by ``SPARK_RAPIDS_TPU_LOCKDEP=1`` — tests/conftest
for tier-1, benchmarks/chaos_soak for the fleet storm) monkeypatches
the ``threading.Lock``/``RLock`` factories so every lock CONSTRUCTED
from engine code is wrapped in a tracing proxy. Like kernel lockdep,
locks are bucketed into CLASSES by construction site (``path:line`` —
every ``LruDict`` instance's lock is one class), and each successful
acquire records the per-thread held-set → acquired edge into one
observed-order graph. The first edge that closes a cycle raises
``LockOrderViolation`` with both edges' capture stacks — a deadlock
certificate from a run that did NOT deadlock (witnessing A→B and B→A
needs only unlucky interleaving once, an actual deadlock needs it
twice at the same instant).

The vocabulary is SHARED with the static linter
(tools/lint_concurrency.py): `compare_to_static()` maps each observed
site-keyed edge through the linter's lock table (construction site →
``module:Class.attr`` name) and reports any dynamic edge the static
graph missed — the linter's interprocedural resolution is empirically
audited by every armed run. Edges touching a lock constructed at a
site the linter does not model (a local lock in a test helper) are
reported as `unmapped`, not divergence.

Same-class self-edges are skipped, mirroring the static tool: RLock
reentrancy on one instance is legal and a class-keyed self-edge cannot
distinguish it from a two-instance inversion. ``Condition`` wrappers
work unmodified: the proxy implements ``_is_owned``/``_release_save``/
``_acquire_restore``, so a ``wait()`` correctly drops the lock from
the held-set and re-enters it on wakeup.

The witness costs one dict/list touch per acquire — fine for tests and
soaks, not meant for production serving (hence the env gate).
"""
from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["LockOrderViolation", "install", "uninstall", "active",
           "reset", "snapshot", "compare_to_static", "certify"]

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ROOT = os.path.dirname(_PKG_DIR)


class LockOrderViolation(RuntimeError):
    """Two lock classes acquired in both orders — a potential deadlock,
    raised at the acquire that closed the cycle."""


def _stack_summary(skip: int = 3, limit: int = 8) -> str:
    frames = traceback.extract_stack()[:-skip]
    return "".join(traceback.format_list(frames[-limit:]))


class _Witness:
    """The observed-order graph. One global instance backs install();
    tests construct private ones to exercise cycles without poisoning
    the session graph."""

    def __init__(self):
        # a REAL lock (created before any patching) guarding the graph;
        # strictly leaf — nothing is acquired while it is held
        self._mu = threading.Lock()
        self._tls = threading.local()
        # (src_site, dst_site) -> (stack_at_first_observation, count)
        self._edges: Dict[Tuple[str, str], List] = {}
        self._adj: Dict[str, Set[str]] = {}
        self._cycles: List[str] = []

    def _held(self) -> List:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h                           # [ [id(lock), site, count] ]

    # -- bookkeeping ----------------------------------------------------------

    def note_acquire(self, lock: "_TracedLock", count: int = 1) -> None:
        held = self._held()
        ident = id(lock)
        for ent in held:
            if ent[0] == ident:
                ent[2] += count            # reentrant re-acquire: no edge
                return
        new_edges = []
        for ent in held:
            if ent[1] != lock._site:       # same-class policy (docstring)
                new_edges.append((ent[1], lock._site))
        held.append([ident, lock._site, count])
        if not new_edges:
            return
        stack = None
        cycle_msg = None
        with self._mu:
            for edge in new_edges:
                rec = self._edges.get(edge)
                if rec is not None:
                    rec[1] += 1
                    continue
                if stack is None:
                    stack = _stack_summary()
                self._edges[edge] = [stack, 1]
                self._adj.setdefault(edge[0], set()).add(edge[1])
                path = self._find_path(edge[1], edge[0])
                if path is not None:
                    cycle = [edge[0]] + path
                    back = self._edges.get((edge[1], path[1] if
                                            len(path) > 1 else edge[0]))
                    cycle_msg = (
                        "lock-order cycle observed: "
                        + " -> ".join(cycle)
                        + f"\nnew edge {edge[0]} -> {edge[1]} "
                        f"acquired at:\n{stack}"
                        + (f"\nreverse path first observed at:\n{back[0]}"
                           if back else ""))
                    self._cycles.append(" -> ".join(cycle))
        if cycle_msg is not None:
            raise LockOrderViolation(cycle_msg)

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src -> ... -> dst in the observed graph (caller
        holds self._mu)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self._adj.get(node, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def note_release(self, lock: "_TracedLock") -> None:
        held = self._held()
        ident = id(lock)
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == ident:
                held[i][2] -= 1
                if held[i][2] <= 0:
                    del held[i]
                return

    def drop_all(self, lock: "_TracedLock") -> int:
        """Forget every held entry for this instance (Condition.wait's
        _release_save); returns the recursion count to restore."""
        held = self._held()
        ident = id(lock)
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == ident:
                count = held[i][2]
                del held[i]
                return count
        return 1

    # -- reporting ------------------------------------------------------------

    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return {e: rec[1] for e, rec in self._edges.items()}

    def cycles(self) -> List[str]:
        with self._mu:
            return list(self._cycles)

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._adj.clear()
            self._cycles.clear()


_witness = _Witness()


class _TracedLock:
    """Tracing proxy over a real Lock/RLock. Identity (its lock CLASS)
    is the construction site. Implements the Condition protocol so
    ``threading.Condition(traced_lock)`` keeps the held-set honest
    across wait/notify."""

    __slots__ = ("_inner", "_site", "_wit")

    def __init__(self, inner, site: str, wit: Optional[_Witness] = None):
        self._inner = inner
        self._site = site
        self._wit = wit if wit is not None else _witness

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                self._wit.note_acquire(self)
            except LockOrderViolation:
                self._inner.release()
                self._wit.note_release(self)
                raise
        return ok

    def release(self):
        self._inner.release()
        self._wit.note_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # -- Condition protocol ---------------------------------------------------

    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        # plain Lock: CPython's own approximation (threading.Condition
        # does exactly this for primitive locks)
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):
        count = self._wit.drop_all(self)
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        return (state, count)

    def _acquire_restore(self, saved):
        state, count = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        # re-entering after wait is a real ordering event: record edges
        # from whatever else this thread still holds
        self._wit.note_acquire(self, count)

    def __repr__(self):
        return f"<_TracedLock {self._site} over {self._inner!r}>"


# ---- installation -----------------------------------------------------------

_real_lock = None
_real_rlock = None
# Guards the factory swap itself; bound at import time, before install()
# can ever patch the factory, so it is always a plain stdlib lock.
_install_lock = threading.Lock()


def _caller_site() -> Optional[str]:
    """Construction site of the lock being created: the nearest caller
    frame inside the engine package (None for stdlib/test/bench
    callers — those get real, untraced locks)."""
    here = os.path.abspath(__file__)
    f = sys._getframe(2)
    while f is not None:
        # normalize: a relative sys.path entry (benchmarks insert ".")
        # leaves "/repo/./pkg/..." in co_filename, defeating the
        # prefix check below
        fn = os.path.abspath(f.f_code.co_filename)
        if fn != here:
            if fn.startswith(_PKG_DIR + os.sep):
                rel = os.path.relpath(fn, _ROOT).replace(os.sep, "/")
                return f"{rel}:{f.f_lineno}"
            return None
        f = f.f_back
    return None


def _lock_factory():
    site = _caller_site()
    if site is None:
        return _real_lock()
    return _TracedLock(_real_lock(), site)


def _rlock_factory():
    site = _caller_site()
    if site is None:
        return _real_rlock()
    return _TracedLock(_real_rlock(), site)


def active() -> bool:
    return _real_lock is not None


def install() -> None:
    """Patch the threading lock factories. Idempotent. Must run BEFORE
    the engine modules are imported so module-level locks (serving/
    cache._digest_lock, plan/stats._default_lock, ...) get wrapped."""
    global _real_lock, _real_rlock
    with _install_lock:
        if _real_lock is not None:
            return
        _real_lock = threading.Lock
        _real_rlock = threading.RLock
        threading.Lock = _lock_factory
        threading.RLock = _rlock_factory


def uninstall() -> None:
    """Restore the real factories. Locks already wrapped keep tracing
    (they are self-contained proxies)."""
    global _real_lock, _real_rlock
    with _install_lock:
        if _real_lock is None:
            return
        threading.Lock = _real_lock
        threading.RLock = _real_rlock
        _real_lock = _real_rlock = None


def reset() -> None:
    _witness.reset()


# ---- static comparison ------------------------------------------------------

def _load_static_graph() -> Dict:
    import importlib.util
    path = os.path.join(_ROOT, "tools", "lint_concurrency.py")
    spec = importlib.util.spec_from_file_location("_lint_concurrency", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod    # the linter's dataclasses need it
    spec.loader.exec_module(mod)
    return mod.build_graph_json(repo_root=_ROOT)


def snapshot() -> Dict:
    """Raw witness state: site-keyed edges with observation counts,
    plus any cycles recorded before their raise unwound."""
    edges = _witness.edges()
    return {"edges": {f"{a} -> {b}": n for (a, b), n in
                      sorted(edges.items())},
            "cycles": _witness.cycles()}


def compare_to_static(graph: Optional[Dict] = None) -> Dict:
    """Map observed edges through the static lock table and report
    divergence. Returns {"observed": n, "mapped": [...], "missing":
    [...], "unmapped": [...]} where `missing` lists dynamic edges
    (as 'A -> B' lock-name strings) absent from the static graph —
    the linter's resolution gap, which fails the armed suite/soak."""
    if graph is None:
        graph = _load_static_graph()
    site_to_name = {site: name for name, site in graph["locks"].items()}
    static_edges = {tuple(e) for e in graph["edges"]}
    observed = _witness.edges()
    mapped, missing, unmapped = [], [], []
    seen: Set[Tuple[str, str]] = set()
    for (a_site, b_site), _count in sorted(observed.items()):
        a = site_to_name.get(a_site)
        b = site_to_name.get(b_site)
        if a is None or b is None:
            unmapped.append(f"{a_site} -> {b_site}")
            continue
        if a == b or (a, b) in seen:
            continue
        seen.add((a, b))
        if (a, b) in static_edges:
            mapped.append(f"{a} -> {b}")
        else:
            missing.append(f"{a} -> {b}")
    return {"observed": len(observed), "mapped": mapped,
            "missing": missing, "unmapped": unmapped}


def certify(graph: Optional[Dict] = None) -> Dict:
    """The armed run's verdict: observed cycles + static divergence in
    one report (what conftest's sessionfinish and the chaos soak
    assert on)."""
    rep = compare_to_static(graph)
    rep["cycles"] = _witness.cycles()
    rep["ok"] = not rep["cycles"] and not rep["missing"]
    return rep
