"""Admission control at the Table-op/IO boundary.

In the reference every device allocation crosses the arbiter because the
allocator itself is wrapped (`spark_resource_adaptor::do_allocate`,
SparkResourceAdaptorJni.cpp:1733). XLA owns its allocator, so the TPU-native
crossing point is *op dispatch*: output and working-set bytes are computable
from input shapes before any device work is launched, and a reservation is
acquired from the active `DeviceSession`'s budget first. The acquire path is
the same state machine — under pressure the thread blocks, deadlocks escalate
to RetryOOM/SplitAndRetryOOM, and `with_retry`/`halve_table` recover exactly
as the reference's recovery contract prescribes (RmmSpark.java:402-416).

Lifetime: after the op completes, the reservation is shrunk to the actual
bytes of the op's outputs and tied to the output objects — when the last
output is garbage-collected the bytes return to the budget and blocked
threads wake, mirroring `do_deallocate` (SparkResourceAdaptorJni.cpp:1756).

With no active session every wrapper is a zero-cost pass-through, so the
engine runs unbudgeted by default (the reference likewise only arbitrates
once RmmSpark.setEventHandler installs the adaptor).

Two session notions compose here (docs/serving.md): a `DeviceSession` is
a MEMORY BUDGET (this module's thread-scoped `active_session`), while a
serving-tenant session is an ACCOUNTING IDENTITY
(`runtime/sessionctx.py`, installed by the serving dispatcher around
every job). Health budgets/sticky windows key on the tenant identity —
per-session, thread fallback — so a DeviceSession shared by all serving
workers still arbitrates one device budget while failure isolation stays
per tenant.
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, Optional

import jax
import numpy as np

from .pool import DeviceSession

_state = threading.local()
_global_session: Optional[DeviceSession] = None
_global_lock = threading.Lock()


def set_active_session(session: Optional[DeviceSession]) -> None:
    """Install `session` process-wide (executor startup: the analogue of
    RmmSpark.setEventHandler). Pass None to uninstall."""
    global _global_session
    with _global_lock:
        old = _global_session
        _global_session = session
    # Drop the displaced session's reference OUTSIDE the lock: its teardown
    # runs weakref finalizers (buffer releases -> arbiter.dealloc under
    # ResourceArbiter._close_lock), and a finalizer that reached back into
    # this module would self-deadlock on the plain Lock above.
    del old


def get_active_session() -> Optional[DeviceSession]:
    override = getattr(_state, "session", None)
    if override is not None:
        return override
    return _global_session


class active_session:
    """Context manager scoping a session to the current thread (tests)."""

    def __init__(self, session: DeviceSession):
        self.session = session

    def __enter__(self):
        self._prev = getattr(_state, "session", None)
        _state.session = self.session
        return self.session

    def __exit__(self, *exc):
        _state.session = self._prev
        return False


# ---- byte accounting --------------------------------------------------------

def array_nbytes(a) -> int:
    """Bytes of one dense buffer, from shape+dtype (works on tracers too)."""
    if a is None:
        return 0
    try:
        return int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
    except Exception:
        return 0


def operand_nbytes(obj: Any) -> int:
    """Total buffer bytes reachable from a Column/Table/array/pytree."""
    # local imports: columnar imports dtypes which must not cycle into runtime
    from ..columnar.column import Column
    from ..columnar.table import Table
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return 0
    if isinstance(obj, Column):
        return (array_nbytes(obj.data) + array_nbytes(obj.validity) +
                array_nbytes(obj.offsets) +
                sum(operand_nbytes(c) for c in obj.children))
    if isinstance(obj, Table):
        return sum(operand_nbytes(c) for c in obj.columns)
    if isinstance(obj, (list, tuple)):
        return sum(operand_nbytes(x) for x in obj)
    if isinstance(obj, dict):
        return sum(operand_nbytes(v) for v in obj.values())
    if hasattr(obj, "shape") and hasattr(obj, "dtype"):
        return array_nbytes(obj)
    # generic pytree holders (e.g. BloomFilter wraps a device bits array):
    # count every array leaf so their HBM stays visible to the budget
    try:
        leaves = jax.tree_util.tree_leaves(obj)
    except Exception:
        return 0
    if len(leaves) == 1 and leaves[0] is obj:
        return 0
    return sum(array_nbytes(l) if hasattr(l, "shape") else 0 for l in leaves)


# ---- reservation lifetime ---------------------------------------------------

class _SharedRelease:
    """Releases one reservation when the last of N output objects dies."""

    def __init__(self, budget, reservation, count: int):
        self.budget = budget
        self.reservation = reservation
        self.count = count
        self.lock = threading.Lock()

    def dec(self):
        with self.lock:
            self.count -= 1
            done = self.count == 0
        if done:
            # runs from a weakref finalizer on an arbitrary thread: the
            # release is host-side accounting and must always land — a
            # poisoned-device fail-fast here would leak budget forever and
            # never wake blocked threads
            from .. import faultinj
            with faultinj.suppressed():
                self.budget.release(self.reservation)


def _weakrefable_outputs(out: Any) -> list:
    """Output objects whose lifetime should own the reservation."""
    from ..columnar.column import Column
    from ..columnar.table import Table
    found = []

    def walk(o):
        if isinstance(o, (Column, Table)):
            found.append(o)        # do not descend: the holder is enough
        elif isinstance(o, (list, tuple)):
            for x in o:
                walk(x)
        elif isinstance(o, dict):
            for v in o.values():
                walk(v)
        elif isinstance(o, jax.Array):
            found.append(o)
        elif o is not None and not isinstance(o, (bool, int, float, str, bytes)):
            # pytree holder carrying device arrays (e.g. BloomFilter)
            try:
                leaves = jax.tree_util.tree_leaves(o)
            except Exception:
                return
            if any(l is not o and hasattr(l, "shape") for l in leaves):
                found.append(o)

    walk(out)
    return found


def tie_to_outputs(budget, reservation, out: Any) -> None:
    """Shrink `reservation` to the outputs' true bytes and hand ownership to
    the output objects; falls back to immediate release when the output holds
    no device buffers (e.g. a plain Python scalar)."""
    actual = operand_nbytes(out)
    budget.resize(reservation, actual)
    if actual == 0:
        budget.release(reservation)
        return
    holders = _weakrefable_outputs(out)
    live = []
    for h in holders:
        try:
            weakref.ref(h)
            live.append(h)
        except TypeError:
            pass
    if not live:
        budget.release(reservation)
        return
    shared = _SharedRelease(budget, reservation, len(live))
    for h in live:
        weakref.finalize(h, shared.dec)


# ---- the op wrapper ---------------------------------------------------------

def admitted_op(fn, factor: float = 2.0, min_bytes: int = 0, estimator=None):
    """Wrap a Table-level op with reservation-based admission.

    The working-set estimate is `factor × input buffer bytes` (+min_bytes):
    inputs are already resident, the op materializes outputs plus transient
    fusion buffers of the same order. An explicit `estimator(*args, **kw) →
    bytes` overrides that (IO ops estimate from file size). After the op runs
    the reservation is shrunk to the outputs' actual bytes (concrete
    post-dispatch) and tied to their lifetime.
    """
    import functools

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        session = get_active_session()
        if session is None:
            return fn(*args, **kwargs)
        if estimator is not None:
            est = int(estimator(*args, **kwargs))
        else:
            est = int(factor * (operand_nbytes(args) + operand_nbytes(kwargs)))
        est = max(est, min_bytes)
        if est <= 0:
            return fn(*args, **kwargs)
        reservation = session.device.acquire(est)
        try:
            out = fn(*args, **kwargs)
        except BaseException:
            session.device.release(reservation)
            raise
        tie_to_outputs(session.device, reservation, out)
        return out

    wrapper.__wrapped__ = fn
    wrapper.__admitted__ = True
    return wrapper
