"""Spillable device buffers + the MemoryEventHandler that frees them.

The reference's allocator chain has an event-handler adaptor between the
arbiter and the pool (`RmmEventHandlerResourceAdaptor`, SURVEY.md §3.2): on
allocation failure the plugin's handler makes cached buffers spillable/frees
them and returns true so the allocation retries immediately, *before* the
task-level blocking state machine engages. `SpillPool` is that handler made
real for HBM: registered buffers are copied to host numpy and their device
arrays deleted (`jax.Array.delete()` actually drops the HBM buffer), their
reservations returned to the budget.

Restore (`SpillableBuffer.get`) re-admits through the budget, so a restore
under pressure can itself trigger further spills or the retry protocol —
the same recursion the reference guards in `pre_alloc_core`
(SparkResourceAdaptorJni.cpp:1238-1265); the arbiter's recursive-allocation
detection makes it safe here too.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import jax
import numpy as np

from .admission import array_nbytes
from .pool import MemoryBudget, MemoryEventHandler, Reservation


class SpillableBuffer:
    """One device array whose residency is budget-backed and revocable."""

    def __init__(self, pool: "SpillPool", array: jax.Array,
                 reservation: Reservation):
        self._pool = pool
        self._device = array
        self._host: Optional[np.ndarray] = None
        self._reservation: Optional[Reservation] = reservation
        self.nbytes = array_nbytes(array)
        self._pinned = False
        self._mu = threading.Lock()

    @property
    def spilled(self) -> bool:
        with self._mu:
            return self._device is None

    @property
    def pinned(self) -> bool:
        with self._mu:
            return self._pinned

    def pin(self) -> None:
        """Exclude this buffer from spilling while it is in active use —
        the reference's spillable-state contract: a batch is spillable
        only while its task is NOT computing on it (RmmSpark.java:402-416
        'make the inputs spillable' happens on rollback, and the retry
        unspills before touching them)."""
        with self._mu:
            self._pinned = True

    def unpin(self) -> None:
        with self._mu:
            self._pinned = False

    def spill(self) -> int:
        """Move to host, delete the device buffer, free the budget.
        Returns bytes freed (0 if already spilled or pinned)."""
        with self._mu:
            if self._device is None or self._pinned:
                return 0
            self._host = np.asarray(self._device)     # D2H copy
            self._device.delete()                     # drop the HBM buffer
            self._device = None
            r, self._reservation = self._reservation, None
        self._pool.budget.release(r)
        return self.nbytes

    def get(self) -> jax.Array:
        """The live device array; restores (re-admitting budget) if spilled.

        Loops: the buffer can be re-spilled between our restore attempt and
        the return (another thread's alloc failure), and a race-lost restore
        must re-read under the lock — never hand out a deleted array."""
        import jax.numpy as jnp
        while True:
            with self._mu:
                if self._device is not None:
                    return self._device
                host = self._host
            # acquire outside our own lock: admission may call back into the
            # pool's on_alloc_failure, which takes other buffers' locks
            r = self._pool.budget.acquire(self.nbytes)
            dev = jnp.asarray(host)
            with self._mu:
                if self._device is None:
                    self._device = dev
                    self._host = None
                    self._reservation = r
                    return dev
            # lost a restore race; give the budget back and re-check
            self._pool.budget.release(r)
            dev.delete()

    def close(self) -> None:
        with self._mu:
            if self._device is not None:
                self._device.delete()
                self._device = None
            self._host = None
            r, self._reservation = self._reservation, None
        if r is not None:
            self._pool.budget.release(r)


class SpillableTable:
    """A Table whose buffers live in a SpillPool — the 'make inputs
    spillable' half of the recovery contract (RmmSpark.java:402-416: catch
    RetryOOM → make inputs spillable → block until ready → retry).

    `protect()` registers every device buffer of the table (first call) and
    marks them spillable — call it on rollback, while the task is NOT
    computing on the table. `get()` restores any spilled buffers through
    budget admission and PINS them (in active use: the pool must not
    delete arrays a running op reads). Use as the `on_rollback` of
    runtime.retry.with_retry:

        st = SpillableTable(pool, table)
        out = with_retry(arbiter, lambda t: op(st.get()), table,
                         on_rollback=st.protect, split=...)
        st.close()
    """

    def __init__(self, pool: "SpillPool", table):
        self._pool = pool
        self._table = table
        self._protected = False
        self._closed = False

    def protect(self) -> None:
        """Register the buffers (first call) and make them spillable:
        the rollback half of the recovery contract."""
        if self._closed:
            raise RuntimeError("SpillableTable is closed")
        if not self._protected:
            self._protected = True
            leaves, self._treedef = jax.tree_util.tree_flatten(self._table)
            self._slots = []
            seen: Dict[int, SpillableBuffer] = {}   # alias-safe: one
            for leaf in leaves:                     # buffer per device array
                if isinstance(leaf, jax.Array):
                    buf = seen.get(id(leaf))
                    if buf is None:
                        buf = self._pool.register(leaf)
                        seen[id(leaf)] = buf
                    self._slots.append(buf)
                else:
                    self._slots.append(leaf)
            self._table = None         # drop the direct strong refs
        for s in self._unique_buffers():
            s.unpin()

    def _unique_buffers(self):
        seen = set()
        for s in self._slots:
            if isinstance(s, SpillableBuffer) and id(s) not in seen:
                seen.add(id(s))
                yield s

    def get(self):
        """The live Table, pinned for use; restores spilled buffers
        (admitted — a restore under pressure can spill OTHER unpinned
        buffers or block through the retry protocol). Balance with
        unpin() (or use()) once the op is done, so idle inputs stay
        spillable for other tasks."""
        if self._closed:
            raise RuntimeError("SpillableTable is closed")
        if not self._protected:
            return self._table
        leaves = []
        for s in self._slots:
            if isinstance(s, SpillableBuffer):
                # pin FIRST: a pinned buffer cannot be spilled, so the
                # array returned by get() below is guaranteed to stay live
                s.pin()
                leaves.append(s.get())
            else:
                leaves.append(s)
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def unpin(self) -> None:
        """Make the buffers spillable again (op finished with them)."""
        if self._protected and not self._closed:
            for s in self._unique_buffers():
                s.unpin()

    def use(self):
        """Context manager: pinned table inside, spillable again outside.

            with st.use() as t:
                out = op(t)
        """
        import contextlib

        @contextlib.contextmanager
        def cm():
            try:
                yield self.get()
            finally:
                self.unpin()
        return cm()

    def close(self) -> None:
        self._closed = True
        if not self._protected:
            self._table = None
            return
        for s in self._unique_buffers():
            self._pool.unregister(s)
        self._slots = []


class SpillPool(MemoryEventHandler):
    """Registry of spillable buffers; spills oldest-first on alloc failure."""

    def __init__(self):
        self.budget: Optional[MemoryBudget] = None   # set by attach()
        self._mu = threading.Lock()
        self._buffers: Dict[int, SpillableBuffer] = {}
        self._next_id = 0
        self.spill_count = 0
        self.spilled_bytes = 0

    def attach(self, budget: MemoryBudget) -> "SpillPool":
        self.budget = budget
        budget.event_handler = self
        return self

    def register(self, array: jax.Array) -> SpillableBuffer:
        """Admit an already-materialized device array into the pool: its
        bytes are charged to the budget and become revocable."""
        assert self.budget is not None, "attach() a budget first"
        r = self.budget.acquire(array_nbytes(array))
        buf = SpillableBuffer(self, array, r)
        with self._mu:
            buf._id = self._next_id
            self._next_id += 1
            self._buffers[buf._id] = buf
        return buf

    def unregister(self, buf: SpillableBuffer) -> None:
        with self._mu:
            self._buffers.pop(getattr(buf, "_id", -1), None)
        buf.close()

    # -- MemoryEventHandler ---------------------------------------------------
    def on_alloc_failure(self, nbytes: int, retry_count: int) -> bool:
        """Spill buffers oldest-first until `nbytes` are freed. True iff any
        bytes were freed (the RmmEventHandlerResourceAdaptor contract:
        true → retry the allocation immediately). Serialized under the pool
        lock so concurrent alloc failures do not over-spill or race the
        counters; individual spills release budget via each buffer's own
        lock, which is never taken while holding another buffer's."""
        freed = 0
        with self._mu:
            candidates = [b for _, b in sorted(self._buffers.items())
                          if not b.spilled and not b.pinned]
            for b in candidates:
                freed += b.spill()
                if freed >= nbytes:
                    break
            if freed > 0:
                self.spill_count += 1
                self.spilled_bytes += freed
        return freed > 0

    def close(self) -> None:
        with self._mu:
            bufs = list(self._buffers.values())
            self._buffers.clear()
        for b in bufs:
            b.close()
