"""Spillable device buffers + the MemoryEventHandler that frees them.

The reference's allocator chain has an event-handler adaptor between the
arbiter and the pool (`RmmEventHandlerResourceAdaptor`, SURVEY.md §3.2): on
allocation failure the plugin's handler makes cached buffers spillable/frees
them and returns true so the allocation retries immediately, *before* the
task-level blocking state machine engages. `SpillPool` is that handler made
real for HBM: registered buffers are copied to host numpy and their device
arrays deleted (`jax.Array.delete()` actually drops the HBM buffer), their
reservations returned to the budget.

Restore (`SpillableBuffer.get`) re-admits through the budget, so a restore
under pressure can itself trigger further spills or the retry protocol —
the same recursion the reference guards in `pre_alloc_core`
(SparkResourceAdaptorJni.cpp:1238-1265); the arbiter's recursive-allocation
detection makes it safe here too.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import jax
import numpy as np

from .admission import array_nbytes
from .pool import MemoryBudget, MemoryEventHandler, Reservation


class SpillableBuffer:
    """One device array whose residency is budget-backed and revocable."""

    def __init__(self, pool: "SpillPool", array: jax.Array,
                 reservation: Reservation):
        self._pool = pool
        self._device = array
        self._host: Optional[np.ndarray] = None
        self._reservation: Optional[Reservation] = reservation
        self.nbytes = array_nbytes(array)
        self._mu = threading.Lock()

    @property
    def spilled(self) -> bool:
        with self._mu:
            return self._device is None

    def spill(self) -> int:
        """Move to host, delete the device buffer, free the budget.
        Returns bytes freed (0 if already spilled)."""
        with self._mu:
            if self._device is None:
                return 0
            self._host = np.asarray(self._device)     # D2H copy
            self._device.delete()                     # drop the HBM buffer
            self._device = None
            r, self._reservation = self._reservation, None
        self._pool.budget.release(r)
        return self.nbytes

    def get(self) -> jax.Array:
        """The live device array; restores (re-admitting budget) if spilled.

        Loops: the buffer can be re-spilled between our restore attempt and
        the return (another thread's alloc failure), and a race-lost restore
        must re-read under the lock — never hand out a deleted array."""
        import jax.numpy as jnp
        while True:
            with self._mu:
                if self._device is not None:
                    return self._device
                host = self._host
            # acquire outside our own lock: admission may call back into the
            # pool's on_alloc_failure, which takes other buffers' locks
            r = self._pool.budget.acquire(self.nbytes)
            dev = jnp.asarray(host)
            with self._mu:
                if self._device is None:
                    self._device = dev
                    self._host = None
                    self._reservation = r
                    return dev
            # lost a restore race; give the budget back and re-check
            self._pool.budget.release(r)
            dev.delete()

    def close(self) -> None:
        with self._mu:
            if self._device is not None:
                self._device.delete()
                self._device = None
            self._host = None
            r, self._reservation = self._reservation, None
        if r is not None:
            self._pool.budget.release(r)


class SpillPool(MemoryEventHandler):
    """Registry of spillable buffers; spills oldest-first on alloc failure."""

    def __init__(self):
        self.budget: Optional[MemoryBudget] = None   # set by attach()
        self._mu = threading.Lock()
        self._buffers: Dict[int, SpillableBuffer] = {}
        self._next_id = 0
        self.spill_count = 0
        self.spilled_bytes = 0

    def attach(self, budget: MemoryBudget) -> "SpillPool":
        self.budget = budget
        budget.event_handler = self
        return self

    def register(self, array: jax.Array) -> SpillableBuffer:
        """Admit an already-materialized device array into the pool: its
        bytes are charged to the budget and become revocable."""
        assert self.budget is not None, "attach() a budget first"
        r = self.budget.acquire(array_nbytes(array))
        buf = SpillableBuffer(self, array, r)
        with self._mu:
            buf._id = self._next_id
            self._next_id += 1
            self._buffers[buf._id] = buf
        return buf

    def unregister(self, buf: SpillableBuffer) -> None:
        with self._mu:
            self._buffers.pop(getattr(buf, "_id", -1), None)
        buf.close()

    # -- MemoryEventHandler ---------------------------------------------------
    def on_alloc_failure(self, nbytes: int, retry_count: int) -> bool:
        """Spill buffers oldest-first until `nbytes` are freed. True iff any
        bytes were freed (the RmmEventHandlerResourceAdaptor contract:
        true → retry the allocation immediately). Serialized under the pool
        lock so concurrent alloc failures do not over-spill or race the
        counters; individual spills release budget via each buffer's own
        lock, which is never taken while holding another buffer's."""
        freed = 0
        with self._mu:
            candidates = [b for _, b in sorted(self._buffers.items())
                          if not b.spilled]
            for b in candidates:
                freed += b.spill()
                if freed >= nbytes:
                    break
            if freed > 0:
                self.spill_count += 1
                self.spilled_bytes += freed
        return freed > 0

    def close(self) -> None:
        with self._mu:
            bufs = list(self._buffers.values())
            self._buffers.clear()
        for b in bufs:
            b.close()
