"""Device health monitor + circuit breaker for the plan/op surface.

The fault injector exists to prove one thing: the framework STOPS retrying
on a dead device (faultinj/README.md:6-16 and `spark_rapids_tpu.faultinj`'s
fatal tier). This module is the production half of that story — it turns
raw failures from the executor into a *policy*:

- **transient** — an injected nonfatal assert, a substituted return code,
  or a `RetryOOM` pressure spike. Worth retrying, but only with jittered
  exponential backoff and only while the plan attempt's shared retry
  *budget* lasts (no retry storms).
- **sticky** — the same operator keeps failing inside a time window, or
  the retry budget / per-op retry bound is exhausted. The device may be
  fine but this workload on it is not; stop hammering it.
- **fatal** — `DeviceFatalError`: the device is poisoned until
  `reset_device()`. Never retried (the whole point of the fatal tier).

Sticky and fatal failures **trip the circuit breaker**:

    closed ── sticky/fatal ──▶ open ── reset_device() ─────▶ half_open
      ▲                         ▲ │      or cooldown_s elapsed   │
      └───── probe succeeds ────┼─┴───────── probe fails ────────┘

While the breaker is open the device is quarantined — the plan executor
routes work to the degraded CPU tier instead (plan/executor.py). The
breaker arms HALF_OPEN either when the operator intervenes
(`reset_device()`, the executor-restart analogue) or on its own once
`cooldown_s` has elapsed since the trip (quarantine is never permanent: a
passed pressure burst or recovered device is re-discovered automatically);
the next admission then runs a cheap heartbeat probe op through the same
faultinj-intercepted surface — success closes the breaker, failure
re-opens it and restarts the cooldown.

Health metrics drain with get-and-reset semantics like the arbiter's
(`ResourceArbiter.get_and_reset_num_retry_throw`): `get_and_reset_metrics()`
returns the counters accumulated since the previous call and zeroes them.

Multi-tenant keying (runtime/sessionctx.py, docs/serving.md): the
SESSION the work belongs to — the explicit id installed by
`sessionctx.session_scope` (the serving dispatcher wraps every job in
one), falling back to thread identity when unscoped — keys the failure
state. Thread keying alone aliased tenants the moment the serving layer
multiplexed sessions over worker threads: one pathological tenant's
failures would drain the budget — or arm the sticky window — of whoever
landed on that thread next. Sticky windows key per (session, op);
retry budgets per (session, thread), so one tenant's concurrent plans
on different workers stay independently bounded per plan attempt. The
breaker itself stays DEVICE-scoped: a fatal fault poisons the device
for every session, whoever triggered it.

Co-processing precedent: treating the CPU as a second execution tier is
how coupled CPU-GPU systems keep serving under device loss ("Revisiting
Co-Processing for Hash Joins on the Coupled CPU-GPU Architecture",
"Accelerating Presto with GPUs" — PAPERS.md).

Knobs (read at monitor construction, `SPARK_RAPIDS_TPU_BREAKER_*` —
config.py): retry budget, backoff base/max, sticky threshold/window,
degrade policy.
"""
from __future__ import annotations

import collections
import random
import threading
import time
from typing import Callable, Deque, Dict, List, Optional

# breaker states
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# failure classifications
TRANSIENT = "transient"
STICKY = "sticky"
FATAL = "fatal"


def device_probe() -> bool:
    """Cheap heartbeat: one tiny device computation, routed through the
    faultinj interception surface (key "health.probe", also matched by `*`
    rules) so a poisoned device fails the probe exactly like a real op."""
    from .. import faultinj
    inj = faultinj.active()
    if inj is not None:
        inj.on_compute("health.probe")
    import jax
    import jax.numpy as jnp
    x = jnp.arange(8, dtype=jnp.int32)
    return int(jax.block_until_ready(jnp.sum(x))) == 28


class CircuitBreaker:
    """closed → open → half_open state machine over one device.

    `trip()` opens it (quarantine); `half_open()` is the reset_device
    lifecycle hook arming a probation period immediately; an OPEN breaker
    also self-arms HALF_OPEN once `cooldown_s` has elapsed since the trip,
    so a quarantine is never permanent — a device that recovered (or a
    pressure burst that passed) is re-discovered by the next admission
    without operator intervention. `probe()` runs the heartbeat and closes
    (success) or re-opens (failure, restarting the cooldown clock).

    `admit()` is the gate: closed admits, open refuses (until cooldown),
    half_open probes. `DeviceHealthMonitor.admit()` is the same gate with
    probe metrics counted — the state transitions live only here."""

    def __init__(self, probe: Optional[Callable[[], bool]] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        from .. import config
        self._probe = probe or device_probe
        self.cooldown_s = (config.breaker_cooldown_s()
                           if cooldown_s is None else cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._opened_at = 0.0
        self.trips = 0
        self.last_trip_reason: Optional[str] = None
        self.last_trip_error: Optional[str] = None

    @property
    def state(self) -> str:
        return self._state

    def trip(self, reason: str, detail: Optional[str] = None) -> None:
        with self._lock:
            self._state = OPEN
            self._opened_at = self._clock()
            self.trips += 1
            self.last_trip_reason = reason
            self.last_trip_error = detail

    def half_open(self) -> None:
        with self._lock:
            if self._state == OPEN:
                self._state = HALF_OPEN

    def maybe_cooldown(self) -> None:
        """Arm HALF_OPEN when an OPEN breaker's cooldown has elapsed
        (cooldown_s <= 0 disables: quarantine until reset_device())."""
        with self._lock:
            if (self._state == OPEN and self.cooldown_s > 0
                    and self._clock() - self._opened_at >= self.cooldown_s):
                self._state = HALF_OPEN

    def probe(self) -> bool:
        try:
            ok = bool(self._probe())
        except Exception:
            ok = False
        with self._lock:
            if ok:
                self._state = CLOSED
            else:
                self._state = OPEN
                self._opened_at = self._clock()   # restart the cooldown
        return ok

    def admit(self, probe: Optional[Callable[[], bool]] = None) -> bool:
        """ONE admission gate: closed admits, open refuses (until the
        cooldown arms half_open), half_open probes. `probe` overrides the
        probe call so callers can route it through counted wrappers
        (DeviceHealthMonitor.admit) without duplicating this dispatch."""
        self.maybe_cooldown()
        if self._state == CLOSED:
            return True
        if self._state == HALF_OPEN:
            return (probe or self.probe)()
        return False


class DeviceHealthMonitor:
    """Classifies device failures and owns the breaker + retry policy.

    One monitor guards one device (a PlanExecutor creates its own by
    default). Injectable `sleep`/`clock`/`rng`/`probe` keep tests fast and
    deterministic."""

    def __init__(self, *,
                 retry_budget: Optional[int] = None,
                 backoff_base_ms: Optional[float] = None,
                 backoff_max_ms: Optional[float] = None,
                 sticky_threshold: Optional[int] = None,
                 sticky_window_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 probe: Optional[Callable[[], bool]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None,
                 worker_id: str = ""):
        from .. import config
        # fleet worker identity (serving/fleet.py): one monitor guards
        # one worker's device, so breaker snapshots carry WHOSE breaker
        # tripped — "" outside a fleet
        self.worker_id = str(worker_id)
        self.retry_budget = (config.breaker_retry_budget()
                             if retry_budget is None else retry_budget)
        self.backoff_base_ms = (config.breaker_backoff_base_ms()
                                if backoff_base_ms is None else backoff_base_ms)
        self.backoff_max_ms = (config.breaker_backoff_max_ms()
                               if backoff_max_ms is None else backoff_max_ms)
        self.sticky_threshold = (config.breaker_sticky_threshold()
                                 if sticky_threshold is None else sticky_threshold)
        self.sticky_window_s = (config.breaker_sticky_window_s()
                                if sticky_window_s is None else sticky_window_s)
        self.breaker = CircuitBreaker(probe=probe, cooldown_s=cooldown_s,
                                      clock=clock)
        self._sleep = sleep
        self._clock = clock
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        # retry budget is per plan attempt, keyed by (session, thread)
        # (sessionctx.session_key x executing thread): the session
        # component stops two tenants multiplexed over one serving worker
        # thread from sharing one bound, while the thread component keeps
        # ONE tenant's concurrent plans on different workers independently
        # bounded — a same-tenant neighbour's start_plan_attempt() must
        # not refill (or its retries starve) this plan's budget mid-plan.
        # Bounded: dead sessions' residue must not grow the monitor
        # forever. The bound errs on the soft side — an evicted live
        # entry refills on the next try_retry — so it sits far above any
        # plausible in-flight count: keys are created only by
        # start_plan_attempt/try_retry, one per concurrently executing
        # plan per thread, and 8192 distinct keys would have to churn
        # through DURING one plan's backoff sleep to soften its bound.
        from ..utils.lru import LruDict
        self._budgets: Dict[tuple, int] = LruDict(8192)
        self._failures: Dict[tuple, Deque[float]] = {}
        self._reset_hooks: List[Callable[[], None]] = []
        self._metrics: Dict[str, float] = collections.defaultdict(float)
        # trip attribution (serving/fleet.py poison quarantine): the
        # fingerprint of the plan executing on THIS thread when a trip
        # lands — thread-local, because the serving dispatcher runs
        # several tenants' plans concurrently through one monitor.
        # Bounded log, drained by the fleet with get-and-reset semantics
        # like the metrics counters.
        self._attr = threading.local()
        self._trip_log: Deque[tuple] = collections.deque(maxlen=64)

    # ---- classification ----------------------------------------------------

    def record_failure(self, op: str, exc: BaseException) -> str:
        """Record one failure of `op` and classify it. Fatal faults classify
        immediately; otherwise stickiness is N failures of the SAME op
        UNDER THE SAME SESSION within the window (old entries age out) —
        tenant A's flaky operator must not arm a sticky trip against
        tenant B's first failure of the same op."""
        from .. import faultinj
        from . import sessionctx
        now = self._clock()
        with self._lock:
            if isinstance(exc, faultinj.DeviceFatalError):
                self._metrics["fatal_faults"] += 1
                return FATAL
            dq = self._failures.setdefault((sessionctx.session_key(), op),
                                           collections.deque())
            dq.append(now)
            while dq and now - dq[0] > self.sticky_window_s:
                dq.popleft()
            if len(self._failures) > 4096:
                # dead-session residue: windows whose every entry has aged
                # out carry no sticky evidence — drop them instead of
                # growing per (session, op) forever
                self._failures = {
                    k: d for k, d in self._failures.items()
                    if d and now - d[-1] <= self.sticky_window_s}
            if len(dq) >= self.sticky_threshold:
                self._metrics["sticky_faults"] += 1
                return STICKY
            self._metrics["transient_faults"] += 1
            return TRANSIENT

    def record_success(self, op: str) -> None:
        """A unit that eventually SUCCEEDED proves its faults were not
        sticky: clear the op's failure window (for the session that ran
        it) so occasional absorbed transients (one per job, say) never
        accumulate across executions into a quarantine of a device that
        recovers every time. Sticky therefore means: repeated failures
        with no intervening success."""
        from . import sessionctx
        with self._lock:
            dq = self._failures.get((sessionctx.session_key(), op))
            if dq:
                dq.clear()

    # ---- retry budget + backoff --------------------------------------------

    def _budget_key(self) -> tuple:
        from . import sessionctx
        return (sessionctx.session_key(), threading.get_ident())

    def start_plan_attempt(self) -> None:
        """Refill this plan attempt's retry budget (keyed by session x
        thread — see __init__: tenants never alias across a shared
        worker thread, and one tenant's concurrent plans never refill or
        starve each other's bound mid-plan)."""
        with self._lock:
            self._budgets[self._budget_key()] = self.retry_budget

    def try_retry(self, attempt: int) -> Optional[float]:
        """Consume one unit of the plan attempt's retry budget and sleep a
        jittered exponential backoff for retry number `attempt` (0-based).
        Returns the milliseconds slept, or None when the budget is
        exhausted (the caller must escalate, not retry)."""
        key = self._budget_key()
        with self._lock:
            budget = self._budgets.get(key)
            if budget is None:
                budget = self.retry_budget
            if budget <= 0:
                self._metrics["budget_exhausted"] += 1
                return None
            self._budgets[key] = budget - 1
        delay_ms = min(self.backoff_max_ms,
                       self.backoff_base_ms * (2 ** attempt))
        delay_ms *= self._rng.uniform(0.5, 1.0)   # jitter: decorrelate peers
        self._sleep(delay_ms / 1e3)
        with self._lock:
            self._metrics["retries"] += 1
            self._metrics["backoff_ms"] += delay_ms
        return delay_ms

    # ---- breaker lifecycle -------------------------------------------------

    def attribution(self, fingerprint: str):
        """Context manager installing `fingerprint` as the CURRENT
        THREAD's trip attribution: a breaker trip landing inside the
        scope logs (fingerprint, reason) for the fleet's poison-plan
        quarantine (serving/fleet.py — a fingerprint that trips breakers
        on >= 2 distinct workers is the crash amplifier auto-respawn
        must not keep feeding). The serving dispatcher wraps every
        execution in one; unattributed trips log fingerprint ""."""
        import contextlib

        @contextlib.contextmanager
        def _scope():
            prev = getattr(self._attr, "fp", "")
            self._attr.fp = str(fingerprint)
            try:
                yield
            finally:
                self._attr.fp = prev
        return _scope()

    def drain_trips(self) -> List[tuple]:
        """Drain the attributed-trip log — `[(fingerprint, reason),
        ...]` since the last drain (get-and-reset, like the metrics
        counters). The fleet absorbs these on every submit and before
        every worker removal, so a dying worker's attributions are
        collected before its stack is torn down."""
        with self._lock:
            out = list(self._trip_log)
            self._trip_log.clear()
        return out

    def trip(self, reason: str, exc: Optional[BaseException] = None) -> None:
        # the underlying error rides the snapshot: a degraded nightly run
        # must say WHICH failure tripped it, not just the classification
        detail = None if exc is None else f"{type(exc).__name__}: {exc}"[:300]
        self.breaker.trip(reason, detail=detail)
        with self._lock:
            self._metrics["trips"] += 1
            self._metrics[f"{reason}_trips"] += 1
            self._trip_log.append(
                (getattr(self._attr, "fp", ""), reason))

    def probe(self) -> bool:
        ok = self.breaker.probe()
        with self._lock:
            self._metrics["probes"] += 1
            if not ok:
                self._metrics["probe_failures"] += 1
            else:
                # recovery (probed closed) restarts every stickiness window,
                # exactly like reset_device(): pre-trip failures must not
                # instantly re-trip the just-recovered device
                self._failures.clear()
        return ok

    def admit(self) -> bool:
        """The executor's device-admission gate: the breaker's single
        dispatch with the half-open probe routed through the counted
        `probe()` wrapper."""
        return self.breaker.admit(probe=self.probe)

    def note_degraded_plan(self) -> None:
        with self._lock:
            self._metrics["degraded_plans"] += 1

    def add_reset_hook(self, fn: Callable[[], None]) -> None:
        """Register a callable run by reset_device() (e.g. re-initializing a
        client) — the quarantine-exit lifecycle hook."""
        self._reset_hooks.append(fn)

    def reset_device(self) -> None:
        """Executor-restart analogue: clear the injector's poisoned-device
        state, run the registered lifecycle hooks, and arm the breaker
        HALF_OPEN so the next admission probes before trusting the device."""
        from .. import faultinj
        inj = faultinj.active()
        if inj is not None:
            inj.reset_device()
        for fn in self._reset_hooks:
            fn()
        with self._lock:
            # pre-recovery failures must not re-trip the breaker: the reset
            # starts a fresh stickiness window for every operator
            self._failures.clear()
        self.breaker.half_open()

    # ---- metrics -----------------------------------------------------------

    def get_and_reset_metrics(self) -> Dict[str, float]:
        """Drain the health counters (arbiter-style get-and-reset)."""
        with self._lock:
            snap = dict(self._metrics)
            self._metrics.clear()
        return snap
