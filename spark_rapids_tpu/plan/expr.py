"""Expression mini-language for plan predicates and projections.

The slot Catalyst expressions fill in the reference plugin: `Filter` takes a
boolean `Expr`, `Project` takes named `Expr`s. Expressions evaluate to raw
device arrays over one input relation; evaluation is pure jnp, so the same
expression works in the eager tier (concrete arrays) and inside the capped
whole-plan jit (tracers).

Scalar-aggregate expressions (`scalar_max(col("rev"))`) evaluate an
aggregate over the WHOLE input relation and broadcast it — the scalar
subquery shape q23's `HAVING sum > 0.95 * MAX(...)` needs. In the capped
tier they reduce only over `alive` rows (the padded-row contract).

Null semantics: expressions read the data buffer only; rows whose inputs
are null must be dropped by validity-aware operators (the NDS tier is
null-free). This matches the capped kernels, which also carry validity
out-of-band.
"""
from __future__ import annotations

import dataclasses
from typing import Any, FrozenSet, Optional

import jax.numpy as jnp


class Expr:
    """Base expression. Build with `col`/`lit` and python operators."""

    def references(self) -> FrozenSet[str]:
        raise NotImplementedError

    def evaluate(self, table, alive: Optional[jnp.ndarray] = None):
        """Array of the expression over `table` ((n,) jnp array; scalar
        aggregates reduce over `alive` rows when a mask is given)."""
        raise NotImplementedError

    # ---- operator sugar ---------------------------------------------------
    def _bin(self, op: str, other) -> "BinOp":
        return BinOp(op, self, _wrap(other))

    def __eq__(self, other):                       # noqa: D105
        return self._bin("==", other)

    def __ne__(self, other):
        return self._bin("!=", other)

    __hash__ = None   # comparison builds expressions; not hashable

    def __lt__(self, other):
        return self._bin("<", other)

    def __le__(self, other):
        return self._bin("<=", other)

    def __gt__(self, other):
        return self._bin(">", other)

    def __ge__(self, other):
        return self._bin(">=", other)

    def __and__(self, other):
        return self._bin("&", other)

    def __or__(self, other):
        return self._bin("|", other)

    def __add__(self, other):
        return self._bin("+", other)

    def __radd__(self, other):
        return _wrap(other)._bin("+", self)

    def __sub__(self, other):
        return self._bin("-", other)

    def __rsub__(self, other):
        return _wrap(other)._bin("-", self)

    def __mul__(self, other):
        return self._bin("*", other)

    def __rmul__(self, other):
        return _wrap(other)._bin("*", self)

    def __invert__(self):
        return UnaryOp("~", self)

    def __neg__(self):
        return UnaryOp("-", self)


def _wrap(v) -> Expr:
    return v if isinstance(v, Expr) else Literal(v)


@dataclasses.dataclass(frozen=True, eq=False)
class ColumnRef(Expr):
    name: str

    def references(self):
        return frozenset((self.name,))

    def evaluate(self, table, alive=None):
        return table[self.name].data

    def __repr__(self):
        return self.name


@dataclasses.dataclass(frozen=True, eq=False)
class Literal(Expr):
    value: Any

    def references(self):
        return frozenset()

    def evaluate(self, table, alive=None):
        n = table.num_rows
        return jnp.full((n,), self.value)

    def __repr__(self):
        return repr(self.value)


_BIN_FNS = {
    "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
    "&": lambda a, b: a & b, "|": lambda a, b: a | b,
    "+": lambda a, b: a + b, "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
}


@dataclasses.dataclass(frozen=True, eq=False)
class BinOp(Expr):
    op: str
    left: Expr
    right: Expr

    def references(self):
        return self.left.references() | self.right.references()

    def evaluate(self, table, alive=None):
        return _BIN_FNS[self.op](self.left.evaluate(table, alive),
                                 self.right.evaluate(table, alive))

    def __repr__(self):
        return f"({self.left!r} {self.op} {self.right!r})"


@dataclasses.dataclass(frozen=True, eq=False)
class UnaryOp(Expr):
    op: str
    child: Expr

    def references(self):
        return self.child.references()

    def evaluate(self, table, alive=None):
        v = self.child.evaluate(table, alive)
        return ~v if self.op == "~" else -v

    def __repr__(self):
        return f"{self.op}{self.child!r}"


@dataclasses.dataclass(frozen=True, eq=False)
class ScalarAgg(Expr):
    """Aggregate over the whole input relation, broadcast as a scalar —
    the scalar-subquery shape (q23's `> 0.95 * MAX(rev)`). Honors the
    capped tier's `alive` mask by reducing over live rows only."""
    op: str                  # max | min | sum
    child: Expr

    def references(self):
        return self.child.references()

    def evaluate(self, table, alive=None):
        v = self.child.evaluate(table, alive)
        if alive is not None:
            ident = _reduce_identity(self.op, v.dtype)
            v = jnp.where(alive, v, ident)
        return {"max": jnp.max, "min": jnp.min, "sum": jnp.sum}[self.op](v)

    def __repr__(self):
        return f"{self.op}({self.child!r})"


def _reduce_identity(op: str, dtype):
    if op == "sum":
        return jnp.asarray(0, dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        inf = jnp.asarray(jnp.inf, dtype)
        return -inf if op == "max" else inf
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.min if op == "max" else info.max, dtype)


# ---- structural helpers (the optimizer's expression toolkit) ----------------

def _foldable(v) -> bool:
    """Folded python arithmetic matches runtime jnp arithmetic because the
    engine runs under x64 (int64/float64 storage, enabled at import): an
    int that no longer fits int64 would RAISE at Literal.evaluate where
    the unfolded tree silently wraps — don't fold those."""
    if isinstance(v, bool) or not isinstance(v, int):
        return True
    return -(2 ** 63) <= v < 2 ** 63


def fold(e: Expr) -> Expr:
    """Constant-fold literal-only subtrees bottom-up. `BinOp(lit, lit)` and
    `UnaryOp(lit)` become a `Literal` of the evaluated python value —
    including comparisons, so a whole literal predicate reduces to
    `Literal(True/False)` and the optimizer's trivial-predicate rule can
    drop/short-circuit the Filter. Returns `e` itself when nothing folded
    (callers detect a rewrite by identity). Scalar aggregates never fold:
    even over a literal, their value depends on the live-row set (an
    empty relation reduces max/min to the identity, sum to n*v)."""
    if isinstance(e, BinOp):
        l, r = fold(e.left), fold(e.right)
        if isinstance(l, Literal) and isinstance(r, Literal):
            v = _BIN_FNS[e.op](l.value, r.value)
            if _foldable(v):
                return Literal(v)
        if l is e.left and r is e.right:
            return e
        return BinOp(e.op, l, r)
    if isinstance(e, UnaryOp):
        c = fold(e.child)
        if isinstance(c, Literal):
            if e.op == "~":
                # python's ~True is -2; the jnp evaluation of ~ on a bool
                # array is logical not — fold must match the array semantics
                v = (not c.value) if isinstance(c.value, bool) else ~c.value
            else:
                v = -c.value
            if _foldable(v):
                return Literal(v)
        return e if c is e.child else UnaryOp(e.op, c)
    if isinstance(e, ScalarAgg):
        c = fold(e.child)
        return e if c is e.child else ScalarAgg(e.op, c)
    return e


def substitute(e: Expr, mapping) -> Expr:
    """Replace every `ColumnRef(name)` with `mapping[name]` (an Expr) —
    how a predicate is rewritten through a Project during pushdown.
    Unmapped names raise KeyError (callers guard with references())."""
    if isinstance(e, ColumnRef):
        return mapping[e.name]
    if isinstance(e, BinOp):
        return BinOp(e.op, substitute(e.left, mapping),
                     substitute(e.right, mapping))
    if isinstance(e, UnaryOp):
        return UnaryOp(e.op, substitute(e.child, mapping))
    if isinstance(e, ScalarAgg):
        return ScalarAgg(e.op, substitute(e.child, mapping))
    return e


def has_scalar_agg(e: Expr) -> bool:
    """Whether the expression contains a whole-relation scalar aggregate —
    such expressions are NOT row-wise, so reorderings that change the row
    set under them (pushdown below a join/union, limit pushdown) are
    invalid and the optimizer must skip them."""
    if isinstance(e, ScalarAgg):
        return True
    if isinstance(e, BinOp):
        return has_scalar_agg(e.left) or has_scalar_agg(e.right)
    if isinstance(e, UnaryOp):
        return has_scalar_agg(e.child)
    return False


# ---- public constructors ----------------------------------------------------

def col(name: str) -> ColumnRef:
    """Reference a column of the input relation by name."""
    return ColumnRef(name)


def lit(value) -> Literal:
    """A literal, broadcast to the relation's length."""
    return Literal(value)


def scalar_max(e: Expr) -> ScalarAgg:
    return ScalarAgg("max", _wrap(e))


def scalar_min(e: Expr) -> ScalarAgg:
    return ScalarAgg("min", _wrap(e))


def scalar_sum(e: Expr) -> ScalarAgg:
    return ScalarAgg("sum", _wrap(e))
