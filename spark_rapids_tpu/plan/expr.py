"""Expression mini-language for plan predicates and projections.

The slot Catalyst expressions fill in the reference plugin: `Filter` takes a
boolean `Expr`, `Project` takes named `Expr`s. Expressions evaluate to raw
device arrays over one input relation; evaluation is pure jnp, so the same
expression works in the eager tier (concrete arrays) and inside the capped
whole-plan jit (tracers).

Scalar-aggregate expressions (`scalar_max(col("rev"))`) evaluate an
aggregate over the WHOLE input relation and broadcast it — the scalar
subquery shape q23's `HAVING sum > 0.95 * MAX(...)` needs. In the capped
tier they reduce only over `alive` rows (the padded-row contract).

Null semantics: expressions read the data buffer only; rows whose inputs
are null must be dropped by validity-aware operators (the NDS tier is
null-free). This matches the capped kernels, which also carry validity
out-of-band.
"""
from __future__ import annotations

import dataclasses
from typing import Any, FrozenSet, Optional

import jax.numpy as jnp


class Expr:
    """Base expression. Build with `col`/`lit` and python operators."""

    def references(self) -> FrozenSet[str]:
        raise NotImplementedError

    def evaluate(self, table, alive: Optional[jnp.ndarray] = None):
        """Array of the expression over `table` ((n,) jnp array; scalar
        aggregates reduce over `alive` rows when a mask is given)."""
        raise NotImplementedError

    # ---- operator sugar ---------------------------------------------------
    def _bin(self, op: str, other) -> "BinOp":
        return BinOp(op, self, _wrap(other))

    def __eq__(self, other):                       # noqa: D105
        return self._bin("==", other)

    def __ne__(self, other):
        return self._bin("!=", other)

    __hash__ = None   # comparison builds expressions; not hashable

    def __lt__(self, other):
        return self._bin("<", other)

    def __le__(self, other):
        return self._bin("<=", other)

    def __gt__(self, other):
        return self._bin(">", other)

    def __ge__(self, other):
        return self._bin(">=", other)

    def __and__(self, other):
        return self._bin("&", other)

    def __or__(self, other):
        return self._bin("|", other)

    def __add__(self, other):
        return self._bin("+", other)

    def __radd__(self, other):
        return _wrap(other)._bin("+", self)

    def __sub__(self, other):
        return self._bin("-", other)

    def __rsub__(self, other):
        return _wrap(other)._bin("-", self)

    def __mul__(self, other):
        return self._bin("*", other)

    def __rmul__(self, other):
        return _wrap(other)._bin("*", self)

    def __invert__(self):
        return UnaryOp("~", self)

    def __neg__(self):
        return UnaryOp("-", self)


def _wrap(v) -> Expr:
    return v if isinstance(v, Expr) else Literal(v)


@dataclasses.dataclass(frozen=True, eq=False)
class ColumnRef(Expr):
    name: str

    def references(self):
        return frozenset((self.name,))

    def evaluate(self, table, alive=None):
        return table[self.name].data

    def __repr__(self):
        return self.name


@dataclasses.dataclass(frozen=True, eq=False)
class Literal(Expr):
    value: Any

    def references(self):
        return frozenset()

    def evaluate(self, table, alive=None):
        n = table.num_rows
        return jnp.full((n,), self.value)

    def __repr__(self):
        return repr(self.value)


_BIN_FNS = {
    "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
    "&": lambda a, b: a & b, "|": lambda a, b: a | b,
    "+": lambda a, b: a + b, "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
}


@dataclasses.dataclass(frozen=True, eq=False)
class BinOp(Expr):
    op: str
    left: Expr
    right: Expr

    def references(self):
        return self.left.references() | self.right.references()

    def evaluate(self, table, alive=None):
        return _BIN_FNS[self.op](self.left.evaluate(table, alive),
                                 self.right.evaluate(table, alive))

    def __repr__(self):
        return f"({self.left!r} {self.op} {self.right!r})"


@dataclasses.dataclass(frozen=True, eq=False)
class UnaryOp(Expr):
    op: str
    child: Expr

    def references(self):
        return self.child.references()

    def evaluate(self, table, alive=None):
        v = self.child.evaluate(table, alive)
        return ~v if self.op == "~" else -v

    def __repr__(self):
        return f"{self.op}{self.child!r}"


@dataclasses.dataclass(frozen=True, eq=False)
class ScalarAgg(Expr):
    """Aggregate over the whole input relation, broadcast as a scalar —
    the scalar-subquery shape (q23's `> 0.95 * MAX(rev)`). Honors the
    capped tier's `alive` mask by reducing over live rows only."""
    op: str                  # max | min | sum
    child: Expr

    def references(self):
        return self.child.references()

    def evaluate(self, table, alive=None):
        v = self.child.evaluate(table, alive)
        if alive is not None:
            ident = _reduce_identity(self.op, v.dtype)
            v = jnp.where(alive, v, ident)
        return {"max": jnp.max, "min": jnp.min, "sum": jnp.sum}[self.op](v)

    def __repr__(self):
        return f"{self.op}({self.child!r})"


def _reduce_identity(op: str, dtype):
    if op == "sum":
        return jnp.asarray(0, dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        inf = jnp.asarray(jnp.inf, dtype)
        return -inf if op == "max" else inf
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.min if op == "max" else info.max, dtype)


# ---- public constructors ----------------------------------------------------

def col(name: str) -> ColumnRef:
    """Reference a column of the input relation by name."""
    return ColumnRef(name)


def lit(value) -> Literal:
    """A literal, broadcast to the relation's length."""
    return Literal(value)


def scalar_max(e: Expr) -> ScalarAgg:
    return ScalarAgg("max", _wrap(e))


def scalar_min(e: Expr) -> ScalarAgg:
    return ScalarAgg("min", _wrap(e))


def scalar_sum(e: Expr) -> ScalarAgg:
    return ScalarAgg("sum", _wrap(e))
