"""Full-plan SPMD distributed lowering for the eager tier.

PR 1 distributed exactly one shape — HashAggregate over Exchange — and
every other operator of a meshed plan still funneled through one chip.
This module generalizes that special case into a whole-plan tier
(docs/distributed.md): when `PlanExecutor(mesh=...)` runs an eager plan,
every operator with a distributed form executes ON the mesh over a
`ShardedRel` — a padded, row-sharded relation (global logical arrays with
`NamedSharding`, a live-row mask, and the hash-partitioning property the
rows currently satisfy) — and data crosses the ICI only at explicit
`Exchange` boundaries (hash / broadcast / gather) or the fused exchanges
inside the two-phase aggregate and sample-sort primitives:

- Scan: the bound table pads to a multiple of the mesh size and shards
  row-wise (`NamedSharding(mesh, P(axis))`); padding rows are dead.
- Filter / Project / FusedSelect: elementwise over the sharded columns —
  sharding propagates through plain jnp, no collective; scalar-aggregate
  expressions reduce over live rows (GSPMD all-reduce).
- Exchange(hash): `distributed_repartition_keyed` — the standalone
  shuffle; Exchange(broadcast): the build side replicates onto every
  shard; Exchange(gather): the sharded relation collects to one device
  (the sink boundary, or the handoff into an operator with no
  distributed form — the same graceful-boundary pattern as the streaming
  tier's concat).
- HashJoin: consumes its exchanges — both sides partitioned (or one
  replicated) means `distributed_colocated_join_keyed` joins shard-local
  with NO further movement; an unplanned join repartitions implicitly.
- HashAggregate over Exchange(hash) FUSES into the two-phase
  partial→all-to-all→final `distributed_groupby_keyed` program (the
  exchange ships per-group partials, not rows); over an input already
  partitioned by a subset of its keys the exchange is ELIDED and
  `distributed_local_groupby` merges shard-locally.
- Sort / TopK: `distributed_sort_keyed` sample-sorts to global order
  (range partitioning; descending keys ride bitwise-inverted words);
  TopK masks the global rank prefix.
- Union: logical concatenation resharded across the mesh.

Static capacities (row_cap / key_cap / slack) escalate geometrically via
`parallel.autoretry.auto_retry_overflow` and the final values memoize per
(plan fingerprint, node) on the executor, exactly like the capped tier's
caps memo. Every primitive call goes through a bounded cache of
`jax.jit`-wrapped callables — an eager `shard_map` re-traces per call;
the jitted form re-traces only per (program, shapes).

Runtime gates (a node that fails one gathers its inputs and runs on the
local eager path): fixed-width 1-D columns only, aggregate value columns
non-null and non-float (the exchange accumulates in int64), no `mean`,
keyless aggregates and Limit have no distributed form. Join emission
order and aggregate output placement differ from the single-device
kernels, so relations carry `order_keys` — the gather re-sorts a
distributed aggregate's output by its group keys to match the local
sort-based kernel row for row; Sort's own output is globally ordered and
gathers in place (ties may order differently than the local stable sort
when the sort keys do not totally order the rows).

Transport (plan/transport.py, docs/distributed.md#transport): with
SPARK_RAPIDS_TPU_EXCHANGE_PACK on (default), every exchange payload
ships in packed wire form — FOR-narrowed integer planes and bit-packed
validity inside the collectives, dictionary/RLE on the host-materialized
broadcast build side, packed planes on the device→host gather pull —
and unpacks on the receiving side. Byte accounting is per edge, live
payload only, each edge counted once (broadcast x (n_peers-1)):
`exchange_bytes` is the wire form, `exchange_bytes_logical` the
unpacked per-column payload, and both stay at or under the certifier's
per-edge bound (analysis/footprint.py). SPARK_RAPIDS_TPU_EXCHANGE_ASYNC
dispatches an Exchange's pack+transfer on a worker thread (`PendingRel`)
so the transfer overlaps downstream operators' compute until a consumer
resolves it — the PR 4 prefetch shape at the exchange boundary; a
transfer fault then surfaces (and degrades) at the consuming operator.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import dtypes
from ..columnar import Column, Table
from ..parallel.keys import (KeySpec, _ONE_WORD_KINDS, decode_key_columns,
                             encode_key_column)
from ..utils.lru import LruDict
from . import transport
from .nodes import (Exchange, Filter, FusedSelect, HashAggregate, HashJoin,
                    Limit, PlanNode, Project, Scan, Sort, TopK, Union)

_KEYABLE_KINDS = set(_ONE_WORD_KINDS) | {dtypes.Kind.FLOAT32,
                                         dtypes.Kind.FLOAT64}
_DIST_AGGS = ("sum", "count", "min", "max", "size")

# jitted distributed primitives, keyed by (name, mesh, axis, static params):
# an eager shard_map re-traces AND re-compiles per call; one bounded cache
# for the whole process keeps repeat executions at dispatch cost.
# LruDict.get/__setitem__ are internally locked (utils/lru.py — the
# serving layer made every shared memo self-guarding), so the async
# exchange workers (PendingRel) that hit this cache concurrently need no
# external lock for single get/insert operations
_JIT_PRIMS = LruDict(256)


def _jitted(key, builder):
    """Bounded cache of compiled primitive callables; `builder()` returns
    the final (already jit-wrapped) function. Safe under concurrent async
    exchange workers: a lost race builds one redundant (cheap, un-traced)
    wrapper, never corrupts the cache."""
    fn = _JIT_PRIMS.get(key)
    if fn is None:
        fn = builder()
        _JIT_PRIMS[key] = fn
    return fn


class ShardedRel:
    """A relation living on the mesh: `table` columns are GLOBAL logical
    arrays sharded row-wise (`NamedSharding(mesh, P(axis))`, or fully
    replicated for a broadcast build side), `valid` marks live rows
    (padding and exchange dead slots are False), `part` is the set of key
    tuples the rows are hash-partitioned by (equal tuples co-located —
    the exchange-elision property), and `order_keys` names the columns a
    gather must re-sort by to reproduce the local tier's row order (set
    by aggregates, whose local kernel emits key-sorted rows).

    Quacks like a Table where the executor's metric loop needs it:
    `columns` and `num_rows` (live count)."""

    __slots__ = ("table", "valid", "part", "replicated", "order_keys",
                 "_num_rows", "_local")

    def __init__(self, table: Table, valid: jnp.ndarray,
                 part: frozenset = frozenset(), replicated: bool = False,
                 order_keys: Optional[List[str]] = None):
        self.table = table
        self.valid = valid
        self.part = part
        self.replicated = replicated
        self.order_keys = order_keys
        self._num_rows = None
        self._local = None

    @property
    def columns(self):
        return self.table.columns

    @property
    def num_rows(self) -> int:
        if self._num_rows is None:
            # reduce on device, ship 8 bytes — the executor's metric loop
            # reads this per operator, and pulling the whole global mask
            # to host (np.asarray) would serialize the walk on a
            # full-mask transfer every node
            self._num_rows = int(jnp.sum(self.valid.astype(jnp.int64)))
        return self._num_rows

    @property
    def padded_rows(self) -> int:
        return self.table.num_rows

    def sharding_str(self, n_peers: int) -> str:
        return _sharding_str(self.part, self.replicated, n_peers)

    def to_local_table(self) -> Table:
        """Gather to one device and compact to the live rows (restoring
        the local tier's row order via `order_keys` when set) — the sink
        boundary. Cached: DAG-shared consumers gather once."""
        if self._local is not None:
            return self._local
        mask = np.asarray(self.valid)
        idx = np.nonzero(mask)[0]
        cols = []
        for c in self.table.columns:
            data = jnp.asarray(np.asarray(c.data)[idx])
            validity = c.validity
            if validity is not None:
                validity = jnp.asarray(np.asarray(validity)[idx])
            cols.append(dataclasses.replace(c, data=data, validity=validity,
                                            length=int(idx.shape[0])))
        t = Table(cols, names=list(self.table.names))
        if self.order_keys:
            from .executor import _ops
            t = _ops().sort_table(t, key_names=list(self.order_keys),
                                  ascending=[True] * len(self.order_keys))
        self._local = t
        return t


def _sharding_str(part: frozenset, replicated: bool, n_peers: int) -> str:
    if replicated:
        return f"replicated@{n_peers}"
    if part:
        keys = min(part)   # deterministic pick for display
        return f"hash[{','.join(keys)}]@{n_peers}"
    return f"rows@{n_peers}"


class PendingRel:
    """A ShardedRel still in flight on an exchange worker thread
    (SPARK_RAPIDS_TPU_EXCHANGE_ASYNC): the plan walk continues past the
    Exchange node while pack+transfer run on the thread, and the first
    consumer `resolve()`s — the transfer wall that ran while the main
    thread was NOT blocked waiting here is the edge's measured
    `exchange_overlap_ms`. Placement facts (`part`/`replicated`) are
    known statically so the metric loop stamps `sharding` without
    forcing a wait; every data accessor resolves first. A transfer
    error raises at the consumer (the async fault-attribution caveat in
    docs/distributed.md#transport), and the consumer's retry loop gets
    REAL re-execution: each later resolve re-runs the exchange
    synchronously instead of re-raising a cached error."""

    pending = True

    def __init__(self, fn, metric, nbytes_fn,
                 part: frozenset = frozenset(), replicated: bool = False):
        self._fn = fn
        self._metric = metric
        self._nbytes_fn = nbytes_fn
        self.part = part
        self.replicated = replicated
        self._result = None
        self._err = None
        self._t0 = self._t1 = 0.0
        self._resolved = False

        def work():
            self._t0 = time.perf_counter()
            try:
                out = fn()
                # the transfer must COMPLETE on the thread — otherwise
                # "async" would just defer the device work to the
                # consumer and the overlap would be fiction
                jax.block_until_ready([c.data for c in out.table.columns])
                self._result = out
            except BaseException as e:    # surfaces at the consumer
                self._err = e
            finally:
                self._t1 = time.perf_counter()

        self._thread = threading.Thread(
            target=work, daemon=True, name="spark-rapids-tpu-exchange")
        self._thread.start()

    def _stamp(self, dur: float) -> None:
        m = self._metric
        m.wall_ms = dur * 1e3
        m.rows_out = self._result.num_rows
        m.bytes_out = self._nbytes_fn(self._result.table)

    def resolve(self) -> "ShardedRel":
        if not self._resolved:
            w0 = time.perf_counter()
            self._thread.join()
            blocked = time.perf_counter() - w0
            self._resolved = True
            dur = self._t1 - self._t0
            self._metric.exchange_overlap_ms = max(0.0, dur - blocked) * 1e3
            if self._result is not None:
                self._stamp(dur)
        if self._result is None:
            # the worker thread failed. Raise the original error ONCE on
            # the consuming thread; every later resolve (the consumer's
            # fault-retry loop re-entering exec_node) RE-RUNS the
            # exchange synchronously here, so transient faults get real
            # re-execution semantics instead of a cached error that
            # makes every retry futile
            err, self._err = self._err, None
            if err is not None:
                raise err
            t0 = time.perf_counter()
            out = self._fn()
            jax.block_until_ready([c.data for c in out.table.columns])
            self._result = out
            self._stamp(time.perf_counter() - t0)
        return self._result

    def sharding_str(self, n_peers: int) -> str:
        return _sharding_str(self.part, self.replicated, n_peers)

    # -- data accessors force resolution -------------------------------------
    @property
    def table(self):
        return self.resolve().table

    @property
    def valid(self):
        return self.resolve().valid

    @property
    def columns(self):
        return self.resolve().columns

    @property
    def num_rows(self) -> int:
        return self.resolve().num_rows

    @property
    def padded_rows(self) -> int:
        return self.resolve().padded_rows

    @property
    def order_keys(self):
        return self.resolve().order_keys

    def to_local_table(self) -> Table:
        return self.resolve().to_local_table()


def _resolve_rel(c):
    return c.resolve() if getattr(c, "pending", False) else c


def table_shardable(t: Table) -> bool:
    """Whether every column can ride the distributed tier: fixed-width
    1-D buffers only (strings/lists/decimal128 keep the plan local —
    the graceful gather boundary, not an error)."""
    return all(c.data is not None and c.offsets is None and not c.children
               and getattr(c.data, "ndim", 1) == 1 for c in t.columns)


def shard_table(mesh, axis: str, t: Table,
                part: frozenset = frozenset()) -> ShardedRel:
    """Pad a bound Table to a multiple of the mesh size and shard it
    row-wise across the peers (dead padding rows carry zeros and a False
    live mask) — the mesh-sharded Scan. An empty table becomes one dead
    slot per shard so the SPMD shapes stay non-degenerate."""
    n_peers = mesh.shape[axis]
    n = t.num_rows
    pad = (-n) % n_peers if n else n_peers
    spec = NamedSharding(mesh, P(axis))

    def put(a, fill):
        if pad:
            a = jnp.concatenate([a, jnp.full((pad,), fill, a.dtype)])
        return jax.device_put(a, spec)

    cols = []
    for c in t.columns:
        validity = c.validity
        if validity is not None:
            validity = put(validity, False)
        cols.append(dataclasses.replace(c, data=put(c.data, 0),
                                        validity=validity, length=n + pad))
    valid = put(jnp.ones((n,), bool), False)
    return ShardedRel(Table(cols, names=list(t.names)), valid, part=part)


# ---- value packing (columns <-> primitive payload arrays) -------------------

def _pack_cols(t: Table, names: List[str]):
    """Columns -> flat payload arrays for the exchange primitives. Each
    column contributes its data array plus, when nullable, its validity
    (a bool payload — the exchanges preserve payload dtypes). Returns
    (arrays, layout) where layout rebuilds the columns."""
    arrays, layout = [], []
    for nm in names:
        c = t[nm]
        arrays.append(c.data)
        has_v = c.validity is not None
        if has_v:
            arrays.append(c.validity)
        layout.append((nm, c.dtype, has_v))
    return arrays, layout


def _unpack_cols(arrays, layout) -> List[Column]:
    """Payload arrays -> typed columns (casting back any dtype the
    collective math promoted)."""
    cols = []
    i = 0
    for nm, dt, has_v in layout:
        data = arrays[i].astype(dt.storage_dtype())
        i += 1
        validity = None
        if has_v:
            validity = arrays[i].astype(jnp.bool_)
            i += 1
        cols.append(Column(dtype=dt, length=int(data.shape[0]), data=data,
                           validity=validity))
    return cols


def _key_specs(lt: Table, lkeys, rt: Optional[Table] = None,
               rkeys=None) -> Optional[List[KeySpec]]:
    """Shared static key layout for one or two sides; None when a key
    dtype has no distributed encoding (or the sides' kinds differ)."""
    specs = []
    for i, lk in enumerate(lkeys):
        lc = lt[lk]
        kind = lc.dtype.kind
        if kind not in _KEYABLE_KINDS:
            return None
        nullable = lc.validity is not None
        if rt is not None:
            rc = rt[rkeys[i]]
            if rc.dtype.kind != kind:
                return None
            nullable = nullable or rc.validity is not None
        specs.append(KeySpec(lc.dtype, 1, nullable))
    return specs


def _encode_keys(t: Table, keys, specs) -> List[jnp.ndarray]:
    words = []
    for k, sp in zip(keys, specs):
        w, _ = encode_key_column(t[k], spec=sp)
        words.extend(w)
    return words


def _decode_keys(words, specs, names, alive) -> List[Tuple[str, Column]]:
    """Key word arrays back to typed named columns. The relation's `valid`
    mask owns dead-slot liveness, so decode must NOT fold `alive` into
    column validity — a non-nullable key column stays non-nullable (the
    downstream aggregate's non-null gate, and any later encode under the
    same spec, depend on it). Dead slots decode to sentinel garbage that
    no consumer reads."""
    del alive
    return list(zip(names, decode_key_columns(words, specs)))


# ---- partitioning transfer (the exchange-elision property) ------------------

def transfer_part(node: PlanNode, child_parts: List[frozenset],
                  child_schemas=None) -> frozenset:
    """Static/runtime-shared rule: the hash-partitioning property of a
    node's OUTPUT given its children's. Each element is a tuple of column
    names; rows equal on that tuple are co-located. Used by the
    optimizer's exchange_planning (insert/elide decisions) and mirrored
    by the runtime rels."""
    from .expr import ColumnRef
    if isinstance(node, (Filter, Limit)):
        return child_parts[0]
    if isinstance(node, (Project, FusedSelect)):
        renames = {}
        for out_name, e in node.exprs:
            if isinstance(e, ColumnRef) and e.name not in renames:
                renames[e.name] = out_name
        out = set()
        for p in child_parts[0]:
            if all(c in renames for c in p):
                out.add(tuple(renames[c] for c in p))
        return frozenset(out)
    if isinstance(node, Exchange):
        if node.how == "hash":
            return frozenset({tuple(node.keys)})
        if node.how in ("broadcast", "gather"):
            return frozenset()
        return child_parts[0]
    if isinstance(node, HashJoin):
        lp = child_parts[0]
        broadcast = (isinstance(node.right, Exchange)
                     and node.right.how == "broadcast")
        if node.how != "inner":
            # semi/anti keep the left relation's shape; shuffled -> placed
            # by left keys; broadcast -> left rows never moved
            if broadcast:
                return lp
            return frozenset({tuple(node.left_keys)})
        if broadcast:
            return lp
        return frozenset({tuple(node.left_keys), tuple(node.right_keys)})
    if isinstance(node, HashAggregate):
        if not node.keys:
            return frozenset()
        # mirror the executor's two aggregate paths, each with its own
        # TRUE placement: with a satisfying child claim the exchange is
        # ELIDED (local merge — rows never move, so exactly the child's
        # subset claims survive); otherwise the fused two-phase program
        # re-places groups by the hash of the full key tuple. Claims
        # from the other path must not leak: a stale child claim after a
        # fused re-place (or a full-keys claim after an elided merge)
        # would let a downstream consumer elide a REQUIRED exchange.
        # (A static mis-prediction of the runtime path is still safe:
        # the executor checks elision against its own runtime claims and
        # repartitions implicitly when they don't hold.)
        keys = set(node.keys)
        sub = frozenset(p for p in child_parts[0] if set(p) <= keys)
        return sub if sub else frozenset({tuple(node.keys)})
    return frozenset()      # Sort/TopK (range), Union, Scan, unknown


def part_satisfies(part: frozenset, keys) -> bool:
    """Whether `part` already co-locates every group of `keys` — the
    groupby exchange-elision test (a partition tuple that is a SUBSET of
    the group keys suffices: equal group tuples imply equal subsets)."""
    keyset = set(keys)
    return any(set(p) <= keyset for p in part)


def join_alignment(lpart: frozenset, rpart: frozenset, lkeys, rkeys
                   ) -> Optional[Tuple[Tuple[str, ...], Tuple[str, ...]]]:
    """The (left tuple, right tuple) placement pair under which both join
    sides are already partitioned positionally alike (same permutation of
    the key pairing on both sides) — matching rows are then guaranteed
    co-located and the join needs no exchange. Returns the ACTUAL aligned
    tuples (which may be a permutation of the join-key order — the
    output's true placement claim), or None."""
    lk, rk = tuple(lkeys), tuple(rkeys)
    for lp in lpart:
        if len(lp) != len(lk) or set(lp) != set(lk):
            continue
        perm = tuple(lk.index(c) for c in lp)
        rp = tuple(rk[i] for i in perm)
        if rp in rpart:
            return lp, rp
    return None


def join_aligned(lpart: frozenset, rpart: frozenset, lkeys, rkeys) -> bool:
    return join_alignment(lpart, rpart, lkeys, rkeys) is not None


# ---- the distributed walk ---------------------------------------------------

class DistContext:
    """Per-execution distributed lowering state: the mesh, the jitted
    primitive handles, the fused-exchange set, and the caps memo shared
    with the executor."""

    def __init__(self, executor, plan, inputs):
        from .. import config
        self.ex = executor
        self.mesh = executor.mesh
        self.axis = executor.mesh_axis
        self.n_peers = self.mesh.shape[self.axis]
        self.plan = plan
        self.slack = config.dist_slack()
        # transport knobs (plan/transport.py), snapshotted per execution:
        # pack off restores the byte-identical legacy payload layout
        self.pack = config.exchange_pack()
        self.codecs = config.exchange_codecs() if self.pack else frozenset()
        self.async_on = config.exchange_async()
        self.spec = NamedSharding(self.mesh, P(self.axis))
        self.rep_spec = NamedSharding(self.mesh, P())
        parents: Dict[int, List[PlanNode]] = {}
        for n in plan.nodes:
            for c in n.children:
                parents.setdefault(id(c), []).append(n)
        self.parents = parents
        self._node_index = {id(n): i for i, n in enumerate(plan.nodes)}
        # hash Exchanges whose only consumer is a HashAggregate FUSE into
        # the two-phase groupby program: the Exchange defers (identity) and
        # the aggregate attributes the exchange bytes back to it
        self.fused_exchanges = {
            id(n) for n in plan.nodes
            if isinstance(n, Exchange) and n.how == "hash"
            and len(parents.get(id(n), [])) == 1
            and isinstance(parents[id(n)][0], HashAggregate)
            and parents[id(n)][0].keys
        }

    # -- caps memo (fingerprint x node index x primitive, like the capped
    # tier's fingerprint-keyed memo) -----------------------------------------
    def _memo_key(self, node, tag: str):
        # `tag` separates the primitives one node may drive (a join's
        # implicit side repartitions escalate slack; the join itself
        # escalates row_cap — their caps must not merge)
        return (self.plan.fingerprint, self._node_index[id(node)], tag)

    def _caps(self, node, tag: str, defaults: Dict) -> Dict:
        memo = self.ex._dist_caps_memo.get(self._memo_key(node, tag))
        caps = dict(defaults)
        for k, v in (memo or {}).items():
            if k in caps:
                caps[k] = max(caps[k], v)
        return caps

    def _retry(self, node, tag: str, run, caps: Dict, m):
        from ..parallel.autoretry import auto_retry_overflow
        attempts = [0]

        def attempt(**kw):
            attempts[0] += 1
            return run(**kw)

        out, final = auto_retry_overflow(attempt, caps,
                                         self.ex.max_cap_attempts)
        if m is not None:
            m.escalations += attempts[0] - 1
        self.ex._dist_caps_memo[self._memo_key(node, tag)] = \
            dict(final)
        return out

    # -- helpers -------------------------------------------------------------
    def lift(self, rel_or_table, part: frozenset = frozenset()):
        if isinstance(rel_or_table, ShardedRel):
            return rel_or_table
        return shard_table(self.mesh, self.axis, rel_or_table, part=part)

    def localize(self, rel_or_table) -> Table:
        rel_or_table = _resolve_rel(rel_or_table)
        if isinstance(rel_or_table, ShardedRel):
            return rel_or_table.to_local_table()
        return rel_or_table

    @staticmethod
    def _nbytes(table: Table) -> int:
        from ..runtime.admission import operand_nbytes
        return operand_nbytes(table)

    def _put(self, arr):
        return jax.device_put(arr, self.spec)

    def _default_cap(self, *padded_lens) -> int:
        per_shard = max(max(padded_lens, default=1) // self.n_peers, 1)
        return max(64, 2 * per_shard)

    # -- node dispatch -------------------------------------------------------
    def exec_node(self, node, childs, inputs, schemas, m, metrics):
        """Execute one node: distributed when it has a form and its
        children allow it, local otherwise (gathering sharded children —
        the graceful boundary). Returns a ShardedRel, a PendingRel (async
        exchange in flight), or a Table. In-flight child exchanges
        resolve HERE — the consumer boundary is where the async overlap
        window closes."""
        childs = [_resolve_rel(c) for c in childs]
        out = self._try_dist(node, childs, inputs, schemas, m, metrics)
        if out is None:
            local = [self.localize(c) for c in childs]
            out = self.ex._exec_eager_node(node, local, inputs, schemas, m)
        if isinstance(out, (ShardedRel, PendingRel)):
            m.sharding = out.sharding_str(self.n_peers)
            m.n_peers = self.n_peers
        elif any(isinstance(c, ShardedRel) for c in childs):
            m.sharding = "local"
        return out

    def _try_dist(self, node, childs, inputs, schemas, m, metrics):
        try:
            if isinstance(node, Scan):
                return self._dist_scan(node, inputs, m)
            if isinstance(node, Filter):
                return self._dist_filter(node, childs)
            if isinstance(node, (Project, FusedSelect)):
                return self._dist_project(node, childs)
            if isinstance(node, Exchange):
                return self._dist_exchange(node, childs, m)
            if isinstance(node, HashJoin):
                return self._dist_join(node, childs, m, metrics)
            if isinstance(node, HashAggregate):
                return self._dist_aggregate(node, childs, schemas, m,
                                            metrics)
            if isinstance(node, (Sort, TopK)):
                return self._dist_sort(node, childs, m)
            if isinstance(node, Union):
                return self._dist_union(node, childs)
        except NotImplementedError:
            return None
        return None        # Limit & anything else: no distributed form

    # -- scans ---------------------------------------------------------------
    def _dist_scan(self, node, inputs, m):
        t = inputs[node.source]
        if not isinstance(t, Table):
            # streaming source: one pruned+projected materialized read,
            # then shard — the distributed tier's morsel is the shard
            t = self.ex._materialize_scan(node, t, m)
        elif node.projection is not None:
            t = t.select(list(node.projection))
        if t.num_rows == 0 or not table_shardable(t):
            return None
        return self.lift(t)

    # -- row-wise ------------------------------------------------------------
    def _dist_filter(self, node, childs):
        (c,) = childs
        if not isinstance(c, ShardedRel) or c.replicated:
            return None
        mask = node.predicate.evaluate(c.table, c.valid)
        return ShardedRel(c.table, c.valid & mask, part=c.part,
                          order_keys=c.order_keys)

    def _dist_project(self, node, childs):
        from .executor import _col_from_array
        from .expr import ColumnRef
        (c,) = childs
        if not isinstance(c, ShardedRel) or c.replicated:
            return None
        valid = c.valid
        if isinstance(node, FusedSelect):
            mask = node.predicate.evaluate(c.table, valid)
            valid = valid & mask
        cols = []
        for name, e in node.exprs:
            if isinstance(e, ColumnRef):
                cols.append(c.table[e.name])
            else:
                v = e.evaluate(c.table, valid)
                if getattr(v, "ndim", 1) == 0:
                    v = jnp.broadcast_to(v, (c.table.num_rows,))
                cols.append(_col_from_array(v))
        part = transfer_part(node, [c.part])
        order = None
        if c.order_keys:
            renames = {e.name: nm for nm, e in node.exprs
                       if isinstance(e, ColumnRef)}
            if all(k in renames for k in c.order_keys):
                order = [renames[k] for k in c.order_keys]
        return ShardedRel(Table(cols, names=[n for n, _ in node.exprs]),
                          valid, part=part, order_keys=order)

    # -- exchanges -----------------------------------------------------------
    def _dist_exchange(self, node, childs, m):
        (c,) = childs
        if not isinstance(c, ShardedRel):
            if node.how == "broadcast" and isinstance(c, Table) and \
                    table_shardable(c) and c.num_rows:
                # a locally-computed small build side can still feed a
                # distributed broadcast join: replicate it directly
                if self.async_on:
                    return PendingRel(
                        lambda: self._replicate_local(c, m), m,
                        self._nbytes, replicated=True)
                return self._replicate_local(c, m)
            return None       # single-chip semantics: Exchange is a no-op
        if node.how == "identity":
            return c
        if node.how == "gather":
            return self._gather(c, m)
        if node.how == "broadcast":
            if c.replicated:
                return c
            if self.async_on:
                return PendingRel(lambda: self._broadcast(c, m), m,
                                  self._nbytes, replicated=True)
            return self._broadcast(c, m)
        if id(node) in self.fused_exchanges:
            return c          # defers into the aggregate above (fusion)
        if self.async_on:
            # the has-a-distributed-form checks must fail HERE,
            # synchronously: a NotImplementedError raised on the worker
            # thread would surface at the consumer, outside _try_dist's
            # graceful local-fallback net
            if _key_specs(c.table, list(node.keys)) is None or \
                    not table_shardable(c.table):
                raise NotImplementedError
            return PendingRel(lambda: self._repartition(node, c, m), m,
                              self._nbytes,
                              part=frozenset({tuple(node.keys)}))
        return self._repartition(node, c, m)

    def _edge(self, m, how: str, logical: int, wire: int, codec: str,
              copies: int = 1):
        """Stamp one exchange edge's movement on a metric row: logical =
        unpacked per-column payload, wire = packed bytes actually shipped
        (== logical with packing off). Live payload only, each edge
        counted once; broadcast passes copies = n_peers-1."""
        m.exchange_how = how
        m.exchange_bytes_logical += logical * copies
        m.exchange_bytes += wire * copies
        if codec:
            m.exchange_codecs = (m.exchange_codecs + ";" + codec
                                 if m.exchange_codecs else codec)

    @staticmethod
    def _reset_edge(m):
        """A retried (or re-run) exchange attempt RE-DESCRIBES its edge:
        the metric must show the execution that produced the output, not
        a sum over failed attempts."""
        m.exchange_bytes = 0
        m.exchange_bytes_logical = 0
        m.exchange_codecs = ""

    def _gather(self, c: ShardedRel, m) -> Table:
        """The sink/boundary collect. Packed: static wire planes compute
        on the mesh, ONE narrow pull per plane crosses to host, and the
        receiving side decodes + compacts (plan/transport.py); the result
        caches on the rel like to_local_table so DAG-shared consumers
        gather once — a cache-served gather moves NOTHING and reports
        zero bytes (the first crossing carried the payload)."""
        self._reset_edge(m)
        if c._local is not None:
            m.exchange_how = "gather"
            return c._local
        live = c.num_rows
        cols = list(c.table.columns)
        logical = live * transport.logical_row_bytes(cols)
        if self.pack:
            t, wire_row, codec = self._gather_packed(c)
            self._edge(m, "gather", logical, live * wire_row, codec)
        else:
            t = c.to_local_table()
            self._edge(m, "gather", logical, logical, "")
        return t

    def _gather_packed(self, c: ShardedRel):
        names = list(c.table.names)
        dp = transport.pack_device(list(c.table.columns), names, c.valid,
                                   self.codecs)
        mask_plane, n = transport.pack_bits_device(c.valid)
        planes = [np.asarray(p) for p in dp.planes]
        mask = transport.unpack_bits_np(np.asarray(mask_plane), n)
        idx = np.nonzero(mask)[0]
        decoded = transport.unpack_device_np(planes, dp)
        cols = []
        for src, (data, validity) in zip(c.table.columns, decoded):
            v = None if validity is None else jnp.asarray(validity[idx])
            cols.append(dataclasses.replace(
                src, data=jnp.asarray(data[idx]), validity=v,
                length=int(idx.shape[0])))
        t = Table(cols, names=names)
        if c.order_keys:
            from .executor import _ops
            t = _ops().sort_table(t, key_names=list(c.order_keys),
                                  ascending=[True] * len(c.order_keys))
        c._local = t
        return t, dp.wire_row_bytes, dp.codec_str

    def _replicate_local(self, t: Table, m) -> ShardedRel:
        self._reset_edge(m)
        live = t.num_rows
        logical = live * transport.logical_row_bytes(t.columns)
        copies = self.n_peers - 1
        rep = self.rep_spec

        def put(a):
            return jax.device_put(a, rep)

        if self.pack:
            # host-materialized payload: the dynamic-size codecs
            # (dict/rle) apply here, and the decode runs on the lifted
            # (replicated) planes — unpack on the receiving shard
            hp = transport.pack_host(list(t.columns), list(t.names),
                                     self.codecs)
            cols = transport.unpack_host_device(hp, put)
            self._edge(m, "broadcast", logical, hp.wire_bytes,
                       hp.codec_str, copies=copies)
        else:
            cols = []
            for c in t.columns:
                validity = c.validity
                if validity is not None:
                    validity = put(validity)
                cols.append(dataclasses.replace(c, data=put(c.data),
                                                validity=validity))
            self._edge(m, "broadcast", logical, logical, "",
                       copies=copies)
        valid = put(jnp.ones((t.num_rows,), bool))
        return ShardedRel(Table(cols, names=list(t.names)), valid,
                          replicated=True)

    def _broadcast(self, c: ShardedRel, m) -> ShardedRel:
        if c.replicated:
            return c
        self._reset_edge(m)
        names = list(c.table.names)
        cols = list(c.table.columns)
        live = c.num_rows
        copies = self.n_peers - 1
        logical = live * transport.logical_row_bytes(cols)
        dp = layout = None
        if self.pack:
            dp = transport.pack_device(cols, names, c.valid, self.codecs)
            arrays = dp.planes
            wire = live * dp.wire_row_bytes
            codec = dp.codec_str
        else:
            arrays, layout = _pack_cols(c.table, names)
            wire, codec = logical, ""
        key = ("broadcast", self.mesh, self.axis, len(arrays) + 1)
        fn = _jitted(key, lambda: jax.jit(
            lambda *xs: xs, out_shardings=self.rep_spec))
        outs = fn(*arrays, c.valid)
        if dp is not None:
            out_cols = transport.unpack_device(outs[:-1], dp)
        else:
            out_cols = _unpack_cols(outs[:-1], layout)
        self._edge(m, "broadcast", logical, wire, codec, copies=copies)
        return ShardedRel(Table(out_cols, names=names),
                          outs[-1].astype(jnp.bool_), replicated=True)

    def _repartition(self, node, c: ShardedRel, m) -> ShardedRel:
        self._reset_edge(m)
        rel, logical, wire, codec = self._repartition_rel(
            node, c, list(node.keys), m, "repart")
        self._edge(m, "hash", logical, wire, codec)
        return rel

    def _repartition_rel(self, node, c: ShardedRel, keys, m, tag: str):
        """Hash-exchange a sharded relation by `keys`; returns
        (repartitioned rel, logical payload bytes, wire bytes, codec
        string). Key columns ride their 64-bit order-preserving word
        encoding — logically 8 B x total_words each; with packing on
        the shipped planes FOR-narrow (transport.narrow_words) and the
        collective body widens them back for the Spark-exact hash, so
        placement stays bit-identical while the wire shrinks. Value
        columns ship packed."""
        from ..parallel.relational import distributed_repartition_keyed
        specs = _key_specs(c.table, keys)
        if specs is None or not table_shardable(c.table):
            raise NotImplementedError
        words = _encode_keys(c.table, keys, specs)
        vnames = [nm for nm in c.table.names if nm not in set(keys)]
        val_cols = [c.table[nm] for nm in vnames]
        live = c.num_rows
        key_word_bytes = 8 * sum(sp.total_words for sp in specs)
        logical_row = key_word_bytes + transport.logical_row_bytes(val_cols)
        dp = layout = wplans = None
        word_codecs, refs = (), []
        if self.pack:
            dp = transport.pack_device(val_cols, vnames, c.valid,
                                       self.codecs)
            vals = dp.planes
            codec = dp.codec_str
            key_wire_bytes = key_word_bytes
            if "for" in self.codecs:
                words, wplans, key_wire_bytes, knote = \
                    transport.narrow_words(words, c.valid)
                if knote:
                    codec = ",".join(x for x in (codec, knote) if x)
                word_codecs = tuple(p.codec for p in wplans)
                # references ride as traced (1,) arrays so the compiled
                # program is reusable across executions (and the jit
                # cache keys on the static codec layout, not the data)
                refs = [jnp.full((1,), p.ref, jnp.int64)
                        for p in wplans if p.codec != "raw"]
            wire_row = key_wire_bytes + dp.wire_row_bytes
        else:
            vals, layout = _pack_cols(c.table, vnames)
            wire_row, codec = logical_row, ""

        nw, nv = len(words), len(vals)
        # the cached jitted callables must close over LOCALS only: a
        # `self` capture would pin the executor (and its plan/LRU graph)
        # in the process-global cache long after the session ends
        mesh, axis = self.mesh, self.axis

        def run(slack):
            key = ("repart", mesh, axis, tuple(specs), nw, nv, slack,
                   word_codecs)
            fn = _jitted(key, lambda: jax.jit(
                lambda *arrs: distributed_repartition_keyed(
                    mesh, list(arrs[:nw]), specs,
                    list(arrs[nw:nw + nv]), slack=slack, axis=axis,
                    alive=arrs[nw + nv],
                    word_codecs=word_codecs or None,
                    word_refs=list(arrs[nw + nv + 1:]) or None)))
            return fn(*words, *vals, c.valid, *refs)

        ws, vs, alive, _ = self._retry(
            node, tag, run, self._caps(node, tag, {"slack": self.slack}), m)
        alive = alive.astype(jnp.bool_)
        if wplans is not None:
            ws = transport.widen_words(list(ws), wplans)
        cols = dict(_decode_keys(ws, specs, keys, alive))
        if dp is not None:
            unpacked = transport.unpack_device(list(vs), dp)
        else:
            unpacked = _unpack_cols(vs, layout)
        cols.update({nm: col for nm, col in zip(vnames, unpacked)})
        table = Table([cols[nm] for nm in c.table.names],
                      names=list(c.table.names))
        return (ShardedRel(table, alive, part=frozenset({tuple(keys)})),
                live * logical_row, live * wire_row, codec)

    # -- joins ---------------------------------------------------------------
    def _dist_join(self, node, childs, m, metrics):
        from ..parallel.relational import distributed_colocated_join_keyed
        if node.how not in ("inner", "left_semi", "left_anti"):
            return None
        l, r = childs
        # lift a local side when the other is on the mesh (a broadcast
        # Exchange above a local child already replicated it)
        if not isinstance(l, ShardedRel) and not isinstance(r, ShardedRel):
            return None
        if not isinstance(l, ShardedRel):
            if not (isinstance(l, Table) and table_shardable(l)
                    and l.num_rows):
                return None
            l = self.lift(l)
        if not isinstance(r, ShardedRel):
            if not (isinstance(r, Table) and table_shardable(r)
                    and r.num_rows):
                return None
            r = self.lift(r)
        if l.replicated:
            return None     # probe side must be partitioned, not replicated
        if not (table_shardable(l.table) and table_shardable(r.table)):
            return None
        specs = _key_specs(l.table, node.left_keys, r.table, node.right_keys)
        if specs is None:
            return None

        lk, rk = list(node.left_keys), list(node.right_keys)
        inner = node.how == "inner"
        l_moved = False
        # align the sides: already-aligned parts (explicit exchanges ran,
        # or upstream operators preserved a suitable partitioning) join
        # co-located; a replicated right side probes locally; anything
        # else repartitions implicitly here (bytes on this node's metric)
        if not r.replicated and \
                not join_aligned(l.part, r.part, lk, rk):
            # a fault-retried attempt re-describes its implicit edges
            self._reset_edge(m)
            if tuple(lk) not in l.part:
                l, lg, lwb, lc = self._repartition_rel(node, l, lk, m,
                                                       "repart_l")
                self._edge(m, "hash", lg, lwb, lc)
                l_moved = True
            if tuple(rk) not in r.part:
                r, rg, rwb, rc = self._repartition_rel(node, r, rk, m,
                                                       "repart_r")
                self._edge(m, "hash", rg, rwb, rc)
        # the output's placement claim must name the tuples the rows are
        # ACTUALLY placed by — the aligned permutation, not the join-key
        # order (hash(b,a) placement claimed as (a,b) would let a
        # downstream consumer elide a required exchange)
        aligned = (None if r.replicated
                   else join_alignment(l.part, r.part, lk, rk))

        l_words = _encode_keys(l.table, lk, specs)
        r_words = _encode_keys(r.table, rk, specs)
        lvnames = [nm for nm in l.table.names if nm not in set(lk)]
        lvals, l_layout = _pack_cols(l.table, lvnames)
        if inner:
            rvnames = [nm for nm in r.table.names if nm not in set(rk)]
            rvals, r_layout = _pack_cols(r.table, rvnames)
        else:
            rvnames, rvals, r_layout = [], [], []

        nlw, nlv, nrv = len(l_words), len(lvals), len(rvals)

        rrep = r.replicated
        mesh, axis, how = self.mesh, self.axis, node.how  # no self capture

        def run(row_cap):
            key = ("cojoin", mesh, axis, tuple(specs), how,
                   nlw, nlv, nrv, rrep, row_cap)
            fn = _jitted(key, lambda: jax.jit(
                lambda *arrs: distributed_colocated_join_keyed(
                    mesh, list(arrs[:nlw]),
                    list(arrs[nlw:nlw + nlv]),
                    list(arrs[nlw + nlv:2 * nlw + nlv]),
                    list(arrs[2 * nlw + nlv:2 * nlw + nlv + nrv]),
                    specs, row_cap=row_cap, axis=axis, how=how,
                    lalive=arrs[-2], ralive=arrs[-1],
                    r_replicated=rrep)))
            return fn(*l_words, *lvals, *r_words, *rvals, l.valid, r.valid)

        if inner:
            cap0 = self._default_cap(l.padded_rows, r.padded_rows
                                     * (self.n_peers if r.replicated else 1))
            out = self._retry(node, "join", run,
                              self._caps(node, "join", {"row_cap": cap0}), m)
            ws, lvs, rvs, live, _ = out
        else:
            ws, lvs, live, _ = run(row_cap=0)
        live = live.astype(jnp.bool_)
        cols = dict(_decode_keys(ws, specs, lk, live))
        cols.update({nm: col for nm, col
                     in zip(lvnames, _unpack_cols(lvs, l_layout))})
        names = list(l.table.names)
        if inner:
            # right key columns equal the left keys on every matched row
            for nm, sp, lkey in zip(rk, specs, lk):
                rc = r.table[nm]
                cols[nm] = dataclasses.replace(
                    cols[lkey], dtype=rc.dtype,
                    data=cols[lkey].data.astype(rc.dtype.storage_dtype()))
            cols.update({nm: col for nm, col
                         in zip(rvnames, _unpack_cols(rvs, r_layout))})
            names = names + list(r.table.names)
        if r.replicated:
            part = l.part              # probe side never moved
        elif aligned is None:
            part = frozenset()         # defensive: repartition guarantees
            #                            an identity-permutation alignment
        elif inner:
            part = frozenset(aligned)
        else:
            part = frozenset({aligned[0]})   # left columns only survive
        # a broadcast semi/anti never moves the left rows, so the left
        # relation's gather-order contract survives; everything else
        # (inner emission, shuffled placement) re-orders
        order = l.order_keys if (not inner and r.replicated
                                 and not l_moved) else None
        return ShardedRel(Table([cols[nm] for nm in names], names=names),
                          live, part=part, order_keys=order)

    # -- aggregates ----------------------------------------------------------
    def _dist_aggregate(self, node, childs, schemas, m, metrics):
        from ..parallel.relational import (distributed_groupby_keyed,
                                           distributed_local_groupby)
        (c,) = childs
        fused_child = (isinstance(node.child, Exchange)
                       and id(node.child) in self.fused_exchanges)
        if not isinstance(c, ShardedRel) or c.replicated:
            return None
        if not node.keys:
            return None       # global aggregate: gather boundary
        if any(o not in _DIST_AGGS for _, o, _ in node.aggs):
            return None
        specs = _key_specs(c.table, node.keys)
        if specs is None:
            return None
        val_names, agg_pairs = [], []
        for cn, o, _ in node.aggs:
            if o == "size":
                agg_pairs.append((0, "count"))
                continue
            col = c.table[cn]
            if col.validity is not None or not (col.dtype.is_integer or
                                                col.dtype.kind ==
                                                dtypes.Kind.BOOL):
                return None   # exact int64 accumulation only
            if cn not in val_names:
                val_names.append(cn)
            agg_pairs.append((val_names.index(cn),
                              "count" if o == "count" else o))
        words = _encode_keys(c.table, list(node.keys), specs)
        vals = [c.table[v].data for v in val_names]
        key_cap0 = node.key_cap or self.ex.caps.get("key_cap") or \
            self._default_cap(c.padded_rows)
        elide = (not fused_child) and part_satisfies(c.part, node.keys)
        nbytes = [0]
        live_in = c.num_rows

        nw, nv = len(words), len(vals)
        mesh, axis, n_peers = self.mesh, self.axis, self.n_peers

        def run(key_cap):
            if elide:
                key = ("lgroup", mesh, axis, tuple(specs),
                       nw, nv, tuple(agg_pairs), key_cap)
                fn = _jitted(key, lambda: jax.jit(
                    lambda *arrs: distributed_local_groupby(
                        mesh, list(arrs[:nw]),
                        list(arrs[nw:-1]), list(agg_pairs),
                        key_cap=key_cap, axis=axis, alive=arrs[-1])))
            else:
                key = ("group", mesh, axis, tuple(specs),
                       nw, nv, tuple(agg_pairs), key_cap)
                fn = _jitted(key, lambda: jax.jit(
                    lambda *arrs: distributed_groupby_keyed(
                        mesh, list(arrs[:nw]), specs,
                        list(arrs[nw:-1]), list(agg_pairs),
                        key_cap=key_cap, axis=axis, alive=arrs[-1])))
                # the all-to-all ships per-group PARTIALS, not rows: one
                # int64 per key word and per agg partial, for at most
                # min(live input rows, key_cap per shard) groups — the
                # payload, counted once (bucket padding/slack excluded,
                # like every other edge)
                nbytes[0] = (8 * (nw + len(agg_pairs))
                             * min(live_in, n_peers * key_cap))
            return fn(*words, *vals, c.valid)

        gws, outs, gvalid, _ = self._retry(
            node, "group", run,
            self._caps(node, "group", {"key_cap": key_cap0}), m)
        gvalid = gvalid.astype(jnp.bool_)
        if not elide:
            # the fused program's all-to-all ships per-group partials; the
            # bytes belong to the exchange BOUNDARY — the child Exchange
            # node when the optimizer placed one, this node otherwise.
            # Partials are 64-bit exact accumulators: no packing applies,
            # wire == logical on this edge
            tgt = m
            if fused_child and node.child.label in metrics:
                tgt = metrics[node.child.label]
            # re-describe on a fault-retried aggregate attempt (the
            # fused Exchange's own execution deferred, so the child row
            # carries only this attribution)
            self._reset_edge(tgt)
            tgt.exchange_how = "hash"
            tgt.exchange_bytes = nbytes[0]
            tgt.exchange_bytes_logical = nbytes[0]
        from ..ops.aggregate import _agg_value_dtype
        cols = dict(_decode_keys(gws, specs, list(node.keys), gvalid))
        for (i, op), arr, (cn, o, out_name) in zip(agg_pairs, outs,
                                                   node.aggs):
            dt = _agg_value_dtype(o, c.table[cn].dtype
                                  if o != "size" else dtypes.INT64)
            cols[out_name] = Column(dtype=dt, length=int(arr.shape[0]),
                                    data=arr.astype(dt.storage_dtype()))
        names = schemas[id(node)]
        # truthful placement per the path that RAN: the elided local
        # merge left rows at the child's satisfying subset claims; the
        # fused two-phase program re-placed groups by the hash of the
        # full key tuple (so any child claim — including one riding
        # through a deferred fused Exchange — is stale here)
        if elide:
            keyset = set(node.keys)
            part = frozenset(p for p in c.part if set(p) <= keyset)
        else:
            part = frozenset({tuple(node.keys)})
        return ShardedRel(Table([cols[nm] for nm in names],
                                names=list(names)),
                          gvalid, part=part, order_keys=list(node.keys))

    # -- sort / topk ---------------------------------------------------------
    def _dist_sort(self, node, childs, m):
        from ..parallel.relational import distributed_sort_keyed
        (c,) = childs
        if not isinstance(c, ShardedRel) or c.replicated:
            return None
        if not table_shardable(c.table):
            return None
        specs = _key_specs(c.table, node.keys)
        if specs is None:
            return None
        keys = list(node.keys)
        words = []
        for k, sp, asc in zip(keys, specs, node.ascending):
            w, _ = encode_key_column(c.table[k], spec=sp)
            if not asc:
                # bitwise NOT reverses signed int64 order word-wise, and
                # word-wise reversal reverses the tuple's lexicographic
                # order — a descending key costs one elementwise op
                w = [~x for x in w]
            words.extend(w)
        vnames = [nm for nm in c.table.names if nm not in set(keys)]
        val_cols = [c.table[nm] for nm in vnames]
        live = c.num_rows
        key_word_bytes = 8 * sum(sp.total_words for sp in specs)
        logical_row = key_word_bytes + transport.logical_row_bytes(val_cols)
        dp = layout = None
        if self.pack:
            dp = transport.pack_device(val_cols, vnames, c.valid,
                                       self.codecs)
            vals = dp.planes
            wire_row = key_word_bytes + dp.wire_row_bytes
            codec = dp.codec_str
        else:
            vals, layout = _pack_cols(c.table, vnames)
            wire_row, codec = logical_row, ""
        nw, nv = len(words), len(vals)
        mesh, axis = self.mesh, self.axis

        def run(slack):
            key = ("sort", mesh, axis, tuple(specs),
                   tuple(node.ascending), nw, nv, slack)
            fn = _jitted(key, lambda: jax.jit(
                lambda *arrs: distributed_sort_keyed(
                    mesh, list(arrs[:nw]), None, list(arrs[nw:-1]),
                    slack=slack, axis=axis, alive=arrs[-1])))
            return fn(*words, *vals, c.valid)

        ws, vs, valid, _ = self._retry(
            node, "sort", run, self._caps(node, "sort",
                                          {"slack": self.slack}), m)
        valid = valid.astype(jnp.bool_)
        # each live row crosses the range partition once; splitter
        # samples/pool are metadata (uncounted, like bucket counts). A
        # fault-retried attempt re-describes the edge, not accumulates
        self._reset_edge(m)
        self._edge(m, "range", live * logical_row, live * wire_row, codec)
        # un-invert descending words before decode
        i = 0
        dec_words = []
        for sp, asc in zip(specs, node.ascending):
            tw = list(ws[i:i + sp.total_words])
            if not asc:
                tw = [~x for x in tw]
            dec_words.extend(tw)
            i += sp.total_words
        cols = dict(_decode_keys(dec_words, specs, keys, valid))
        if nv:
            if dp is not None:
                unpacked = transport.unpack_device(list(vs), dp)
            else:
                unpacked = _unpack_cols(list(vs), layout)
            cols.update({nm: col for nm, col in zip(vnames, unpacked)})
        table = Table([cols[nm] for nm in c.table.names],
                      names=list(c.table.names))
        if isinstance(node, TopK):
            # global rank mask: the live slots in logical order ARE the
            # globally sorted run (shard 0 holds the smallest keys), so
            # the first-n filter is a sharded prefix count — on device,
            # GSPMD turns the logical cumsum into the cross-shard scan
            valid = valid & (jnp.cumsum(valid.astype(jnp.int32)) <= node.n)
        return ShardedRel(table, valid)

    # -- union ---------------------------------------------------------------
    def _dist_union(self, node, childs):
        if not all(isinstance(c, ShardedRel) and not c.replicated
                   for c in childs):
            return None
        names = list(childs[0].table.names)
        k = len(childs)
        key = ("concat", self.mesh, self.axis, k)
        fn = _jitted(key, lambda: jax.jit(
            lambda *xs: jnp.concatenate(xs), out_shardings=self.spec))
        cols = []
        for i, nm in enumerate(names):
            parts = [c.table.columns[i] for c in childs]
            data = fn(*[p.data for p in parts])
            validity = None
            if any(p.validity is not None for p in parts):
                validity = fn(*[p.null_mask for p in parts])
            cols.append(dataclasses.replace(parts[0], data=data,
                                            validity=validity,
                                            length=int(data.shape[0])))
        valid = fn(*[c.valid for c in childs])
        return ShardedRel(Table(cols, names=names), valid)
