"""Physical-plan subsystem: the declarative operator layer between the
plugin-facing API and the `ops`/`parallel` kernel tiers.

The reference stack receives *plans* from Spark's Catalyst optimizer and
lowers them operator-by-operator onto libcudf ("Accelerating Presto with
GPUs" makes the same argument for a declarative operator layer above native
kernels; StreamBox-HBM uses per-operator pipelines as the unit of memory
arbitration — PAPERS.md). Before this subsystem every NDS query hand-wired
operator sequencing, cap management and retry; now a query is a `Plan` — a
DAG of typed operator nodes over `columnar.Table` — and the engine-side
concerns live in ONE executor:

- `nodes` / `expr`: the operator set (Scan, Filter, Project, HashJoin,
  HashAggregate, Sort, Exchange, Limit, Union) and the expression
  mini-language predicates/projections are written in.
- `builder`: fluent, validating construction (`PlanBuilder`); schema and
  reference errors surface at build time as `PlanValidationError`.
- `optimizer`: Catalyst-style rule pipeline (column pruning, predicate/
  limit pushdown, constant folding, Filter+Project fusion into
  `FusedSelect`, Sort+Limit fusion into `TopK`, join build-side
  selection) run to fixpoint inside `execute()` before tier dispatch,
  plus the canonical `plan_fingerprint` the executor keys its compiled-
  program and caps memos by (docs/optimizer.md).
- `executor`: walks the DAG composing the public `ops` kernels (eager tier)
  or traces the whole plan into ONE capped XLA program (jit tier) with
  geometric cap escalation via `parallel.autoretry` at plan granularity;
  with a device mesh the eager walk runs full-plan SPMD over sharded
  relations (`distributed`, docs/distributed.md) — shuffle/broadcast
  joins, fused two-phase aggregates, sample-sort — crossing the ICI only
  at the `Exchange` boundaries the optimizer plans, and gathering to one
  device only at the sink;
  admission (`runtime.admission`), `faultinj` interception and
  `utils.tracing` ranges apply per operator. Device failures resolve
  through the `runtime.health` degradation policy — backoff-paced retries
  for transient faults, circuit-breaker trip + degraded CPU-tier
  completion for sticky/fatal ones (docs/robustness.md).
- `metrics`: `explain()` (pre-run plan tree) and `profile()` (post-run
  per-operator rows/bytes/wall-time/retry counts).

Build-time validation, execute()'s bind-time re-resolution, and the
debug-mode pre-execution gate (`SPARK_RAPIDS_TPU_VERIFY_PLANS`) all
route through the static plan verifier (`spark_rapids_tpu.analysis`,
docs/analysis.md) — one error vocabulary of invariant codes naming the
offending operator, from the builder to the optimizer's fall-back
diagnostics.

See docs/plan.md for the operator contract and how a JVM/plugin front-end
targets this layer.
"""
from .expr import col, lit, scalar_max, scalar_min, scalar_sum, Expr
from .nodes import (Exchange, Filter, FusedSelect, HashAggregate, HashJoin,
                    Limit, PlanNode, Project, Scan, Sort, TopK, Union)
from .builder import Plan, PlanBuilder, PlanValidationError
from .executor import PlanExecutor, PlanResult
from .metrics import OperatorMetrics
from .optimizer import (OptimizeReport, optimize, plan_fingerprint,
                        subtree_fingerprints)
from .stats import StatsStore, active_store, scoped_store

__all__ = [
    "col", "lit", "scalar_max", "scalar_min", "scalar_sum", "Expr",
    "Scan", "Filter", "Project", "FusedSelect", "HashJoin",
    "HashAggregate", "Sort", "TopK", "Exchange", "Limit", "Union",
    "PlanNode",
    "Plan", "PlanBuilder", "PlanValidationError",
    "PlanExecutor", "PlanResult", "OperatorMetrics",
    "optimize", "plan_fingerprint", "subtree_fingerprints",
    "OptimizeReport",
    "StatsStore", "active_store", "scoped_store",
]
