"""Per-fingerprint operator-stats store: the engine's feedback loop.

Every successful execution already produces a rich stream — per-op
rows/bytes/wall (`OperatorMetrics`), escalated capacities
(`PlanResult.caps`), streaming-scan decode throughput, and the kernel
registry's per-dispatch choices — that used to be stamped on the result
and dropped. This module keeps it: a bounded, **backend-keyed** store of
what each plan fingerprint actually did, consulted on the next execution
of the same (or a structurally overlapping) plan by three consumers
(docs/adaptive.md):

1. **optimizer** (`plan/optimizer.py`): `_Estimator` resolves INTERIOR
   nodes' row estimates from the store's *observed* subtree
   cardinalities before falling back to the static selectivity guesses
   (at scans, a bound table's exact size always wins; observed and
   `est_rows` hints fill in only for unbound scans) — join build-side
   selection and `exchange_planning`'s shuffle-vs-broadcast choice
   become observation-driven on warm fingerprints, with the decision
   source recorded per rule firing on `OptimizeReport`;
2. **executor** (`plan/executor.py`): the capped tier seeds its initial
   capacities from the observed high-water caps, so a repeat fingerprint
   compiles once instead of re-climbing the geometric escalation ladder
   (the per-executor caps memo, promoted across executor instances and —
   with `SPARK_RAPIDS_TPU_STATS_PATH` — across processes); the eager
   streaming tier sizes its morsels from observed decode throughput;
3. **kernel registry** (`ops/registry.py`): `select()` demotes a kernel
   that has benched slower than its fallback on this (op, backend,
   signature) shape, recording the demotion on `KernelChoice`.

Adaptivity may change HOW a plan executes, never WHAT it returns: every
consumer feeds decisions the engine already guards for semantic
neutrality (build-side swaps re-verify through `verify_rewrite`, caps
are starting capacities the overflow ladder would have grown anyway,
chunking is merge-exact, kernels are parity-gated), and the fuzzer's
two-run check (`analysis/fuzz.py`) plus the nightly adaptive gate
(`benchmarks/adaptive_bench.py`) hold that line bit-exactly.

Backend isolation is a correctness rule, not bookkeeping: a degraded
(breaker-tripped) plan finishes on the CPU tier, and its stats record
under ``backend="cpu"`` — they must never seed device-side caps or
demote device kernels. Every table in the store is therefore keyed by
backend first, and the executor passes the backend the result actually
ran on.

Knobs (config.py): ``SPARK_RAPIDS_TPU_STATS`` (on/off — off restores
byte-identical static behavior), ``SPARK_RAPIDS_TPU_STATS_CAPACITY``
(LRU bound), ``SPARK_RAPIDS_TPU_STATS_PATH`` (optional JSONL
persistence). Tests and benches install an explicit store with
`scoped_store(...)`, which outranks the knob family.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import threading
from typing import Dict, Optional, Tuple

from ..utils.lru import LruDict

__all__ = ["StatsStore", "active_store", "default_store",
           "reset_default_store", "scoped_store"]

# morsel sizing (eager streaming tier): aim each decoded chunk at this
# much host decode wall — big enough to amortize per-chunk dispatch,
# small enough to keep the prefetch double-buffer working set bounded
_TARGET_CHUNK_MS = 25.0
_MIN_CHUNK_ROWS = 4096
# kernel tie-break hysteresis: a kernel must bench this much slower than
# its fallback (per row) before it loses the pick — noise must not flap
# the selection (and with it the capped tier's compiled-program cache)
_DEMOTE_MARGIN = 1.25
_EWMA_ALPHA = 0.5


def _ewma(old: Optional[float], new: float) -> float:
    return new if old is None else (1 - _EWMA_ALPHA) * old + _EWMA_ALPHA * new


class StatsStore:
    """Bounded feedback store. All tables key on backend first:

    - plans:    (backend, source fingerprint) -> {executed_fp, runs,
                caps{cap key: high-water}, peak_bytes (high-water
                observed live bytes — serving admission's warm charge),
                ops{toposort idx: row}}
    - subtrees: (backend, subtree fingerprint) -> {rows (high-water),
                runs} — observed output cardinality of that exact
                operator subtree, the optimizer's estimate override
    - walls:    (backend, subtree fingerprint) -> {wall_ms (EWMA of the
                CUMULATIVE subtree wall — the node plus every
                descendant), runs} — the placement rule's warm input:
                host-vs-device wall for the same subtree shape. Kept
                separate from `subtrees` because a host-placed op inside
                a device result files its wall under "cpu" (that is
                where it ran) while its cardinality is
                backend-independent
    - io:       (backend, scan subtree fingerprint) -> {rows_per_ms
                (EWMA), runs} — streaming-scan decode throughput
    - kernels:  (backend, op, signature repr) -> {kernel name:
                {ms_per_krow (EWMA), runs}} — the registry tie-break

    `generation` bumps on every record (the executor's rewrite cache
    keys on it — a cached rewrite must not outlive the observations it
    ignored); `kernel_epoch` bumps only when a recorded timing flips a
    DEMOTION VERDICT for some signature (the capped tier's jit cache
    keys on it, so compiled programs stay shared across runs whose
    kernel picks cannot have changed). `hits` counts successful
    consults — the bench JSONL `stats_hits` stamp.

    Constructor: `capacity`/`path` default from the config knobs. Pass
    `path=""` to force a store in-memory-only regardless of
    SPARK_RAPIDS_TPU_STATS_PATH — every *fresh isolated* store (the
    fuzzer's per-case stores, the adaptive bench, tests) must, or an
    operator's persisted stats would silently pre-warm a run that
    documents itself as cold and pollute the persisted file with
    throwaway plans.
    """

    _uids = itertools.count()

    def __init__(self, capacity: Optional[int] = None,
                 path: Optional[str] = None):
        from .. import config
        # process-unique, never-reused identity for executor cache keys
        # (id() can be recycled after GC — a stale compiled program must
        # not alias a new store that landed on the same address)
        self.uid = next(StatsStore._uids)
        self.capacity = (config.stats_capacity() if capacity is None
                         else max(1, int(capacity)))
        self.path = (config.stats_path() or None) if path is None else \
            (path or None)
        self._plans: Dict[Tuple, Dict] = LruDict(self.capacity)
        self._subtrees: Dict[Tuple, Dict] = LruDict(self.capacity * 16)
        self._walls: Dict[Tuple, Dict] = LruDict(self.capacity * 16)
        self._io: Dict[Tuple, Dict] = LruDict(self.capacity * 4)
        self._kernels: Dict[Tuple, Dict] = LruDict(self.capacity * 16)
        self.generation = 0
        self.kernel_epoch = 0
        self.hits = 0
        self._lock = threading.RLock()
        # persistence appends serialize separately from the table lock:
        # two sessions recording concurrently must not interleave half a
        # JSONL line each (replay tolerates torn lines, but silently
        # dropping both records is not "best-effort", it is data loss),
        # and file IO must not extend the hot lock's hold time
        self._io_lock = threading.Lock()
        if self.path:
            self._load(self.path)

    # ---- recording ---------------------------------------------------------

    def record_result(self, plan, result, *, backend: str,
                      source_fp: Optional[str] = None) -> None:
        """Record one successful execution. `plan` is the EXECUTED plan
        (the optimized form when the optimizer ran — metric labels refer
        to its nodes); `source_fp` is the authored plan's fingerprint,
        under which the plan-level entry files (cold and warm executions
        of one authored plan share it even when a stats-driven rewrite
        changes the executed fingerprint). `backend` is the backend the
        result actually ran on — the executor passes "cpu" for degraded
        results, keeping salvage runs out of device-side decisions."""
        from .optimizer import subtree_fingerprints
        source_fp = source_fp or plan.fingerprint
        sub = subtree_fingerprints(plan.root)
        # observed peak live bytes: the widest node-plus-inputs frontier
        # the walk actually materialized — the serving layer's admission
        # charge for WARM fingerprints (ISSUE 16: certified cross-product
        # bounds overcharge; what the plan DID is the better sizer)
        peak = 0
        for node in plan.nodes:
            m = result.metrics.get(node.label)
            if m is None:
                continue
            tot = int(m.bytes_out) + sum(
                int(result.metrics[c.label].bytes_out)
                for c in node.children if c.label in result.metrics)
            peak = max(peak, tot)
        # cumulative subtree wall (node plus every descendant; a shared
        # child counts toward each referencing subtree, matching the
        # subtree-fingerprint definition) — None wherever any descendant
        # lacks a per-op wall (capped/SPMD tiers time the whole plan)
        swall: Dict[int, Optional[float]] = {}
        for node in plan.nodes:
            m = result.metrics.get(node.label)
            w = None if (m is None or m.wall_ms is None) \
                else float(m.wall_ms)
            if w is not None:
                for c in node.children:
                    cw = swall.get(id(c))
                    if cw is None:
                        w = None
                        break
                    w += cw
            swall[id(node)] = w
        event = {"backend": backend, "source_fp": source_fp,
                 "executed_fp": plan.fingerprint, "caps": {},
                 "peak_bytes": peak,
                 "ops": {}, "subtrees": {}, "subtree_walls": {},
                 "io": {}, "kernels": []}
        with self._lock:
            key = (backend, source_fp)
            ps = self._plans.get(key) or {
                "executed_fp": plan.fingerprint, "runs": 0, "caps": {},
                "peak_bytes": 0, "ops": {}}
            ps["runs"] += 1
            ps["executed_fp"] = plan.fingerprint
            ps["peak_bytes"] = max(int(ps.get("peak_bytes", 0)), peak)
            if (result.caps and result.mode == "capped"
                    and not result.degraded):
                # final (possibly escalated) capacities: high-water.
                # Degraded caps are skipped — they describe the failed
                # device attempts, not a completed sizing.
                for k, v in result.caps.items():
                    ps["caps"][k] = max(int(ps["caps"].get(k, 0)), int(v))
                event["caps"] = dict(ps["caps"])
            for i, node in enumerate(plan.nodes):
                m = result.metrics.get(node.label)
                if m is None:
                    continue
                ps["ops"][i] = event["ops"][i] = {
                    "rows_out": int(m.rows_out),
                    "bytes_out": int(m.bytes_out),
                    "wall_ms": m.wall_ms,
                    "kernel": m.kernel}
                sfp = sub[id(node)]
                e = self._subtrees.get((backend, sfp)) or \
                    {"rows": 0, "runs": 0}
                e["rows"] = max(int(e["rows"]), int(m.rows_out))
                e["runs"] += 1
                self._subtrees[(backend, sfp)] = e
                event["subtrees"][sfp] = e["rows"]
                w = swall.get(id(node))
                if w is not None and \
                        not (result.degraded and not m.degraded):
                    # a host-placed subtree inside a device result ran
                    # on CPU — its wall files under "cpu", the backend
                    # the time was actually spent on (the placement
                    # rule's warm comparison depends on this purity)
                    wb = "cpu" if m.placement == "host" else backend
                    we = self._walls.get((wb, sfp)) or \
                        {"wall_ms": None, "runs": 0}
                    we["wall_ms"] = _ewma(we["wall_ms"], w)
                    we["runs"] += 1
                    self._walls[(wb, sfp)] = we
                    event["subtree_walls"][sfp] = [wb, we["wall_ms"]]
                if result.degraded and not m.degraded:
                    # a partially-degraded plan: this op ran on the
                    # DEVICE before the breaker tripped. Its observed
                    # cardinality is backend-independent (recorded
                    # above), but its wall-derived kernel timing and
                    # decode rate are device measurements — filing them
                    # under "cpu" would let device numbers drive CPU
                    # tie-breaks and morsel sizing
                    continue
                if m.io_decode_ms > 0 and m.rows_out > 0:
                    rate = m.rows_out / m.io_decode_ms
                    ioe = self._io.get((backend, sfp)) or \
                        {"rows_per_ms": None, "runs": 0}
                    ioe["rows_per_ms"] = _ewma(ioe["rows_per_ms"], rate)
                    ioe["runs"] += 1
                    self._io[(backend, sfp)] = ioe
                    event["io"][sfp] = ioe["rows_per_ms"]
                ksig = getattr(m, "_kernel_sig", None)
                if ksig is not None and m.kernel and m.wall_ms:
                    op, sig = ksig
                    name = m.kernel.split(":", 1)[0]
                    per_krow = m.wall_ms / max(int(m.rows_in), 1) * 1e3
                    self._record_kernel_locked(backend, op, sig, name,
                                               per_krow)
                    event["kernels"].append(
                        [op, self._sig_key(sig), name, per_krow])
            self._plans[key] = ps
            self.generation += 1
        if self.path:
            self._append(event)

    @staticmethod
    def _sig_key(sig) -> str:
        return "" if sig is None else repr(sig)

    @staticmethod
    def _verdict_pairs(m: Dict) -> frozenset:
        """Every ordered (slower, faster) pair past the demotion margin —
        the complete set of `kernel_slower` verdicts this signature's
        timings can currently produce, whatever the fallback name."""
        return frozenset(
            (a, b) for a in m for b in m
            if a != b and m[a]["ms_per_krow"] is not None
            and m[b]["ms_per_krow"] is not None
            and m[a]["ms_per_krow"] > m[b]["ms_per_krow"] * _DEMOTE_MARGIN)

    def _record_kernel_locked(self, backend: str, op: str, sig, name: str,
                              ms_per_krow: float) -> None:
        key = (backend, op, self._sig_key(sig))
        m = self._kernels.get(key) or {}
        before = self._verdict_pairs(m)
        e = m.get(name) or {"ms_per_krow": None, "runs": 0}
        e["ms_per_krow"] = _ewma(e["ms_per_krow"], float(ms_per_krow))
        e["runs"] += 1
        m[name] = e
        if self._verdict_pairs(m) != before:
            # a demotion VERDICT a tie-break could observe flipped (an
            # EWMA drift crossing the margin counts even when the raw
            # ordering is unchanged): compiled programs keyed on the old
            # epoch must not serve new picks
            self.kernel_epoch += 1
        self._kernels[key] = m

    def record_kernel(self, backend: str, op: str, sig, name: str,
                      wall_ms: float, rows: int = 1000) -> None:
        """Public timing feed (benches, tests): `wall_ms` over `rows`
        rows normalizes to the store's ms-per-1k-rows basis."""
        with self._lock:
            self._record_kernel_locked(
                backend, op, sig, name, wall_ms / max(int(rows), 1) * 1e3)

    # ---- consults ----------------------------------------------------------

    def observed_rows(self, backend: str,
                      subtree_fp: str) -> Optional[Tuple[int, int]]:
        """(high-water rows, run count) observed for this exact operator
        subtree on this backend; None when never seen (cold start — the
        estimator falls back to bound sizes and hints)."""
        with self._lock:
            e = self._subtrees.get((backend, subtree_fp))
            if e is None:
                return None
            self.hits += 1
            return int(e["rows"]), int(e["runs"])

    def observed_wall(self, backend: str,
                      subtree_fp: str) -> Optional[Tuple[float, int]]:
        """(EWMA cumulative subtree wall ms, run count) observed for this
        exact operator subtree on this backend — the placement rule's
        warm decision input (docs/optimizer.md#placement): host wins a
        subtree when its "cpu" wall is at or below the device wall for
        the same fingerprint. None when never timed here (cold — the
        rule falls back to certified bytes)."""
        with self._lock:
            e = self._walls.get((backend, subtree_fp))
            if e is None or e["wall_ms"] is None:
                return None
            self.hits += 1
            return float(e["wall_ms"]), int(e["runs"])

    def observed_caps(self, backend: str, source_fp: str,
                      executed_fp: Optional[str] = None) -> Dict[str, int]:
        """Observed high-water capacities for this authored plan. When
        the executed fingerprint differs from the recorded one (a
        stats-driven rewrite changed the plan shape since), only the
        GLOBAL cap keys carry over — per-node `row_cap:<i>` entries are
        toposort-indexed into a plan that no longer exists."""
        with self._lock:
            ps = self._plans.get((backend, source_fp))
            if ps is None or not ps["caps"]:
                return {}
            caps = dict(ps["caps"])
            if executed_fp is not None and \
                    ps.get("executed_fp") != executed_fp:
                caps = {k: v for k, v in caps.items() if ":" not in k}
            if caps:
                self.hits += 1
            return caps

    def plan_runs(self, backend: str, source_fp: str) -> int:
        with self._lock:
            ps = self._plans.get((backend, source_fp))
            return 0 if ps is None else int(ps["runs"])

    def observed_peak_bytes(self, backend: str, source_fp: str
                            ) -> Optional[Tuple[int, int]]:
        """(high-water observed live bytes, run count) for this authored
        plan on this backend — the serving layer's warm-fingerprint
        admission charge (docs/serving.md#admission). None when the plan
        was never seen here or no run produced byte counts (admission
        falls back to the certified bound, then the flat default)."""
        with self._lock:
            ps = self._plans.get((backend, source_fp))
            if ps is None or not ps.get("peak_bytes"):
                return None
            self.hits += 1
            return int(ps["peak_bytes"]), int(ps["runs"])

    def forget_plan(self, source_fp: str) -> int:
        """Drop every backend's entry for this authored plan (the fleet
        invalidation bus: a source input's digest changed, so observed
        sizes may describe data that no longer exists). Subtree/io/kernel
        tables survive — they key on structural fingerprints that remain
        valid observations of whatever data they saw. Returns the number
        of entries dropped."""
        with self._lock:
            doomed = [k for k in list(self._plans.keys())
                      if k[1] == source_fp]
            for k in doomed:
                del self._plans[k]
            if doomed:
                self.generation += 1
            return len(doomed)

    # ---- fleet gossip (serving/fleet.py, docs/serving.md#fleet) ------------

    def export_plans(self, fps=None) -> list:
        """Snapshot the plan-level observations as gossip rows —
        `{backend, source_fp, executed_fp, runs, caps, peak_bytes}` per
        (backend, fingerprint) entry, restricted to `fps` when given.
        This is the warm-failover payload: caps and high-water bytes are
        what a rehomed fingerprint needs to compile once and charge
        observed bytes immediately; per-op rows stay home (toposort-
        indexed detail no remote consumer reads). Rows are copies — the
        receiver's merge must not alias this store's tables."""
        with self._lock:
            out = []
            for (backend, source_fp), ps in self._plans.items():
                if fps is not None and source_fp not in fps:
                    continue
                out.append({"backend": backend, "source_fp": source_fp,
                            "executed_fp": ps.get("executed_fp", ""),
                            "runs": int(ps.get("runs", 0)),
                            "caps": dict(ps.get("caps", {})),
                            "peak_bytes": int(ps.get("peak_bytes", 0))})
            return out

    def merge_plans(self, rows) -> int:
        """Merge gossip rows from a peer store: high-water everything
        (caps, peak_bytes, runs), so the merge is idempotent and
        order-independent — gossiping the same snapshot twice changes
        nothing, which lets the fleet re-gossip without bookkeeping.
        Returns the number of rows that changed anything; bumps
        `generation` once if any did (cached rewrites must not outlive
        observations they ignored, same rule as record_result)."""
        changed = 0
        with self._lock:
            for row in rows:
                try:
                    key = (row["backend"], row["source_fp"])
                    ps = self._plans.get(key)
                    if ps is None:
                        ps = {"executed_fp": row.get("executed_fp", ""),
                              "runs": 0, "caps": {}, "peak_bytes": 0,
                              "ops": {}}
                    before = (ps["runs"], ps["peak_bytes"],
                              dict(ps["caps"]))
                    ps["runs"] = max(int(ps["runs"]),
                                     int(row.get("runs", 0)))
                    ps["peak_bytes"] = max(int(ps["peak_bytes"]),
                                           int(row.get("peak_bytes", 0)))
                    for k, v in (row.get("caps") or {}).items():
                        ps["caps"][k] = max(int(ps["caps"].get(k, 0)),
                                            int(v))
                    if not ps.get("executed_fp"):
                        ps["executed_fp"] = row.get("executed_fp", "")
                    if (ps["runs"], ps["peak_bytes"], ps["caps"]) \
                            != before:
                        changed += 1
                    self._plans[key] = ps
                except (KeyError, TypeError, ValueError):
                    continue    # tolerate a torn/foreign row, like _load
            if changed:
                self.generation += 1
        return changed

    def hot_fingerprints(self, k: int) -> list:
        """The top-`k` source fingerprints by total observed runs across
        backends — the store-side HOT signal replication can fall back
        on when the router's own submission counter is cold (a respawned
        worker inherits gossiped runs, not router history)."""
        if k <= 0:
            return []
        with self._lock:
            runs: Dict[str, int] = {}
            for (_backend, source_fp), ps in sorted(self._plans.items()):
                runs[source_fp] = runs.get(source_fp, 0) + \
                    int(ps.get("runs", 0))
        # ties break on the fingerprint, not dict insertion order — the
        # hot set must be identical across stores holding the same rows
        return [fp for fp, _ in sorted(runs.items(),
                                       key=lambda kv: (-kv[1], kv[0]))[:k]]

    def op_stats(self, backend: str, source_fp: str) -> Dict[int, Dict]:
        """toposort index -> {rows_out, bytes_out, wall_ms, kernel} of
        the last recorded execution of this authored plan on `backend`.
        The per-op history the ROADMAP's CPU/TPU co-placement direction
        reads (observed per-op wall on BOTH backends — the store is
        backend-keyed — is exactly the placement-rule input); today's
        in-tree consumers are observability (tests, future profile
        surfaces), not decisions."""
        with self._lock:
            ps = self._plans.get((backend, source_fp))
            return {} if ps is None else {
                int(i): dict(v) for i, v in ps["ops"].items()}

    def suggest_chunk_rows(self, backend: str, scan_fp: str) -> int:
        """Morsel row bound from observed decode throughput: about
        `_TARGET_CHUNK_MS` of host decode per chunk. 0 = no suggestion
        (cold, or throughput too low to matter); callers treat 0 the
        same as an unset SPARK_RAPIDS_TPU_IO_CHUNK_ROWS."""
        with self._lock:
            e = self._io.get((backend, scan_fp))
            if e is None or not e["rows_per_ms"]:
                return 0
            self.hits += 1
            return max(_MIN_CHUNK_ROWS,
                       int(e["rows_per_ms"] * _TARGET_CHUNK_MS))

    def kernel_slower(self, backend: str, op: str, sig, name: str,
                      fallback_name: str
                      ) -> Optional[Tuple[float, float]]:
        """(candidate, fallback) observed ms-per-1k-rows when the
        candidate has benched slower than the fallback past the
        `_DEMOTE_MARGIN` hysteresis on this exact signature; None when
        either timing is missing or the candidate holds up. The registry
        turns a non-None verdict into a decline (docs/kernels.md)."""
        if sig is None:
            return None         # shape unknown: nothing to compare
        with self._lock:
            m = self._kernels.get((backend, op, self._sig_key(sig)))
            if not m or name not in m or fallback_name not in m:
                return None
            a = m[name]["ms_per_krow"]
            b = m[fallback_name]["ms_per_krow"]
            if a is None or b is None or a <= b * _DEMOTE_MARGIN:
                return None
            self.hits += 1
            return float(a), float(b)

    # ---- persistence (JSONL) -----------------------------------------------

    def _append(self, event: Dict) -> None:
        try:
            with self._io_lock, open(self.path, "a", encoding="utf-8") as f:
                f.write(json.dumps(event) + "\n")
        except OSError:
            pass                # persistence is best-effort observability

    def _load(self, path: str) -> None:
        try:
            with open(path, "r", encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            return
        # construction is single-threaded today, but the tables this
        # fills are the lock-protected shared state — the lint_hazards
        # lock-discipline rule (tools/lint_hazards.py) holds every
        # mutation site to the same standard, replay included
        with self._lock:
            self._load_locked(lines)

    def _load_locked(self, lines) -> None:
        for line in lines:
            try:
                ev = json.loads(line)
                backend = ev["backend"]
                key = (backend, ev["source_fp"])
                ps = self._plans.get(key) or {
                    "executed_fp": ev["executed_fp"], "runs": 0,
                    "caps": {}, "peak_bytes": 0, "ops": {}}
                ps["runs"] += 1
                ps["executed_fp"] = ev["executed_fp"]
                ps["peak_bytes"] = max(int(ps.get("peak_bytes", 0)),
                                       int(ev.get("peak_bytes") or 0))
                for k, v in (ev.get("caps") or {}).items():
                    ps["caps"][k] = max(int(ps["caps"].get(k, 0)), int(v))
                for i, v in (ev.get("ops") or {}).items():
                    ps["ops"][int(i)] = dict(v)
                self._plans[key] = ps
                for sfp, rows in (ev.get("subtrees") or {}).items():
                    e = self._subtrees.get((backend, sfp)) or \
                        {"rows": 0, "runs": 0}
                    e["rows"] = max(int(e["rows"]), int(rows))
                    e["runs"] += 1
                    self._subtrees[(backend, sfp)] = e
                for sfp, (wb, wall) in (ev.get("subtree_walls")
                                        or {}).items():
                    we = self._walls.get((wb, sfp)) or \
                        {"wall_ms": None, "runs": 0}
                    we["wall_ms"] = _ewma(we["wall_ms"], float(wall))
                    we["runs"] += 1
                    self._walls[(wb, sfp)] = we
                for sfp, rate in (ev.get("io") or {}).items():
                    ioe = self._io.get((backend, sfp)) or \
                        {"rows_per_ms": None, "runs": 0}
                    ioe["rows_per_ms"] = _ewma(ioe["rows_per_ms"],
                                               float(rate))
                    ioe["runs"] += 1
                    self._io[(backend, sfp)] = ioe
                for op, sig_key, name, per_krow in (ev.get("kernels")
                                                    or []):
                    m = self._kernels.get((backend, op, sig_key)) or {}
                    e = m.get(name) or {"ms_per_krow": None, "runs": 0}
                    e["ms_per_krow"] = _ewma(e["ms_per_krow"],
                                             float(per_krow))
                    e["runs"] += 1
                    m[name] = e
                    self._kernels[(backend, op, sig_key)] = m
                self.generation += 1
            except (KeyError, TypeError, ValueError):
                continue        # tolerate a torn/foreign line


# ---- process wiring ---------------------------------------------------------

_default_store: Optional[StatsStore] = None
# guards the singleton hand-off: without it two threads racing first use
# would construct two stores and BOTH replay the persistence file —
# double-counted EWMAs and a torn generation counter (the
# unguarded-module-global-mutation lint rule now machine-checks this)
_default_lock = threading.Lock()
# explicit-scope stack: tests/benches push a store (or None, to force
# adaptivity OFF regardless of the knob) — the top outranks the knob.
# THREAD-LOCAL, like runtime/admission's active_session: concurrent
# executors must not see (or pop) each other's scopes — one session's
# isolated test store leaking into another thread's production
# executions would defeat the isolation the scope exists for.
_scope = threading.local()


def _scope_stack() -> list:
    stack = getattr(_scope, "stack", None)
    if stack is None:
        stack = _scope.stack = []
    return stack


def default_store() -> StatsStore:
    """The process singleton (capacity/path snapshot from config at first
    construction; `reset_default_store` re-reads)."""
    global _default_store
    with _default_lock:
        if _default_store is None:
            _default_store = StatsStore()
        return _default_store


def reset_default_store() -> None:
    global _default_store
    with _default_lock:
        _default_store = None


def active_store() -> Optional[StatsStore]:
    """The store consumers consult/record through, or None when
    adaptivity is off: the innermost `scoped_store` of THIS thread wins
    (even a scoped None — an explicit off), then
    `SPARK_RAPIDS_TPU_STATS` gates the process default."""
    stack = _scope_stack()
    if stack:
        return stack[-1]
    from .. import config
    if not config.stats_enabled():
        return None
    return default_store()


@contextlib.contextmanager
def scoped_store(store: Optional[StatsStore]):
    """Install `store` as the active store for the dynamic extent on the
    CURRENT thread (None forces adaptivity off). Used by tests, the
    fuzzer's two-run parity check, and the nightly adaptive gate to
    isolate observations."""
    stack = _scope_stack()
    stack.append(store)
    try:
        yield store
    finally:
        stack.pop()
