"""Per-operator execution metrics — the plugin's GpuMetric slot.

The reference plugin hangs NVTX ranges and task metrics off every exec
node; here each executed operator records rows/bytes/wall-time and the two
recovery counters this engine's contracts produce: `retries` (faultinj /
device-assert recoveries, the RetryOOM analogue) and `escalations` (cap
growth attempts charged to the node whose capacity overflowed — the
SplitAndRetry analogue at plan granularity).

`profile()` on a PlanResult returns these rows; the executor additionally
brackets every operator with `utils.tracing.range_ctx("plan.<label>")`, so
the same names show up in the xplane/perfetto timeline when
SPARK_RAPIDS_TPU_TRACE=1.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class OperatorMetrics:
    label: str                 # node label, e.g. HashJoin#3
    kind: str                  # node kind, e.g. HashJoin
    describe: str = ""         # the node's parameter summary
    rows_in: int = 0           # live input rows (sum over children)
    rows_out: int = 0          # live output rows
    bytes_out: int = 0         # output buffer bytes (padded size in capped)
    wall_ms: Optional[float] = None   # per-op wall (eager tier only)
    retries: int = 0           # operator re-runs after injected/device faults
    escalations: int = 0       # cap-growth retries charged to this node
    backoff_ms: float = 0.0    # time spent backing off before retries
    degraded: bool = False     # ran on the degraded CPU tier (breaker open)
    # serving-session stamp (serving/scheduler.py, docs/serving.md): the
    # tenant session this operator executed for, "" outside the serving
    # layer — per-tenant accounting must never be inferred from thread
    # identity (dispatcher workers are multiplexed across sessions)
    session: str = ""
    # fleet worker stamp (serving/fleet.py): which executor worker ran
    # this operator, "" outside a fleet — multi-worker soaks attribute
    # per-op numbers to the worker that produced them
    worker_id: str = ""
    # kernel-registry choice for operators with registered alternatives
    # (ops/registry.py, docs/kernels.md): "pallas:fused_select",
    # "scan:groupby", "xla:topk", ... — trajectory numbers must never
    # silently compare kernel backends (same rule as the bench `backend`
    # stamp). Empty for operators with no registry dispatch.
    kernel: str = ""
    # streaming-scan IO metrics (Scan nodes bound to a parquet source;
    # docs/io.md). Decode wall is host-side bitstream decode; overlap is
    # the time decode of chunk N+1 ran concurrently with executing chunk N
    # (the prefetch pipeline's win — 0 with SPARK_RAPIDS_TPU_IO_PREFETCH=0).
    io_row_groups_total: int = 0
    io_row_groups_pruned: int = 0
    io_bytes_skipped: int = 0      # compressed chunk bytes never decoded
    io_decode_ms: float = 0.0
    io_overlap_ms: float = 0.0
    # distributed-tier metrics (docs/distributed.md). `sharding` is the
    # operator's OUTPUT distribution ("rows@4" row-sharded over 4 peers,
    # "hash[k]@4" hash-partitioned by k, "replicated@4", "local" gathered
    # to one device). `exchange_how` records the movement kind
    # (hash/broadcast/gather, plus "range" for the sample-sort's splitter
    # exchange inside Sort/TopK) — on Exchange nodes for planned
    # boundaries, on the operator itself for implicit movement (an
    # unplanned shuffle join's internal exchange, a Sort's range
    # partition). Byte accounting is per edge, each edge counted ONCE
    # (broadcast = payload x (n_peers-1)), live payload only — capacity
    # padding, slack, and exchange metadata (masks, bucket counts) are
    # excluded, matching the certifier's per-edge exchange model
    # (analysis/footprint.py): `exchange_bytes` is the WIRE form (packed
    # planes the edge actually ships; == logical with packing off) and
    # `exchange_bytes_logical` the unpacked per-column payload the edge
    # represents. `exchange_codecs` names the non-pass-through encodings
    # chosen (plan/transport.py); `exchange_overlap_ms` is the transfer
    # wall that ran concurrently with other plan work under async
    # dispatch (SPARK_RAPIDS_TPU_EXCHANGE_ASYNC).
    sharding: str = ""
    exchange_how: str = ""
    exchange_bytes: int = 0            # bytes on the wire (packed form)
    exchange_bytes_logical: int = 0    # unpacked payload bytes
    exchange_codecs: str = ""
    exchange_overlap_ms: float = 0.0
    n_peers: int = 0               # mesh size the operator ran over
    # co-placement metrics (plan/optimizer.py placement rule,
    # docs/optimizer.md#placement): `placement` is "host" when the
    # operator executed on a co-placement host worker thread (the
    # optimizer placed its subtree on CPU overlapped with device work),
    # "" for the device walk. `placement_overlap_ms` lands on the
    # CONSUMING operator at the join point: the host-subtree wall that
    # ran concurrently with device execution of the sibling side (0 when
    # the device side finished first and the join blocked).
    placement: str = ""
    placement_overlap_ms: float = 0.0

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        # both byte counters under explicit names: a JSONL consumer must
        # never have to know that `exchange_bytes` means the wire form
        d["exchange_bytes_wire"] = self.exchange_bytes
        return d


def render_profile(rows: List[OperatorMetrics],
                   plan_wall_ms: Optional[float] = None,
                   attempts: int = 1,
                   caps: Optional[Dict] = None,
                   degraded: bool = False,
                   breaker: Optional[Dict] = None,
                   optimizer: Optional[Dict] = None,
                   jit_cache_hits: int = 0,
                   cert=None) -> str:
    """Human-readable profile table (the `profile()` text form)."""
    out = []
    if plan_wall_ms is not None:
        caps_s = f" caps={caps}" if caps else ""
        hits_s = f", {jit_cache_hits} jit cache hit(s)" if jit_cache_hits \
            else ""
        out.append(f"plan: {plan_wall_ms:.3f} ms, "
                   f"{attempts} attempt(s){caps_s}{hits_s}")
    if cert is not None:
        # static resource certifier (analysis/footprint.py): the sound
        # hi-bounds this execution was admitted and cap-seeded under
        peak = ("unbounded" if cert.peak_bytes_hi is None
                else f"{cert.peak_bytes_hi} B")
        root_rows = ("unbounded" if cert.root.rows_hi is None
                     else str(cert.root.rows_hi))
        ub = (f", {len(cert.unbounded)} op(s) unbounded"
              if cert.unbounded else "")
        out.append(f"footprint: peak resident <= {peak} certified, "
                   f"root rows <= {root_rows}{ub}")
    if optimizer is not None:
        fired = optimizer.get("rules_fired") or {}
        pruned = optimizer.get("pruned_columns", 0)
        out.append(f"optimizer: rules_fired={fired or 'none'}"
                   + (f", pruned {pruned} column(s) "
                      f"(~{optimizer.get('pruned_bytes_est', 0)} B est)"
                      if pruned else "")
                   + f", fingerprint={optimizer.get('fingerprint', '')}")
        # adaptive-execution provenance (plan/stats.py, docs/adaptive.md):
        # where each build-side/exchange decision's cardinalities came
        # from — a warm (observed-driven) profile must never read like a
        # cold one
        sources = optimizer.get("decision_sources") or {}
        if sources:
            tag = (" [STATS REVERTED]"
                   if optimizer.get("stats_reverted") else "")
            for key, src in sorted(sources.items()):
                out.append(f"  decision {key}: {src}{tag}")
    if degraded:
        reason = (breaker or {}).get("reason")
        state = (breaker or {}).get("state", "open")
        out.append(f"DEGRADED: breaker {state}"
                   f"{f' ({reason})' if reason else ''}; "
                   "plan completed on the CPU tier")
    hdr = (f"{'operator':<28} {'rows_in':>10} {'rows_out':>10} "
           f"{'bytes_out':>12} {'wall_ms':>9} {'retry':>5} {'escal':>5} "
           f"{'backoff':>8} {'deg':>4}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for m in rows:
        wall = f"{m.wall_ms:.3f}" if m.wall_ms is not None else "-"
        out.append(f"{m.label:<28} {m.rows_in:>10} {m.rows_out:>10} "
                   f"{m.bytes_out:>12} {wall:>9} {m.retries:>5} "
                   f"{m.escalations:>5} {m.backoff_ms:>8.1f} "
                   f"{'yes' if m.degraded else '-':>4}")
        if m.kernel:
            out.append(f"  kernel: {m.kernel}")
        if m.io_row_groups_total:
            kept = m.io_row_groups_total - m.io_row_groups_pruned
            out.append(f"  io: row groups {kept}/{m.io_row_groups_total} "
                       f"({m.io_row_groups_pruned} pruned), "
                       f"{m.io_bytes_skipped} B skipped, "
                       f"decode {m.io_decode_ms:.3f} ms, "
                       f"overlap {m.io_overlap_ms:.3f} ms")
        if m.sharding or m.exchange_how:
            parts = []
            if m.sharding:
                parts.append(f"sharding {m.sharding}")
            if m.exchange_how:
                ex = (f"exchange {m.exchange_how} "
                      f"{m.exchange_bytes} B moved")
                if m.exchange_bytes_logical and \
                        m.exchange_bytes_logical != m.exchange_bytes:
                    ex += f" ({m.exchange_bytes_logical} B logical)"
                parts.append(ex)
            if m.exchange_codecs:
                parts.append(f"codecs {m.exchange_codecs}")
            if m.exchange_overlap_ms:
                parts.append(f"overlap {m.exchange_overlap_ms:.3f} ms")
            out.append(f"  dist: {', '.join(parts)}")
        if m.placement or m.placement_overlap_ms:
            parts = []
            if m.placement:
                parts.append(m.placement)
            if m.placement_overlap_ms:
                parts.append(f"overlap {m.placement_overlap_ms:.3f} ms")
            out.append(f"  placement: {', '.join(parts)}")
    return "\n".join(out)
