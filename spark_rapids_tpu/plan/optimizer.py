"""Rule-based plan optimizer: Catalyst-style logical rewrites before tier
dispatch.

The reference plugin receives plans AFTER Spark's Catalyst optimizer has
rewritten them; this engine's builder hands over plans exactly as authored.
On accelerators the dominant wins come from not moving or computing
unneeded columns and rows before any HBM byte is touched ("Accelerating
Presto with GPUs", "Do GPUs Really Need New Tabular File Formats?" —
PAPERS.md), so `PlanExecutor.execute()` runs this pipeline by default
(`SPARK_RAPIDS_TPU_OPTIMIZER=off`, or `PlanExecutor(optimize=False)`, to
disable) and executes the rewritten DAG on whichever tier was selected.

Rules — each a pure `root -> root'` rewrite, the pipeline run to fixpoint
with a pass-count guard (`MAX_PASSES`):

- `constant_folding`: literal-only expression subtrees fold to `Literal`s
  (expr.fold); `Filter(true)` drops; `Filter(false)` short-circuits to
  `Limit(0)` (an empty relation of the same schema — no new node kind).
- `predicate_pushdown`: Filter moves below Project (predicate rewritten
  through cheap ColumnRef/Literal projections), below Union (one copy per
  input), and into the side of a HashJoin whose columns it references —
  rows die before the join/union/materialization instead of after.
- `limit_pushdown`: Limit(Limit) collapses, Limit moves below row-wise
  Projects, and Limit(Sort) fuses into one `TopK` operator.
- `build_side`: inner-join children swap (plus a column-order-restoring
  Project) when row-count estimates say the left side is much smaller —
  the smaller relation becomes the right/build side, as a CBO picks.
  Estimates come from bound table sizes, falling back to the `est_rows`
  scan hint threaded through `PlanBuilder.scan()`. Swapping reorders the
  join's output ROWS, so the rule fires only where that order is
  unobservable — every path to the root crosses a HashAggregate — keeping
  results row-for-row identical.
- `column_pruning`: required columns walk top-down through the DAG;
  Scans narrow to a `projection` (unused columns never enter the plan),
  Project/FusedSelect outputs and HashAggregate agg lists drop dead
  entries, and width-sensitive operators (join/aggregate/sort/exchange
  inputs) get a zero-copy select-Project inserted when their input still
  carries dead columns (e.g. a Filter's predicate-only columns).
- `select_fusion`: adjacent Filters merge (`a & b`) and Project(Filter)
  fuses into one `FusedSelect` node, so the eager tier gathers the
  projection-referenced columns once instead of materializing the full
  filtered relation first.

DAG sharing is preserved: rewrites memoize per node object, and rules that
restructure a parent/child pair skip children referenced by more than one
parent (restructuring would un-share the subtree and re-execute it).
Scalar-aggregate expressions (`scalar_max(...)`) are never moved across
operators that change their input row set.

`plan_fingerprint` is the canonical structural hash (node kinds, params,
exprs, declared schemas, DAG shape) the executor keys its compiled-program
and caps memos by, so structurally identical plans built independently
share compiled XLA programs — see `Plan.fingerprint`.

If a rewritten DAG fails re-validation (a defensive impossibility given
the rule guards, but plans are user input), `optimize` falls back to the
authored plan and reports `fell_back=True` instead of failing the query.
"""
from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, Optional, Tuple

from .builder import Plan, _toposort
from .expr import (BinOp, ColumnRef, Expr, Literal, ScalarAgg, UnaryOp,
                   col, fold, has_scalar_agg, substitute)
from .nodes import (Exchange, Filter, FusedSelect, HashAggregate, HashJoin,
                    Limit, PlanNode, PlanValidationError, Project, Scan,
                    Sort, TopK, Union)

__all__ = ["optimize", "plan_fingerprint", "subtree_fingerprints",
           "OptimizeReport", "RULE_NAMES", "MAX_PASSES",
           "pruning_conjuncts", "split_conjuncts"]

MAX_PASSES = 10           # fixpoint guard: rewrite passes, not rewrites
_EST_BYTES_PER_CELL = 8   # the engine's INT64-tier column width


# ---- fingerprint ------------------------------------------------------------

# pure hints that do not change the program a plan compiles to — plus the
# attached streaming source object (its identity is execution state, not
# plan structure; shapes/names already key the executor's program cache)
_FP_SKIP_FIELDS = {"est_rows", "parquet"}


def _fp_expr(e: Expr) -> Tuple:
    """Type-TAGGED expression serialization: `col("1")` and `lit(1)` repr
    identically ("1") but must hash apart — a collision would let two
    semantically different plans share one compiled program."""
    if isinstance(e, ColumnRef):
        return ("col", e.name)
    if isinstance(e, Literal):
        return ("lit", repr(e.value))
    if isinstance(e, BinOp):
        return ("bin", e.op, _fp_expr(e.left), _fp_expr(e.right))
    if isinstance(e, UnaryOp):
        return ("un", e.op, _fp_expr(e.child))
    if isinstance(e, ScalarAgg):
        return ("agg", e.op, _fp_expr(e.child))
    return ("expr", repr(e))


def _fp_value(v) -> object:
    if isinstance(v, Expr):
        return _fp_expr(v)
    if isinstance(v, tuple):
        return tuple(_fp_value(x) for x in v)
    return repr(v)


def _node_params(node: PlanNode) -> Tuple:
    """Canonical value tuple over the node's non-child parameters; exprs
    serialize type-tagged (`_fp_expr`), so the hash distinguishes a
    mutated literal — and a literal from a same-repr column ref — but not
    a rebuilt-identical plan."""
    params = []
    for f in dataclasses.fields(node):
        if f.name in _FP_SKIP_FIELDS:
            continue
        v = getattr(node, f.name)
        if isinstance(v, PlanNode):
            continue
        if isinstance(v, tuple) and v and isinstance(v[0], PlanNode):
            continue
        params.append((f.name, _fp_value(v)))
    return tuple(params)


def plan_fingerprint(plan: Plan) -> str:
    """Structural hash over the plan DAG: per node (kind, params, child
    indices in toposort order). The toposort is deterministic for a given
    structure, so two independently built identical plans — including the
    same subtree-sharing shape — hash equal."""
    nodes = plan.nodes
    index = {id(n): i for i, n in enumerate(nodes)}
    toks = [(n.kind, _node_params(n),
             tuple(index[id(c)] for c in n.children)) for n in nodes]
    return hashlib.sha256(repr(toks).encode()).hexdigest()[:16]


def _subtree_token_hash(node: PlanNode, child_fps) -> str:
    """THE per-node subtree-hash definition — the single point the
    store's record keys (subtree_fingerprints over the executed plan)
    and the estimator's consult keys (_Estimator._subtree_fp over the
    plan being optimized) both derive from; a second copy drifting would
    silently make observed stats never match."""
    toks = (node.kind, _node_params(node), tuple(child_fps))
    return hashlib.sha256(repr(toks).encode()).hexdigest()[:16]


def subtree_fingerprints(root: PlanNode) -> Dict[int, str]:
    """node-id -> structural hash of the subtree BELOW each node (kind,
    params, child subtree hashes — same token vocabulary as
    `plan_fingerprint`, same `_FP_SKIP_FIELDS` hint exclusions). Two
    occurrences of one operator subtree hash equal across plans and
    across runs, which is what lets the stats store (plan/stats.py)
    carry an observed output cardinality from an executed plan's node to
    the structurally identical node the optimizer is re-estimating on
    the next execution — and why a schema or parameter change (a stale
    fingerprint) can never match."""
    out: Dict[int, str] = {}
    for n in _toposort(root):
        out[id(n)] = _subtree_token_hash(
            n, (out[id(c)] for c in n.children))
    return out


# ---- report -----------------------------------------------------------------

RULE_NAMES = ("constant_folding", "predicate_pushdown", "limit_pushdown",
              "build_side", "column_pruning", "select_fusion",
              "scan_pruning", "exchange_planning", "placement")


# ---- pruning-conjunct extraction (shared with the executor's scan IO) -------

# comparison ops a row group's min/max range can prove empty
_PRUNE_OPS = ("<", "<=", ">", ">=", "==")
_FLIP_OP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "=="}


def split_conjuncts(e: Expr) -> List[Expr]:
    """Top-level AND conjuncts of a predicate (the predicate itself when
    its root is not `&`)."""
    if isinstance(e, BinOp) and e.op == "&":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def _as_comparison(e: Expr) -> Optional[Tuple[str, str, object]]:
    """`col <op> literal` (either orientation) as (name, op, value); None
    for any other shape — an OR, a column-column compare, arithmetic, a
    scalar aggregate — which min/max stats cannot prove anything about."""
    if not isinstance(e, BinOp) or e.op not in _PRUNE_OPS:
        return None
    l, r = e.left, e.right
    if isinstance(l, ColumnRef) and isinstance(r, Literal):
        return (l.name, e.op, r.value)
    if isinstance(r, ColumnRef) and isinstance(l, Literal):
        return (r.name, _FLIP_OP[e.op], l.value)
    return None


def pruning_conjuncts(e: Expr) -> List[Tuple[str, str, object]]:
    """The (column, op, literal) triples of `e`'s top-level AND conjuncts
    that row-group min/max statistics can evaluate. Pruning on this SUBSET
    of an AND is always conservative-exact (every extracted conjunct must
    hold for a row to survive the retained Filter); a non-conjunct shape —
    e.g. an OR at the top level — contributes nothing, so the scan_pruning
    rule declines rather than over-prunes."""
    out = []
    for c in split_conjuncts(e):
        cmp = _as_comparison(c)
        if cmp is not None:
            out.append(cmp)
    return out


@dataclasses.dataclass
class OptimizeReport:
    """What the pipeline did to one plan — surfaced by explain(optimized=
    True), PlanResult.optimizer, and the bench JSONL `rules_fired` field."""
    rules: Dict[str, int]
    passes: int = 0
    pruned_columns: int = 0        # columns dropped (scan/project/insert)
    pruned_bytes_est: int = 0      # est rows x 8B per dropped column
    source_fingerprint: str = ""
    fingerprint: str = ""
    fell_back: bool = False
    # precise fall-back diagnostic (analysis/verifier.py): which rule
    # produced the invalid rewrite, which node, which invariant —
    # surfaced by summary(), PlanResult.optimizer and the bench JSONL
    # instead of the bare fell_back flag
    fallback: Optional[Dict] = None
    # distributed planning (exchange_planning rule, docs/distributed.md):
    # Exchange insertions per kind, elisions (a boundary the partitioning
    # already satisfied), and the final plan's per-node sharding specs
    exchanges: Dict[str, int] = dataclasses.field(default_factory=dict)
    exchanges_elided: int = 0
    sharding: Dict[str, str] = dataclasses.field(default_factory=dict)
    # adaptive execution (plan/stats.py, docs/adaptive.md): per rule
    # firing, WHERE the cardinalities behind a build-side or
    # exchange-mode choice came from — "<node label>/<rule>" ->
    # "<decision> (hint | observed:<run count> | default)". "observed"
    # means the stats store's recorded subtree cardinality drove the
    # estimate; "hint" an `est_rows` scan hint; "default" bound table
    # sizes / structural guesses. Trajectory numbers and explain output
    # must never silently mix cold and warm decisions.
    decision_sources: Dict[str, str] = dataclasses.field(
        default_factory=dict)
    # co-placement annotation (placement rule, docs/optimizer.md#
    # placement): subtree-root label -> "host" for every subtree the
    # executor should run on a host worker thread overlapped with device
    # execution of the sibling side. ANNOTATION ONLY — the tree is never
    # mutated, so fingerprints (and with them the compiled-program and
    # caps memos) are placement-independent.
    placements: Dict[str, str] = dataclasses.field(default_factory=dict)
    # a stats-driven rewrite failed the verify_rewrite gate and the
    # pipeline re-ran statically (defensive — the same guards protect
    # both paths; see PlanExecutor._optimized)
    stats_reverted: bool = False

    def rules_fired(self) -> Dict[str, int]:
        return {k: v for k, v in self.rules.items() if v}

    def total_rewrites(self) -> int:
        return sum(self.rules.values())

    def stats_driven(self) -> bool:
        """Whether an observed-sourced decision actually CHANGED the
        plan: a build-side `swap` stamped from observed cardinalities,
        or an observed-driven exchange-mode pick (which only exists when
        exchange_planning placed boundaries). A `keep (observed:N)` is
        the static outcome confirmed by observations — not a rewrite —
        and must not trigger the executor's always-on verify_rewrite
        gate on every warm production run of any join-bearing plan.
        Exchange stamps are DELIBERATELY conservative the other way:
        telling an observed pick apart from the identical static one
        would need a parallel static estimate per join, so every
        observed exchange decision counts — the extra verify walk is
        proportionally small next to a distributed mesh execution."""
        for key, v in self.decision_sources.items():
            if "observed" not in v:
                continue
            if key.endswith("/exchange"):
                return True
            if key.endswith("/build_side") and v.startswith("swap"):
                return True
            if key.endswith("/placement") and v.startswith("host"):
                # an observed-wall-driven host placement changes HOW the
                # plan executes — it rides the same verify-or-revert gate
                # as every stats-driven rewrite (the tree is unchanged,
                # so the verify trivially passes, but a revert restores
                # the static placement decision too)
                return True
        return False

    def to_dict(self) -> Dict:
        return {"rules_fired": self.rules_fired(), "passes": self.passes,
                "pruned_columns": self.pruned_columns,
                "pruned_bytes_est": self.pruned_bytes_est,
                "fingerprint": self.fingerprint,
                "source_fingerprint": self.source_fingerprint,
                "fell_back": self.fell_back,
                "fallback": dict(self.fallback) if self.fallback else None,
                "exchanges": dict(self.exchanges),
                "exchanges_elided": self.exchanges_elided,
                "sharding": dict(self.sharding),
                "decision_sources": dict(self.decision_sources),
                "placements": dict(self.placements),
                "stats_driven": self.stats_driven(),
                "stats_reverted": self.stats_reverted}

    def summary(self) -> str:
        lines = [f"optimizer: {self.passes} pass(es), "
                 f"{self.total_rewrites()} rewrite(s)"
                 + (" [FELL BACK: re-validation failed, authored plan ran]"
                    if self.fell_back else "")]
        if self.fallback:
            lines.append(f"  fell back on rule={self.fallback.get('rule')} "
                         f"node={self.fallback.get('node')} "
                         f"invariant={self.fallback.get('invariant')}: "
                         f"{self.fallback.get('message')}")
        for name, n in self.rules_fired().items():
            lines.append(f"  {name}: {n}")
        if self.pruned_columns:
            lines.append(f"  pruned {self.pruned_columns} column(s) "
                         f"(~{self.pruned_bytes_est} bytes est)")
        if self.exchanges or self.exchanges_elided:
            placed = ", ".join(f"{k}={v}" for k, v in
                               sorted(self.exchanges.items()) if v)
            lines.append(f"  exchanges: {placed or 'none'}, "
                         f"{self.exchanges_elided} elided")
        if self.sharding:
            lines.append("  sharding:")
            for label, spec in self.sharding.items():
                lines.append(f"    {label}: {spec}")
        if self.placements:
            lines.append("  placement: " + ", ".join(
                f"{label}->{where}"
                for label, where in sorted(self.placements.items())))
        if self.decision_sources:
            lines.append("  decision sources"
                         + (" [STATS REVERTED: observed-driven rewrite "
                            "failed verify_rewrite, static decisions ran]"
                            if self.stats_reverted else "") + ":")
            for key, src in sorted(self.decision_sources.items()):
                lines.append(f"    {key}: {src}")
        lines.append(f"  fingerprint {self.source_fingerprint} -> "
                     f"{self.fingerprint}")
        return "\n".join(lines)


# ---- rewrite infrastructure -------------------------------------------------

def _with_children(node: PlanNode, kids: Tuple[PlanNode, ...]) -> PlanNode:
    if isinstance(node, HashJoin):
        return dataclasses.replace(node, left=kids[0], right=kids[1])
    if isinstance(node, Union):
        return dataclasses.replace(node, inputs=tuple(kids))
    if node.children:
        return dataclasses.replace(node, child=kids[0])
    return node


def _rewrite(root: PlanNode, fn, shared: Optional[set] = None) -> PlanNode:
    """Bottom-up memoized rewrite. `fn(node) -> replacement | None` runs on
    each node AFTER its children were rewritten; the memo keys on the
    original objects so DAG-shared subtrees rewrite once and stay shared.

    `shared` (the pass's shared-node id set) is kept LIVE: when a shared
    original is rebuilt with rewritten children, the rebuilt node's id
    joins the set — a parent-side guard checking `id(child) in shared`
    would otherwise pass on the fresh object and un-share the subtree."""
    memo: Dict[int, PlanNode] = {}

    def go(node: PlanNode) -> PlanNode:
        got = memo.get(id(node))
        if got is not None:
            return got
        kids = tuple(go(c) for c in node.children)
        if any(k is not c for k, c in zip(kids, node.children)):
            node2 = _with_children(node, kids)
        else:
            node2 = node
        if shared is not None and node2 is not node and id(node) in shared:
            shared.add(id(node2))
        out = fn(node2)
        memo[id(node)] = node2 if out is None else out
        return memo[id(node)]

    return go(root)


def _shared_ids(root: PlanNode) -> set:
    """ids of nodes referenced by >1 parent — rules that restructure a
    parent/child pair must skip these or the subtree would un-share."""
    counts: Dict[int, int] = {}
    for n in _toposort(root):
        for c in n.children:
            counts[id(c)] = counts.get(id(c), 0) + 1
    return {i for i, c in counts.items() if c > 1}


class _Schemas:
    """Lazy output-schema resolver usable on any node, old or freshly
    rewritten. Unresolvable subtrees (scan without declared schema and no
    binding) resolve to None and schema-dependent rules skip them."""

    def __init__(self, bound: Optional[Dict[str, Tuple[str, ...]]] = None):
        self.bound = dict(bound or {})
        self.memo: Dict[int, Optional[Tuple[str, ...]]] = {}

    def of(self, node: PlanNode) -> Optional[Tuple[str, ...]]:
        got = self.memo.get(id(node), _Schemas)
        if got is not _Schemas:
            return got
        if isinstance(node, Scan):
            base = self.bound.get(node.source, node.schema)
            s = None if base is None else node.apply_projection(base)
        else:
            kids = [self.of(c) for c in node.children]
            s = (None if any(k is None for k in kids)
                 else tuple(node.output_names(kids)))
        self.memo[id(node)] = s
        return s


# estimate-source severity lattice: a decision that consumed ANY observed
# cardinality is stats-driven; certified bounds and hints outrank
# structural defaults (a certified bound is SOUND but loose, a hint is
# the author's guess at the actual — both lose to observations)
_SRC_RANK = {"default": 0, "certified": 1, "hint": 2, "observed": 3}


class _Estimator:
    """Row-count estimates, bottom-up. OBSERVED subtree cardinalities
    from the stats store (plan/stats.py) win for interior nodes; bound
    table sizes win at scans; `est_rows` scan hints fill in; where the
    static chain has nothing at all, the resource certifier's sound
    rows-hi bound (analysis/footprint.py) fills in LAST before None
    propagates (rules skip). Selectivity guesses are crude on purpose —
    only the build_side and exchange rules consume them, both behind
    margins. Alongside each estimate the SOURCE is tracked ("observed" /
    "hint" / "certified" / "default", plus the observed run count or the
    certified bound) so rule firings can stamp their decision source on
    the report."""

    def __init__(self, bound_rows: Optional[Dict[str, int]] = None,
                 stats=None, backend: Optional[str] = None, cert=None):
        self.bound = dict(bound_rows or {})
        self.stats = stats          # plan/stats.StatsStore or None
        self.backend = backend
        self.cert = cert            # node -> Optional[int] certified rows hi
        self.memo: Dict[int, Optional[float]] = {}
        self.src: Dict[int, Tuple[str, Optional[int]]] = {}
        self._subfp: Dict[int, str] = {}

    def of(self, node: PlanNode) -> Optional[float]:
        got = self.memo.get(id(node), _Estimator)
        if got is not _Estimator:
            return got
        e, src, runs = self._compute(node)
        self.memo[id(node)] = e
        if e is not None:
            self.src[id(node)] = (src, runs)
        return e

    def source_of(self, *nodes: PlanNode) -> str:
        """Rendered decision source over the nodes whose estimates fed
        one rule decision: the severity-max of their sources, with the
        smallest observed run count when observed (a decision is only as
        warm as its coldest observation) and the largest certified bound
        when certified (the loosest proof the decision leaned on)."""
        best, runs, bnd = "default", None, None
        for n in nodes:
            s, r = self.src.get(id(n), ("default", None))
            if _SRC_RANK[s] > _SRC_RANK[best]:
                best = s
            if s == "observed" and r is not None:
                runs = r if runs is None else min(runs, r)
            if s == "certified" and r is not None:
                bnd = r if bnd is None else max(bnd, r)
        if best == "observed":
            return f"observed:{runs}"
        if best == "certified":
            return f"certified:{bnd}"
        return best

    def _subtree_fp(self, node: PlanNode) -> str:
        got = self._subfp.get(id(node))
        if got is None:
            got = _subtree_token_hash(
                node, (self._subtree_fp(c) for c in node.children))
            self._subfp[id(node)] = got
        return got

    def _observed(self, node: PlanNode) -> Optional[Tuple[int, int]]:
        if self.stats is None or self.backend is None:
            return None
        return self.stats.observed_rows(self.backend,
                                        self._subtree_fp(node))

    def _certified(self, node: PlanNode) -> Optional[int]:
        """The resource certifier's sound rows-hi bound for this node, or
        None (no certifier wired, or the subtree is unbounded). Last
        resort before the estimate chain gives up: a hi bound is a LOOSE
        stand-in for a cardinality, but rules behind margins prefer it
        over skipping the decision entirely (docs/analysis.md)."""
        if self.cert is None:
            return None
        return self.cert(node)

    def _compute(self, node: PlanNode
                 ) -> Tuple[Optional[float], str, Optional[int]]:
        if isinstance(node, Scan):
            v = self.bound.get(node.source)
            if v is not None:
                return float(v), "default", None
            obs = self._observed(node)
            if obs is not None:
                return float(obs[0]), "observed", obs[1]
            if node.est_rows is not None:
                return float(node.est_rows), "hint", None
            c = self._certified(node)
            if c is not None:
                return float(c), "certified", c
            return None, "default", None
        obs = self._observed(node)
        if obs is not None:
            return float(obs[0]), "observed", obs[1]
        kids = [self.of(c) for c in node.children]
        if any(k is None for k in kids):
            c = self._certified(node)
            if c is not None:
                return float(c), "certified", c
            return None, "default", None
        src, runs = "default", None
        for c in node.children:
            s, r = self.src.get(id(c), ("default", None))
            if _SRC_RANK[s] > _SRC_RANK[src]:
                src = s
            if s == "observed" and r is not None:
                runs = r if runs is None else min(runs, r)
        if isinstance(node, (Filter, FusedSelect)):
            return 0.5 * kids[0], src, runs
        if isinstance(node, (Project, Exchange, Sort)):
            return kids[0], src, runs
        if isinstance(node, Limit):
            return min(float(node.n), kids[0]), src, runs
        if isinstance(node, TopK):
            return min(float(node.n), kids[0]), src, runs
        if isinstance(node, Union):
            return sum(kids), src, runs
        if isinstance(node, HashJoin):
            if node.how == "inner":
                return max(kids), src, runs
            return 0.5 * kids[0], src, runs
        if isinstance(node, HashAggregate):
            if not node.keys:
                return 1.0, src, runs
            return max(1.0, kids[0] / 10.0), src, runs   # distinct guess
        return (kids[0] if kids else None), src, runs


# ---- rules ------------------------------------------------------------------
# Each rule: (root, ctx) -> (root', hits). ctx carries schemas/estimates/
# shared-ids computed fresh for the pass, plus the report for prune stats.

class _Ctx:
    def __init__(self, root, bound, bound_rows, report,
                 float_inputs=False, streaming=frozenset(),
                 stats=None, backend=None, input_dtypes=None):
        self.root = root
        self.bound = bound
        self.bound_rows = bound_rows
        self.input_dtypes = input_dtypes
        self._cert = None               # lazy footprint cert over `root`
        self.schemas = _Schemas(bound)
        self.est = _Estimator(bound_rows, stats, backend,
                              cert=self.cert_rows_hi)
        self.shared = _shared_ids(root)
        self.report = report
        self.float_inputs = float_inputs
        self.streaming = streaming      # scan sources bound to streaming
        #                                 (parquet) sources this execution

    def _cert_map(self):
        """Resource-certifier bounds over this pass's root
        (analysis/footprint.py), computed on first consult only — most
        rule invocations never ask. Keyed by node id over the CURRENT
        root's toposort, so estimator misses and the exchange rule's
        byte-legality proof read the same walk."""
        if self._cert is None:
            from ..analysis.footprint import certify_nodes
            self._cert = certify_nodes(
                _toposort(self.root), bound=self.bound,
                bound_rows=self.bound_rows,
                input_dtypes=self.input_dtypes)
        return self._cert

    def cert_rows_hi(self, node: PlanNode) -> Optional[int]:
        b = self._cert_map().get(id(node))
        return None if b is None else b.rows_hi

    def cert_out_bytes_hi(self, node: PlanNode) -> Optional[int]:
        b = self._cert_map().get(id(node))
        return None if b is None else b.out_bytes_hi


def _rule_constant_folding(root, ctx):
    hits = [0]

    def fn(node):
        if isinstance(node, Filter):
            p = fold(node.predicate)
            if isinstance(p, Literal):
                hits[0] += 1
                if bool(p.value):
                    return node.child              # Filter(true): drop
                return Limit(node.child, 0)        # Filter(false): empty
            if p is not node.predicate:
                hits[0] += 1
                return dataclasses.replace(node, predicate=p)
            return None
        if isinstance(node, FusedSelect):
            p = fold(node.predicate)
            exprs = tuple((n, fold(e)) for n, e in node.exprs)
            changed = (p is not node.predicate or
                       any(e is not o for (_, e), (_, o)
                           in zip(exprs, node.exprs)))
            if isinstance(p, Literal):
                hits[0] += 1
                child = (node.child if bool(p.value)
                         else Limit(node.child, 0))
                return Project(child, exprs)
            if changed:
                hits[0] += 1
                return FusedSelect(node.child, p, exprs)
            return None
        if isinstance(node, Project):
            exprs = tuple((n, fold(e)) for n, e in node.exprs)
            if any(e is not o for (_, e), (_, o) in zip(exprs, node.exprs)):
                hits[0] += 1
                return dataclasses.replace(node, exprs=exprs)
        return None

    return _rewrite(root, fn), hits[0]


def _rule_predicate_pushdown(root, ctx):
    hits = [0]

    def fn(node):
        if not isinstance(node, Filter):
            return None
        child, p = node.child, node.predicate
        if id(child) in ctx.shared:
            return None    # restructuring would un-share the subtree
        if isinstance(child, Project):
            if any(has_scalar_agg(e) for _, e in child.exprs):
                # the filter below would change the row set the project's
                # scalar aggregate reduces over — same hazard (and guard)
                # as limit_pushdown's Project branch
                return None
            mapping = dict(child.exprs)
            refs = p.references()
            # substitute only through cheap projections: re-evaluating a
            # computed expression twice would trade bytes for FLOPs
            if refs <= set(mapping) and all(
                    isinstance(mapping[r], (ColumnRef, Literal))
                    for r in refs):
                hits[0] += 1
                pushed = Filter(child.child, substitute(p, mapping))
                return dataclasses.replace(child, child=pushed)
            return None
        if isinstance(child, Union) and not has_scalar_agg(p):
            hits[0] += 1
            return Union(tuple(Filter(i, p) for i in child.inputs))
        if isinstance(child, HashJoin) and not has_scalar_agg(p):
            refs = p.references()
            ls = ctx.schemas.of(child.left)
            rs = ctx.schemas.of(child.right)
            if child.how == "inner" and rs is not None and refs <= set(rs):
                hits[0] += 1
                return dataclasses.replace(
                    child, right=Filter(child.right, p))
            if ls is not None and refs <= set(ls):
                # inner: left-only columns; semi/anti: output IS the left
                # schema, so a row filter always commutes to the left side
                hits[0] += 1
                return dataclasses.replace(child, left=Filter(child.left, p))
        return None

    return _rewrite(root, fn, ctx.shared), hits[0]


def _rule_limit_pushdown(root, ctx):
    hits = [0]

    def fn(node):
        if not isinstance(node, Limit):
            return None
        c = node.child
        if id(c) in ctx.shared:
            return None
        if isinstance(c, Limit):
            hits[0] += 1
            return Limit(c.child, min(node.n, c.n))
        if isinstance(c, Project) and not any(
                has_scalar_agg(e) for _, e in c.exprs):
            hits[0] += 1
            return dataclasses.replace(c, child=Limit(c.child, node.n))
        if isinstance(c, Sort):
            hits[0] += 1
            return TopK(c.child, c.keys, c.ascending, node.n)
        return None

    return _rewrite(root, fn, ctx.shared), hits[0]


def _order_safe_ids(root: PlanNode) -> set:
    """ids of nodes whose output ROW ORDER is unobservable: every path to
    the root passes through a HashAggregate (whose output order depends on
    keys, not input order) via operators that merely propagate rows.
    Swapping a join reorders its output rows, so the build_side rule only
    fires inside these regions — result parity stays row-for-row exact.
    (Sort is NOT a pass-through: a stable sort exposes input order on key
    ties; Limit/TopK take the first n rows, observably.)"""
    nodes = _toposort(root)
    parents: Dict[int, List[PlanNode]] = {}
    for n in nodes:
        for c in n.children:
            parents.setdefault(id(c), []).append(n)
    pass_through = (Filter, FusedSelect, Project, HashJoin, Union, Exchange)
    safe: Dict[int, bool] = {}
    for n in reversed(nodes):             # parents before children
        ps = parents.get(id(n), [])
        safe[id(n)] = bool(ps) and all(
            isinstance(p, HashAggregate)
            or (isinstance(p, pass_through) and safe[id(p)])
            for p in ps)
    return {i for i, v in safe.items() if v}


def _rule_build_side(root, ctx):
    hits = [0]
    if ctx.float_inputs:
        # floating-point sums/means are not associative: the aggregate
        # above absorbs the ROW reorder but not the fp reduction-order
        # change on m:n joins (within-group pair enumeration flips), so
        # bit-exact parity only holds for exact (integer/bool) inputs —
        # skip the rule entirely when any bound input carries floats
        return root, 0
    if any(isinstance(n, HashAggregate)
           and any(o == "mean" for _, o, _ in n.aggs)
           for n in _toposort(root)):
        # mean accumulates in float64 even over integer inputs (and its
        # output stays float for anything above), so a mean anywhere in
        # the plan reintroduces the fp reorder-exactness problem
        return root, 0
    safe = _order_safe_ids(root)
    memo: Dict[int, PlanNode] = {}

    def go(n: PlanNode) -> PlanNode:      # custom recursion: the safety
        got = memo.get(id(n))             # set keys on ORIGINAL node ids
        if got is not None:
            return got
        kids = tuple(go(c) for c in n.children)
        node2 = (_with_children(n, kids)
                 if any(k is not c for k, c in zip(kids, n.children)) else n)
        if (isinstance(n, HashJoin) and n.how == "inner"
                and id(n) in safe):
            le = ctx.est.of(n.left)
            re_ = ctx.est.of(n.right)
            ls = ctx.schemas.of(n.left)
            rs = ctx.schemas.of(n.right)
            # 2x hysteresis: swap only on a clear margin so the rule is
            # stable (the swapped join's sides never re-qualify)
            if None not in (le, re_, ls, rs):
                swap = le * 2 < re_
                # decision provenance (docs/adaptive.md): which estimate
                # tier fed this choice — re-stamped each pass, so the
                # fixpoint pass (where warm observed stats have become
                # visible through the converged subtree shapes) wins
                ctx.report.decision_sources[f"{n.label}/build_side"] = (
                    f"{'swap' if swap else 'keep'} "
                    f"({ctx.est.source_of(n.left, n.right)})")
                if swap:
                    hits[0] += 1
                    swapped = HashJoin(node2.right, node2.left,
                                       n.right_keys, n.left_keys,
                                       how="inner", row_cap=n.row_cap)
                    order = tuple(ls) + tuple(rs)  # restore authored order
                    node2 = Project(swapped,
                                    tuple((nm, col(nm)) for nm in order))
        memo[id(n)] = node2
        return node2

    return go(root), hits[0]


def _rule_select_fusion(root, ctx):
    hits = [0]

    def fn(node):
        if (isinstance(node, Filter) and isinstance(node.child, Filter)
                and id(node.child) not in ctx.shared
                and not has_scalar_agg(node.predicate)):
            # inner predicate first is irrelevant for a row-wise AND; a
            # scalar-agg outer predicate reduces over the FILTERED rows,
            # so it must not move over the inner filter
            inner = node.child
            hits[0] += 1
            return Filter(inner.child, inner.predicate & node.predicate)
        if (isinstance(node, Project) and isinstance(node.child, Filter)
                and id(node.child) not in ctx.shared):
            f = node.child
            hits[0] += 1
            return FusedSelect(f.child, f.predicate, node.exprs)
        return None

    return _rewrite(root, fn, ctx.shared), hits[0]


# width-sensitive operators: a dead column crossing one of these edges is
# materialized/sorted/shuffled, so a zero-copy select pays for itself
_NARROW_PARENTS = (HashJoin, HashAggregate, Sort, TopK, Exchange)


def _rule_column_pruning(root, ctx):
    nodes = _toposort(root)
    schemas = {id(n): ctx.schemas.of(n) for n in nodes}
    if any(s is None for s in schemas.values()):
        return root, 0                    # unresolved subtree: skip the pass
    required: Dict[int, set] = {}
    extra: Dict[int, set] = {}     # union-equalization floor (see below)
    edge_req: Dict[Tuple[int, int], set] = {}

    def req_of(n):
        return required[id(n)] | extra.get(id(n), set())

    def push(parent, i, req):
        edge_req[(id(parent), i)] = req
        required[id(parent.children[i])] |= req

    # Recompute until stable: Union inputs must all narrow to the SAME
    # schema (positional contract), but a DAG-shared input can pick up
    # extra requirements from parents OUTSIDE the union — equalize every
    # union's inputs to their union-of-requirements and re-propagate.
    # Requirements only grow, so this terminates well inside the bound.
    for _ in range(len(nodes) + 1):
        required = {id(n): set() for n in nodes}
        edge_req.clear()
        required[id(root)] = set(schemas[id(root)])
        # reversed toposort = parents before children: each node's
        # required set is complete (over all parents) when we reach it
        for n in reversed(nodes):
            req = req_of(n)
            if isinstance(n, Filter):
                push(n, 0, set(req) | n.predicate.references())
            elif isinstance(n, (Project, FusedSelect)):
                kept = [e for name, e in n.exprs if name in req] or \
                       [n.exprs[0][1]]
                r = set().union(*[e.references() for e in kept])
                if isinstance(n, FusedSelect):
                    r |= n.predicate.references()
                if not r:                 # all-literal: keep a row carrier
                    r = {schemas[id(n.children[0])][0]}
                push(n, 0, r)
            elif isinstance(n, HashJoin):
                ls = schemas[id(n.left)]
                rs = schemas[id(n.right)]
                if n.how == "inner":
                    push(n, 0, (req & set(ls)) | set(n.left_keys))
                    push(n, 1, (req & set(rs)) | set(n.right_keys))
                else:
                    push(n, 0, set(req) | set(n.left_keys))
                    push(n, 1, set(n.right_keys))
            elif isinstance(n, HashAggregate):
                kept = [a for a in n.aggs if a[2] in req] or [n.aggs[0]]
                r = set(n.keys) | {c for c, o, _ in kept if o != "size"}
                if not r:                 # global size-only aggregate
                    r = {schemas[id(n.children[0])][0]}
                push(n, 0, r)
            elif isinstance(n, (Sort, TopK)):
                push(n, 0, set(req) | set(n.keys))
            elif isinstance(n, Exchange):
                push(n, 0, set(req) | set(n.keys))
            elif isinstance(n, (Limit, Union)):
                for i in range(len(n.children)):
                    push(n, i, set(req))
        stable = True
        for n in nodes:
            if isinstance(n, Union):
                eq = set().union(*[req_of(c) for c in n.children])
                for c in n.children:
                    if req_of(c) != eq:
                        extra.setdefault(id(c), set()).update(eq)
                        stable = False
        if stable:
            break

    hits = [0]
    rep = ctx.report

    def note_pruned(n_cols, est_rows):
        hits[0] += 1
        rep.pruned_columns += n_cols
        if est_rows is not None:
            rep.pruned_bytes_est += int(
                n_cols * est_rows * _EST_BYTES_PER_CELL)

    memo: Dict[int, PlanNode] = {}

    def go(n: PlanNode) -> PlanNode:
        got = memo.get(id(n))
        if got is not None:
            return got
        kids = [go(c) for c in n.children]
        if isinstance(n, _NARROW_PARENTS):
            for i, (orig_c, new_c) in enumerate(zip(n.children, kids)):
                if isinstance(new_c, Exchange):
                    continue    # narrow below it: Exchange is pass-through,
                    # and a Project in between would break the distributed
                    # HashAggregate-on-Exchange lowering
                r = edge_req[(id(n), i)]
                cs = ctx.schemas.of(new_c)
                if cs is None or not (set(cs) - r):
                    continue
                keep = tuple(c for c in cs if c in r)
                note_pruned(len(cs) - len(keep), ctx.est.of(orig_c))
                kids[i] = Project(new_c,
                                  tuple((c, ColumnRef(c)) for c in keep))
        node2 = (_with_children(n, tuple(kids))
                 if any(k is not c for k, c in zip(kids, n.children)) else n)
        req = req_of(n)
        if isinstance(n, Scan):
            cur = schemas[id(n)]
            keep = tuple(c for c in cur if c in req) or (cur[0],)
            if keep != tuple(cur):
                note_pruned(len(cur) - len(keep), ctx.est.of(n))
                node2 = dataclasses.replace(node2, projection=keep)
        elif isinstance(n, (Project, FusedSelect)):
            kept = tuple((name, e) for name, e in n.exprs if name in req) \
                or (n.exprs[0],)
            if len(kept) < len(n.exprs):
                note_pruned(len(n.exprs) - len(kept), ctx.est.of(n))
                node2 = dataclasses.replace(node2, exprs=kept)
        elif isinstance(n, HashAggregate):
            kept = tuple(a for a in n.aggs if a[2] in req) or (n.aggs[0],)
            if len(kept) < len(n.aggs):
                note_pruned(len(n.aggs) - len(kept), ctx.est.of(n))
                node2 = dataclasses.replace(node2, aggs=kept)
        memo[id(n)] = node2
        return node2

    return go(root), hits[0]


def _rule_scan_pruning(root, ctx):
    """Filter/FusedSelect directly over a streaming-source Scan: lower the
    min/max-provable AND-conjuncts of the predicate into `Scan.predicate`
    for row-group pruning. PRUNING-ONLY: the Filter/FusedSelect stays
    above for exact row semantics; a row group is skipped at scan time
    only when footer statistics prove the lowered conjuncts match nothing
    (io/parquet.select_row_groups). Predicates with no provable top-level
    conjunct — an OR at the root, column-column compares, scalar
    aggregates — lower nothing: extracting from inside an OR would
    over-prune rows the retained Filter still wants."""
    hits = [0]

    def fn(node):
        if not isinstance(node, (Filter, FusedSelect)):
            return None
        child = node.child
        if not isinstance(child, Scan) or child.predicate is not None:
            return None
        if child.parquet is None and child.source not in ctx.streaming:
            return None     # table-bound scan: nothing to prune at IO time
        if id(child) in ctx.shared:
            # a shared scan feeds OTHER parents that did not author this
            # filter — pruning it would starve them of rows
            return None
        safe = [c for c in split_conjuncts(node.predicate)
                if _as_comparison(c) is not None]
        if not safe:
            return None
        pred = safe[0]
        for c in safe[1:]:
            pred = BinOp("&", pred, c)
        hits[0] += 1
        return _with_children(
            node, (dataclasses.replace(child, predicate=pred),))

    return _rewrite(root, fn, ctx.shared), hits[0]


_RULES = (
    ("constant_folding", _rule_constant_folding),
    ("predicate_pushdown", _rule_predicate_pushdown),
    ("limit_pushdown", _rule_limit_pushdown),
    ("build_side", _rule_build_side),
    ("column_pruning", _rule_column_pruning),
    ("select_fusion", _rule_select_fusion),
    ("scan_pruning", _rule_scan_pruning),
)


# ---- exchange planning (distributed tier, docs/distributed.md) --------------

def _statically_distributable(n: PlanNode, float_inputs: bool) -> bool:
    """Whether a node kind CAN run on the mesh — the static half of the
    gate (the executor re-checks runtime properties like column dtypes and
    gathers gracefully when they fail). Limit and global aggregates have
    no distributed form; `mean` and any-float inputs disable aggregates
    (the exchange accumulates partials in exact int64)."""
    if isinstance(n, Limit):
        return False
    if isinstance(n, HashAggregate):
        if not n.keys or any(o == "mean" for _, o, _ in n.aggs):
            return False
        if float_inputs:
            return False
    return True


def _plan_exchanges(root: PlanNode, ctx: "_Ctx", n_peers: int):
    """Post-fixpoint distributed planning: walk the DAG bottom-up tracking
    each node's hash-partitioning property (plan/distributed.transfer_part
    — the SAME rule the runtime rels follow) and insert the Exchange
    boundaries the mesh execution needs:

    - each shuffle-join side gets Exchange(hash, its keys) unless the
      side is already partitioned by exactly that key tuple (ELIDED);
    - a join whose build (right) side estimate is at or below
      `config.broadcast_rows()` — and no larger than the probe side —
      gets Exchange(broadcast) instead: the small side replicates, the
      probe side never moves (est_rows-driven, Spark's
      autoBroadcastJoinThreshold shape);
    - a keyed HashAggregate gets Exchange(hash, group keys) below it
      (the executor FUSES the pair into the two-phase partial-agg
      program) unless the input partitioning already co-locates every
      group — a subset of the group keys suffices — in which case the
      boundary is elided and the aggregate merges shard-locally;
    - sharded relations flowing into an operator with NO distributed
      form — and the plan root — get Exchange(gather): the only
      hops off the mesh, visible in explain().

    Returns (new root, insertions); fills report.exchanges/
    exchanges_elided/sharding."""
    from .. import config
    from .distributed import part_satisfies, transfer_part
    report = ctx.report
    nodes = _toposort(root)
    if any(ctx.schemas.of(n) is None for n in nodes):
        return root, 0
    thresh = config.broadcast_rows()
    stats = {"hash": 0, "broadcast": 0, "gather": 0}
    elided = [0]
    sharded: Dict[int, bool] = {}
    part: Dict[int, frozenset] = {}
    memo: Dict[int, PlanNode] = {}
    gathers: Dict[int, PlanNode] = {}   # one gather per shared child

    def add_exchange(child: PlanNode, keys, how: str) -> PlanNode:
        if how == "gather" and id(child) in gathers:
            return gathers[id(child)]
        stats[how] += 1
        ex = Exchange(child, tuple(keys), how=how)
        part[id(ex)] = transfer_part(ex, [part[id(child)]])
        sharded[id(ex)] = how != "gather"
        if how == "gather":
            gathers[id(child)] = ex
        return ex

    def go(n: PlanNode) -> PlanNode:
        got = memo.get(id(n))
        if got is not None:
            return got
        kids = [go(c) for c in n.children]
        on_mesh = _statically_distributable(n, ctx.float_inputs) and (
            isinstance(n, Scan) or (bool(kids)
                                    and all(sharded[id(k)] for k in kids)))
        if not on_mesh:
            # graceful boundary: sharded children collect here
            kids = [add_exchange(k, (), "gather") if sharded[id(k)] else k
                    for k in kids]
        elif isinstance(n, HashJoin):
            l_new, r_new = kids
            le = ctx.est.of(n.left)
            re_ = ctx.est.of(n.right)
            row_ok = (re_ is not None and re_ <= thresh
                      and (le is None or re_ <= le))
            # broadcast LEGALITY is a proven byte bound
            # (analysis/footprint.py, docs/analysis.md): the certified
            # build-side hi must fit config.broadcast_bytes() — the row
            # estimate stays the cost heuristic, but a mis-estimated
            # side whose certified bytes exceed the ceiling never
            # replicates onto every peer. Unbounded sides (strings,
            # unbound scans) keep the row heuristic alone.
            bytes_hi = ctx.cert_out_bytes_hi(n.right)
            bc_bytes = config.broadcast_bytes()
            byte_ok = bytes_hi is None or bytes_hi <= bc_bytes
            broadcast = row_ok and byte_ok
            # decision provenance, same vocabulary as build_side: what
            # kind of estimate picked the exchange mode for this join —
            # plus the byte proof (or veto) when the certifier bounded
            # the build side
            note = ("" if bytes_hi is None else
                    f"; certified:{bytes_hi}B"
                    f"{'<=' if byte_ok else '>'}{bc_bytes}B")
            report.decision_sources[f"{n.label}/exchange"] = (
                f"{'broadcast' if broadcast else 'shuffle'} "
                f"({ctx.est.source_of(n.left, n.right)}{note})")
            if broadcast:
                r_new = add_exchange(r_new, (), "broadcast")
            else:
                if tuple(n.left_keys) in part[id(l_new)]:
                    elided[0] += 1
                else:
                    l_new = add_exchange(l_new, n.left_keys, "hash")
                if tuple(n.right_keys) in part[id(r_new)]:
                    elided[0] += 1
                else:
                    r_new = add_exchange(r_new, n.right_keys, "hash")
            kids = [l_new, r_new]
        elif isinstance(n, HashAggregate):
            (c_new,) = kids
            if isinstance(c_new, Exchange) and c_new.how == "hash":
                pass                    # authored boundary, keep it
            elif part_satisfies(part[id(c_new)], n.keys):
                elided[0] += 1          # input already co-locates groups
            else:
                kids = [add_exchange(c_new, n.keys, "hash")]
        node2 = (_with_children(n, tuple(kids))
                 if any(k is not c for k, c in zip(kids, n.children)) else n)
        sharded[id(node2)] = on_mesh
        part[id(node2)] = (transfer_part(
            node2, [part[id(k)] for k in node2.children])
            if on_mesh else frozenset())
        memo[id(n)] = node2
        return node2

    new_root = go(root)
    if sharded[id(new_root)]:
        new_root = add_exchange(new_root, (), "gather")   # the sink

    for node in _toposort(new_root):
        if isinstance(node, Exchange) and node.how != "identity":
            if node.how == "gather":
                spec = "local (gather)"
            elif node.how == "broadcast":
                spec = f"replicated@{n_peers}"
            else:
                spec = f"hash[{','.join(node.keys)}]@{n_peers}"
        elif not sharded.get(id(node), False):
            spec = "local"
        elif part.get(id(node)):
            keys = min(part[id(node)])
            spec = f"hash[{','.join(keys)}]@{n_peers}"
        else:
            spec = f"rows@{n_peers}"
        report.sharding[node.label] = spec
    report.exchanges = stats
    report.exchanges_elided = elided[0]
    return new_root, sum(stats.values())


# ---- co-placement (placement rule, docs/optimizer.md#placement) -------------

def _host_placeable(sub_nodes, ctx: "_Ctx") -> bool:
    """Whether a candidate subtree may run on a host worker thread at
    all: exclusive (no node inside it is DAG-shared with a consumer
    outside it — a deferred result another branch reads synchronously
    would serialize the overlap away), no Exchange boundaries (the
    distributed tier owns those), and no streaming-bound scans (the
    morsel pipeline's prefetch threads stay single-walk)."""
    for s in sub_nodes:
        if isinstance(s, Exchange):
            return False
        if id(s) in ctx.shared:
            return False
        if isinstance(s, Scan) and (s.source in ctx.streaming
                                    or getattr(s, "parquet", None)
                                    is not None):
            return False
    return True


def _plan_placement(root: PlanNode, ctx: "_Ctx",
                    max_bytes: Optional[int] = None) -> int:
    """Post-fixpoint co-placement annotation: pick HashJoin build
    (right) sides to run on a host worker thread OVERLAPPED with device
    execution of the probe side (plan/executor.py's co-placement
    dispatch; "Revisiting Co-Processing for Hash Joins on the Coupled
    CPU-GPU Architecture", PAPERS.md). PURE ANNOTATION — the tree is
    never mutated (fingerprints and compiled-program memos stay
    placement-independent); the executor reads `report.placements`
    (subtree-root label -> "host").

    Decision, per candidate: WARM fingerprints compare backend-keyed
    observed cumulative subtree wall (plan/stats.observed_wall) — host
    wins when its "cpu" wall is at or below the device wall for the
    same subtree shape; COLD subtrees qualify when every node's
    certified output-byte hi-bound (analysis/footprint.py) fits
    `max_bytes` (config.placement_bytes() when None). Either way the
    decision source is stamped on `report.decision_sources`
    ("<join label>/placement" -> "host|keep (observed:N|certified:B)"),
    and an observed-driven host placement counts as stats-driven — the
    executor's verify-or-revert gate covers it like every other
    stats-driven rewrite. Placements never nest: a join inside (or
    overlapping) an already-placed subtree is skipped — its build side
    already runs on the host thread as part of the outer subtree.
    Single-node subtrees (a bare scan) are skipped: there is no host
    compute to overlap, only a round trip."""
    from .. import config
    report = ctx.report
    if max_bytes is None:
        max_bytes = config.placement_bytes()
    est = ctx.est
    placed: set = set()
    n_placed = 0
    for n in _toposort(root):
        if not isinstance(n, HashJoin):
            continue
        cand = n.right
        sub = list(_toposort(cand))
        ids = {id(s) for s in sub}
        if len(sub) < 2 or id(n) in placed or ids & placed:
            continue
        if not _host_placeable(sub, ctx):
            continue
        decision = None
        if est.stats is not None and est.backend is not None:
            fp = est._subtree_fp(cand)
            host = est.stats.observed_wall("cpu", fp)
            dev = est.stats.observed_wall(est.backend, fp)
            if host is not None and dev is not None:
                runs = min(host[1], dev[1])
                cmp = "<=" if host[0] <= dev[0] else ">"
                decision = ("host" if host[0] <= dev[0] else "keep",
                            f"observed:{runs}; cpu:{host[0]:.3f}ms{cmp}"
                            f"{est.backend}:{dev[0]:.3f}ms")
        if decision is None:
            sub_hi: Optional[int] = 0
            for s in sub:
                b = ctx.cert_out_bytes_hi(s)
                if b is None:
                    sub_hi = None
                    break
                sub_hi = max(sub_hi, b)
            if sub_hi is not None and sub_hi <= max_bytes:
                decision = ("host",
                            f"certified:{sub_hi}B<={max_bytes}B")
            else:
                decision = ("keep", "unbounded" if sub_hi is None else
                            f"certified:{sub_hi}B>{max_bytes}B")
        report.decision_sources[f"{n.label}/placement"] = \
            f"{decision[0]} ({decision[1]})"
        if decision[0] == "host":
            report.placements[cand.label] = "host"
            placed |= ids | {id(n)}
            n_placed += 1
    return n_placed


# ---- fall-back diagnostics (analysis/verifier.py, docs/analysis.md) ---------

def _plan_error(root: PlanNode, bound=None) -> Optional[PlanValidationError]:
    """Re-validate a rewritten root; the schema error (None when clean).
    Plan construction routes through the static verifier, so the error
    carries structured violations naming the invariant and node. `bound`
    matters: a Scan with no declared schema resolves only against the
    bound tables, so without it an invalid rewrite over such a plan
    validates vacuously here and detonates later inside a DIFFERENT
    rule's schema resolution — the victim, not the culprit."""
    try:
        p = Plan(root)
        if bound:
            p.resolve_schemas(bound)
    except PlanValidationError as e:
        return e
    return None


def _diagnose(rule: str, err: PlanValidationError) -> Dict:
    """The (rule, node, invariant, message) fall-back record. Verifier
    errors carry structured violations; a bare PlanValidationError falls
    back to parsing the leading `Kind#id:` label convention."""
    violations = getattr(err, "violations", None)
    if violations:
        v = violations[0]
        return {"rule": rule, "node": v.node, "invariant": v.invariant,
                "message": v.message}
    msg = str(err)
    head = msg.split(":", 1)[0]
    node = head if "#" in head and " " not in head else ""
    return {"rule": rule, "node": node, "invariant": "schema",
            "message": msg}


def _fall_back(plan: Plan, report: OptimizeReport):
    """Discard the rewrite and run the authored plan. The report must
    describe what RAN, so the discarded rewrite's counts are zeroed: a
    parity gate reading rules_fired/pruned_columns would otherwise
    celebrate rewrites that never executed. `report.fallback` (set by the
    caller) survives — it describes why the rewrite was discarded."""
    report.fell_back = True
    report.rules = {name: 0 for name in RULE_NAMES}
    report.pruned_columns = 0
    report.pruned_bytes_est = 0
    report.exchanges = {}
    report.exchanges_elided = 0
    report.sharding = {}
    report.decision_sources = {}
    report.placements = {}
    report.fingerprint = report.source_fingerprint
    return plan, report


def _attribute_fallback(plan: Plan, bound, bound_rows, float_inputs,
                        streaming, mesh_peers,
                        err: PlanValidationError,
                        stats=None, backend=None,
                        input_dtypes=None) -> Dict:
    """Post-hoc attribution for the validate-or-fall-back net: re-run the
    pipeline from the authored root, re-validating after every rule that
    rewrites, to name the rule/node/invariant that produced the invalid
    DAG. Only runs on the (defensively impossible) fall-back path, so the
    duplicated rule work costs nothing in the common case. `stats`/
    `backend`/`input_dtypes` replay the SAME adaptive estimates and
    certified bounds the failing pipeline consumed — attribution must
    reproduce the rewrite it is naming."""
    scratch = OptimizeReport(rules={name: 0 for name in RULE_NAMES})
    root = plan.root
    for _ in range(MAX_PASSES):
        pass_hits = 0
        for name, rule in _RULES:
            ctx = _Ctx(root, bound, bound_rows, scratch, float_inputs,
                       streaming, stats, backend, input_dtypes)
            try:
                new_root, n = rule(root, ctx)
            except PlanValidationError as bad:
                return _diagnose(name, bad)   # the rule itself blew up
            if new_root is not root:
                bad = _plan_error(new_root, bound)
                if bad is not None:
                    return _diagnose(name, bad)
            root = new_root
            pass_hits += n
        if not pass_hits:
            break
    if mesh_peers is not None and mesh_peers > 1:
        ctx = _Ctx(root, bound, bound_rows, scratch, float_inputs,
                   streaming, stats, backend, input_dtypes)
        try:
            new_root, _ = _plan_exchanges(root, ctx, mesh_peers)
        except PlanValidationError as bad:
            return _diagnose("exchange_planning", bad)
        bad = _plan_error(new_root, bound)
        if bad is not None:
            return _diagnose("exchange_planning", bad)
    return _diagnose("unknown", err)


# ---- pipeline ---------------------------------------------------------------

def optimize(plan: Plan,
             bound: Optional[Dict[str, Tuple[str, ...]]] = None,
             bound_rows: Optional[Dict[str, int]] = None,
             max_passes: int = MAX_PASSES,
             float_inputs: bool = False,
             streaming_sources=frozenset(),
             mesh_peers: Optional[int] = None,
             verify_rules: bool = False,
             stats=None,
             backend: Optional[str] = None,
             input_dtypes: Optional[Dict[str, Dict]] = None,
             placement: bool = False,
             placement_bytes: Optional[int] = None
             ) -> Tuple[Plan, OptimizeReport]:
    """Run the rule pipeline to fixpoint over `plan`. `bound` maps scan
    source -> actual column names and `bound_rows` -> actual row counts
    (execute() passes both; explain-time callers may pass neither and the
    schema/estimate-dependent rules degrade gracefully). `float_inputs`
    disables the build_side rule (execute() sets it when any bound column
    is floating point — fp reductions are not reorder-exact).
    `streaming_sources` names the scans bound to streaming (parquet)
    sources this execution — the scan_pruning rule fires only for those
    (a Scan carrying its own `parquet` binding qualifies regardless).
    `mesh_peers` (the meshed eager executor passes its mesh width) runs
    the `exchange_planning` rule once AFTER the fixpoint: Exchange(hash|
    broadcast|gather) boundaries are inserted/elided for the distributed
    tier (docs/distributed.md) — after, because the logical rules must
    not thrash against the physical boundary nodes they'd have to move
    through. `verify_rules` (the executor passes
    `config.verify_plans()`, on in tests) re-validates EVERY rule's
    output as it lands instead of only net-validating the pipeline's end
    state — the first invalid rewrite falls back immediately with a
    precise (rule, node, invariant) diagnostic in `report.fallback`.
    `stats` (a plan/stats.StatsStore) + `backend` make the estimator
    observation-driven (docs/adaptive.md): recorded subtree
    cardinalities for `backend` override the static estimate chain, and
    every build-side/exchange decision stamps its source on
    `report.decision_sources`. With stats=None (the
    SPARK_RAPIDS_TPU_STATS=off path) decisions are byte-identical to
    the static pipeline. `input_dtypes` (source -> {column: DType})
    enables the resource certifier's BYTE bounds
    (analysis/footprint.py): broadcast-join legality becomes a proven
    byte ceiling (`SPARK_RAPIDS_TPU_BROADCAST_BYTES`) and estimator
    dead-ends fall back to certified rows-hi bounds with a
    `certified:<bound>` decision source. `placement` (the executor
    passes `config.placement_enabled()`) runs the post-fixpoint
    co-placement pass (`_plan_placement`): HashJoin build sides
    annotated "host" on `report.placements` for the executor's
    overlapped host-thread dispatch — single-device walks only (a mesh
    execution keeps its exchange boundaries), annotation-only (the
    returned plan and fingerprint are placement-independent);
    `placement_bytes` overrides the cold certified-byte threshold.
    Returns the optimized Plan (the SAME object when nothing fired) +
    the report."""
    report = OptimizeReport(rules={name: 0 for name in RULE_NAMES})
    report.source_fingerprint = plan.fingerprint
    streaming = frozenset(streaming_sources)
    root = plan.root
    try:
        for p in range(max_passes):
            pass_hits = 0
            for name, rule in _RULES:
                ctx = _Ctx(root, bound, bound_rows, report, float_inputs,
                           streaming, stats, backend, input_dtypes)
                new_root, n = rule(root, ctx)
                if verify_rules and new_root is not root:
                    # post-optimize assertion, per rule: every rule's
                    # output must re-validate — the first invalid rewrite
                    # names itself instead of hiding behind the
                    # end-of-pipeline net
                    bad = _plan_error(new_root, bound)
                    if bad is not None:
                        report.passes = p + 1
                        report.fallback = _diagnose(name, bad)
                        return _fall_back(plan, report)
                root = new_root
                report.rules[name] += n
                pass_hits += n
            report.passes = p + 1
            if not pass_hits:
                break
        if mesh_peers is not None and mesh_peers > 1:
            ctx = _Ctx(root, bound, bound_rows, report, float_inputs,
                       streaming, stats, backend, input_dtypes)
            new_root, n = _plan_exchanges(root, ctx, mesh_peers)
            if verify_rules and new_root is not root:
                bad = _plan_error(new_root, bound)
                if bad is not None:
                    report.fallback = _diagnose("exchange_planning", bad)
                    return _fall_back(plan, report)
            root = new_root
            report.rules["exchange_planning"] += n
        if placement and (mesh_peers is None or mesh_peers <= 1):
            ctx = _Ctx(root, bound, bound_rows, report, float_inputs,
                       streaming, stats, backend, input_dtypes)
            report.rules["placement"] += _plan_placement(
                root, ctx, placement_bytes)
    except PlanValidationError as err:
        # an invalid mid-pipeline rewrite can detonate inside a LATER
        # rule's schema resolution (not just at the end-of-pipeline
        # re-validation) — that too is a fall-back, not a query failure,
        # and _attribute_fallback re-runs rule-by-rule to name the
        # culprit rather than the victim
        report.fallback = _attribute_fallback(
            plan, bound, bound_rows, float_inputs, streaming, mesh_peers,
            err, stats, backend, input_dtypes)
        return _fall_back(plan, report)
    if root is plan.root:
        report.fingerprint = report.source_fingerprint
        return plan, report
    try:
        opt = Plan(root)
        if bound:
            # declared schemas alone under-validate scans bound only at
            # execute(); the fall-back net must catch what execution would
            opt.resolve_schemas(bound)
    except PlanValidationError as err:
        # defensive: a rewrite produced an invalid DAG — run the authored
        # plan rather than failing the query, with the culprit rule/node/
        # invariant attributed post-hoc (analysis/verifier.py vocabulary)
        report.fallback = _attribute_fallback(
            plan, bound, bound_rows, float_inputs, streaming, mesh_peers,
            err, stats, backend, input_dtypes)
        return _fall_back(plan, report)
    report.fingerprint = opt.fingerprint
    return opt, report


def explain_optimized(plan: Plan,
                      bound: Optional[Dict[str, Tuple[str, ...]]] = None,
                      bound_rows: Optional[Dict[str, int]] = None) -> str:
    """Authored tree, optimized tree, and the per-rule rewrite summary —
    the `explain(plan, optimized=True)` rendering."""
    opt, report = optimize(plan, bound, bound_rows)
    return "\n".join(["== authored ==", plan.explain(), "",
                      "== optimized ==", opt.explain(), "",
                      report.summary()])
