"""Exchange transport layer: packed columnar wire format for the
distributed tier (docs/distributed.md#transport).

Every exchange used to ship raw per-column device arrays — one buffer per
column plus one full bool plane per nullable column — so shuffle cost
scaled with the relation's logical width rather than its information
content. This module packs each exchange payload into dense typed planes
with lightweight per-column encodings, chosen by cheap inspection and
with a STRICT pass-through whenever encoding would not pay (Thallus'
RDMA columnar batches and "Accelerating Presto with GPUs", PAPERS.md,
both ground the dense-batch + cheap-encoding design):

- **frame-of-reference (``for8/16/32``)** — an integer column whose live
  value range fits a narrower unsigned width ships as ``value - lo``
  in that width plus one static reference; exact for every live value.
  Static-shape, so it rides INSIDE the SPMD collectives (hash/range
  all-to-alls, sharded broadcasts).
- **bit-packed validity (``bitpack``)** — the nullable columns' bool
  planes (one byte per row each) collapse into one validity bit-word
  plane per 8 columns (one byte per row total). Also static-shape.
- **dictionary (``dict8/16``)** — a column with few distinct values
  ships as narrow codes plus a value table; **run-length (``rle``)** —
  a sorted/low-cardinality column ships as (values, run lengths). Both
  are dynamic-size, so they apply only where the payload is already
  host-materialized: the local build side of a broadcast join
  (`pack_host`), never inside a jitted collective.

Two accounting truths ride every packed edge (`plan/metrics.py`):
``exchange_bytes_logical`` — the unpacked per-column payload bytes the
edge represents (data itemsize + one validity byte per nullable column,
live rows only, each edge counted once) — and ``exchange_bytes`` (the
wire form): the packed bytes actually shipped. Exchange METADATA (live
masks, bucket counts, FOR references, dictionary/run side tables small
enough to ride the program) is not counted in either, the same
convention as the shuffle's `sent` counts. The static certifier's
per-edge payload bounds (analysis/footprint.py) are proven against the
wire form, so `wire <= certified hi` is a checkable inequality
(`footprint.check_observed`).

Knobs (config.py, read by the distributed tier at execution setup):
SPARK_RAPIDS_TPU_EXCHANGE_PACK (on/off), _EXCHANGE_CODECS
(auto/none/csv subset of for,dict,rle,bitpack), _EXCHANGE_ASYNC
(overlap exchange pack+transfer with downstream compute — see
plan/distributed.py). Pack off restores the byte-identical legacy
payload layout.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .. import dtypes
from ..columnar import Column

ALL_CODECS = frozenset({"for", "dict", "rle", "bitpack"})

__all__ = ["ALL_CODECS", "DevicePack", "HostPacked", "WordPlan",
           "logical_col_bytes", "logical_row_bytes", "narrow_words",
           "widen_words", "pack_device", "unpack_device",
           "unpack_device_np", "pack_host", "unpack_host",
           "unpack_host_device", "pack_bits_device", "unpack_bits_np"]


# ---- logical (unpacked) accounting ------------------------------------------

def logical_col_bytes(col: Column) -> int:
    """Unpacked payload bytes per row for one fixed-width column: the data
    itemsize plus one bool byte when a validity plane rides along."""
    return col.dtype.itemsize() + (1 if col.validity is not None else 0)


def logical_row_bytes(cols: Sequence[Column]) -> int:
    return sum(logical_col_bytes(c) for c in cols)


# ---- device-side static-shape packing (collective edges) --------------------

@dataclasses.dataclass(frozen=True)
class _ColPlan:
    """Static decode recipe for one packed column."""
    name: str
    dtype: dtypes.DType
    codec: str                  # "raw" | "for8" | "for16" | "for32"
    ref: int                    # frame-of-reference lo (exact python int)
    plane: int                  # data plane index
    vplane: int                 # validity plane index (-1: non-nullable)
    vbit: int                   # bit within a packed validity word
    #                             (-1: the validity plane is a raw bool)


@dataclasses.dataclass
class DevicePack:
    """One packed payload: `planes` are equal-length 1-D device arrays
    that ride a collective (or a host pull) in place of the raw columns;
    `plans` rebuild the columns. Byte fields are PER ROW."""
    plans: Tuple[_ColPlan, ...]
    planes: List
    n_planes: int
    wire_row_bytes: int
    logical_row_bytes: int
    codec_str: str


_FOR_TARGETS = ((8, jnp.uint8), (16, jnp.uint16), (32, jnp.uint32))


def _for_probe(col: Column, live):
    """Cheap inspection for frame-of-reference narrowing: one masked
    min/max reduce (two 8-byte host syncs) decides whether the column's
    LIVE value range fits a narrower unsigned plane. Returns (plane, lo,
    codec) or None (pass-through). Null slots are excluded from the
    range — their data is sentinel garbage no consumer reads."""
    st = np.dtype(col.data.dtype)
    if st.kind not in "iu" or st.itemsize < 2 or col.data.shape[0] == 0:
        return None
    mask = live if col.validity is None else (live & col.validity)
    info = jnp.iinfo(col.data.dtype)
    lo = int(jnp.min(jnp.where(mask, col.data, info.max)))
    hi = int(jnp.max(jnp.where(mask, col.data, info.min)))
    if lo > hi:         # no live rows: nothing to prove a range over
        return None
    if lo < -(1 << 63) or lo >= (1 << 63):
        # the reference must be an exact int64 (unsigned storage can
        # exceed it): pass through rather than wrap
        return None
    span = hi - lo
    for bits, tgt in _FOR_TARGETS:
        if bits // 8 >= st.itemsize:
            break
        if span < (1 << bits):
            plane = (col.data.astype(jnp.int64) - lo).astype(tgt)
            return plane, lo, f"for{bits}"
    return None


def pack_device(cols: Sequence[Column], names: Sequence[str], live,
                codecs: frozenset) -> DevicePack:
    """Pack fixed-width 1-D columns into dense wire planes with the
    static-shape codecs (FOR narrowing + bit-packed validity). `live` is
    the relation's live-row mask (the FOR inspection domain); the planes
    keep the input length — dead slots carry wrapped garbage that decode
    reproduces as garbage (never read). Pure pass-through (all-raw, raw
    bool validity planes) when `codecs` allows nothing."""
    planes: List = []
    plans: List[_ColPlan] = []
    notes: List[str] = []
    wire = 0
    logical = 0
    nullable: List[int] = []        # indices into `plans`
    for name, c in zip(names, cols):
        logical += logical_col_bytes(c)
        plane, ref, codec = c.data, 0, "raw"
        if "for" in codecs:
            probe = _for_probe(c, live)
            if probe is not None:
                plane, ref, codec = probe
                notes.append(f"{name}:{codec}")
        idx = len(planes)
        planes.append(plane)
        wire += np.dtype(plane.dtype).itemsize
        plans.append(_ColPlan(name=name, dtype=c.dtype, codec=codec,
                              ref=ref, plane=idx, vplane=-1, vbit=-1))
        if c.validity is not None:
            nullable.append(len(plans) - 1)
    if nullable and "bitpack" in codecs and len(nullable) >= 2:
        # one uint8 bit-word plane per 8 nullable columns, replacing one
        # full bool plane each
        for chunk0 in range(0, len(nullable), 8):
            chunk = nullable[chunk0:chunk0 + 8]
            word = jnp.zeros(live.shape, jnp.uint8)
            for bit, pi in enumerate(chunk):
                v = cols[pi].validity
                word = word | (v.astype(jnp.uint8) << np.uint8(bit))
                plans[pi] = dataclasses.replace(plans[pi],
                                                vplane=len(planes),
                                                vbit=bit)
            planes.append(word)
            wire += 1
        notes.append("validity:bitpack")
    else:
        for pi in nullable:
            plans[pi] = dataclasses.replace(plans[pi], vplane=len(planes),
                                            vbit=-1)
            planes.append(cols[pi].validity)
            wire += 1
    return DevicePack(plans=tuple(plans), planes=planes,
                      n_planes=len(planes), wire_row_bytes=wire,
                      logical_row_bytes=logical,
                      codec_str=",".join(notes))


def unpack_device(arrays: Sequence, pack: DevicePack) -> List[Column]:
    """Wire planes (post-collective) back to typed columns — the
    receiving shard's decode. Eager jnp elementwise; sharding/replication
    of the input planes propagates."""
    if not pack.plans:          # key-only payload: nothing rode along
        return []
    n = int(arrays[0].shape[0])
    out: List[Column] = []
    for p in pack.plans:
        raw = arrays[p.plane]
        if p.codec.startswith("for"):
            data = (jnp.int64(p.ref) + raw.astype(jnp.int64)).astype(
                p.dtype.storage_dtype())
        else:
            data = raw.astype(p.dtype.storage_dtype())
        validity = None
        if p.vplane >= 0:
            vp = arrays[p.vplane]
            if p.vbit >= 0:
                validity = ((vp >> np.uint8(p.vbit)) & np.uint8(1)) \
                    .astype(jnp.bool_)
            else:
                validity = vp.astype(jnp.bool_)
        out.append(Column(dtype=p.dtype, length=n, data=data,
                          validity=validity))
    return out


def unpack_device_np(arrays: Sequence[np.ndarray], pack: DevicePack
                     ) -> List[Tuple[np.ndarray, Optional[np.ndarray]]]:
    """Numpy mirror of `unpack_device` for host-pulled planes (the packed
    gather): returns [(data, validity-or-None)] full-length arrays."""
    out = []
    for p in pack.plans:
        raw = arrays[p.plane]
        if p.codec.startswith("for"):
            data = (p.ref + raw.astype(np.int64)).astype(
                np.dtype(p.dtype.storage_dtype()))
        else:
            data = raw
        validity = None
        if p.vplane >= 0:
            vp = arrays[p.vplane]
            validity = (((vp >> p.vbit) & 1) if p.vbit >= 0 else vp) \
                .astype(bool)
        out.append((data, validity))
    return out


# ---- key-word narrowing (hash-exchange edges) -------------------------------

@dataclasses.dataclass(frozen=True)
class WordPlan:
    """Static decode recipe for one key-word plane of a hash exchange
    (the 64-bit order-preserving words of parallel/keys.py). `codec` is
    "raw" (the plane ships as its int64 word) or "forN" (it ships as
    `word - ref` in the narrow unsigned width); `ref` is an exact
    Python int. `nbytes` is the plane's wire bytes per row."""
    codec: str
    ref: int
    nbytes: int


def narrow_words(words: Sequence, live
                 ) -> Tuple[List, Tuple[WordPlan, ...], int, str]:
    """FOR-narrow the int64 key-word planes a hash exchange ships.

    Key columns used to ride hash edges at a flat 8 B per word (the
    "never narrowed" remainder of the packed wire format): the words are
    the HASH input, and the Spark-exact murmur must see them at full
    width inside the collective body. Narrowing is still sound because
    the hash input and the wire form need not be the same arrays — the
    exchange widens each narrowed plane back to its exact word
    (`ref + narrow.astype(int64)`) for the hash, then ships the narrow
    plane (parallel/relational.distributed_repartition_keyed). Placement
    is bit-identical; only the wire narrows.

    Same inspection discipline as `_for_probe`: one masked min/max
    reduce per plane over the LIVE rows — eager reduces over sharded
    arrays are global, so every shard derives the same reference — with
    exact reconstruction for every live slot (null-key rows' data words
    are zeroed at encode time, so they sit inside the probed range).
    Dead slots ship wrapped garbage no consumer reads (decode zeroes
    them under the alive mask). Null-flag words (0/1) narrow to one
    byte for free. The certifier keeps pricing key words at 8 B each
    (analysis/footprint.py) — a sound hi-bound the narrowed wire only
    ever undershoots.

    Returns (planes, plans, wire_bytes_per_row, codec_note); an all-raw
    outcome returns the input planes and an empty note."""
    planes: List = []
    plans: List[WordPlan] = []
    notes: List[str] = []
    wire = 0
    info = jnp.iinfo(jnp.int64)
    for i, w in enumerate(words):
        plan = WordPlan("raw", 0, 8)
        plane = w
        if w.shape[0]:
            lo = int(jnp.min(jnp.where(live, w, info.max)))
            hi = int(jnp.max(jnp.where(live, w, info.min)))
            if lo <= hi:                # any live rows at all
                span = hi - lo          # exact (host ints)
                for bits, tgt in _FOR_TARGETS:
                    if span < (1 << bits):
                        plane = (w - jnp.int64(lo)).astype(tgt)
                        plan = WordPlan(f"for{bits}", lo, bits // 8)
                        notes.append(f"key{i}:for{bits}")
                        break
        planes.append(plane)
        plans.append(plan)
        wire += plan.nbytes
    return planes, tuple(plans), wire, ",".join(notes)


def widen_words(planes: Sequence, plans: Sequence[WordPlan]) -> List:
    """Inverse of `narrow_words` for RECEIVED planes (outside the
    collective): each narrowed plane back to its exact int64 word array.
    Dead slots widen to garbage no consumer reads — the relation's
    alive mask owns liveness, and key decode zeroes dead words."""
    return [p if wp.codec == "raw"
            else (jnp.int64(wp.ref) + p.astype(jnp.int64))
            for p, wp in zip(planes, plans)]


def pack_bits_device(mask) -> Tuple[object, int]:
    """Bit-pack a (n,) bool device array column-wise into a uint8 plane of
    ceil(n/8) bytes (the packed gather's live-mask wire form). Returns
    (plane, n)."""
    n = int(mask.shape[0])
    pad = (-n) % 8
    m = mask.astype(jnp.uint8)
    if pad:
        m = jnp.concatenate([m, jnp.zeros((pad,), jnp.uint8)])
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))[None, :]
    return jnp.sum(m.reshape(-1, 8) * weights, axis=1,
                   dtype=jnp.uint8), n


def unpack_bits_np(plane: np.ndarray, n: int) -> np.ndarray:
    bits = np.unpackbits(np.asarray(plane, np.uint8), bitorder="little")
    return bits[:n].astype(bool)


# ---- host-side codecs (materialized edges) ----------------------------------

@dataclasses.dataclass
class _HostColPlan:
    name: str
    dtype: dtypes.DType
    codec: str                        # raw | forN | dictN | rle
    ref: int
    data: Optional[np.ndarray]        # raw/for plane or dict codes
    values: Optional[np.ndarray]      # dict/rle value table
    lengths: Optional[np.ndarray]     # rle run lengths (int32)
    validity: Optional[np.ndarray]    # packbits bitmask or raw bool
    vpacked: bool


@dataclasses.dataclass
class HostPacked:
    """A host-materialized payload in wire form (the broadcast build
    side). `wire_bytes`/`logical_bytes` cover the WHOLE payload once
    (multiply by peers-1 for a broadcast)."""
    n: int
    cols: List[_HostColPlan]
    names: Tuple[str, ...]
    wire_bytes: int
    logical_bytes: int
    codec_str: str


def _host_encode_int(a: np.ndarray, codecs: frozenset):
    """Pick the cheapest host codec for one integer array by exact byte
    comparison; strict pass-through when nothing is smaller than raw.
    Returns (codec, data, values, lengths, ref, wire_bytes)."""
    n = a.shape[0]
    item = a.dtype.itemsize
    raw = n * item
    best = ("raw", a, None, None, 0, raw)
    if n == 0:
        return best
    if "rle" in codecs:
        bounds = np.empty(n, bool)
        bounds[0] = True
        np.not_equal(a[1:], a[:-1], out=bounds[1:])
        starts = np.nonzero(bounds)[0]
        runs = starts.shape[0]
        rle_bytes = runs * (item + 4)
        if rle_bytes < best[5]:
            lengths = np.diff(np.append(starts, n)).astype(np.int32)
            best = ("rle", None, a[starts], lengths, 0, rle_bytes)
    if "dict" in codecs:
        uniq = np.unique(a)
        for bits, ct in ((8, np.uint8), (16, np.uint16)):
            if uniq.shape[0] <= (1 << bits):
                d_bytes = n * (bits // 8) + uniq.nbytes
                if d_bytes < best[5]:
                    codes = np.searchsorted(uniq, a).astype(ct)
                    best = (f"dict{bits}", codes, uniq, None, 0, d_bytes)
                break
    if "for" in codecs and item >= 2:
        lo, hi = int(a.min()), int(a.max())
        span = hi - lo
        for bits, ct in ((8, np.uint8), (16, np.uint16), (32, np.uint32)):
            if bits // 8 >= item:
                break
            if span < (1 << bits):
                f_bytes = n * (bits // 8)
                if f_bytes < best[5]:
                    best = (f"for{bits}",
                            (a.astype(np.int64) - lo).astype(ct),
                            None, None, lo, f_bytes)
                break
    return best


def pack_host(cols: Sequence[Column], names: Sequence[str],
              codecs: frozenset) -> HostPacked:
    """Encode a host-materializable table payload (dynamic-size codecs
    allowed — the payload is concrete). Lossless for every slot,
    including null-slot data (codecs encode the actual values)."""
    out: List[_HostColPlan] = []
    notes: List[str] = []
    wire = 0
    logical = 0
    n = int(cols[0].length) if cols else 0
    for name, c in zip(names, cols):
        logical += logical_col_bytes(c) * n
        a = np.asarray(c.data)
        codec, data, values, lengths, ref = "raw", a, None, None, 0
        if np.dtype(a.dtype).kind in "iu" and c.dtype.kind != dtypes.Kind.BOOL:
            codec, data, values, lengths, ref, _ = \
                _host_encode_int(a, codecs)
        wire += sum(x.nbytes for x in (data, values, lengths)
                    if x is not None)
        if codec != "raw":
            notes.append(f"{name}:{codec}")
        validity, vpacked = None, False
        if c.validity is not None:
            v = np.asarray(c.validity)
            if "bitpack" in codecs:
                validity = np.packbits(v, bitorder="little")
                vpacked = True
            else:
                validity = v
            wire += validity.nbytes
        out.append(_HostColPlan(name=name, dtype=c.dtype, codec=codec,
                                ref=ref, data=data, values=values,
                                lengths=lengths, validity=validity,
                                vpacked=vpacked))
    if any(p.vpacked for p in out):
        notes.append("validity:bitpack")
    return HostPacked(n=n, cols=out, names=tuple(names), wire_bytes=wire,
                      logical_bytes=logical, codec_str=",".join(notes))


def _host_decode_np(p: _HostColPlan) -> np.ndarray:
    if p.codec == "raw":
        return p.data
    if p.codec.startswith("for"):
        return (p.ref + p.data.astype(np.int64)).astype(
            np.dtype(p.dtype.storage_dtype()))
    if p.codec.startswith("dict"):
        return p.values[p.data]
    if p.codec == "rle":
        return np.repeat(p.values, p.lengths)
    raise ValueError(f"unknown host codec {p.codec!r}")


def unpack_host(packed: HostPacked) -> List[Column]:
    """Pure-numpy round trip (tests + host-side consumers)."""
    out = []
    for p in packed.cols:
        data = _host_decode_np(p)
        validity = None
        if p.validity is not None:
            v = unpack_bits_np(p.validity, packed.n) if p.vpacked \
                else p.validity.astype(bool)
            validity = jnp.asarray(v)
        out.append(Column(dtype=p.dtype, length=packed.n,
                          data=jnp.asarray(data), validity=validity))
    return out


def unpack_host_device(packed: HostPacked, put) -> List[Column]:
    """Decode a HostPacked payload ON DEVICE: `put` lifts each wire plane
    (e.g. `jax.device_put(..., replicated)`), and the decode runs as
    eager jnp over the lifted planes, so the decoded columns keep the
    planes' placement — the broadcast's 'unpack on the receiving shard'.
    """
    out = []
    for p in packed.cols:
        st = p.dtype.storage_dtype()
        if p.codec == "raw":
            data = put(jnp.asarray(p.data))
        elif p.codec.startswith("for"):
            data = (jnp.int64(p.ref)
                    + put(jnp.asarray(p.data)).astype(jnp.int64)).astype(st)
        elif p.codec.startswith("dict"):
            data = jnp.take(put(jnp.asarray(p.values)),
                            put(jnp.asarray(p.data)).astype(jnp.int32),
                            axis=0)
        elif p.codec == "rle":
            data = jnp.repeat(put(jnp.asarray(p.values)),
                              put(jnp.asarray(p.lengths)),
                              total_repeat_length=packed.n)
        else:
            raise ValueError(f"unknown host codec {p.codec!r}")
        validity = None
        if p.validity is not None:
            if p.vpacked:
                vp = put(jnp.asarray(p.validity))
                idx = jnp.arange(packed.n, dtype=jnp.int32)
                validity = ((jnp.take(vp, idx >> 3, axis=0)
                             >> (idx & 7).astype(jnp.uint8))
                            & np.uint8(1)).astype(jnp.bool_)
            else:
                validity = put(jnp.asarray(p.validity)).astype(jnp.bool_)
        out.append(Column(dtype=p.dtype, length=packed.n, data=data,
                          validity=validity))
    return out


# ---- codec-set resolution ---------------------------------------------------

def resolve_codecs(spec: str) -> frozenset:
    """Config string -> codec set: 'auto' = all, 'none' = layout-only
    pass-through (no per-column encodings, raw validity planes), else a
    comma list validated against the catalog (strict-typo policy)."""
    if spec == "auto":
        return ALL_CODECS
    if spec == "none":
        return frozenset()
    chosen = frozenset(s.strip() for s in spec.split(",") if s.strip())
    unknown = chosen - ALL_CODECS
    if unknown:
        raise ValueError(
            f"unknown exchange codec(s) {sorted(unknown)} "
            f"(expected a subset of {sorted(ALL_CODECS)}, 'auto', or "
            "'none')")
    return chosen
