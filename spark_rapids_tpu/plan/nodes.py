"""Typed physical-plan operator nodes.

Each node is an immutable dataclass over child nodes — together a DAG
(shared subtrees execute ONCE per run: q23's two reused subqueries are the
same node object on both sides). Nodes carry only the logical parameters;
execution strategy (eager kernels vs capped whole-plan jit vs the
distributed tier behind `Exchange`) is the executor's concern, exactly as
the reference plugin lowers one Catalyst plan onto different kernel tiers.

`output_names(child_schemas)` is the single place each operator's schema
contract lives; `builder.validate` and the executor both call it, so a
schema error raises the same `PlanValidationError` whether it is caught at
build time (declared scan schemas) or at bind time (inferred from the bound
tables).
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Optional, Tuple

from .expr import Expr

JOIN_TYPES = ("inner", "left_semi", "left_anti")
AGG_OPS = ("sum", "count", "min", "max", "mean", "size")   # ops.aggregate.AGG_OPS

_ids = itertools.count()


class PlanValidationError(ValueError):
    """A plan failed schema/reference validation."""


def _require(cond: bool, msg: str):
    if not cond:
        raise PlanValidationError(msg)


@dataclasses.dataclass(frozen=True, eq=False)
class PlanNode:
    def __post_init__(self):
        object.__setattr__(self, "_id", next(_ids))

    @property
    def kind(self) -> str:
        return type(self).__name__

    @property
    def label(self) -> str:
        return f"{self.kind}#{self._id}"

    @property
    def children(self) -> Tuple["PlanNode", ...]:
        return ()

    def output_names(self, child_schemas) -> Tuple[str, ...]:
        """Output column names given the children's schemas (validates)."""
        raise NotImplementedError

    def describe(self) -> str:
        """One-line parameter summary for explain()."""
        return ""


@dataclasses.dataclass(frozen=True, eq=False)
class Scan(PlanNode):
    """Leaf: one named input relation, bound at execute() to a concrete
    Table (`inputs={name: table}`) or a streaming source (an
    `io.ParquetSource`, either via `inputs=` or attached here as
    `parquet` by `PlanBuilder.scan(parquet=...)`). A declared `schema`
    validates at build time and is checked against the binding.
    `projection` (set by the optimizer's column-pruning rule) narrows the
    output to a subset of the bound columns — unpruned columns never
    enter the plan; on a parquet source they are never even DECODED.
    `predicate` (set by the optimizer's scan_pruning rule) is a
    PRUNING-ONLY hint: row groups whose footer min/max statistics prove
    it matches nothing are skipped, while the authoring Filter stays
    above for exact semantics — it never changes the result, only the
    bytes decoded. `est_rows` is an optional cardinality hint for the
    optimizer's build-side selection when no table is bound yet."""
    source: str
    schema: Optional[Tuple[str, ...]] = None
    projection: Optional[Tuple[str, ...]] = None
    est_rows: Optional[int] = None
    predicate: Optional[Expr] = None
    parquet: Optional[object] = None    # io.ParquetSource (not fingerprinted)

    def __post_init__(self):
        super().__post_init__()
        if self.schema is not None:
            object.__setattr__(self, "schema", tuple(self.schema))
        if self.projection is not None:
            object.__setattr__(self, "projection", tuple(self.projection))

    def output_names(self, child_schemas):
        _require(self.schema is not None,
                 f"{self.label}: schema for input {self.source!r} is unknown "
                 "(declare it at scan() or bind inputs)")
        if self.predicate is not None:
            # pruning predicates compare FILE columns (they need not be
            # projected: stats come from the footer, not decoded data)
            missing = self.predicate.references() - set(self.schema)
            _require(not missing,
                     f"{self.label}: pruning predicate references unknown "
                     f"column(s) {sorted(missing)}")
        return self.apply_projection(self.schema)

    def apply_projection(self, schema) -> Tuple[str, ...]:
        """Narrowed output over a (declared or bound) full schema."""
        if self.projection is None:
            return tuple(schema)
        missing = set(self.projection) - set(schema)
        _require(not missing,
                 f"{self.label}: projected column(s) {sorted(missing)} not "
                 f"in {list(schema)}")
        return self.projection

    def describe(self):
        out = self.source
        if self.parquet is not None:
            out += " (parquet)"
        if self.projection is not None:
            out += f" [{', '.join(self.projection)}]"
        if self.predicate is not None:
            out += f" prune[{self.predicate!r}]"
        return out


@dataclasses.dataclass(frozen=True, eq=False)
class Filter(PlanNode):
    child: PlanNode
    predicate: Expr

    @property
    def children(self):
        return (self.child,)

    def output_names(self, child_schemas):
        (schema,) = child_schemas
        missing = self.predicate.references() - set(schema)
        _require(not missing,
                 f"{self.label}: predicate references unknown column(s) "
                 f"{sorted(missing)} (have {list(schema)})")
        return schema

    def describe(self):
        return repr(self.predicate)


@dataclasses.dataclass(frozen=True, eq=False)
class Project(PlanNode):
    """Full projection: the output is exactly `exprs` [(name, Expr)]."""
    child: PlanNode
    exprs: Tuple[Tuple[str, Expr], ...]

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "exprs", tuple(
            (n, e) for n, e in self.exprs))

    @property
    def children(self):
        return (self.child,)

    def output_names(self, child_schemas):
        (schema,) = child_schemas
        names = [n for n, _ in self.exprs]
        _require(len(set(names)) == len(names),
                 f"{self.label}: duplicate output name in {names}")
        for n, e in self.exprs:
            missing = e.references() - set(schema)
            _require(not missing,
                     f"{self.label}: {n!r} references unknown column(s) "
                     f"{sorted(missing)} (have {list(schema)})")
        return tuple(names)

    def describe(self):
        return ", ".join(f"{e!r} AS {n}" for n, e in self.exprs)


@dataclasses.dataclass(frozen=True, eq=False)
class FusedSelect(PlanNode):
    """Filter + Project in one operator (optimizer-produced: the
    `select_fusion` rule rewrites Project(Filter(c)) into this). Semantics:
    rows passing `predicate` (over the CHILD schema), projected to `exprs`.
    The eager tier gathers only the projection-referenced columns once,
    instead of materializing the full filtered child and projecting it."""
    child: PlanNode
    predicate: Expr
    exprs: Tuple[Tuple[str, Expr], ...]

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "exprs", tuple(
            (n, e) for n, e in self.exprs))

    @property
    def children(self):
        return (self.child,)

    def output_names(self, child_schemas):
        (schema,) = child_schemas
        missing = self.predicate.references() - set(schema)
        _require(not missing,
                 f"{self.label}: predicate references unknown column(s) "
                 f"{sorted(missing)} (have {list(schema)})")
        names = [n for n, _ in self.exprs]
        _require(len(set(names)) == len(names),
                 f"{self.label}: duplicate output name in {names}")
        for n, e in self.exprs:
            missing = e.references() - set(schema)
            _require(not missing,
                     f"{self.label}: {n!r} references unknown column(s) "
                     f"{sorted(missing)} (have {list(schema)})")
        return tuple(names)

    def describe(self):
        proj = ", ".join(f"{e!r} AS {n}" for n, e in self.exprs)
        return f"{self.predicate!r} -> {proj}"


@dataclasses.dataclass(frozen=True, eq=False)
class HashJoin(PlanNode):
    """Equi-join on key column lists. `inner` outputs left++right columns;
    semi/anti output the left columns only (the right side is a filter).
    `row_cap`, when set, overrides the executor's shared row cap for this
    node in the capped tier."""
    left: PlanNode
    right: PlanNode
    left_keys: Tuple[str, ...]
    right_keys: Tuple[str, ...]
    how: str = "inner"
    row_cap: Optional[int] = None

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "left_keys", tuple(self.left_keys))
        object.__setattr__(self, "right_keys", tuple(self.right_keys))
        _require(self.how in JOIN_TYPES,
                 f"{self.label}: join type {self.how!r} not in {JOIN_TYPES}")
        _require(len(self.left_keys) == len(self.right_keys) > 0,
                 f"{self.label}: key lists must be equal-length and "
                 f"non-empty (got {self.left_keys} vs {self.right_keys})")

    @property
    def children(self):
        return (self.left, self.right)

    def output_names(self, child_schemas):
        lschema, rschema = child_schemas
        missing = set(self.left_keys) - set(lschema)
        _require(not missing, f"{self.label}: left key(s) {sorted(missing)} "
                              f"not in {list(lschema)}")
        missing = set(self.right_keys) - set(rschema)
        _require(not missing, f"{self.label}: right key(s) {sorted(missing)} "
                              f"not in {list(rschema)}")
        if self.how != "inner":
            return lschema
        dup = set(lschema) & set(rschema)
        _require(not dup,
                 f"{self.label}: output name collision {sorted(dup)} — "
                 "project/rename one side first")
        return lschema + rschema

    def describe(self):
        on = ", ".join(f"{l} = {r}"
                       for l, r in zip(self.left_keys, self.right_keys))
        return f"{self.how} ({on})"


@dataclasses.dataclass(frozen=True, eq=False)
class HashAggregate(PlanNode):
    """Group by `keys`, computing `aggs` [(column, op, out_name)]; empty
    `keys` is a global (one-row) aggregate. Output schema: keys ++ out
    names. `key_cap` overrides the executor's shared key cap."""
    child: PlanNode
    keys: Tuple[str, ...]
    aggs: Tuple[Tuple[str, str, str], ...]
    key_cap: Optional[int] = None

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "keys", tuple(self.keys))
        object.__setattr__(self, "aggs", tuple(
            (c, o, n) for c, o, n in self.aggs))
        _require(len(self.aggs) > 0,
                 f"{self.label}: at least one aggregation is required")
        for c, o, n in self.aggs:
            _require(o in AGG_OPS,
                     f"{self.label}: unknown aggregation {o!r} (have "
                     f"{AGG_OPS})")
        if not self.keys:
            for c, o, n in self.aggs:
                _require(o in ("sum", "min", "max", "count", "size"),
                         f"{self.label}: global {o!r} is not supported "
                         "(sum/min/max/count/size only)")

    @property
    def children(self):
        return (self.child,)

    def output_names(self, child_schemas):
        (schema,) = child_schemas
        missing = set(self.keys) - set(schema)
        _require(not missing, f"{self.label}: group key(s) "
                              f"{sorted(missing)} not in {list(schema)}")
        for c, o, n in self.aggs:
            _require(o == "size" or c in schema,
                     f"{self.label}: aggregated column {c!r} not in "
                     f"{list(schema)}")
        names = list(self.keys) + [n for _, _, n in self.aggs]
        _require(len(set(names)) == len(names),
                 f"{self.label}: duplicate output name in {names}")
        return tuple(names)

    def describe(self):
        aggs = ", ".join(f"{o}({c}) AS {n}" for c, o, n in self.aggs)
        return f"keys=[{', '.join(self.keys)}] {aggs}"


@dataclasses.dataclass(frozen=True, eq=False)
class Sort(PlanNode):
    child: PlanNode
    keys: Tuple[str, ...]
    ascending: Tuple[bool, ...] = ()

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "keys", tuple(self.keys))
        asc = self.ascending
        if isinstance(asc, bool):
            asc = (asc,) * len(self.keys)
        elif not asc:
            asc = (True,) * len(self.keys)
        object.__setattr__(self, "ascending", tuple(asc))
        _require(len(self.keys) > 0, f"{self.label}: needs sort keys")
        _require(len(self.ascending) == len(self.keys),
                 f"{self.label}: ascending list must match the key count")

    @property
    def children(self):
        return (self.child,)

    def output_names(self, child_schemas):
        (schema,) = child_schemas
        missing = set(self.keys) - set(schema)
        _require(not missing, f"{self.label}: sort key(s) "
                              f"{sorted(missing)} not in {list(schema)}")
        return schema

    def describe(self):
        return ", ".join(f"{k} {'ASC' if a else 'DESC'}"
                         for k, a in zip(self.keys, self.ascending))


@dataclasses.dataclass(frozen=True, eq=False)
class TopK(PlanNode):
    """Sort + Limit in one operator (optimizer-produced: the
    `limit_pushdown` rule rewrites Limit(Sort(c)) into this). Output: the
    first `n` rows of the sorted relation — one operator, one metrics row,
    one traversal step in both tiers."""
    child: PlanNode
    keys: Tuple[str, ...]
    ascending: Tuple[bool, ...]
    n: int

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "keys", tuple(self.keys))
        object.__setattr__(self, "ascending", tuple(self.ascending))
        _require(len(self.keys) > 0, f"{self.label}: needs sort keys")
        _require(len(self.ascending) == len(self.keys),
                 f"{self.label}: ascending list must match the key count")
        _require(self.n >= 0, f"{self.label}: negative limit {self.n}")

    @property
    def children(self):
        return (self.child,)

    def output_names(self, child_schemas):
        (schema,) = child_schemas
        missing = set(self.keys) - set(schema)
        _require(not missing, f"{self.label}: sort key(s) "
                              f"{sorted(missing)} not in {list(schema)}")
        return schema

    def describe(self):
        keys = ", ".join(f"{k} {'ASC' if a else 'DESC'}"
                         for k, a in zip(self.keys, self.ascending))
        return f"top {self.n} by {keys}"


@dataclasses.dataclass(frozen=True, eq=False)
class Limit(PlanNode):
    child: PlanNode
    n: int

    def __post_init__(self):
        super().__post_init__()
        _require(self.n >= 0, f"{self.label}: negative limit {self.n}")

    @property
    def children(self):
        return (self.child,)

    def output_names(self, child_schemas):
        return child_schemas[0]

    def describe(self):
        return str(self.n)


@dataclasses.dataclass(frozen=True, eq=False)
class Union(PlanNode):
    """UNION ALL of same-schema inputs (by name, positional)."""
    inputs: Tuple[PlanNode, ...]

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "inputs", tuple(self.inputs))
        _require(len(self.inputs) >= 2,
                 f"{self.label}: needs at least two inputs")

    @property
    def children(self):
        return self.inputs

    def output_names(self, child_schemas):
        first = child_schemas[0]
        for s in child_schemas[1:]:
            _require(tuple(s) == tuple(first),
                     f"{self.label}: input schemas differ: {list(first)} vs "
                     f"{list(s)}")
        return first

    def describe(self):
        return f"{len(self.inputs)} inputs"


EXCHANGE_KINDS = ("hash", "broadcast", "gather", "identity")


@dataclasses.dataclass(frozen=True, eq=False)
class Exchange(PlanNode):
    """Distribution boundary (Spark's ShuffleExchangeExec /
    BroadcastExchangeExec slot) — a REAL physical node on the distributed
    tier (docs/distributed.md). `how` selects the movement:

    - ``hash``: rows move to the shard given by the Spark-exact hash of
      `keys` (pmod n_peers) — the shuffle boundary below shuffle joins and
      two-phase aggregates. A HashAggregate directly above a hash Exchange
      FUSES into the partial-agg → all-to-all → final-agg SPMD program
      (the exchange ships per-group partials, not rows).
    - ``broadcast``: the (small) relation is replicated onto every shard
      over ICI; a join above it probes locally and its other side never
      moves.
    - ``gather``: the sharded relation collects onto one device — the
      sink boundary (or the handoff into an operator with no distributed
      form).
    - ``identity``: no movement (the pre-distributed-tier marker shape;
      also what every Exchange is on a single chip, where the whole node
      is a no-op).

    The optimizer's `exchange_planning` rule inserts and elides these from
    sharding requirements and row-count estimates; `keys` is required for
    ``hash`` and ignored otherwise."""
    child: PlanNode
    keys: Tuple[str, ...] = ()
    how: str = ""

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "keys", tuple(self.keys))
        if not self.how:
            # back-compat default: a keyed Exchange was always the hash
            # marker, a keyless one the identity marker
            object.__setattr__(self, "how",
                               "hash" if self.keys else "identity")
        _require(self.how in EXCHANGE_KINDS,
                 f"{self.label}: exchange kind {self.how!r} not in "
                 f"{EXCHANGE_KINDS}")
        _require(self.how != "hash" or len(self.keys) > 0,
                 f"{self.label}: hash exchange needs partition keys")

    @property
    def children(self):
        return (self.child,)

    def output_names(self, child_schemas):
        (schema,) = child_schemas
        missing = set(self.keys) - set(schema)
        _require(not missing, f"{self.label}: partition key(s) "
                              f"{sorted(missing)} not in {list(schema)}")
        return schema

    def describe(self):
        if self.how == "hash":
            return f"hash[{', '.join(self.keys)}]"
        return self.how
