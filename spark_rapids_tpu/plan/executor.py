"""Plan executor: walks the operator DAG and runs it on one of three tiers.

Before tier dispatch, `execute()` runs the rule-based logical optimizer
(`plan/optimizer.py`, docs/optimizer.md) over the bound plan — column
pruning, predicate/limit pushdown, constant folding, Filter+Project
fusion, join build-side selection — and executes the rewritten DAG;
`SPARK_RAPIDS_TPU_OPTIMIZER=off` or `PlanExecutor(optimize=False)`
disables it. `PlanResult.optimizer` reports what fired.

- `mode="eager"`: per-operator dispatch through the public `ops` kernels —
  every operator gets its own wall-clock, rows/bytes metrics, a
  `utils.tracing` range, a plan-level faultinj interception point, and a
  bounded, backoff-paced re-run on recoverable injected faults (the
  plan-level retry that replaces per-query hand-wiring).
- `mode="capped"`: the whole DAG traces into ONE XLA program with static
  capacities (`row_cap` for joins, `key_cap` for aggregates — per-node
  overrides take precedence). A too-small cap raises the overflow flag and
  `parallel.autoretry.auto_retry_overflow` grows every cap geometrically
  and re-traces — SplitAndRetry at PLAN granularity, not per-call. The
  compiled program is cached per (plan FINGERPRINT, caps, input
  shapes+names) and the final capacities are memoized per fingerprint, so
  escalated caps are remembered for the rest of the job AND structurally
  identical plans built independently share compiled programs
  (`optimizer.plan_fingerprint`).
- distributed (eager tier only — execute() rejects a mesh with
  mode="capped" when the plan contains a distributed-lowerable operator):
  when a device `mesh` is given, the whole plan runs as SPMD over the mesh
  (plan/distributed.py, docs/distributed.md): Scans shard row-wise,
  Filter/Project stay elementwise-sharded, joins run shuffle
  (hash-exchange both sides) or broadcast (replicate the small build
  side, chosen by the optimizer's `exchange_planning` rule from row
  estimates), aggregates fuse the two-phase partial→all-to-all→final
  program behind their `Exchange` (elided entirely when the input is
  already partitioned by a subset of the group keys), Sort/TopK
  sample-sort to global order, and the result gathers to one device only
  at the sink — or at the first operator with no distributed form, the
  same graceful-boundary pattern as the streaming tier's concat. All
  static capacities escalate via `parallel.autoretry` and memoize per
  plan fingerprint.

Admission (`runtime.admission`) applies per operator automatically: the
executor calls the public `ops` surface through module attribute lookup, so
the admission wrappers — and any installed faultinj shims — intercept every
kernel the plan dispatches. Pass `session=` to scope a DeviceSession to the
execution without touching process-global state.

Failure handling is a *policy*, owned by `runtime.health` (docs/
robustness.md): transient faults (injected nonfatal asserts, substituted
return codes, RetryOOM spikes) retry with jittered exponential backoff
against a per-plan-attempt retry budget; sticky (same op keeps failing) and
fatal (`DeviceFatalError`) failures trip the circuit breaker and — with the
default `degrade="cpu"` — the remaining plan re-executes on the CPU backend
tier, salvaging completed operator outputs through host memory. `explain()`
is unchanged; `profile()`/`PlanResult` record `degraded`, `backoff_ms`, and
the breaker snapshot so a degraded run is visible after the fact. While the
breaker is open the device is quarantined (plans run fully degraded);
`health.reset_device()` arms a half-open probation and a cheap heartbeat
probe op decides whether normal execution resumes.

Results carry `profile()` — per-operator rows (live rows in the capped
tier, computed on-device and returned with the result), output buffer
bytes, wall time, retry and cap-escalation counts.

Feedback loop (plan/stats.py, docs/adaptive.md): after every successful
execution the per-op metrics, final caps, and kernel timings record into
the per-fingerprint stats store under the backend the result ran on
("cpu" for degraded results). The next execution of the same fingerprint
consumes them — observed cardinalities re-pick join build sides and
exchange modes (through `optimize(stats=...)`, every stats-driven
rewrite re-verified), the capped tier seeds its caps at the observed
high-water (no escalation ladder on warm runs), the streaming tier sizes
morsels from observed decode throughput, and the kernel registry demotes
kernels that benched slower than their fallback. `SPARK_RAPIDS_TPU_STATS
=off` restores fully static behavior.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes
from ..columnar import Column, Table
from .builder import Plan
from .metrics import OperatorMetrics, render_profile
from .nodes import (Exchange, Filter, FusedSelect, HashAggregate, HashJoin,
                    Limit, PlanNode, PlanValidationError, Project, Scan,
                    Sort, TopK, Union)
from .expr import ColumnRef

# The device-fault surface the executor turns into policy (runtime/health):
# injected nonfatal asserts and substituted return codes plus RetryOOM
# pressure spikes classify transient (jittered backoff + budgeted retry);
# DeviceFatalError classifies fatal and is NEVER retried on the device —
# a dead device must stop the retry loop, that is the whole point of the
# fatal tier. Sticky/fatal failures trip the breaker; with degrade="cpu"
# the remaining plan re-executes on the CPU backend tier.
def _fault_surface():
    from .. import faultinj
    from ..runtime.adaptor import CpuRetryOOM, RetryOOM
    return (faultinj.DeviceFatalError, faultinj.DeviceAssertError,
            faultinj.InjectedReturnCode, RetryOOM, CpuRetryOOM)


def _ops():
    # attribute lookups on the module keep admission + faultinj shims live
    from .. import ops
    return ops


def _sessionctx():
    from ..runtime import sessionctx
    return sessionctx


# one bounded-cache definition for the whole engine (utils/lru.py): the
# executor's program/caps memos and the optimizer cache share it
from ..utils.lru import LruDict as _LruDict


def bind_scan_sources(plan: Plan, inputs: Optional[Dict]) -> Dict:
    """The ONE scan-binding prologue: a Scan carrying its own parquet
    binding needs no inputs= entry; an explicit entry (Table or source)
    for the same name wins. Shared by execute() and the serving layer's
    submit path (serving/scheduler.py) — the binding the cache digest and
    quota charge are computed from must be the binding that executes."""
    inputs = dict(inputs or {})
    for s in plan.scans:
        if s.source not in inputs and s.parquet is not None:
            inputs[s.source] = s.parquet
    return inputs


def _cpu_device():
    try:
        return jax.devices("cpu")[0]
    except Exception:
        return None


def _table_to_cpu(t: Table, dev) -> Table:
    """Salvage a table onto the CPU backend through host memory (the
    degraded tier's handoff for results computed before the breaker
    tripped). Distributed-tier sharded relations gather + compact first
    (their live rows ARE the relation). Streaming source bindings pass
    through untouched — they are host-side handles the CPU tier re-reads
    directly."""
    import dataclasses

    if hasattr(t, "to_local_table"):          # plan.distributed.ShardedRel
        t = t.to_local_table()
    if not isinstance(t, Table):
        return t

    def put(a):
        if a is None:
            return None
        try:
            if a.devices() == {dev}:
                return a            # already home: no host round-trip
        except Exception:
            pass
        return jax.device_put(np.asarray(a), dev)

    def col_cpu(c: Column) -> Column:
        return dataclasses.replace(
            c, data=put(c.data), validity=put(c.validity),
            offsets=put(c.offsets),
            children=type(c.children)(col_cpu(k) for k in c.children))

    if dev is None:
        return t
    return Table([col_cpu(c) for c in t.columns], names=list(t.names))


def _np_dtype_to_dt(np_dt) -> dtypes.DType:
    m = {"b": dtypes.BOOL, "i1": dtypes.INT8, "i2": dtypes.INT16,
         "i4": dtypes.INT32, "i8": dtypes.INT64,
         "f4": dtypes.FLOAT32, "f8": dtypes.FLOAT64}
    np_dt = np.dtype(np_dt)
    key = "b" if np_dt.kind == "b" else f"{np_dt.kind}{np_dt.itemsize}"
    if key not in m:
        raise PlanValidationError(
            f"expression produced unsupported dtype {np_dt}")
    return m[key]


def _col_from_array(arr) -> Column:
    dt = _np_dtype_to_dt(arr.dtype)
    return Column(dtype=dt, length=int(arr.shape[0]), data=arr)


def _input_has_floats(t) -> bool:
    """Any floating column in a bound Table or streaming source (unknown
    dtypes count as floats — the conservative direction for every gate
    that consumes this)."""
    if isinstance(t, Table):
        return any(
            np.issubdtype(np.dtype(c.dtype.storage_dtype()), np.floating)
            for c in t.columns)
    return bool(getattr(t, "has_floats", True))


# ---- co-placement dispatch (placement rule, docs/optimizer.md#placement) ----

def _subtree_sources(node: PlanNode) -> frozenset:
    """Scan sources reachable from `node` — invariant under optimizer
    rewrites (pruning narrows a scan's projection but keeps its source;
    fusions and Sort+Limit->TopK rebuild nodes but never move a scan
    across a join boundary), which is what makes it a rewrite-stable
    subtree identity for the remap below."""
    out = set()
    stack, seen = [node], set()
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        if isinstance(n, Scan):
            out.add(n.source)
        stack.extend(n.children)
    return frozenset(out)


def _remap_placement_labels(authored, plan, labels):
    """Serving-forced placement labels name AUTHORED subtree roots
    (serving/scheduler._partial_placement admits against the authored
    cert); the executed plan may have rebuilt the root under a new label
    (Sort+Limit fused to TopK, Filter+Project to FusedSelect). Labels
    present in the executed plan pass through; a renamed one remaps to
    the unique MAXIMAL executed node reading the same scan-source set —
    ambiguity (two joins over the same sources) skips the label rather
    than guessing, so a lost remap costs only the offload, never
    correctness."""
    executed = {n.label for n in plan.nodes}
    by_label = {n.label: n for n in authored.nodes}
    parents: Dict[int, List[PlanNode]] = {}
    for n in plan.nodes:
        for c in n.children:
            parents.setdefault(id(c), []).append(n)
    out = []
    for lbl in labels:
        if lbl in executed:
            out.append(lbl)
            continue
        a = by_label.get(lbl)
        if a is None:
            continue
        srcs = _subtree_sources(a)
        matches = [n for n in plan.nodes if n is not plan.root
                   and _subtree_sources(n) == srcs]
        ids = {id(n) for n in matches}
        maximal = [n for n in matches
                   if all(id(p) not in ids
                          for p in parents.get(id(n), []))]
        if len(maximal) == 1:
            out.append(maximal[0].label)
    return out


class _PendingHostRel:
    """A host-placed subtree still in flight on a co-placement worker
    thread (the PendingRel async-resolve shape from plan/distributed.py
    applied to a WHOLE subtree): the main walk launches every host
    subtree up front — a placed subtree is self-contained, its leaves
    bind only to plan inputs — and keeps executing the device side; the
    consuming operator `resolve()`s at its join point. The host wall
    that ran while the main thread was NOT blocked waiting here is the
    consumer's measured `placement_overlap_ms`. The join is LOCK-FREE
    (a bare timeout-less `Thread.join`, no engine lock held — the
    lint_concurrency blocking-under-lock rule's contract). A host
    failure raises the original error ONCE at the consumer, whose
    fault-retry loop gets REAL re-execution: each later resolve re-runs
    the subtree synchronously instead of re-raising a cached error."""

    pending = True

    def __init__(self, fn, root_label: str):
        self._fn = fn
        self.root_label = root_label
        self._outputs = None        # id(node) -> Table, whole subtree
        self._node_metrics = None   # label -> OperatorMetrics
        self._err = None
        self._t0 = self._t1 = 0.0
        self._resolved = False

        def work():
            self._t0 = time.perf_counter()
            try:
                # _run_host_subtree blocks per node, so the subtree has
                # genuinely COMPLETED on the thread — otherwise "async"
                # would just defer the host work to the consumer and the
                # overlap would be fiction
                self._outputs, self._node_metrics = fn()
            except BaseException as e:      # surfaces at the consumer
                self._err = e
            finally:
                self._t1 = time.perf_counter()

        self._thread = threading.Thread(
            target=work, daemon=True, name="spark-rapids-tpu-coplace")
        self._thread.start()

    def resolve(self, consumer_metric: Optional[OperatorMetrics] = None):
        """(outputs by node id, metrics by label); stamps the overlap on
        `consumer_metric` at the first (joining) resolve."""
        if not self._resolved:
            w0 = time.perf_counter()
            self._thread.join()
            blocked = time.perf_counter() - w0
            self._resolved = True
            if consumer_metric is not None:
                dur = self._t1 - self._t0
                consumer_metric.placement_overlap_ms = \
                    max(0.0, dur - blocked) * 1e3
        if self._outputs is None:
            err, self._err = self._err, None
            if err is not None:
                raise err
            self._outputs, self._node_metrics = self._fn()
        return self._outputs, self._node_metrics


class _StreamBreaker(Exception):
    """A streaming chain hit an unrecoverable fault (breaker tripped):
    carries the original error plus the retry cost already paid, so the
    degraded re-run still reports it."""

    def __init__(self, error, retries: int, backoff_ms: float):
        super().__init__(str(error))
        self.error = error
        self.retries = retries
        self.backoff_ms = backoff_ms


class _SyncFeed:
    """Prefetch disabled (SPARK_RAPIDS_TPU_IO_PREFETCH=0): decode inline
    on the executing thread. Same surface as _ChunkPrefetcher."""

    def __init__(self, gen):
        self._gen = gen
        self.decode_intervals = []
        self.decode_ms = 0.0

    def get(self):
        t0 = time.perf_counter()
        try:
            chunk = next(self._gen)
        except StopIteration:
            return None
        t1 = time.perf_counter()
        self.decode_intervals.append((t0, t1))
        self.decode_ms += (t1 - t0) * 1e3
        return chunk

    def close(self):
        self._gen.close()


class _ChunkPrefetcher:
    """Bounded host-side prefetch thread: decodes chunk N+1 (up to `depth`
    ahead) while the consumer executes chunk N — the double-buffer that
    overlaps host bitstream decode with device execution (StreamBox-HBM's
    pipelined-chunk shape; the native decode releases the GIL, so the
    overlap is real CPU concurrency, not just queueing)."""

    _DONE = object()

    def __init__(self, gen, depth: int):
        import queue
        self._gen = gen
        self._q = queue.Queue(maxsize=max(1, depth))
        self._stop = False
        self._err = None
        self.decode_intervals = []      # (start, end) per decoded chunk
        self.decode_ms = 0.0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="spark-rapids-tpu-io-prefetch")
        self._thread.start()

    def _run(self):
        try:
            while not self._stop:
                t0 = time.perf_counter()
                try:
                    chunk = next(self._gen)
                except StopIteration:
                    break
                t1 = time.perf_counter()
                self.decode_intervals.append((t0, t1))
                self.decode_ms += (t1 - t0) * 1e3
                self._q.put(chunk)
        except BaseException as e:       # surfaces at the consumer's get()
            self._err = e
        finally:
            self._q.put(self._DONE)

    def get(self):
        """Next decoded chunk, or None at end of stream. Re-raises a
        decode-thread error on the consumer thread."""
        item = self._q.get()
        if item is self._DONE:
            if self._err is not None:
                raise self._err
            return None
        return item

    def close(self):
        """Unblock and retire the decode thread (consumer aborted early, or
        end-of-stream cleanup): keep draining until the thread exits so a
        put() blocked on a full queue always wakes."""
        import queue
        self._stop = True
        while self._thread.is_alive():
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)
        try:
            self._gen.close()   # release the reader (mmap/file handle) now,
        except Exception:       # not at GC — the degraded tier may be about
            pass                # to re-open the same file


def _interval_overlap_ms(decode, process) -> float:
    """Total wall time decode intervals and processing intervals ran
    concurrently — the prefetch pipeline's measured win. Linear merge:
    each list is chronological and internally non-overlapping (sequential
    decode, sequential execution)."""
    total = 0.0
    i = j = 0
    while i < len(decode) and j < len(process):
        s1, e1 = decode[i]
        s2, e2 = process[j]
        total += max(0.0, min(e1, e2) - max(s1, s2))
        if e1 < e2:
            i += 1
        else:
            j += 1
    return total * 1e3


# HashAggregate ops that decompose into per-chunk partials + an exact
# merge over the partial rows (count/size merge by summing counts)
_STREAM_AGG_MERGE = {"sum": "sum", "count": "sum", "size": "sum",
                     "min": "min", "max": "max"}


class PlanResult:
    """Output of one plan execution.

    `table` is the result relation; in the capped tier it is PADDED and
    `valid` marks the live rows (`compact()` materializes just those).
    `metrics` maps node label -> OperatorMetrics; `profile()` renders them.
    """

    def __init__(self, plan: Plan, table: Table,
                 valid: Optional[jnp.ndarray],
                 metrics: Dict[str, OperatorMetrics],
                 mode: str, wall_ms: float, attempts: int = 1,
                 caps: Optional[Dict[str, int]] = None, retries: int = 0,
                 degraded: bool = False,
                 breaker: Optional[Dict] = None,
                 backoff_ms: float = 0.0,
                 jit_cache_hits: int = 0):
        self.plan = plan              # the EXECUTED plan (optimized form
        #                               when the optimizer ran; metric
        #                               labels refer to its nodes)
        self.table = table
        self.valid = valid
        self.metrics = metrics
        self.mode = mode
        self.wall_ms = wall_ms
        self.attempts = attempts      # capped-tier cap-escalation attempts
        self.caps = caps              # final (possibly grown) capacities
        self.retries = retries        # plan-level recoverable-fault re-runs
        self.degraded = degraded      # finished on the CPU tier (breaker trip)
        self.breaker = breaker        # {"state","trips","reason","error"
        #                               [,"worker_id" in a fleet]}
        self.backoff_ms = backoff_ms  # total retry backoff across the plan
        self.jit_cache_hits = jit_cache_hits  # capped-tier fingerprint-keyed
        #                               compiled-program reuses this execute
        self.optimizer = None         # OptimizeReport.to_dict() when the
        #                               optimizer ran (set by execute())
        self.cert = None              # analysis/footprint.ResourceCert for
        #                               the executed plan (set by execute();
        #                               None when the certifier declined)
        self.session = ""             # serving-session stamp (docs/serving
        #                               .md): set by execute() from the
        #                               active sessionctx scope, "" outside
        #                               the serving layer
        self.worker = ""              # fleet worker stamp (serving/fleet
        #                               .py): the executor's worker_id, ""
        #                               outside a fleet — on a cache-hit
        #                               COPY it names the worker that
        #                               COMPUTED the entry, which is how
        #                               the soak proves cross-worker
        #                               cache locality
        self.cached = False           # served from the serving result cache
        #                               (serving/cache.py): True ONLY on a
        #                               cache-hit COPY — its metrics are
        #                               deep copies, so profile/bench
        #                               consumers never double-attribute
        #                               the original run's wall time

    def compact(self) -> Table:
        """Live rows only (identity in the eager tier)."""
        if self.valid is None:
            return self.table
        idx = jnp.asarray(np.nonzero(np.asarray(self.valid))[0],
                          dtype=jnp.int32)
        return _ops().take_table(self.table, idx, _has_negative=False)

    def profile(self) -> List[Dict]:
        """Per-operator metric rows (post-run observability artifact)."""
        return [m.to_dict() for m in self.metrics.values()]

    def profile_text(self) -> str:
        return render_profile(list(self.metrics.values()),
                              plan_wall_ms=self.wall_ms,
                              attempts=self.attempts, caps=self.caps,
                              degraded=self.degraded, breaker=self.breaker,
                              optimizer=self.optimizer,
                              jit_cache_hits=self.jit_cache_hits,
                              cert=self.cert)


class _CappedRel:
    """A relation inside the capped trace: padded table + live-row mask."""

    __slots__ = ("table", "alive")

    def __init__(self, table: Table, alive: jnp.ndarray):
        self.table = table
        self.alive = alive


class PlanExecutor:
    """Executes validated Plans. One executor may run many plans; compiled
    capped programs are cached per (plan, caps)."""

    def __init__(self, mode: str = "eager",
                 caps: Optional[Dict[str, int]] = None,
                 max_cap_attempts: int = 6,
                 op_retries: int = 2,
                 mesh=None, mesh_axis: str = "data",
                 session=None,
                 block_per_op: bool = True,
                 health=None,
                 degrade: Optional[str] = None,
                 optimize: Optional[bool] = None,
                 cert_budget: Optional[int] = None,
                 worker_id: str = ""):
        if mode not in ("eager", "capped"):
            raise ValueError(f"unknown executor mode {mode!r}")
        # mesh + capped is checked PER PLAN in execute(): only a plan that
        # actually contains a distributed-lowerable operator is rejected
        # (naming it), so trivial row-wise plans still run capped
        from .. import config
        from ..runtime.health import DeviceHealthMonitor
        self.mode = mode
        self.caps = dict(caps or {})
        self.max_cap_attempts = max_cap_attempts
        self.op_retries = op_retries
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.session = session
        # fleet worker identity (serving/fleet.py): stamped on every
        # result and per-op metric this executor produces, "" outside a
        # fleet — failure attribution and the soak's cross-worker
        # cache-locality proof both need to know WHICH worker ran a plan
        self.worker_id = str(worker_id)
        self.block_per_op = block_per_op
        # health: the degradation policy owner (runtime/health.py). Pass a
        # shared monitor to give several executors one breaker per device.
        self.health = health if health is not None else DeviceHealthMonitor()
        self.degrade = degrade if degrade is not None else config.breaker_degrade()
        if self.degrade not in ("cpu", "off"):
            raise ValueError(f"unknown degrade policy {self.degrade!r} "
                             "(expected cpu or off)")
        # rule-based logical optimizer (plan/optimizer.py): on by default,
        # SPARK_RAPIDS_TPU_OPTIMIZER=off or optimize=False disables
        self.optimize = (config.optimizer_enabled() if optimize is None
                         else bool(optimize))
        # admission-time footprint budget (analysis/footprint.py): a plan
        # whose certified per-operator residency hi-bound exceeds this is
        # rejected (or degraded, per SPARK_RAPIDS_TPU_CERT_ADMISSION)
        # before any compilation. None defers to the
        # SPARK_RAPIDS_TPU_CERT_BUDGET_BYTES knob; 0 disables.
        self.cert_budget = cert_budget
        self._opt_cache = _LruDict(64)  # (root, bound sig) -> (plan, schemas,
        #                                 report): one rewrite per binding
        self._cert_cache = _LruDict(64)  # (root, binding sig) ->
        #                                 ResourceCert: one certify walk
        #                                 per binding, not per execute
        self._verify_cache = _LruDict(128)  # passed pre-execution-gate
        #                                 verdicts: repeat executions of a
        #                                 cached (plan, binding) rewrite
        #                                 skip re-verification (failures
        #                                 raise and are never cached)
        self._jit_cache: Dict[Tuple, Tuple[Callable, Dict]] = _LruDict(64)
        # escalated capacities survive per plan STRUCTURE (keyed by the
        # canonical fingerprint — optimizer.plan_fingerprint), so the next
        # execute() of this plan, or of an equivalent plan built
        # independently, starts from the grown caps instead of re-paying
        # the whole overflow ladder
        self._caps_memo: Dict[str, Dict[str, int]] = _LruDict(256)
        # distributed-tier capacity memo: (fingerprint, node index) ->
        # final escalated caps, same contract as _caps_memo
        self._dist_caps_memo: Dict[Tuple, Dict] = _LruDict(256)

    def _check_capped_mesh(self, plan: Plan) -> None:
        """mode="capped" with a mesh: reject ONLY plans that contain a
        distributed-lowerable operator (the capped tier would silently run
        it on one chip), naming the offending node."""
        if self.mesh is None or self.mode == "eager":
            return
        for n in plan.nodes:
            if isinstance(n, (Exchange, HashJoin, HashAggregate, Sort,
                              TopK, Union)):
                raise PlanValidationError(
                    f"{n.label}: distributed lowering (mesh=) exists only "
                    "in the eager tier; a capped executor would silently "
                    f"run this {n.kind} on one chip — drop the mesh or use "
                    "mode=\"eager\"")

    # ---- entry point ------------------------------------------------------
    def execute(self, plan: Plan,
                inputs: Optional[Dict[str, Table]] = None,
                tier: Optional[str] = None,
                placement=None) -> PlanResult:
        """Run `plan` over `inputs`. `tier` pins the execution tier:
        None/"device" is the normal path (device with breaker-gated CPU
        degradation); "cpu" runs the WHOLE plan on the degraded CPU tier
        without touching the device — the serving layer's route for
        over-quota admission under the degrade policy and for draining a
        queue while the breaker is open (docs/serving.md).

        `placement` (iterable of node LABELS) forces those subtrees onto
        co-placement host worker threads in addition to anything the
        optimizer's placement rule annotated — the serving layer's
        partial-placement route (SPARK_RAPIDS_TPU_SERVING_OVER_QUOTA=
        partial, docs/serving.md#partial-placement): offload enough of an
        over-quota plan to host threads that the device remainder fits
        the session quota. Labels that do not survive the optimizer
        rewrite, or that fail the executor's subtree-exclusivity
        validation, are silently skipped (execution stays correct; only
        the offload is lost). Eager tier only — the capped tier traces
        one XLA program and has no per-subtree dispatch to overlap."""
        if tier not in (None, "device", "cpu"):
            raise ValueError(f"unknown execution tier {tier!r} "
                             "(expected device or cpu)")
        self._check_capped_mesh(plan)
        inputs = bind_scan_sources(plan, inputs)
        missing = [s for s in plan.input_names if s not in inputs]
        if missing:
            raise PlanValidationError(f"unbound plan input(s) {missing}")
        # full validation against the bound tables' actual schemas —
        # authored-plan errors surface against authored labels, BEFORE any
        # optimizer rewrite renames nodes (streaming sources expose .names
        # from the parquet footer, so the same contract applies)
        bound = {name: tuple(t.names) for name, t in inputs.items()}
        schemas = plan.resolve_schemas(bound)
        report = None
        authored = plan
        if self.optimize:
            plan, schemas, report = self._optimized(plan, inputs, bound)
        from .. import config
        if config.verify_plans():
            self._verify_execution(authored, plan, report, inputs, bound)
        # the AUTHORED fingerprint keys the adaptive feedback loop
        # (plan/stats.py): cold and warm executions of one authored plan
        # share it even when a stats-driven rewrite changes the executed
        # plan's fingerprint (so warm cap seeding survives a build-side
        # flip via the global cap keys)
        source_fp = authored.fingerprint
        # static resource certifier (analysis/footprint.py): sound
        # per-operator [lo, hi] row and byte bounds over the plan about
        # to run — stamped on the result, consulted by the capped tier's
        # cold-run cap seeding, and compared against the device budget
        # BEFORE any compilation when one is configured
        cert = self._certify(plan, inputs, bound)
        # merged co-placement annotations (plan/optimizer.py placement
        # rule, docs/optimizer.md#placement): the optimizer's observed/
        # certified host placements plus any serving-forced labels.
        # Annotation-only — the tree is never mutated; each label is
        # re-validated against the EXECUTED plan's structure in
        # _execute_eager (subtree exclusivity, no exchanges, no
        # streaming-chain overlap) before a worker thread launches.
        placements: Dict[str, str] = {}
        if report is not None and not report.fell_back:
            placements.update(report.placements)
        if placement:
            for lbl in _remap_placement_labels(authored, plan, placement):
                placements[lbl] = "host"
        res = None
        if tier == "cpu":
            # pinned to the degraded tier: same machinery as a breaker
            # trip, without consulting the device budget (it does not
            # bind on the CPU tier)
            self.health.start_plan_attempt()
            res = self._execute_degraded(
                plan, inputs, schemas, {}, {}, start=0,
                t_plan0=time.perf_counter(), mode=self.mode)
        budget = (self.cert_budget if self.cert_budget is not None
                  else config.cert_budget_bytes())
        if res is None and budget and cert is not None:
            violations = cert.over_budget(budget)
            if violations:
                from ..analysis.footprint import ResourceAdmissionError
                if config.cert_admission() == "reject":
                    raise ResourceAdmissionError(
                        violations, "admission gate: certified footprint "
                        f"exceeds the {budget} B device budget")
                # degrade: the device budget does not bind on the CPU
                # tier — run the whole plan there, same machinery as a
                # breaker trip, without touching the device
                self.health.start_plan_attempt()
                res = self._execute_degraded(
                    plan, inputs, schemas, {}, {}, start=0,
                    t_plan0=time.perf_counter(), mode=self.mode)
        if res is None:
            if self.session is not None:
                from ..runtime.admission import active_session
                with active_session(self.session):
                    res = self._execute(plan, inputs, schemas, source_fp,
                                        cert, placements)
            else:
                res = self._execute(plan, inputs, schemas, source_fp,
                                    cert, placements)
        res.cert = cert
        # serving-session stamp (runtime/sessionctx.py, docs/serving.md):
        # results and per-op metrics carry the tenant they executed for —
        # dispatcher worker threads are multiplexed across sessions, so
        # thread identity cannot answer this after the fact
        sid = _sessionctx().current_session_id()
        if sid is not None:
            res.session = sid
            for mm in res.metrics.values():
                mm.session = sid
        if self.worker_id:
            res.worker = self.worker_id
            for mm in res.metrics.values():
                mm.worker_id = self.worker_id
        if report is not None:
            res.optimizer = report.to_dict()
        from . import stats as stats_mod
        store = stats_mod.active_store()
        if store is not None:
            # record only what actually ran, under the backend it ran
            # ON: a degraded result finished on the CPU tier and must
            # never drive device-side decisions (docs/adaptive.md)
            store.record_result(
                plan, res,
                backend="cpu" if res.degraded else jax.default_backend(),
                source_fp=source_fp)
        return res

    def _verify_execution(self, authored, plan, report, inputs, bound):
        """Debug-mode pre-execution gate (SPARK_RAPIDS_TPU_VERIFY_PLANS,
        on in tests — docs/analysis.md): the plan about to run must pass
        the static verifier. Schema propagation and (for Table bindings)
        dtype typing always check; the rewrite-pair invariants check when
        the optimizer ran; partitioning soundness checks when
        exchange_planning placed distributed boundaries. Raises
        PlanVerificationError naming the invariant and operator."""
        from ..analysis import verifier
        input_dtypes = {
            name: {cn: c.dtype for cn, c in zip(t.names, t.columns)}
            for name, t in inputs.items() if isinstance(t, Table)}
        floats = any(_input_has_floats(t) for t in inputs.values())
        planned = (report is not None and not report.fell_back
                   and self.mesh is not None and self.mode == "eager"
                   and self.mesh.shape[self.mesh_axis] > 1)
        # verdicts memoize on everything the checks read — a repeat
        # execution of the same (plan, binding) pays nothing, the same
        # contract as the rewrite cache feeding it
        key = (authored.root, plan.root, tuple(sorted(bound.items())),
               tuple((n, tuple(repr(d) for d in cols.values()))
                     for n, cols in sorted(input_dtypes.items())),
               floats, planned,
               None if report is None else (report.fingerprint,
                                            report.fell_back))
        if self._verify_cache.get(key):
            return
        if report is None and plan is authored:
            rep = verifier.verify(plan, bound=bound,
                                  input_dtypes=input_dtypes,
                                  float_inputs=floats)
        else:
            rep = verifier.verify_rewrite(authored, plan, bound=bound,
                                          input_dtypes=input_dtypes,
                                          float_inputs=floats,
                                          planned=planned, report=report)
        rep.raise_if_failed("pre-execution gate")
        self._verify_cache[key] = True

    def _optimized(self, plan, inputs, bound):
        """Rewrite `plan` through the rule pipeline, once per (plan,
        binding): repeat executions reuse the cached rewrite (and through
        the fingerprint-keyed program cache, the compiled XLA program)."""
        from .optimizer import optimize as run_optimizer
        # fp reductions are not reorder-exact: float columns anywhere in
        # the inputs disable the row-reordering build_side rule. The flag
        # is part of the cache KEY — a rewrite computed from integer
        # inputs must not be served to a float binding of the same
        # names/shapes (the gate would be bypassed by the cache hit)
        floats = any(_input_has_floats(t) for t in inputs.values())
        # scans bound to streaming sources: the scan_pruning rule fires
        # only for these, so the set belongs in the cache key too
        streaming = frozenset(n for n, t in inputs.items()
                              if not isinstance(t, Table))
        # the exchange_planning rule fires only for a meshed eager
        # executor, and its placements depend on the mesh width AND the
        # broadcast threshold (read at use time per config.py's
        # monkeypatch contract) — all of it belongs in the cache key
        from .. import config
        mesh_peers = (self.mesh.shape[self.mesh_axis]
                      if self.mesh is not None and self.mode == "eager"
                      else None)
        bc_rows = config.broadcast_rows() if mesh_peers else None
        bc_bytes = config.broadcast_bytes() if mesh_peers else None
        # verify mode changes which plan survives a mid-pipeline invalid
        # rewrite (per-rule fall-back), so it belongs in the cache key too
        verify_rules = config.verify_plans()
        # column dtypes feed the resource certifier's byte bounds (the
        # broadcast byte-legality proof and the certified estimator
        # tier), so the dtype signature belongs in the cache key: a
        # rewrite proven over i8 columns must not serve an i64 binding
        # of the same names/shapes
        input_dtypes = {
            name: {cn: c.dtype for cn, c in zip(t.names, t.columns)}
            for name, t in inputs.items() if isinstance(t, Table)}
        dtype_sig = tuple(
            (name, tuple((cn, repr(dt)) for cn, dt in cols.items()))
            for name, cols in sorted(input_dtypes.items()))
        # adaptive rewrites consume the stats store's observations, so
        # the store's generation joins the key: a cached rewrite must not
        # outlive the observations it ignored (each successful execution
        # records, so warm executions re-optimize — the rewrite pipeline
        # is cheap next to execution, and only paid while stats are on)
        from . import stats as stats_mod
        store = stats_mod.active_store()
        stats_gen = None if store is None else (store.uid,
                                                store.generation)
        # the placement rule's decisions depend on the knob state AND the
        # cold-path byte threshold (read at use time per config.py's
        # monkeypatch contract) — both join the cache key
        placement_on = config.placement_enabled()
        placement_bytes = config.placement_bytes() if placement_on else None
        key = (plan.root, tuple(sorted(bound.items())),
               tuple(sorted((n, t.num_rows) for n, t in inputs.items())),
               floats, streaming, mesh_peers, bc_rows, bc_bytes,
               verify_rules, dtype_sig, stats_gen,
               placement_on, placement_bytes)
        hit = self._opt_cache.get(key)
        if hit is None:
            bound_rows = {n: t.num_rows for n, t in inputs.items()}
            backend = jax.default_backend()
            opt, report = run_optimizer(
                plan, bound, bound_rows,
                float_inputs=floats, streaming_sources=streaming,
                mesh_peers=mesh_peers, verify_rules=verify_rules,
                stats=store, backend=backend, input_dtypes=input_dtypes,
                placement=placement_on, placement_bytes=placement_bytes)
            if (store is not None and not verify_rules
                    and opt is not plan and not report.fell_back
                    and report.stats_driven()):
                # EVERY stats-driven rewrite passes the verify_rewrite
                # gate, even with SPARK_RAPIDS_TPU_VERIFY_PLANS off
                # (docs/adaptive.md): observations must never weaken the
                # static pipeline's guarantees. A violation (defensive —
                # the same rule guards protect both paths) reverts to
                # the static rewrite rather than failing the query.
                from ..analysis import verifier
                rep = verifier.verify_rewrite(
                    plan, opt, bound=bound, input_dtypes=input_dtypes,
                    float_inputs=floats, report=report,
                    # distributed plans: the partitioning-soundness
                    # layer must check the very exchange placements the
                    # observed cardinalities picked (same condition as
                    # _verify_execution's `planned`)
                    planned=bool(mesh_peers and mesh_peers > 1))
                if not rep.ok:
                    # the static re-run keeps the placement knobs: with
                    # no stats the rule falls back to its certified-bytes
                    # cold path, which IS the static placement decision
                    opt, report = run_optimizer(
                        plan, bound, bound_rows,
                        float_inputs=floats, streaming_sources=streaming,
                        mesh_peers=mesh_peers, verify_rules=verify_rules,
                        input_dtypes=input_dtypes,
                        placement=placement_on,
                        placement_bytes=placement_bytes)
                    report.stats_reverted = True
            hit = (opt, opt.resolve_schemas(bound), report)
            self._opt_cache[key] = hit
        return hit

    def _certify(self, plan, inputs, bound):
        """Resource-certify the plan about to run (analysis/footprint.py):
        bound input cardinalities (Tables and streaming sources both
        expose num_rows), Table column dtypes for byte widths, validity
        presence for the keyed-aggregate lo bound. Memoized per (plan,
        binding) like the rewrite cache feeding it — a hot fingerprint-
        cached plan must not re-pay the certify walk per execute.
        Defensive-None on an internal certifier error — sizing is an
        optimization layer and must never fail a query that would
        otherwise run."""
        from ..analysis import footprint
        try:
            input_dtypes, input_nullable = footprint.table_metadata(inputs)
            bound_rows = {n: t.num_rows for n, t in inputs.items()}
            n_peers = (self.mesh.shape[self.mesh_axis]
                       if self.mesh is not None and self.mode == "eager"
                       else 1)
            key = (plan.root, tuple(sorted(bound.items())),
                   tuple(sorted(bound_rows.items())),
                   tuple((n, tuple((cn, repr(dt))
                                   for cn, dt in cols.items()))
                         for n, cols in sorted(input_dtypes.items())),
                   tuple((n, tuple(sorted(cols.items())))
                         for n, cols in sorted(input_nullable.items())),
                   n_peers)
            hit = self._cert_cache.get(key)
            if hit is None:
                hit = footprint.certify(
                    plan, bound=bound, bound_rows=bound_rows,
                    input_dtypes=input_dtypes,
                    input_nullable=input_nullable, n_peers=n_peers)
                self._cert_cache[key] = hit
            return hit
        except Exception:
            return None

    def _execute(self, plan, inputs, schemas, source_fp=None, cert=None,
                 placements=None):
        if self.mode == "eager":
            return self._execute_eager(plan, inputs, schemas, placements)
        return self._execute_capped(plan, inputs, schemas, source_fp,
                                    cert)

    def explain(self, plan: Plan, optimized: bool = False,
                inputs: Optional[Dict[str, Table]] = None) -> str:
        """The authored operator tree; with `optimized=True`, the authored
        AND optimizer-rewritten trees plus the per-rule rewrite summary.
        Pass `inputs` to render the EXACT rewrite execute() runs for that
        binding (bound schemas/rows + the float build_side gate); without
        them the rewrite uses declared schemas and est_rows hints only,
        so bind-time pruning/estimates may differ."""
        if not optimized:
            return plan.explain()
        if inputs is not None:
            if not self.optimize:
                # "EXACT rewrite execute() runs" — which, for a disabled
                # optimizer, is no rewrite at all
                return (plan.explain() + "\n\noptimizer: disabled for "
                        "this executor (optimize=False / "
                        "SPARK_RAPIDS_TPU_OPTIMIZER=off) — the authored "
                        "plan executes verbatim")
            bound = {name: tuple(t.names) for name, t in inputs.items()}
            plan.resolve_schemas(bound)         # validate the binding
            opt, _, report = self._optimized(plan, inputs, bound)
            # certified footprint of the EXACT plan execute() would run
            # for this binding (analysis/footprint.py)
            cert = self._certify(opt, inputs, bound)
            cert_block = [cert.render()] if cert is not None else []
            transport_block = ([self._transport_summary()]
                               if self.mesh is not None
                               and self.mode == "eager" else [])
            return "\n".join(["== authored ==", plan.explain(), "",
                              "== optimized ==", opt.explain(), "",
                              report.summary(), *cert_block,
                              *transport_block,
                              self._kernel_summary()])
        from .optimizer import explain_optimized
        return explain_optimized(plan) + "\n" + self._kernel_summary()

    @staticmethod
    def _transport_summary() -> str:
        """One exchange-transport line for a meshed explain(optimized=True)
        (plan/transport.py, docs/distributed.md#transport): what the
        exchanges of this plan would ship under the current knobs."""
        from .. import config
        pack = config.exchange_pack()
        codecs = ",".join(sorted(config.exchange_codecs())) if pack else ""
        return ("transport: pack=" + ("on" if pack else "off")
                + f" codecs={codecs or 'none'}"
                + " async=" + ("on" if config.exchange_async() else "off")
                + " (wire vs logical bytes per edge on profile())")

    @staticmethod
    def _kernel_summary() -> str:
        """One registry line for explain(optimized=True): the signature-
        independent per-op choice on the current backend (docs/kernels.md).
        Signature-conditional kernels (the Pallas set) resolve per dispatch
        and show up on OperatorMetrics.kernel / profile_text post-run."""
        from ..ops.registry import REGISTRY
        pairs = ", ".join(f"{op}={name}"
                          for op, name in sorted(REGISTRY.summary().items()))
        return (f"kernels [{jax.default_backend()}]: {pairs} "
                "(signature-conditional kernels resolve per dispatch; see "
                "profile())")

    # ---- faultinj ---------------------------------------------------------
    @staticmethod
    def _faultinj_point(node: PlanNode):
        """Plan-level interception: rules keyed `plan.<Kind>` (or `*`) fire
        here, in addition to any op-level shims underneath."""
        from .. import faultinj
        inj = faultinj.active()
        if inj is not None:
            inj.on_compute(f"plan.{node.kind}")

    # ---- health / degradation policy --------------------------------------
    def _breaker_snapshot(self) -> Dict:
        br = self.health.breaker
        snap = {"state": br.state, "trips": br.trips,
                "reason": br.last_trip_reason, "error": br.last_trip_error}
        wid = getattr(self.health, "worker_id", "")
        if wid:
            snap["worker_id"] = wid
        return snap

    def _handle_fault(self, err, op_label: str, attempt: int,
                      metric: OperatorMetrics) -> bool:
        """One failure on the device path. Returns True when the caller
        should retry the failed unit (backoff already slept, counters
        bumped); returns False when the breaker tripped and the caller must
        degrade (or re-raise under degrade="off")."""
        from ..runtime import health as _h
        kind = self.health.record_failure(op_label, err)
        if kind == _h.TRANSIENT:
            if attempt < self.op_retries:
                slept = self.health.try_retry(attempt)
                if slept is not None:
                    metric.retries += 1
                    metric.backoff_ms += slept
                    self._maybe_rollback(err)
                    return True
                kind = _h.STICKY        # shared retry budget exhausted
            else:
                kind = _h.STICKY        # per-op retry bound exhausted
        self.health.trip(kind, err)
        return False

    def _maybe_rollback(self, err) -> None:
        """RetryOOM transients: honor the arbiter's rollback contract
        (block until memory frees) before the backoff retry, best-effort."""
        from ..runtime.adaptor import CpuRetryOOM, RetryOOM
        if not isinstance(err, (RetryOOM, CpuRetryOOM)):
            return
        sess = self.session
        if sess is None:
            from ..runtime.admission import get_active_session
            sess = get_active_session()
        if sess is None:
            return
        try:
            sess.arbiter.block_thread_until_ready()
        except Exception:
            pass

    # ---- eager tier -------------------------------------------------------
    def _execute_eager(self, plan, inputs, schemas,
                       placements=None) -> PlanResult:
        from ..runtime.admission import operand_nbytes
        from ..utils import tracing
        t_plan0 = time.perf_counter()
        results: Dict[int, Table] = {}
        metrics: Dict[str, OperatorMetrics] = {}
        self.health.start_plan_attempt()
        if self.degrade != "off" and not self.health.admit():
            # device quarantined (breaker open / failed half-open probe):
            # run the whole plan on the CPU tier without touching it
            return self._execute_degraded(plan, inputs, schemas, results,
                                          metrics, start=0, t_plan0=t_plan0,
                                          mode="eager")
        # full-plan SPMD tier (plan/distributed.py): with a mesh, nodes
        # execute over sharded relations and gather only at the sink (or
        # the first operator with no distributed form). Streaming prefixes
        # are a single-chip pipeline shape — the distributed tier
        # materializes source-bound scans through one pruned read instead.
        dist = None
        if self.mesh is not None:
            from .distributed import DistContext
            dist = DistContext(self, plan, inputs)
        # streamable prefixes over source-bound scans run morsel-at-a-time
        # (decode chunk N+1 on host while chunk N executes); their interior
        # nodes never materialize a whole relation, only the chain tail does
        chains = {} if dist is not None else self._stream_chains(plan, inputs)
        chain_interior = {id(n) for ch in chains.values() for n in ch[:-1]}
        node_index = {id(n): i for i, n in enumerate(plan.nodes)}
        # co-placement dispatch (plan/optimizer.py placement rule,
        # docs/optimizer.md#placement): validated host subtrees launch on
        # worker threads UP FRONT — a placed subtree is self-contained
        # (its leaves bind only to plan inputs), so its host execution
        # overlaps the whole device walk, not just the sibling side. The
        # consuming operator joins in _resolve_placed. Single-device only:
        # the distributed tier has its own overlap story (async exchanges).
        host_roots: Dict[int, List[PlanNode]] = {}
        host_skip: set = set()
        if placements and dist is None:
            host_roots, host_skip = self._placement_subtrees(
                plan, placements, inputs, chains, chain_interior)
        for rid, sub in host_roots.items():
            results[rid] = _PendingHostRel(
                (lambda s: lambda: self._run_host_subtree(
                    s, inputs, schemas))(sub),
                sub[-1].label)
        try:
            for i, node in enumerate(plan.nodes):
                if id(node) in host_skip:
                    # runs on its co-placement worker thread; outputs and
                    # metrics merge at the consumer's resolve
                    continue
                if id(node) in chain_interior:
                    continue        # runs inside its chain, at the tail
                if id(node) in chains:
                    chain = chains[id(node)]
                    try:
                        out = self._exec_stream_chain(chain, inputs,
                                                      schemas, metrics)
                    except _StreamBreaker as sb:
                        if self.degrade == "off":
                            raise sb.error
                        # replay the chain's remaining chunks — and the
                        # whole prefix — on the CPU tier from the scan
                        return self._execute_degraded(
                            plan, inputs, schemas, results, metrics,
                            start=node_index[id(chain[0])],
                            t_plan0=t_plan0, mode="eager",
                            carry_retries=sb.retries,
                            carry_backoff_ms=sb.backoff_ms)
                    results[id(node)] = out
                    continue
                child_tables = [results[id(c)] for c in node.children]
                m = OperatorMetrics(label=node.label, kind=node.kind,
                                    describe=node.describe())
                t0 = time.perf_counter()
                attempt = 0
                out = None
                while True:
                    try:
                        with tracing.range_ctx(f"plan.{node.label}"):
                            self._faultinj_point(node)
                            if dist is not None:
                                out = dist.exec_node(node, child_tables,
                                                     inputs, schemas, m,
                                                     metrics)
                            else:
                                if host_roots:
                                    child_tables = self._resolve_placed(
                                        node, child_tables, results, m,
                                        metrics)
                                out = self._exec_eager_node(
                                    node, child_tables, inputs, schemas, m)
                        break
                    except _fault_surface() as err:
                        if self._handle_fault(err, node.label, attempt, m):
                            attempt += 1
                            continue
                        if self.degrade == "off":
                            raise
                        return self._execute_degraded(
                            plan, inputs, schemas, results, metrics,
                            start=i, t_plan0=t_plan0, mode="eager",
                            first_metric=m)
                if attempt:
                    # retried to success: the fault was genuinely transient,
                    # so it must not count toward a later sticky trip
                    self.health.record_success(node.label)
                if getattr(out, "pending", False):
                    # async exchange in flight (plan/distributed.PendingRel,
                    # SPARK_RAPIDS_TPU_EXCHANGE_ASYNC): blocking here would
                    # forfeit the transfer/compute overlap — wall_ms,
                    # rows_out, bytes_out, and overlap-ms stamp onto this
                    # metric row when a consumer resolves it
                    m.rows_in = sum(t.num_rows for t in child_tables)
                else:
                    if self.block_per_op:
                        jax.block_until_ready([c.data
                                               for c in out.columns])
                    # wall is compute (all attempts), NOT the backoff idle
                    # time — that is reported separately in backoff_ms,
                    # not double-counted
                    m.wall_ms = (time.perf_counter() - t0) * 1e3 \
                        - m.backoff_ms
                    m.rows_in = sum(t.num_rows for t in child_tables)
                    m.rows_out = out.num_rows
                    m.bytes_out = operand_nbytes(
                        out if isinstance(out, Table) else out.table)
                metrics[node.label] = m
                results[id(node)] = out
        except BaseException as err:
            # debuggability: a failed plan still surfaces what completed.
            # First attachment wins — a failed degraded re-run has already
            # recorded ITS metrics, which the stale device-tier dict here
            # must not clobber.
            if not hasattr(err, "plan_metrics"):
                try:
                    err.plan_metrics = dict(metrics)
                except Exception:
                    pass
            raise
        root_out = results[id(plan.root)]
        if not isinstance(root_out, Table):
            # sink gather: the single host-facing collect of a distributed
            # plan (explicit when the optimizer placed Exchange(gather) at
            # the root; implicit here otherwise)
            root_out = root_out.to_local_table()
        wall = (time.perf_counter() - t_plan0) * 1e3
        return PlanResult(plan, root_out, None, metrics,
                          "eager", wall,
                          retries=sum(mm.retries for mm in metrics.values()),
                          breaker=self._breaker_snapshot(),
                          backoff_ms=sum(mm.backoff_ms
                                         for mm in metrics.values()))

    # ---- co-placement host subtrees (docs/optimizer.md#placement) ---------
    @staticmethod
    def _placement_subtrees(plan, placements, inputs, chains,
                            chain_interior):
        """Re-validate every `label -> "host"` annotation against the
        EXECUTED plan's structure and return ({id(root): postorder node
        list}, {all claimed node ids}). Placements are annotations — the
        optimizer never mutated the tree for them — so the executor owns
        the safety checks: the subtree must be EXCLUSIVE (every interior
        node consumed only inside it — its output merges at exactly one
        join point), free of Exchanges (device-resident by construction),
        disjoint from streaming chains (their interior never materializes
        a Table to hand a thread), with every Scan bound to a Table.
        Labels that fail (e.g. a serving-forced label the rewrite
        renamed) are skipped, never an error: placement is an
        optimization and must not fail a query that would otherwise
        run."""
        parents: Dict[int, List[PlanNode]] = {}
        for n in plan.nodes:
            for c in n.children:
                parents.setdefault(id(c), []).append(n)
        by_label = {n.label: n for n in plan.nodes}
        roots: Dict[int, List[PlanNode]] = {}
        claimed: set = set()
        # plan.nodes order makes the claim order deterministic
        for cand in plan.nodes:
            if placements.get(cand.label) != "host" or cand is plan.root:
                continue
            sub: List[PlanNode] = []
            seen: set = set()

            def walk(n):
                if id(n) in seen:
                    return
                seen.add(id(n))
                for c in n.children:
                    walk(c)
                sub.append(n)

            walk(cand)
            ids = {id(s) for s in sub}
            if ids & claimed:
                continue
            ok = True
            for s in sub:
                if isinstance(s, Exchange) or id(s) in chain_interior \
                        or id(s) in chains:
                    ok = False
                    break
                if isinstance(s, Scan) and \
                        not isinstance(inputs.get(s.source), Table):
                    ok = False
                    break
                if s is not cand and any(id(p) not in ids
                                         for p in parents.get(id(s), [])):
                    ok = False   # interior node consumed outside: not
                    break        # exclusive, no single join point
            if ok:
                roots[id(cand)] = sub
                claimed |= ids
        return roots, claimed

    def _run_host_subtree(self, sub, inputs, schemas):
        """Execute one host-placed subtree (postorder node list) — the
        co-placement worker thread's body, also re-run synchronously on
        the main thread when a consumer retries after a host failure.
        Pins JAX dispatch to the CPU device and the kernel registry to
        the cpu backend (via m.placement, see _kernel_choice); copies the
        subtree's OWN scan bindings host-side only. Fault injection stays
        LIVE (thread-local suppression is not set here — host placement
        is an optimization of a healthy device, not degradation), so
        injected faults surface at the consumer's retry loop with the
        same classes as the device walk. Admission wrappers apply as
        everywhere. Returns (outputs by id(node), metrics by label);
        every output is blocked-until-ready so the overlap the consumer
        measures is real completed work."""
        import contextlib
        from ..runtime.admission import operand_nbytes
        from ..utils import tracing
        cpu = _cpu_device()
        ctx = (jax.default_device(cpu) if cpu is not None
               else contextlib.nullcontext())
        outs: Dict[int, Table] = {}
        ms: Dict[str, OperatorMetrics] = {}
        with ctx:
            host_inputs = dict(inputs)
            for n in sub:
                if isinstance(n, Scan):
                    host_inputs[n.source] = _table_to_cpu(
                        inputs[n.source], cpu)
            for n in sub:
                childs = [outs[id(c)] for c in n.children]
                m = OperatorMetrics(label=n.label, kind=n.kind,
                                    describe=n.describe())
                m.placement = "host"  # set BEFORE dispatch: pins the
                #                       registry to cpu kernels
                t0 = time.perf_counter()
                with tracing.range_ctx(f"plan.{n.label}.host"):
                    self._faultinj_point(n)
                    out = self._exec_eager_node(n, childs, host_inputs,
                                                schemas, m)
                jax.block_until_ready([c.data for c in out.columns])
                m.wall_ms = (time.perf_counter() - t0) * 1e3
                m.rows_in = sum(t.num_rows for t in childs)
                m.rows_out = out.num_rows
                m.bytes_out = operand_nbytes(out)
                ms[n.label] = m
                outs[id(n)] = out
        return outs, ms

    @staticmethod
    def _resolve_placed(node, child_tables, results, m, metrics):
        """Join point of the co-placement dispatch: resolve any host
        subtree this operator consumes — a LOCK-FREE, timeout-less
        Thread.join (no engine lock is held anywhere on this path; the
        lint_concurrency contract for blocking joins) — merge the
        subtree's per-op metrics and ALL its node outputs (the degraded
        tier's salvage walk may need interior outputs too), and stamp
        the overlapped host wall on THIS consumer's metric row. Runs
        inside the consumer's fault-retry loop, so a host-subtree
        failure gets the plan-level retry/degrade policy: the first
        resolve raises the original error, each retry re-runs the
        subtree synchronously."""
        resolved = list(child_tables)
        for idx, c in enumerate(node.children):
            r = resolved[idx]
            if not isinstance(r, _PendingHostRel):
                continue
            outs, hms = r.resolve(m)
            metrics.update(hms)
            results.update(outs)
            resolved[idx] = outs[id(c)]
        return resolved

    @staticmethod
    def _drain_placed(results, metrics):
        """Force-resolve every in-flight co-placement handle before the
        degraded tier salvages `results` — the salvage walk needs real
        Tables, and a placed subtree's interior outputs must be present
        for consumers past the degrade point. A host failure raises
        here; the salvage except treats it like lost device buffers and
        restarts from the scans."""
        for r in list(results.values()):
            if isinstance(r, _PendingHostRel):
                outs, hms = r.resolve(None)
                metrics.update(hms)
                results.update(outs)

    # ---- degraded CPU tier ------------------------------------------------
    def _execute_degraded(self, plan, inputs, schemas, results, metrics,
                          start: int, t_plan0: float, mode: str,
                          first_metric: Optional[OperatorMetrics] = None,
                          carry_retries: int = 0,
                          carry_backoff_ms: float = 0.0,
                          attempts: int = 1,
                          caps: Optional[Dict[str, int]] = None) -> PlanResult:
        """Finish the plan on the CPU backend tier after a breaker trip.

        Completed operator outputs are salvaged through host memory onto
        the CPU backend; the remaining nodes re-execute eagerly with ALL
        faultinj interception suppressed (`faultinj.suppressed()` — the
        CPU tier does not touch the quarantined device, so neither the op
        shims, the MemoryBudget shims, nor the poisoned-device fail-fast
        may fire here) and no plan-level injection points. If the salvage
        itself fails (device buffers already lost), the whole plan re-runs
        from the scans. Admission still applies — degraded work is
        budgeted like any other."""
        import contextlib
        from .. import faultinj
        from ..runtime.admission import operand_nbytes
        from ..utils import tracing
        self.health.note_degraded_plan()
        cpu = _cpu_device()
        ctx = (jax.default_device(cpu) if cpu is not None
               else contextlib.nullcontext())
        with faultinj.suppressed(), ctx:
            try:
                self._drain_placed(results, metrics)
                cpu_results = {k: _table_to_cpu(t, cpu)
                               for k, t in results.items()}
                cpu_inputs = {k: _table_to_cpu(t, cpu)
                              for k, t in inputs.items()}
            except Exception:
                # device buffers unrecoverable: restart from the bound inputs
                # (host-side numpy survives a dead device; device copies may
                # not — re-binding is the caller's contract then). The
                # retries/backoff already paid on the device path survive
                # into the carry so the result still reports them.
                carry_retries += sum(mm.retries for mm in metrics.values())
                carry_backoff_ms += sum(mm.backoff_ms
                                        for mm in metrics.values())
                if first_metric is not None:
                    carry_retries += first_metric.retries
                    carry_backoff_ms += first_metric.backoff_ms
                cpu_results, cpu_inputs = {}, inputs
                metrics = {}
                start = 0
                first_metric = None
            try:
                for node in plan.nodes[start:]:
                    childs = [cpu_results[id(c)] for c in node.children]
                    if first_metric is not None and node is plan.nodes[start]:
                        m = first_metric  # keep the failed op's retry record
                    else:
                        m = OperatorMetrics(label=node.label, kind=node.kind,
                                            describe=node.describe())
                    m.degraded = True
                    t0 = time.perf_counter()
                    with tracing.range_ctx(f"plan.{node.label}.degraded"):
                        out = self._exec_eager_node(node, childs, cpu_inputs,
                                                    schemas, m)
                    if self.block_per_op:
                        jax.block_until_ready([c.data for c in out.columns])
                    m.wall_ms = (time.perf_counter() - t0) * 1e3
                    m.rows_in = sum(t.num_rows for t in childs)
                    m.rows_out = out.num_rows
                    m.bytes_out = operand_nbytes(out)
                    metrics[node.label] = m
                    cpu_results[id(node)] = out
            except BaseException as err:
                # the debuggability contract holds on THIS tier too: a
                # failed degraded plan still surfaces what completed
                try:
                    err.plan_metrics = dict(metrics)
                except Exception:
                    pass
                raise
        wall = (time.perf_counter() - t_plan0) * 1e3
        return PlanResult(plan, cpu_results[id(plan.root)], None, metrics,
                          mode, wall, degraded=True,
                          attempts=attempts, caps=caps,
                          retries=carry_retries + sum(
                              mm.retries for mm in metrics.values()),
                          breaker=self._breaker_snapshot(),
                          backoff_ms=carry_backoff_ms + sum(
                              mm.backoff_ms for mm in metrics.values()))

    # ---- streaming prefix (docs/io.md) ------------------------------------
    @staticmethod
    def _stream_chains(plan, inputs) -> Dict[int, List[PlanNode]]:
        """id(tail) -> [Scan, op, ...] streamable prefixes. A chain starts
        at a Scan bound to a streaming source and extends while the node
        has exactly ONE consumer that is a row-wise Filter/Project/
        FusedSelect (no scalar aggregates — those reduce over the whole
        relation); it may terminate INTO a HashAggregate whose ops
        decompose exactly (sum/count/min/max/size over non-float inputs —
        fp partial sums are not reorder-exact). Everything else is the
        concat boundary: the tail materializes one Table and the rest of
        the plan proceeds normally."""
        from .expr import has_scalar_agg
        parents: Dict[int, List[PlanNode]] = {}
        for n in plan.nodes:
            for c in n.children:
                parents.setdefault(id(c), []).append(n)
        chains: Dict[int, List[PlanNode]] = {}
        for scan in plan.scans:
            src = inputs.get(scan.source)
            if src is None or isinstance(src, Table) or \
                    not getattr(src, "is_streaming_source", False):
                continue
            chain = [scan]
            node: PlanNode = scan
            while True:
                ps = parents.get(id(node), [])
                if len(ps) != 1:
                    break
                p = ps[0]
                if isinstance(p, Filter) and \
                        not has_scalar_agg(p.predicate):
                    chain.append(p)
                    node = p
                    continue
                if isinstance(p, Project) and not any(
                        has_scalar_agg(e) for _, e in p.exprs):
                    chain.append(p)
                    node = p
                    continue
                if isinstance(p, FusedSelect) and \
                        not has_scalar_agg(p.predicate) and not any(
                            has_scalar_agg(e) for _, e in p.exprs):
                    chain.append(p)
                    node = p
                    continue
                if (isinstance(p, HashAggregate)
                        and all(o in _STREAM_AGG_MERGE
                                for _, o, _ in p.aggs)
                        and not _input_has_floats(src)):
                    chain.append(p)     # terminal: partial accumulation
                break
            if len(chain) > 1:
                chains[id(chain[-1])] = chain
        return chains

    def _stream_op(self, node, t: Table, inputs, schemas,
                   m: OperatorMetrics, fn=None) -> Table:
        """One chain operator over one chunk, with the same per-op fault
        policy as the materialized path (backoff-paced retries; a breaker
        trip raises _StreamBreaker so the caller can degrade)."""
        from ..utils import tracing
        attempt = 0
        while True:
            t0 = time.perf_counter()
            try:
                with tracing.range_ctx(f"plan.{node.label}"):
                    self._faultinj_point(node)
                    out = (fn(t) if fn is not None else
                           self._exec_eager_node(node, [t], inputs,
                                                 schemas, m))
                break
            except _fault_surface() as err:
                if self._handle_fault(err, node.label, attempt, m):
                    attempt += 1
                    continue
                raise _StreamBreaker(err, m.retries, m.backoff_ms)
        if attempt:
            self.health.record_success(node.label)
        m.wall_ms = (m.wall_ms or 0.0) + (time.perf_counter() - t0) * 1e3
        m.rows_in += t.num_rows
        m.rows_out += out.num_rows
        return out

    def _exec_stream_chain(self, chain, inputs, schemas,
                           metrics: Dict[str, OperatorMetrics]) -> Table:
        """Run one streamable prefix morsel-at-a-time: row-group pruning at
        the scan, bounded host prefetch decoding chunk N+1 while chunk N
        executes, per-chunk Filter/Project/FusedSelect, and partial
        HashAggregate accumulation merged exactly at the end. Fills
        `metrics` for every chain node; returns the tail's Table."""
        from .. import config
        from .optimizer import pruning_conjuncts
        from ..runtime.admission import operand_nbytes
        ops = _ops()
        scan = chain[0]
        src = inputs[scan.source]
        ms = {n.label: OperatorMetrics(label=n.label, kind=n.kind,
                                       describe=n.describe())
              for n in chain}
        sm = ms[scan.label]
        columns = (list(scan.projection) if scan.projection is not None
                   else None)
        conjuncts = (pruning_conjuncts(scan.predicate)
                     if scan.predicate is not None else [])
        kept, pruned, skipped = src.select_groups(conjuncts, columns)
        sm.io_row_groups_total = src.num_row_groups
        sm.io_row_groups_pruned = pruned
        sm.io_bytes_skipped = skipped
        agg = chain[-1] if isinstance(chain[-1], HashAggregate) else None
        body = chain[1:-1] if agg is not None else chain[1:]
        chunk_rows = src.chunk_rows or config.io_chunk_rows() or None
        if chunk_rows is None:
            # adaptive morsel sizing (plan/stats.py, docs/adaptive.md):
            # with no explicit bound, size chunks from this scan's
            # OBSERVED decode throughput — the stream's exact two-phase
            # merge makes the result chunking-invariant, so this only
            # changes pacing, never bytes. Explicit knobs always win.
            from . import stats as stats_mod
            store = stats_mod.active_store()
            if store is not None:
                from .optimizer import subtree_fingerprints
                # a Scan is a leaf: hashing it alone yields the same
                # fingerprint record_result stored, without re-hashing
                # the whole plan on the streaming hot path
                scan_fp = subtree_fingerprints(scan)[id(scan)]
                chunk_rows = store.suggest_chunk_rows(
                    jax.default_backend(), scan_fp) or None
        depth = config.io_prefetch()
        gen = src.chunks(columns=columns, row_groups=kept,
                         chunk_rows=chunk_rows)
        feed = _ChunkPrefetcher(gen, depth) if depth > 0 else _SyncFeed(gen)
        parts: List[Table] = []         # tail outputs (or agg partials)
        empty_t: Optional[Table] = None
        proc_intervals = []
        try:
            while True:
                chunk = feed.get()
                if chunk is None:
                    break
                t0p = time.perf_counter()
                sm.rows_out += chunk.num_rows
                sm.bytes_out += operand_nbytes(chunk)
                t = chunk
                for node in body:
                    t = self._stream_op(node, t, inputs, schemas,
                                        ms[node.label])
                    ms[node.label].bytes_out += operand_nbytes(t)
                if agg is not None:
                    if t.num_rows == 0:
                        # fully-filtered morsel: contributes nothing, and a
                        # keyless min/max over a ZERO-ROW frame would raise
                        # where the table-bound plan (reducing over the
                        # whole non-empty relation) succeeds — skip it,
                        # keeping one empty frame for the all-empty case
                        empty_t = t
                        proc_intervals.append((t0p, time.perf_counter()))
                        continue
                    t = self._stream_op(
                        agg, t, inputs, schemas, ms[agg.label],
                        fn=lambda tt: self._stream_partial_agg(agg, tt,
                                                               schemas))
                parts.append(t)
                if self.block_per_op:
                    jax.block_until_ready([c.data for c in t.columns])
                proc_intervals.append((t0p, time.perf_counter()))
        finally:
            feed.close()
        sm.io_decode_ms = feed.decode_ms
        sm.io_overlap_ms = _interval_overlap_ms(feed.decode_intervals,
                                                proc_intervals)
        sm.wall_ms = feed.decode_ms     # scan wall = host decode
        # concatenate ONLY at the first non-streamable boundary
        tail = chain[-1]
        tm = ms[tail.label]
        t0 = time.perf_counter()
        if agg is not None:
            if not parts:
                # every morsel filtered to zero rows: aggregate the empty
                # frame once — identical semantics (including any keyless
                # min/max error) to the table-bound plan over an empty
                # filtered relation
                parts = [self._stream_op(
                    agg, empty_t, inputs, schemas, ms[agg.label],
                    fn=lambda tt: self._stream_partial_agg(agg, tt,
                                                           schemas))]
            out = self._finalize_stream_agg(agg, parts, schemas)
            tm.rows_out = out.num_rows  # partial rows were internal
        else:
            out = parts[0] if len(parts) == 1 else ops.concat_tables(parts)
        if self.block_per_op:
            jax.block_until_ready([c.data for c in out.columns])
        tm.wall_ms = (tm.wall_ms or 0.0) + (time.perf_counter() - t0) * 1e3
        tm.bytes_out = operand_nbytes(out)
        for n in chain:
            metrics[n.label] = ms[n.label]
        return out

    def _stream_partial_agg(self, node: HashAggregate, t: Table,
                            schemas) -> Table:
        """Per-chunk partial aggregation (named like the final schema, so
        the merge groups on the output columns)."""
        ops = _ops()
        if not node.keys:
            return self._global_aggregate(t, node)
        agg = ops.groupby_aggregate(t, list(node.keys),
                                    [(c, o) for c, o, _ in node.aggs])
        return Table(list(agg.columns), names=schemas[id(node)])

    def _finalize_stream_agg(self, node: HashAggregate,
                             partials: List[Table], schemas) -> Table:
        """Exact merge of per-chunk partials: counts sum, sums sum, min/max
        re-reduce — the same two-phase shape as the distributed tier, over
        chunks instead of mesh peers. The sort-based groupby kernel's
        key-ordered output makes the merged result row-identical to the
        single-pass aggregate."""
        ops = _ops()
        cat = (partials[0] if len(partials) == 1
               else ops.concat_tables(partials))
        merged_aggs = tuple((out, _STREAM_AGG_MERGE[o], out)
                            for _, o, out in node.aggs)
        if not node.keys:
            merge_node = HashAggregate(node.child, (), merged_aggs)
            return self._global_aggregate(cat, merge_node)
        agg = ops.groupby_aggregate(cat, list(node.keys),
                                    [(c, o) for c, o, _ in merged_aggs])
        return Table(list(agg.columns), names=schemas[id(node)])

    def _materialize_scan(self, node: Scan, src,
                          m: Optional[OperatorMetrics]) -> Table:
        """Source-bound Scan outside a streamable prefix (shared scans,
        join inputs, the capped tier): one admitted read, still with
        selective decode (projection columns only) and stats-driven
        row-group pruning."""
        from .optimizer import pruning_conjuncts
        columns = (list(node.projection) if node.projection is not None
                   else None)
        conjuncts = (pruning_conjuncts(node.predicate)
                     if node.predicate is not None else [])
        kept, pruned, skipped = src.select_groups(conjuncts, columns)
        t0 = time.perf_counter()
        t = src.read_all(columns=columns, row_groups=kept)
        if m is not None:
            m.io_row_groups_total = src.num_row_groups
            m.io_row_groups_pruned = pruned
            m.io_bytes_skipped = skipped
            m.io_decode_ms += (time.perf_counter() - t0) * 1e3
        return t

    @staticmethod
    def _kernel_choice(op: str, sig, m: Optional[OperatorMetrics] = None,
                       pin_degraded: bool = True):
        """Resolve one registry dispatch (ops/registry.py, docs/kernels.md)
        and stamp the choice on the operator's metrics. On the degraded CPU
        tier the backend is pinned to "cpu" (default_backend still reports
        the quarantined platform under jax.default_device): auto-selection
        must not hand work back to the device the breaker just isolated.
        Host-PLACED operators (co-placement worker threads, m.placement ==
        "host") pin the same way — the whole point of the placement is
        that the subtree does not touch the device."""
        from ..ops.registry import REGISTRY
        backend = "cpu" if (pin_degraded and m is not None
                            and (m.degraded or m.placement == "host")) \
            else None
        choice = REGISTRY.select(op, sig, backend=backend)
        if m is not None:
            m.kernel = choice.label
            if sig is not None:
                # side-channel for the stats store (plan/stats.py): the
                # op + signature this metric's wall time was measured
                # under, consumed by record_result to feed the registry
                # tie-break. A dynamic attribute, not a dataclass field —
                # profile()/to_dict() rows must not grow a non-JSON blob.
                m._kernel_sig = (op, sig)
        return choice

    def _exec_eager_node(self, node, childs: List[Table], inputs, schemas,
                         m: OperatorMetrics) -> Table:
        ops = _ops()
        if isinstance(node, Scan):
            t = inputs[node.source]
            if not isinstance(t, Table):
                # streaming source outside a streamable prefix: materialize
                # (pruned + projected) in one read
                return self._materialize_scan(node, t, m)
            if node.projection is not None:
                # pruned scan: unused columns never enter the plan
                t = t.select(list(node.projection))
            return t
        if isinstance(node, Filter):
            (t,) = childs
            mask = node.predicate.evaluate(t)
            return ops.apply_boolean_mask(t, mask)
        if isinstance(node, FusedSelect):
            # fused Filter+Project: gather ONLY the projection-referenced
            # columns through the mask, then project — one pass, instead of
            # materializing the full filtered child first. The registry
            # (ops/registry.py) may hand the front half to the Pallas
            # predicate+compaction kernel; the XLA mask+gather is the
            # fallback.
            (t,) = childs
            from ..ops import select_pallas
            # one shared definition with make_signature: the supports()
            # gate must describe exactly the columns the kernel is handed
            needed = select_pallas.needed_columns(t, node.exprs)
            choice = self._kernel_choice(
                "fused_select",
                select_pallas.make_signature(t, node.predicate, node.exprs,
                                             "eager"), m)
            if not choice.fallback:
                ft = choice.fn(t, node.predicate, needed)
            else:
                mask = node.predicate.evaluate(t)
                ft = ops.apply_boolean_mask(t.select(needed), mask)
            return self._project(ft, node)
        if isinstance(node, Project):
            (t,) = childs
            return self._project(t, node)
        if isinstance(node, HashJoin):
            lt, rt = childs
            lkeys = [lt[k] for k in node.left_keys]
            rkeys = [rt[k] for k in node.right_keys]
            from ..ops import join_pallas
            choice = self._kernel_choice(
                "hash_join",
                join_pallas.make_signature(lkeys, rkeys, node.how, "eager"),
                m)
            if node.how == "inner":
                if not choice.fallback:
                    lm, rm = choice.fn(lkeys, rkeys)
                else:
                    lm, rm = ops.inner_join(lkeys, rkeys)
                return Table(
                    list(ops.take_table(lt, lm.data,
                                        _has_negative=False).columns) +
                    list(ops.take_table(rt, rm.data,
                                        _has_negative=False).columns),
                    names=list(lt.names) + list(rt.names))
            keep = (ops.left_semi_join(lkeys, rkeys) if node.how == "left_semi"
                    else ops.left_anti_join(lkeys, rkeys))
            return ops.take_table(lt, keep.data, _has_negative=False)
        if isinstance(node, HashAggregate):
            (t,) = childs
            if not node.keys:
                return self._global_aggregate(t, node)
            # dispatch happens inside groupby_aggregate (registry op
            # "groupby"); re-selecting here only stamps the choice. Backend
            # intentionally NOT pinned for the degraded tier: the scan/
            # scatter pick keys on jax.default_backend(), exactly like the
            # kernel itself
            self._kernel_choice("groupby", None, m, pin_degraded=False)
            agg = ops.groupby_aggregate(t, list(node.keys),
                                        [(c, o) for c, o, _ in node.aggs])
            out_names = schemas[id(node)]
            return Table(list(agg.columns), names=out_names)
        if isinstance(node, Sort):
            (t,) = childs
            return ops.sort_table(t, key_names=list(node.keys),
                                  ascending=list(node.ascending))
        if isinstance(node, TopK):
            (t,) = childs
            from ..ops import topk_pallas
            choice = self._kernel_choice(
                "topk",
                topk_pallas.make_signature(t, node.keys, node.ascending,
                                           node.n, "eager"), m)
            if not choice.fallback:
                return choice.fn(t, list(node.keys), list(node.ascending),
                                 node.n)
            t = ops.sort_table(t, key_names=list(node.keys),
                               ascending=list(node.ascending))
            return ops.slice_table(t, 0, min(node.n, t.num_rows))
        if isinstance(node, Limit):
            (t,) = childs
            return ops.slice_table(t, 0, min(node.n, t.num_rows))
        if isinstance(node, Union):
            return ops.concat_tables(childs)
        if isinstance(node, Exchange):
            # single-chip tier: a no-op distribution marker. With a mesh,
            # the parent operator consumes it (distributed lowering).
            return childs[0]
        raise PlanValidationError(f"no eager lowering for {node.kind}")

    def _project(self, t: Table, node: Project,
                 alive: Optional[jnp.ndarray] = None) -> Table:
        cols = []
        for name, e in node.exprs:
            if isinstance(e, ColumnRef):
                cols.append(t[e.name])      # preserve dtype + validity
            else:
                v = e.evaluate(t, alive)
                if getattr(v, "ndim", 1) == 0:
                    # bare scalar aggregate (or literal fold): broadcast to
                    # the relation's length, as the Expr contract promises
                    v = jnp.broadcast_to(v, (t.num_rows,))
                cols.append(_col_from_array(v))
        return Table(cols, names=[n for n, _ in node.exprs])

    def _global_aggregate(self, t: Table, node: HashAggregate,
                          alive: Optional[jnp.ndarray] = None) -> Table:
        """Keyless (one-row) aggregate; honors `alive` in the capped tier."""
        from ..ops.aggregate import _agg_value_dtype
        cols, names = [], []
        for c, op, out_name in node.aggs:
            if op == "size":
                n_live = (jnp.sum(alive.astype(jnp.int64)) if alive is not None
                          else jnp.asarray(t.num_rows, jnp.int64))
                dt = dtypes.INT64
                val = n_live
            else:
                src = t[c]
                v = src.data
                ok = src.validity
                if alive is not None:
                    ok = alive if ok is None else (ok & alive)
                if op == "count":
                    val = (jnp.sum(ok.astype(jnp.int64)) if ok is not None
                           else jnp.asarray(t.num_rows, jnp.int64))
                    dt = dtypes.INT64
                else:
                    dt = _agg_value_dtype(op, src.dtype)
                    acc = v.astype(dt.storage_dtype())
                    if op == "sum":
                        if ok is not None:
                            acc = jnp.where(ok, acc, 0)
                        val = jnp.sum(acc)
                    else:
                        from .expr import _reduce_identity
                        if ok is not None:
                            acc = jnp.where(ok, acc,
                                            _reduce_identity(op, acc.dtype))
                        val = jnp.min(acc) if op == "min" else jnp.max(acc)
            cols.append(Column(dtype=dt, length=1,
                               data=val[None].astype(dt.storage_dtype())))
            names.append(out_name)
        return Table(cols, names=names)

    # ---- capped tier ------------------------------------------------------
    def _default_caps(self, plan, inputs) -> Dict[str, int]:
        """Initial capacities: the executor's shared caps (defaulted from
        the largest input) plus one per-node entry for each node-level
        override — those ride the SAME escalation dict, so an undersized
        override grows geometrically like everything else instead of
        livelocking through identical attempts. Per-node entries key on
        the toposort INDEX (stable across fingerprint-equal plans, whose
        labels differ), so the caps memo and program cache stay shared
        when the same plan is rebuilt."""
        caps = dict(self.caps)
        max_rows = max((t.num_rows for t in inputs.values()), default=1)
        needs_row = needs_key = False
        for i, n in enumerate(plan.nodes):
            if isinstance(n, HashJoin) and n.how == "inner":
                if n.row_cap is None:
                    needs_row = True
                else:
                    caps[f"row_cap:{i}"] = n.row_cap
            elif isinstance(n, HashAggregate) and n.keys:
                if n.key_cap is None:
                    needs_key = True
                else:
                    caps[f"key_cap:{i}"] = n.key_cap
        if needs_row:
            caps.setdefault("row_cap", max(max_rows, 1))
        if needs_key:
            caps.setdefault("key_cap", max(max_rows, 1))
        return caps

    @staticmethod
    def _node_cap(caps: Dict[str, int], which: str, idx: int) -> int:
        return caps.get(f"{which}:{idx}") or caps[which]

    @staticmethod
    def _cert_caps(plan, caps, cert):
        """Fold the resource certifier's sound rows-hi bounds
        (analysis/footprint.py) into the capped tier's capacities:

        - STARTING caps tighten to the certified hi where it is below the
          static start (a sound bound can never overflow, so a tighter
          start only shrinks padding and compiles a smaller program —
          per-node `row_cap:<i>`/`key_cap:<i>` entries, which outrank the
          shared keys exactly like authored overrides);
        - the escalation ladder CEILINGS at the certified hi (growing a
          capacity past a proven bound is wasted memory) — per node where
          a per-node entry exists, else on the shared key at the max hi
          over the nodes that fall through to it (an unbounded node
          poisons the shared ceiling, never the clamp safety).

        Returns (caps, ceil) for `auto_retry_overflow(ceil=...)`; the
        ceiling is advisory there — a clamped attempt that still
        overflows drops it (certifier-bug escape hatch)."""
        caps = dict(caps)
        ceil: Dict[str, int] = {}
        shared_hi: Dict[str, Optional[int]] = {"row_cap": 0, "key_cap": 0}
        for i, n in enumerate(plan.nodes):
            if isinstance(n, HashJoin) and n.how == "inner":
                which = "row_cap"
            elif isinstance(n, HashAggregate) and n.keys:
                which = "key_cap"
            else:
                continue
            b = cert.by_index.get(i)
            hi = None if b is None else b.rows_hi
            key = f"{which}:{i}"
            if key in caps:
                if hi is not None:
                    if hi < caps[key]:
                        caps[key] = hi
                    ceil[key] = max(caps[key], hi)
                continue
            cur = caps.get(which)
            if hi is not None and cur is not None and hi < cur:
                caps[key] = hi
                ceil[key] = hi
            elif shared_hi[which] is not None:
                shared_hi[which] = (None if hi is None
                                    else max(shared_hi[which], hi))
        for which, g in shared_hi.items():
            if g and which in caps:
                ceil[which] = max(g, caps[which])
        return caps, ceil

    def _execute_capped(self, plan, inputs, schemas,
                        source_fp=None, cert=None) -> PlanResult:
        from ..parallel.autoretry import auto_retry_overflow
        # the capped tier traces ONE whole-plan program over concrete
        # shapes, so streaming sources materialize first — still through
        # the pruned/projected read, so the decode savings carry over
        scan_io: Dict[str, OperatorMetrics] = {}
        if any(not isinstance(t, Table) for t in inputs.values()):
            inputs = dict(inputs)
            # one Scan per source is a Plan invariant (Plan.__init__
            # rejects duplicate sources), so materializing per NAME with
            # that scan's projection/predicate loses nothing
            by_source = {n.source: n for n in plan.nodes
                         if isinstance(n, Scan)}
            for name, v in list(inputs.items()):
                if isinstance(v, Table):
                    continue
                node = by_source.get(name)
                holder = OperatorMetrics(label=name, kind="Scan")
                if node is not None:
                    inputs[name] = self._materialize_scan(node, v, holder)
                else:
                    inputs[name] = v.read_all()
                scan_io[name] = holder
        # start from the input-derived defaults, floored up by any caps the
        # plan already escalated to: the memo must never UNDERSIZE a run on
        # larger inputs than it was learned on (only skip re-learning)
        caps = self._default_caps(plan, inputs)
        fp = plan.fingerprint        # canonical structural hash: equivalent
        #                              plans built independently share the
        #                              caps memo and compiled programs
        for k, v in (self._caps_memo.get(fp) or {}).items():
            caps[k] = max(caps.get(k, 0), v)
        # adaptive cap seeding (plan/stats.py, docs/adaptive.md): floor
        # the starting capacities at the observed high-water marks from
        # prior executions of this authored plan, so a repeat fingerprint
        # compiles once instead of re-climbing the escalation ladder —
        # the per-executor memo above, promoted across executor
        # instances (and processes, with persistence on). Same
        # floor-only contract: caps are STARTING capacities the overflow
        # ladder would have grown anyway, so seeding can never change
        # results, only skip retries. Keyed by the backend about to run:
        # degraded-run stats recorded under "cpu" never seed a device.
        from . import stats as stats_mod
        store = stats_mod.active_store()
        if store is not None and source_fp is not None:
            for k, v in store.observed_caps(jax.default_backend(),
                                            source_fp,
                                            executed_fp=fp).items():
                caps[k] = max(caps.get(k, 0), v)
        # certified cap bounds (analysis/footprint.py, docs/adaptive.md):
        # with adaptivity on, cold starting caps tighten to the sound
        # hi-bound and the escalation ladder ceilings at it — the warm
        # observed high-water (merged above) must always sit at or below
        # the certified bound; that inequality IS the certifier's
        # soundness check (fuzz property 5). Stats off stays
        # byte-identical static: the certifier then only stamps results.
        from .. import config
        cert_ceil: Dict[str, int] = {}
        if store is not None and cert is not None and config.cert_seed():
            caps, cert_ceil = self._cert_caps(plan, caps, cert)
        t0 = time.perf_counter()
        attempts = 0
        cache_hits = 0
        bytes_map: Dict[int, int] = {}
        kernel_map: Dict[int, str] = {}
        last_caps = dict(caps)
        self.health.start_plan_attempt()
        if self.degrade != "off" and not self.health.admit():
            return self._execute_degraded(plan, inputs, schemas, {}, {},
                                          start=0, t_plan0=t0, mode="capped")

        def run(**caps_now):
            nonlocal attempts, cache_hits
            attempts += 1
            last_caps.clear()
            last_caps.update(caps_now)
            # plan-level faultinj surface: fires every attempt, including
            # cache-hit runs where the op-level shims never re-trace
            for node in plan.nodes:
                self._faultinj_point(node)
            # shapes AND names in the key: jax retraces per input shape
            # anyway, a per-shape entry keeps each bytes_map true to ITS
            # trace, and the names guard fingerprint-shared undeclared
            # scans bound to differently-named tables
            fn, bm, km, hit = self._jitted_capped(
                plan, schemas, caps_now,
                tuple(sorted((n, tuple(t.names), t.num_rows)
                             for n, t in inputs.items())))
            cache_hits += hit
            out = fn(dict(inputs))
            bytes_map.clear()
            bytes_map.update(bm)    # bm fills during the first trace
            kernel_map.clear()
            kernel_map.update(km)
            return out

        retries = 0
        backoff_total = 0.0
        plan_metric = OperatorMetrics(label="plan", kind="Plan")
        while True:
            try:
                (table, valid, counts, overflow), final_caps = \
                    auto_retry_overflow(run, caps, self.max_cap_attempts,
                                        ceil=cert_ceil)
                if retries:
                    self.health.record_success("plan")
                self._caps_memo[fp] = dict(final_caps)
                break
            except _fault_surface() as err:
                # failures are plan-granular here (one XLA program), so the
                # sticky window keys on the plan attempt, not an operator
                if self._handle_fault(err, "plan", retries, plan_metric):
                    retries += 1
                    backoff_total = plan_metric.backoff_ms
                    # resume from the escalated capacities, not the
                    # originals: growth already paid for must survive
                    caps = dict(last_caps)
                    continue
                if self.degrade == "off":
                    raise
                return self._execute_degraded(
                    plan, inputs, schemas, {}, {}, start=0, t_plan0=t0,
                    mode="capped", carry_retries=plan_metric.retries,
                    carry_backoff_ms=plan_metric.backoff_ms,
                    # escalation history survives the trip: the device path
                    # DID run `attempts` times over these (grown) caps
                    attempts=attempts, caps=dict(last_caps))
        jax.block_until_ready(valid)
        wall = (time.perf_counter() - t0) * 1e3
        metrics: Dict[str, OperatorMetrics] = {}
        # cap growths only: each of the (retries+1) auto_retry runs gets a
        # free first attempt that is not an escalation
        escal = max(0, attempts - (retries + 1))
        counts_np = {k: (int(a), int(b))
                     for k, (a, b) in zip(counts.keys(),
                                          np.asarray(list(counts.values()),
                                                     dtype=np.int64))}
        for i, node in enumerate(plan.nodes):
            # counts/bytes key on the toposort INDEX, not the label: a
            # fingerprint-shared program was traced over an equivalent
            # plan whose node labels differ, but its toposort lines up 1:1
            rows_in, rows_out = counts_np[i]
            uses_cap = (isinstance(node, HashJoin) and node.how == "inner") \
                or (isinstance(node, HashAggregate) and node.keys)
            # retries are plan-granular in this tier (one XLA program) and
            # live on PlanResult.retries — copying them onto every row would
            # make per-op aggregation overcount N-fold
            metrics[node.label] = OperatorMetrics(
                label=node.label, kind=node.kind, describe=node.describe(),
                rows_in=rows_in, rows_out=rows_out,
                bytes_out=bytes_map.get(i, 0),
                escalations=escal if uses_cap else 0,
                kernel=kernel_map.get(i, ""))
            if isinstance(node, Scan) and node.source in scan_io:
                io = scan_io[node.source]
                mm = metrics[node.label]
                mm.io_row_groups_total = io.io_row_groups_total
                mm.io_row_groups_pruned = io.io_row_groups_pruned
                mm.io_bytes_skipped = io.io_bytes_skipped
                mm.io_decode_ms = io.io_decode_ms
        return PlanResult(plan, table, valid, metrics, "capped", wall,
                          attempts=attempts, caps=final_caps,
                          retries=retries,
                          breaker=self._breaker_snapshot(),
                          backoff_ms=backoff_total,
                          jit_cache_hits=cache_hits)

    def _jitted_capped(self, plan, schemas, caps, input_key):
        # the canonical FINGERPRINT is the key: structurally equivalent
        # plans built independently (same kinds/exprs/schemas/DAG shape)
        # share one compiled program instead of re-tracing. The backend +
        # kernel-override knob join the key: registry selection happens at
        # trace time, so a program compiled under one kernel choice must
        # never serve another (docs/kernels.md). Returns (jitted_fn,
        # bytes_map, kernel_map, cache_hit).
        from .. import config
        from . import stats as stats_mod
        store = stats_mod.active_store()
        # the stats store's kernel tie-break resolves at trace time, so
        # its epoch (bumped only when a recorded timing changes some
        # signature's kernel ORDERING) joins the key: compiled programs
        # stay shared across runs whose picks cannot have changed, and
        # never alias across a demotion flip (docs/adaptive.md)
        kern_key = (jax.default_backend(),
                    tuple(sorted(config.kernel_overrides().items())),
                    None if store is None else (store.uid,
                                                store.kernel_epoch))
        key = (plan.fingerprint, tuple(sorted(caps.items())), input_key,
               kern_key)
        hit = self._jit_cache.get(key)
        if hit is not None:
            return hit[0], hit[1], hit[2], True
        bytes_map: Dict[int, int] = {}
        kernel_map: Dict[int, str] = {}

        def fn(tables: Dict[str, Table]):
            return self._run_capped(plan, schemas, caps, tables, bytes_map,
                                    kernel_map)

        jitted = jax.jit(fn)
        self._jit_cache[key] = (jitted, bytes_map, kernel_map)
        return jitted, bytes_map, kernel_map, False

    def _run_capped(self, plan, schemas, caps, tables, bytes_map,
                    kernel_map):
        from ..runtime.admission import operand_nbytes
        rels: Dict[int, _CappedRel] = {}
        # counts/bytes key on the toposort index: stable across
        # fingerprint-equal plans, whose labels differ (see _jitted_capped)
        counts: Dict[int, Tuple] = {}
        overflow = jnp.asarray(False)
        for i, node in enumerate(plan.nodes):
            childs = [rels[id(c)] for c in node.children]
            rel, ovf = self._exec_capped_node(node, i, childs, tables,
                                              schemas, caps, kernel_map)
            if ovf is not None:
                overflow = overflow | ovf
            bytes_map[i] = operand_nbytes(rel.table)
            rows_in = sum((jnp.sum(c.alive.astype(jnp.int64))
                           for c in childs), start=jnp.int64(0))
            counts[i] = (rows_in, jnp.sum(rel.alive.astype(jnp.int64)))
            rels[id(node)] = rel
        root = rels[id(plan.root)]
        return root.table, root.alive, counts, overflow

    def _exec_capped_node(self, node, idx: int, childs: List[_CappedRel],
                          tables, schemas, caps, kernel_map):
        ops = _ops()

        def pick(op: str, sig):
            # registry dispatch at trace time; choices key on the toposort
            # index (like counts/bytes) so fingerprint-shared programs stamp
            # consistently
            from ..ops.registry import REGISTRY
            choice = REGISTRY.select(op, sig)
            kernel_map[idx] = choice.label
            return choice
        if isinstance(node, Scan):
            t = tables[node.source]
            if node.projection is not None:
                t = t.select(list(node.projection))
            return _CappedRel(t, jnp.ones((t.num_rows,), bool)), None
        if isinstance(node, Filter):
            (c,) = childs
            # predicate as a mask AND — the jit tier's filter idiom: no
            # compaction, dead rows stay and stay dead
            mask = node.predicate.evaluate(c.table, c.alive)
            return _CappedRel(c.table, c.alive & mask), None
        if isinstance(node, FusedSelect):
            # filter-then-project over the padded frame: the predicate ANDs
            # into alive and the projection evaluates under the new mask
            # (scalar aggregates reduce over the filtered live rows). No
            # compaction happens here, so there is no Pallas form — the
            # registry consult documents the decline (tier="capped")
            (c,) = childs
            from ..ops import select_pallas
            pick("fused_select",
                 select_pallas.make_signature(c.table, node.predicate,
                                              node.exprs, "capped"))
            mask = node.predicate.evaluate(c.table, c.alive)
            alive = c.alive & mask
            return _CappedRel(self._project(c.table, node, alive),
                              alive), None
        if isinstance(node, Project):
            (c,) = childs
            return _CappedRel(self._project(c.table, node, c.alive),
                              c.alive), None
        if isinstance(node, HashJoin):
            l, r = childs
            lkeys = [l.table[k] for k in node.left_keys]
            rkeys = [r.table[k] for k in node.right_keys]
            from ..ops import join_pallas
            choice = pick("hash_join",
                          join_pallas.make_signature(lkeys, rkeys, node.how,
                                                     "capped"))
            if node.how == "inner":
                row_cap = self._node_cap(caps, "row_cap", idx)
                if not choice.fallback:
                    lm, rm, valid, ovf = join_pallas.inner_join_capped_pallas(
                        lkeys, rkeys, row_cap=row_cap, lalive=l.alive,
                        ralive=r.alive)
                else:
                    lm, rm, valid, ovf = ops.inner_join_capped(
                        lkeys, rkeys, row_cap=row_cap, lalive=l.alive,
                        ralive=r.alive)
                cols = [ops.take(col, lm, _has_negative=False)
                        for col in l.table.columns]
                cols += [ops.take(col, rm, _has_negative=False)
                         for col in r.table.columns]
                t = Table(cols, names=list(l.table.names) +
                          list(r.table.names))
                return _CappedRel(t, valid), ovf
            mask = ops.semi_join_mask(lkeys, rkeys, lalive=l.alive,
                                      ralive=r.alive)
            alive = (l.alive & mask if node.how == "left_semi"
                     else l.alive & ~mask)
            return _CappedRel(l.table, alive), None
        if isinstance(node, HashAggregate):
            (c,) = childs
            if not node.keys:
                t = self._global_aggregate(c.table, node, alive=c.alive)
                return _CappedRel(t, jnp.ones((1,), bool)), None
            pick("groupby", None)   # dispatch inside groupby_aggregate_capped
            key_cap = self._node_cap(caps, "key_cap", idx)
            agg, valid, ovf = ops.groupby_aggregate_capped(
                c.table, list(node.keys), [(cn, o) for cn, o, _ in node.aggs],
                key_cap=key_cap, alive=c.alive)
            t = Table(list(agg.columns), names=schemas[id(node)])
            return _CappedRel(t, valid), ovf
        if isinstance(node, Sort):
            (c,) = childs
            t, alive = ops.sort_table_capped(
                c.table, key_names=list(node.keys),
                ascending=list(node.ascending), alive=c.alive)
            return _CappedRel(t, alive), None
        if isinstance(node, TopK):
            # fused Sort+Limit: dead rows sink in the capped sort, then the
            # first n LIVE rows survive via the inclusive prefix count. The
            # Pallas kernel instead returns the top-n live rows directly
            # (narrower frame, same live set — downstream capped operators
            # accept any row count)
            (c,) = childs
            from ..ops import topk_pallas
            choice = pick("topk",
                          topk_pallas.make_signature(c.table, node.keys,
                                                     node.ascending, node.n,
                                                     "capped"))
            if not choice.fallback:
                t, alive = topk_pallas.topk_capped(
                    c.table, list(node.keys), list(node.ascending), node.n,
                    c.alive)
                return _CappedRel(t, alive), None
            t, alive = ops.sort_table_capped(
                c.table, key_names=list(node.keys),
                ascending=list(node.ascending), alive=c.alive)
            prefix = jnp.cumsum(alive.astype(jnp.int32))
            return _CappedRel(t, alive & (prefix <= node.n)), None
        if isinstance(node, Limit):
            (c,) = childs
            # first n LIVE rows: inclusive prefix count over the mask
            prefix = jnp.cumsum(c.alive.astype(jnp.int32))
            return _CappedRel(c.table, c.alive & (prefix <= node.n)), None
        if isinstance(node, Union):
            t = ops.concat_tables([c.table for c in childs])
            alive = jnp.concatenate([c.alive for c in childs])
            return _CappedRel(t, alive), None
        if isinstance(node, Exchange):
            return childs[0], None
        raise PlanValidationError(f"no capped lowering for {node.kind}")
