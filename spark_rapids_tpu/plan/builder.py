"""Validating plan builder: fluent construction + whole-DAG validation.

`PlanBuilder` hands out `Rel` wrappers whose chained methods append operator
nodes; `Rel.build()` (or `Plan(root)`) validates the whole DAG bottom-up —
schema resolution, expression references, join-key arity, agg ops — and
raises `PlanValidationError` with the offending node's label. Scans with
declared schemas validate fully at build time; undeclared scans defer the
checks of their subtree to execute(), where the bound tables provide the
real schemas (both paths run the same `output_names` contract).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union as TUnion

from .expr import Expr
from .nodes import (Exchange, Filter, HashAggregate, HashJoin, Limit,
                    PlanNode, PlanValidationError, Project, Scan, Sort,
                    Union)

__all__ = ["Plan", "PlanBuilder", "Rel", "PlanValidationError"]


def _toposort(root: PlanNode) -> List[PlanNode]:
    """Children-first order; each DAG-shared node appears exactly once."""
    order: List[PlanNode] = []
    seen = set()
    stack = [(root, False)]
    while stack:
        node, expanded = stack.pop()
        if id(node) in seen:
            continue
        if expanded:
            seen.add(id(node))
            order.append(node)
        else:
            stack.append((node, True))
            for c in node.children:
                if id(c) not in seen:
                    stack.append((c, False))
    return order


class Plan:
    """A validated operator DAG. `schemas` maps node -> output names for
    every node whose schema is resolvable from declared scan schemas;
    execute() re-resolves with the bound inputs."""

    def __init__(self, root: PlanNode):
        self.root = root
        self.nodes = _toposort(root)
        self.scans = [n for n in self.nodes if isinstance(n, Scan)]
        # build-time validation routes through the static verifier
        # (analysis/verifier.py, docs/analysis.md), so builder-time and
        # execute-time diagnostics share one error vocabulary: a
        # PlanVerificationError (still a PlanValidationError) whose
        # violations carry an invariant code + the offending operator's
        # label. Lazy import: analysis pulls heavier plan modules.
        from ..analysis import verifier
        self.schemas = verifier.check_build(self)

    # ---- validation -------------------------------------------------------
    def resolve_schemas(self, bound: Optional[Dict[str, Sequence[str]]] = None,
                        strict: bool = True) -> Dict[int, Tuple[str, ...]]:
        """node-id -> output names. `bound` gives scan schemas from actual
        tables (overriding declarations, which are then cross-checked).
        strict=False skips subtrees fed by undeclared scans instead of
        raising (build-time pass). Delegates to the static verifier's
        schema-propagation layer — the single home of the
        `output_names` contract's error vocabulary."""
        from ..analysis import verifier
        return verifier.resolve_schemas(self.nodes, bound, strict)

    @property
    def input_names(self) -> List[str]:
        return [s.source for s in self.scans]

    @property
    def fingerprint(self) -> str:
        """Canonical structural hash (node kinds, parameters, exprs,
        declared schemas, DAG shape). Two independently built plans with
        the same structure share one fingerprint — the executor keys its
        compiled-program and caps memos on it, so equivalent plans reuse
        compiled XLA programs (see plan/optimizer.py)."""
        fp = self.__dict__.get("_fingerprint")
        if fp is None:
            from .optimizer import plan_fingerprint
            fp = self.__dict__["_fingerprint"] = plan_fingerprint(self)
        return fp

    # ---- explain ----------------------------------------------------------
    def explain(self) -> str:
        """Pre-run plan tree (Spark's `EXPLAIN` analogue). DAG-shared nodes
        print once and are referenced by label afterwards."""
        lines: List[str] = []
        printed = set()

        def walk(node: PlanNode, prefix: str, tail: bool, root: bool):
            if root:
                head, child_prefix = "", ""
            else:
                head = prefix + ("└─ " if tail else "├─ ")
                child_prefix = prefix + ("   " if tail else "│  ")
            desc = node.describe()
            schema = self.schemas.get(id(node))
            cols = f" -> [{', '.join(schema)}]" if schema is not None else ""
            if id(node) in printed:
                lines.append(f"{head}[ref {node.label}]")
                return
            printed.add(id(node))
            lines.append(f"{head}{node.label}"
                         f"{' ' + desc if desc else ''}{cols}")
            kids = node.children
            for i, c in enumerate(kids):
                walk(c, child_prefix, i == len(kids) - 1, False)

        walk(self.root, "", True, True)
        return "\n".join(lines)

    def __repr__(self):
        return f"Plan({self.root.label}, {len(self.nodes)} nodes)"


class Rel:
    """Fluent wrapper over one node; every method returns a new Rel."""

    def __init__(self, node: PlanNode):
        self.node = node

    def filter(self, predicate: Expr) -> "Rel":
        return Rel(Filter(self.node, predicate))

    def project(self, exprs: TUnion[Dict[str, Expr],
                                    Sequence[Tuple[str, Expr]]]) -> "Rel":
        items = list(exprs.items()) if isinstance(exprs, dict) else list(exprs)
        return Rel(Project(self.node, tuple(items)))

    def select(self, names: Sequence[str]) -> "Rel":
        from .expr import col
        return self.project([(n, col(n)) for n in names])

    def join(self, other: "Rel", left_on: TUnion[str, Sequence[str]],
             right_on: TUnion[str, Sequence[str], None] = None,
             how: str = "inner", row_cap: Optional[int] = None) -> "Rel":
        lk = (left_on,) if isinstance(left_on, str) else tuple(left_on)
        if right_on is None:
            rk = lk
        else:
            rk = (right_on,) if isinstance(right_on, str) else tuple(right_on)
        return Rel(HashJoin(self.node, other.node, lk, rk, how=how,
                            row_cap=row_cap))

    def aggregate(self, keys: Sequence[str],
                  aggs: Sequence[Tuple[str, str, str]],
                  key_cap: Optional[int] = None) -> "Rel":
        return Rel(HashAggregate(self.node, tuple(keys),
                                 tuple(tuple(a) for a in aggs),
                                 key_cap=key_cap))

    def sort(self, keys: Sequence[str],
             ascending: TUnion[bool, Sequence[bool]] = True) -> "Rel":
        asc = ((ascending,) * len(keys) if isinstance(ascending, bool)
               else tuple(ascending))
        return Rel(Sort(self.node, tuple(keys), asc))

    def limit(self, n: int) -> "Rel":
        return Rel(Limit(self.node, n))

    def exchange(self, keys: Sequence[str] = ()) -> "Rel":
        return Rel(Exchange(self.node, tuple(keys)))

    def union(self, *others: "Rel") -> "Rel":
        return Rel(Union((self.node,) + tuple(o.node for o in others)))

    def build(self) -> Plan:
        return Plan(self.node)


class PlanBuilder:
    """Entry point: `scan()` leaves, then chain on the returned Rel."""

    def scan(self, source: str,
             schema: Optional[Sequence[str]] = None,
             est_rows: Optional[int] = None,
             parquet=None) -> Rel:
        """`est_rows` is an optional cardinality hint threaded to the
        optimizer's build-side selection; bound tables' actual row counts
        take precedence at execute().

        `parquet=` binds the scan to a STREAMING source instead of a
        materialized Table: a path, whole-file bytes, or an
        `io.ParquetSource`. The file's schema is read from the footer
        here, so the subtree validates at build time, and execute() needs
        no `inputs=` entry for this scan — the executor streams the file
        morsel-at-a-time through the plan's streamable prefix, pruning
        row groups against `Scan.predicate` (docs/io.md)."""
        if parquet is None:
            return Rel(Scan(source,
                            None if schema is None else tuple(schema),
                            est_rows=est_rows))
        from ..io.parquet import ParquetSource
        src = (parquet if isinstance(parquet, ParquetSource)
               else ParquetSource(parquet))
        if schema is not None and tuple(schema) != tuple(src.names):
            raise PlanValidationError(
                f"scan {source!r}: declared schema {list(schema)} does not "
                f"match the parquet file's {list(src.names)}")
        return Rel(Scan(source, tuple(src.names),
                        est_rows=src.num_rows if est_rows is None
                        else est_rows,
                        parquet=src))

    @staticmethod
    def union(rels: Sequence[Rel]) -> Rel:
        return Rel(Union(tuple(r.node for r in rels)))
