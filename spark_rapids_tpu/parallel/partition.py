"""Scatter-free, sort-free bucket partitioning for the shuffle hot path.

Round-1 measurements on the chip (docs/architecture.md): at 10M rows,
`jnp.searchsorted` ≈ 2 s (≈log₂n whole-array gather passes) and scatter-add
under x64 emulation ≈ 930 ms, while the ops the VPU loves — compares,
cumsum, block reduces — are tens of ms. `build_partition_map`
(parallel/shuffle.py) pays one stable sort + two searchsorted calls per
exchange; the functions here produce the same information from a single
streaming pass:

    histogram:  counts[b] = Σ rows (part == b)      — compare-reduce blocks
    ranks:      rank[r]   = #prior rows in r's bucket — running-count scan

Both are `lax.scan` over row blocks carrying a (P,) running count: no sort,
no searchsorted, no scatter. Memory is O(block × P) for the transient
one-hot, streamed block by block. `build_partition_map_scan` is a drop-in
replacement for `build_partition_map` (one int32 set-scatter builds the
(P, capacity) gather map from the ranks — a *set* scatter of row ids, not
the emulated-u64 add-scatter the measurement flagged).

The Pallas explicit-kernel tier of the same histogram lives in
parallel/partition_pallas.py; benchmarks/bench_partition.py A/Bs all three.
"""
from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

_DEFAULT_BLOCK = 65536


def _pad_blocks(part: jnp.ndarray, num_partitions: int, block_rows: int):
    n = part.shape[0]
    m = max(1, math.ceil(n / block_rows))
    pad = m * block_rows - n
    # out-of-range id: matches no bucket, so padding never counts
    padded = jnp.concatenate(
        [part.astype(jnp.int32),
         jnp.full((pad,), num_partitions, jnp.int32)]) if pad else \
        part.astype(jnp.int32)
    return padded.reshape(m, block_rows), n


def partition_histogram(part: jnp.ndarray, num_partitions: int,
                        block_rows: int = _DEFAULT_BLOCK) -> jnp.ndarray:
    """(P,) int32 bucket counts via blocked compare-reduce (no scatter)."""
    blocks, _ = _pad_blocks(part, num_partitions, block_rows)
    buckets = jnp.arange(num_partitions, dtype=jnp.int32)

    def body(acc, blk):
        onehot = (blk[:, None] == buckets[None, :])
        return acc + jnp.sum(onehot, axis=0, dtype=jnp.int32), None

    counts, _ = jax.lax.scan(body, jnp.zeros((num_partitions,), jnp.int32),
                             blocks)
    return counts


def partition_ranks(part: jnp.ndarray, num_partitions: int,
                    block_rows: int = _DEFAULT_BLOCK
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stable intra-bucket rank per row + (P,) counts, one streaming pass.

    rank[r] = number of earlier rows with the same partition id — exactly
    the slot a stable radix partition assigns. Scan blocks carry the (P,)
    running counts; within a block the rank is an exclusive cumsum of the
    one-hot matrix gathered back through the same one-hot (a multiply-sum,
    not an indexed gather)."""
    blocks, n = _pad_blocks(part, num_partitions, block_rows)
    buckets = jnp.arange(num_partitions, dtype=jnp.int32)

    def body(running, blk):
        onehot = (blk[:, None] == buckets[None, :]).astype(jnp.int32)
        csum = jnp.cumsum(onehot, axis=0)
        excl = csum - onehot
        rank = jnp.sum(onehot * (excl + running[None, :]), axis=1)
        return running + csum[-1], rank

    counts, ranks = jax.lax.scan(
        body, jnp.zeros((num_partitions,), jnp.int32), blocks)
    return ranks.reshape(-1)[:n], counts


def build_partition_map_scan(part: jnp.ndarray, num_partitions: int,
                             capacity: int):
    """Same contract as shuffle.build_partition_map — (gather_idx (P, cap),
    valid (P, cap), counts (P,)) — built from the streaming ranks instead
    of sort + searchsorted. Rows past a bucket's capacity are dropped and
    reported via counts > capacity (the SplitAndRetry overflow signal)."""
    n = part.shape[0]
    ranks, counts = partition_ranks(part, num_partitions)
    dest = jnp.where(ranks < capacity,
                     part.astype(jnp.int32) * capacity + ranks,
                     jnp.int32(num_partitions * capacity))
    flat = jnp.zeros((num_partitions * capacity,), jnp.int32).at[dest].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop")
    gather_idx = flat.reshape(num_partitions, capacity)
    slot = jnp.arange(capacity, dtype=jnp.int32)[None, :]
    valid = slot < counts[:, None]
    return gather_idx, valid, counts
