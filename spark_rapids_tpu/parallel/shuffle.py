"""ICI all-to-all partition exchange — the TPU-native shuffle slot.

The reference repo has no in-repo shuffle (SURVEY.md §2.4): partition exchange
lives one level up in spark-rapids' UCX shuffle manager, and the JNI layer only
models shuffle *threads* as a priority class. On TPU the equivalent first-class
component (BASELINE.json north star) keeps partition exchange on-device: rows
are hash-partitioned with Spark's murmur3 pmod, bucketed to a fixed per-peer
capacity, and exchanged over ICI with `jax.lax.all_to_all` inside `shard_map`.

Design notes (TPU-first):
- XLA needs static shapes, so the exchange uses fixed-capacity buckets
  (capacity = ceil(rows_per_shard / P) * slack). Overflowing rows would be
  dropped; callers size slack for their skew, and `exchange` returns per-bucket
  counts so overflow is detectable (the moral equivalent of the reference's
  SplitAndRetry contract: detect, then retry with a bigger capacity).
- The bucketing sort is a single stable `argsort` on partition id — this is
  the radix-partition step of a shuffle, fused by XLA with the gathers.
- Works identically on a CPU-host virtual mesh (tests) and a real slice: only
  the Mesh construction differs.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:      # jax < 0.5 keeps it in experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def make_mesh(n_devices: Optional[int] = None, axis: str = "data",
              cpu_fallback: bool = False) -> Mesh:
    """Build a 1-D device mesh. `cpu_fallback=True` is for validation runs on
    underprovisioned machines only (it substitutes virtual CPU devices, whose
    count is configurable even when the default backend is a TPU); production
    callers must leave it False so a short slice fails fast instead of
    silently running on host."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices and cpu_fallback:
            try:
                devs = jax.devices("cpu")
            except RuntimeError:
                pass
        if len(devs) < n_devices:
            raise ValueError(
                f"requested a {n_devices}-device mesh but only {len(devs)} "
                f"devices are visible")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def partition_ids(hashes: jnp.ndarray, num_partitions: int) -> jnp.ndarray:
    """Spark's `pmod(hash, numPartitions)` partitioner (non-negative mod)."""
    h = hashes.astype(jnp.int32)
    m = jnp.int32(num_partitions)
    r = jax.lax.rem(h, m)
    return jnp.where(r < 0, r + m, r).astype(jnp.int32)


def build_partition_map(part: jnp.ndarray, num_partitions: int,
                        capacity: int) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Bucket local rows by target partition into fixed-capacity slots.

    Returns (gather_idx (P, capacity) int32 row indices into the local shard,
             valid (P, capacity) bool, counts (P,) int32). Rows beyond
    `capacity` for a bucket are dropped (reported via counts > capacity).
    """
    n = part.shape[0]
    order = jnp.argsort(part, stable=True)            # radix-partition step
    sorted_part = part[order]
    # start offset of each partition in the sorted order
    starts = jnp.searchsorted(sorted_part, jnp.arange(num_partitions, dtype=part.dtype))
    ends = jnp.searchsorted(sorted_part, jnp.arange(num_partitions, dtype=part.dtype),
                            side="right")
    counts = (ends - starts).astype(jnp.int32)
    slot = jnp.arange(capacity, dtype=jnp.int32)[None, :]           # (P, cap)
    src = starts[:, None].astype(jnp.int32) + slot
    valid = slot < counts[:, None]
    src = jnp.clip(src, 0, max(n - 1, 0))
    gather_idx = order[src].astype(jnp.int32)
    return gather_idx, valid, counts


def _exchange_local(axis: str, num_partitions: int, capacity: int,
                    part: jnp.ndarray, *payloads: jnp.ndarray):
    """Per-shard body: bucket rows, all_to_all the buckets over `axis`."""
    gather_idx, valid, counts = build_partition_map(part, num_partitions, capacity)
    out = []
    for p in payloads:
        bucketed = jnp.take(p, gather_idx, axis=0)        # (P, cap, ...)
        zero = jnp.zeros((), dtype=p.dtype)
        mask = valid.reshape(valid.shape + (1,) * (bucketed.ndim - 2))
        bucketed = jnp.where(mask, bucketed, zero)
        # (P, cap, ...) -> exchange bucket p to peer p
        recv = jax.lax.all_to_all(bucketed, axis, split_axis=0, concat_axis=0,
                                  tiled=True)              # (P, cap, ...) one bucket/peer
        out.append(recv.reshape((-1,) + recv.shape[2:]))   # (P*cap, ...) rows for me
    # exchange only the (P,) sent counts and rebuild the mask receiver-side —
    # capacity× less ICI traffic than shipping the full bool mask
    sent = jnp.minimum(counts, capacity)
    sent_recv = jax.lax.all_to_all(sent, axis, split_axis=0, concat_axis=0,
                                   tiled=True)
    slot = jnp.arange(capacity, dtype=jnp.int32)[None, :]
    recv_valid = slot < sent_recv[:, None]
    return tuple(out), recv_valid.reshape(-1), counts, sent


def exchange(mesh: Mesh, part: jnp.ndarray, payloads: Sequence[jnp.ndarray],
             capacity: int, axis: str = "data"):
    """All-to-all repartition: rows of `payloads` move to the shard given by
    `part` (values in [0, n_shards)). All arrays are sharded on axis 0.

    Returns (payloads_out, valid, counts): payloads_out rows are grouped by
    source shard with `valid` marking live slots; counts is the (global-view)
    per-source bucket histogram for overflow detection.
    """
    num_partitions = mesh.shape[axis]
    body = partial(_exchange_local, axis, num_partitions, capacity)
    specs = P(axis)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(specs,) + tuple(specs for _ in payloads),
        out_specs=(tuple(specs for _ in payloads), specs, specs, specs))
    return fn(part, *payloads)


def repartition_table(mesh: Mesh, hashes: jnp.ndarray,
                      columns: Dict[str, jnp.ndarray],
                      slack: float = 2.0, axis: str = "data"):
    """Hash-repartition named fixed-width columns across the mesh.

    The host-facing wrapper: picks capacity from the row count and `slack`,
    computes Spark pmod partition ids from `hashes`, and runs the exchange.
    Returns (columns_out, valid, counts, capacity); any counts > capacity
    means rows were dropped — retry with larger slack.
    """
    n = hashes.shape[0]
    p = mesh.shape[axis]
    capacity = max(1, math.ceil(n / p / p * slack))
    part = partition_ids(hashes, p)
    names = list(columns)
    outs, valid, counts, _ = exchange(mesh, part, [columns[k] for k in names],
                                      capacity, axis)
    return {k: v for k, v in zip(names, outs)}, valid, counts, capacity
