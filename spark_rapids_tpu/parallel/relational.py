"""Distributed relational ops over the device mesh.

The reference's distributed story is Spark's: the plugin partial-aggregates
per task, shuffles by key hash (UCX), and final-aggregates (SURVEY.md §2.4).
Here the same physical plan runs as ONE jitted SPMD program per op —
`shard_map` over the mesh with the ICI all-to-all from shuffle.py in the
middle, XLA static shapes throughout:

    distributed_groupby:  local sorted partial agg (padded, key_cap groups)
        → murmur-pmod partition of the group keys → all-to-all (capacity =
        key_cap: a source sends ≤ key_cap groups total, so no bucket can
        overflow) → local final merge agg.
    distributed_inner_join: both sides hash-partitioned by key → all-to-all
        (slack-sized buckets, like shuffle.repartition_table) → shard-local
        sort-merge join into a fixed row_cap output.

Every stage reports overflow instead of corrupting: the returned flag is
the SplitAndRetry signal (retry with bigger caps / smaller batch), the same
detect-then-retry contract as the arbiter (SURVEY.md §5).

Everything is device-resident end to end; the only host interaction is the
caller-supplied static capacities, exactly like exchange()'s slack model.
"""
from __future__ import annotations

import math
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:      # jax < 0.5 keeps it in experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.join import expand_spans, join_spans
from .shuffle import build_partition_map, partition_ids

_AGGS = ("sum", "count", "min", "max")

# key int64.max is the dead-slot sentinel throughout (padded all-to-all
# slots); a real key with that exact value would merge with padding
_DEAD_KEY = jnp.iinfo(jnp.int64).max


def _spark_murmur_i64(keys) -> jnp.ndarray:
    """Spark murmur3_32 (seed 42, like GpuHashPartitioning) of one or more
    int64 key columns (chained per column, like Spark's hash of the key
    tuple)."""
    from ..ops.hash import murmur_hash3_32
    from ..columnar import Column, Table
    from .. import dtypes
    key_list = keys if isinstance(keys, (list, tuple)) else [keys]
    cols = [Column(dtype=dtypes.INT64, length=k.shape[0],
                   data=k.astype(jnp.int64)) for k in key_list]
    return murmur_hash3_32(Table(cols), seed=42).data


def _fit(x: jnp.ndarray, cap: int, fill) -> jnp.ndarray:
    """Slice or pad a (n,) array to exactly (cap,)."""
    n = x.shape[0]
    if n >= cap:
        return x[:cap]
    return jnp.concatenate([x, jnp.full((cap - n,), fill, x.dtype)])


def _identity(op: str) -> int:
    info = jnp.iinfo(jnp.int64)
    return {"sum": 0, "min": info.max, "max": info.min}[op]


def _bucket_exchange(axis: str, n_peers: int, cap: int, part: jnp.ndarray,
                     payloads: Sequence[Tuple[jnp.ndarray, object]]):
    """Shared bucket-then-all-to-all body (the shape of shuffle.py's
    _exchange_local): bucket rows by `part` into (n_peers, cap) slots, ship
    each bucket to its peer, and — like _exchange_local — ship only the (P,)
    sent counts and rebuild the validity mask receiver-side (capacity× less
    ICI traffic than a full bool mask).

    payloads: [(array, dead-slot fill)]. Returns (received arrays (P*cap,),
    recv_valid (P*cap,), spilled scalar bool)."""
    gi, bvalid, counts = build_partition_map(part, n_peers, cap)
    spilled = jnp.any(counts > cap)
    outs = []
    for x, fill in payloads:
        b = jnp.where(bvalid, jnp.take(x, gi, axis=0),
                      jnp.asarray(fill, x.dtype))
        outs.append(jax.lax.all_to_all(b, axis, 0, 0, tiled=True).reshape(-1))
    sent = jnp.minimum(counts, cap)
    sent_recv = jax.lax.all_to_all(sent, axis, 0, 0, tiled=True)
    slot = jnp.arange(cap, dtype=jnp.int32)[None, :]
    recv_valid = (slot < sent_recv[:, None]).reshape(-1)
    return outs, recv_valid, spilled


def _merge_groups(keys, alive: jnp.ndarray,
                  cols: Sequence[Tuple[jnp.ndarray, str]], key_cap: int):
    """Shard-local merge of rows with equal keys (the shared kernel behind
    both the partial and final stages; same sorted-span machinery as
    ops/aggregate.py's scatter-free groupby).

    `keys` is one int64 array or a list of them (multi-key groupby: rows
    merge when ALL key columns are equal). cols: [(int64 column, merge op in
    sum|min|max)]. Dead rows (alive False) are excluded. Returns
    (keys like the input shape, outs [(key_cap,)], valid (key_cap,),
    n_real_groups) — padded/sliced to exactly key_cap."""
    multi = isinstance(keys, (list, tuple))
    key_list = list(keys) if multi else [keys]
    n = key_list[0].shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    ks = [jnp.where(alive, k, _DEAD_KEY) for k in key_list]  # dead rows last
    sorted_all = jax.lax.sort([*ks, iota], num_keys=len(ks), is_stable=True)
    sks, order = sorted_all[:-1], sorted_all[-1]
    salive = jnp.take(alive, order, axis=0)

    neq = jnp.zeros((n,), bool)
    for o in sks:
        neq = neq | (o != jnp.roll(o, 1))
    boundary = neq.at[0].set(True) if n else neq
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    # boundary-compaction sort for group starts (see ops/aggregate.py)
    flag = jnp.where(boundary, jnp.int32(0), jnp.int32(1))
    payload = jnp.where(boundary, iota, jnp.int32(n))
    starts = jax.lax.sort([flag, payload], num_keys=1, is_stable=True)[1]
    if n:
        ends = jnp.concatenate([starts[1:], jnp.full((1,), n, jnp.int32)])
    else:
        ends = starts
    last = jnp.clip(ends - 1, 0, max(n - 1, 0))
    prev = starts - 1

    def span_sum(x):
        c = jnp.cumsum(x)
        hi = jnp.take(c, last, axis=0)
        lo = jnp.where(prev >= 0, jnp.take(c, jnp.maximum(prev, 0), axis=0), 0)
        return hi - lo

    alive_cnt = span_sum(salive.astype(jnp.int32))
    outs: List[jnp.ndarray] = []
    for col, op in cols:
        sc = jnp.take(col, order, axis=0)
        if op == "sum":
            outs.append(span_sum(jnp.where(salive, sc.astype(jnp.int64), 0)))
        else:
            ident = jnp.int64(_identity(op))
            masked = jnp.where(salive, sc.astype(jnp.int64), ident)

            def combine(a, b, op=op):
                ab, av = a
                bb, bv = b
                m = jnp.minimum(av, bv) if op == "min" else jnp.maximum(av, bv)
                return ab | bb, jnp.where(bb, bv, m)
            _, res = jax.lax.associative_scan(combine, (boundary, masked))
            outs.append(jnp.take(res, last, axis=0))

    n_groups = (gid[-1] + 1) if n else jnp.int32(0)
    # real groups only: the dead-key sentinel group (if any padding existed)
    # sorts last and has alive_cnt == 0 — it must not trip overflow
    in_range = iota < n_groups
    n_real = jnp.sum((alive_cnt > 0) & in_range).astype(jnp.int32)

    valid = (_fit(alive_cnt, key_cap, 0) > 0) & \
        (jnp.arange(key_cap, dtype=jnp.int32) < n_groups)
    gkeys = [_fit(jnp.take(k, starts, axis=0, mode="clip"), key_cap,
                  _DEAD_KEY) for k in sks]
    out_keys = gkeys if multi else gkeys[0]
    return (out_keys, [_fit(o, key_cap, 0) for o in outs], valid, n_real)


def distributed_groupby(mesh: Mesh, keys: jnp.ndarray, vals: jnp.ndarray,
                        aggs: Sequence[str], key_cap: int,
                        axis: str = "data"):
    """Groupby over mesh-sharded int64 key/value columns — ONE jitted SPMD
    program (partial agg → ICI all-to-all by key hash → final agg).

    `key_cap` bounds the distinct keys per shard at both stages (static
    shapes); the returned per-shard `overflow` flag means results are
    incomplete — retry with a bigger key_cap (SplitAndRetry contract).
    Returns per-shard padded (keys, [agg arrays], valid, overflow).

    Thin wrapper over distributed_groupby_multi (single key, single value
    column)."""
    (gk,), outs, valid, overflow = distributed_groupby_multi(
        mesh, [keys], [vals], [(0, a) for a in aggs], key_cap, axis)
    return gk, outs, valid, overflow


def distributed_groupby_multi(mesh: Mesh, keys: Sequence[jnp.ndarray],
                              vals: Sequence[jnp.ndarray],
                              aggs: Sequence[Tuple[int, str]], key_cap: int,
                              axis: str = "data", hash_fn=None, alive=None):
    """Multi-key, multi-value groupby over the mesh — same two-stage shape
    as distributed_groupby but grouping on a tuple of int64 key columns and
    aggregating [(value index, op)] pairs.

    `hash_fn(key_arrays) -> (n,) hash` overrides the partition hash (the
    typed-key path passes keys.spark_partition_hash so string/decimal keys
    place exactly like GpuHashPartitioning); default is the chained murmur
    over raw int64 words.

    `alive` (optional sharded (n,) bool) excludes dead rows — the plan
    tier's padded sharded relations aggregate live rows only.

    Returns per-shard padded ([key arrays], [agg arrays], valid, overflow).
    """
    for _, a in aggs:
        if a not in _AGGS:
            raise ValueError(f"unsupported distributed agg {a!r}")
    keys = list(keys)
    vals = list(vals)
    if not keys:
        raise ValueError("at least one key column is required")
    n_peers = mesh.shape[axis]
    aggs = tuple((int(i), a) for i, a in aggs)
    for i, a in aggs:
        if a != "count" and not (0 <= i < len(vals)):
            raise ValueError(f"agg value index {i} out of range "
                             f"({len(vals)} value columns)")

    def partial_cols(key0, val_arrays):
        ones = jnp.ones(key0.shape, jnp.int64)   # count needs no value column
        return [(ones if a == "count" else val_arrays[i],
                 "sum" if a in ("sum", "count") else a) for i, a in aggs]

    def merge_cols(partials):
        return [(p, "sum" if a in ("sum", "count") else a)
                for p, (_, a) in zip(partials, aggs)]

    nk = len(keys)
    nv = len(vals)
    has_alive = alive is not None

    def local(*arrs):
        ks, vs = list(arrs[:nk]), list(arrs[nk:nk + nv])
        live = arrs[-1] if has_alive else jnp.ones(ks[0].shape, bool)
        gks, partials, gvalid, n_real = _merge_groups(
            ks, live, partial_cols(ks[0], vs), key_cap)
        overflow = n_real > key_cap

        part = partition_ids((hash_fn or _spark_murmur_i64)(gks), n_peers)
        part = jnp.where(gvalid, part, jnp.int32(n_peers))
        recv, recv_alive, _ = _bucket_exchange(
            axis, n_peers, key_cap, part,
            [(g, _DEAD_KEY) for g in gks] +
            [(p, _identity(op)) for p, op in merge_cols(partials)])
        recv_ks, recv_ps = recv[:nk], recv[nk:]

        fks, fouts, fvalid, fn_real = _merge_groups(
            list(recv_ks), recv_alive, merge_cols(list(recv_ps)), key_cap)
        overflow = overflow | (fn_real > key_cap)
        return (tuple(fks), tuple(fouts), fvalid, overflow.reshape(1))

    spec = P(axis)
    fn = shard_map(local, mesh=mesh,
                   in_specs=(spec,) * (nk + nv + int(has_alive)),
                   out_specs=(tuple(spec for _ in keys),
                              tuple(spec for _ in aggs), spec, spec))
    args = list(keys) + list(vals) + ([alive] if has_alive else [])
    return fn(*args)


def distributed_groupby_keyed(mesh: Mesh, key_words: Sequence[jnp.ndarray],
                              key_specs, vals: Sequence[jnp.ndarray],
                              aggs: Sequence[Tuple[int, str]], key_cap: int,
                              axis: str = "data", alive=None):
    """Typed-key groupby: key columns of ANY supported dtype (string,
    decimal128, float, nullable int — see parallel/keys.py) encoded as word
    lists ride the same SPMD program as the int64 path; partition placement
    is Spark-exact (keys.spark_partition_hash). Returns per-shard padded
    ([key word arrays], [agg arrays], valid, overflow); decode the words
    with keys.decode_key_columns(words, specs, alive=valid)."""
    from .keys import spark_partition_hash
    return distributed_groupby_multi(
        mesh, key_words, vals, aggs, key_cap, axis,
        hash_fn=lambda ws: spark_partition_hash(ws, key_specs), alive=alive)


def distributed_local_groupby(mesh: Mesh, key_words: Sequence[jnp.ndarray],
                              vals: Sequence[jnp.ndarray],
                              aggs: Sequence[Tuple[int, str]], key_cap: int,
                              axis: str = "data", alive=None):
    """Shard-local groupby merge for PRE-PARTITIONED inputs: every row of a
    group is already co-located (the input sits below an ELIDED exchange —
    e.g. a shuffle join on a subset of the group keys already placed equal
    keys on one shard), so the two-stage shape collapses to ONE
    `_merge_groups` per shard with no collective at all. Same return
    contract as distributed_groupby_multi; `overflow` means a shard held
    more than key_cap distinct live groups."""
    for _, a in aggs:
        if a not in _AGGS:
            raise ValueError(f"unsupported distributed agg {a!r}")
    key_words = list(key_words)
    vals = list(vals)
    nk, nv = len(key_words), len(vals)
    aggs = tuple((int(i), a) for i, a in aggs)
    has_alive = alive is not None

    def local(*arrs):
        ks, vs = list(arrs[:nk]), list(arrs[nk:nk + nv])
        live = arrs[-1] if has_alive else jnp.ones(ks[0].shape, bool)
        ones = jnp.ones(ks[0].shape, jnp.int64)
        cols = [(ones if a == "count" else vs[i],
                 "sum" if a in ("sum", "count") else a) for i, a in aggs]
        gks, outs, gvalid, n_real = _merge_groups(ks, live, cols, key_cap)
        overflow = n_real > key_cap
        return (tuple(gks), tuple(outs), gvalid, overflow.reshape(1))

    spec = P(axis)
    fn = shard_map(local, mesh=mesh,
                   in_specs=(spec,) * (nk + nv + int(has_alive)),
                   out_specs=(tuple(spec for _ in key_words),
                              tuple(spec for _ in aggs), spec, spec))
    args = key_words + vals + ([alive] if has_alive else [])
    return fn(*args)


def distributed_repartition_keyed(mesh: Mesh,
                                  key_words: Sequence[jnp.ndarray],
                                  key_specs, vals: Sequence[jnp.ndarray],
                                  slack: float = 2.0, axis: str = "data",
                                  alive=None, word_codecs=None,
                                  word_refs=None):
    """Standalone hash-partition exchange of one relation — the physical
    form of an `Exchange(hash)` plan node: every row moves to the shard
    given by the Spark-exact hash of its key words (pmod n_peers), so a
    downstream co-located operator (colocated join, elided-exchange
    groupby) can run with no further collective. `alive` marks live rows
    of a padded sharded relation; dead rows are dropped by the bucketing.

    `word_codecs`/`word_refs` carry the narrowed-key wire form
    (plan/transport.narrow_words): `word_codecs` is a static per-word
    codec tuple ("raw" | "forN") and `word_refs` the traced (1,) int64
    reference arrays, one per non-raw word in order. Narrowed planes are
    widened back to their exact 64-bit words INSIDE the collective body
    for the Spark-exact hash — placement is bit-identical to the raw
    path — while the all-to-all ships the narrow planes. References ride
    as traced arrays (replicated specs), not baked constants, so one
    compiled program serves every execution of the same layout.

    Returns ([key words], [vals], valid, overflow); the key words come
    back in the wire form they were passed (the caller widens). overflow
    means a bucket spilled its slack-sized capacity — retry with bigger
    slack (SplitAndRetry contract)."""
    from .keys import spark_partition_hash
    n_peers = mesh.shape[axis]
    hash_fn = lambda ws: spark_partition_hash(ws, key_specs)  # noqa: E731
    key_words = list(key_words)
    vals = list(vals)
    nk, nv = len(key_words), len(vals)
    has_alive = alive is not None
    codecs_t = tuple(word_codecs) if word_codecs else ("raw",) * nk
    refs = list(word_refs or [])
    narrowed = any(c != "raw" for c in codecs_t)

    def local(*arrs):
        ws, vs = list(arrs[:nk]), list(arrs[nk:nk + nv])
        live = arrs[nk + nv] if has_alive else None
        if narrowed:
            rs = iter(arrs[nk + nv + int(has_alive):])
            ws64 = [w if c == "raw" else next(rs)[0] + w.astype(jnp.int64)
                    for w, c in zip(ws, codecs_t)]
            fills = [_DEAD_KEY if c == "raw" else 0 for c in codecs_t]
            Ws, Vs, recv_alive, spilled = _hash_exchange(
                axis, n_peers, slack, ws, vs, hash_fn, alive=live,
                hash_keys=ws64, key_fills=fills)
        else:
            Ws, Vs, recv_alive, spilled = _hash_exchange(
                axis, n_peers, slack, ws, vs, hash_fn, alive=live)
        return (tuple(Ws), tuple(Vs), recv_alive, spilled.reshape(1))

    spec = P(axis)
    fn = shard_map(local, mesh=mesh,
                   in_specs=(spec,) * (nk + nv + int(has_alive))
                   + (P(),) * len(refs),
                   out_specs=(tuple(spec for _ in key_words),
                              tuple(spec for _ in vals), spec, spec))
    args = key_words + vals + ([alive] if has_alive else []) + refs
    return fn(*args)


def distributed_colocated_join_keyed(mesh: Mesh,
                                     l_words: Sequence[jnp.ndarray],
                                     lvals: Sequence[jnp.ndarray],
                                     r_words: Sequence[jnp.ndarray],
                                     rvals: Sequence[jnp.ndarray],
                                     key_specs, row_cap: int = 0,
                                     axis: str = "data", how: str = "inner",
                                     lalive=None, ralive=None,
                                     r_replicated: bool = False):
    """Equi-join of two ALREADY-ALIGNED sides with no exchange: both sides
    are either hash-partitioned by the positionally-matching key tuples
    (the explicit `Exchange(hash)` ran upstream, so matching rows are
    co-located), or the right side is REPLICATED (`r_replicated=True`: the
    `Exchange(broadcast)` replicated the small build side onto every
    shard, the probe side never moves). Each shard then joins locally —
    the plan tier's counterpart of Spark executing a join above its
    exchanges.

    `how`: inner (padded row_cap output), left_semi / left_anti (output
    stays left-shaped, no row_cap). `lalive`/`ralive` mark live rows of
    padded sharded relations; NULL keys never match (Spark equi-join
    semantics).

    Returns: inner -> ([l key words], [lvals], [rvals], valid, overflow);
    semi/anti -> ([l key words], [lvals], keep, overflow)."""
    from .keys import keys_null_mask
    l_words, lvals = list(l_words), list(lvals)
    r_words, rvals = list(r_words), list(rvals)
    _check_word_counts(l_words, r_words)
    nw, nlv, nrv = len(l_words), len(lvals), len(rvals)
    has_lal, has_ral = lalive is not None, ralive is not None
    semi_anti = how in ("left_semi", "left_anti")
    if how not in ("inner", "left_semi", "left_anti"):
        raise ValueError(f"unsupported colocated join type {how!r}")

    def local(*arrs):
        i = 0
        lw = list(arrs[i:i + nw]); i += nw
        lv = list(arrs[i:i + nlv]); i += nlv
        rw = list(arrs[i:i + nw]); i += nw
        rv = list(arrs[i:i + nrv]); i += nrv
        Lal = arrs[i] if has_lal else jnp.ones(lw[0].shape, bool)
        i += int(has_lal)
        Ral = arrs[i] if has_ral else jnp.ones(rw[0].shape, bool)
        lmatch = Lal & ~keys_null_mask(lw, key_specs)
        rmatch = Ral & ~keys_null_mask(rw, key_specs)
        if semi_anti:
            nl = lw[0].shape[0]
            operands = tuple(jnp.concatenate([a, b])
                             for a, b in zip(lw, rw))
            counts, _, _ = join_spans(operands, lmatch, rmatch, nl=nl,
                                      need_rorder=False)
            hit = counts > 0
            keep = Lal & (hit if how == "left_semi" else ~hit)
            out_lw = [jnp.where(keep, w, jnp.asarray(0, w.dtype))
                      for w in lw]
            out_lv = [jnp.where(keep, v, jnp.asarray(0, v.dtype))
                      for v in lv]
            return (tuple(out_lw), tuple(out_lv), keep,
                    jnp.zeros((1,), bool))
        out_lw, out_lv, out_rv, _, live, ovf = _local_join_tail(
            lw, lv, Lal, rw, rv, Ral, row_cap, outer=False,
            lmatch=lmatch, rmatch=rmatch)
        return (tuple(out_lw), tuple(out_lv), tuple(out_rv), live,
                ovf.reshape(1))

    spec = P(axis)
    rspec = P() if r_replicated else spec
    in_specs = ((spec,) * (nw + nlv) + (rspec,) * (nw + nrv)
                + (spec,) * int(has_lal) + (rspec,) * int(has_ral))
    if semi_anti:
        out_specs = (tuple(spec for _ in l_words),
                     tuple(spec for _ in lvals), spec, spec)
    else:
        out_specs = (tuple(spec for _ in l_words),
                     tuple(spec for _ in lvals),
                     tuple(spec for _ in rvals), spec, spec)
    fn = shard_map(local, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs)
    args = (l_words + lvals + r_words + rvals
            + ([lalive] if has_lal else [])
            + ([ralive] if has_ral else []))
    return fn(*args)


def distributed_sort(mesh: Mesh, keys: jnp.ndarray, vals: jnp.ndarray,
                     slack: float = 2.0, axis: str = "data"):
    """Global sort of mesh-sharded (key, value) columns — sample-sort as one
    jitted SPMD program. This is the scale-past-one-device primitive (a
    "sequence" longer than any single chip's memory): shard 0 ends with the
    smallest keys, shard P-1 the largest, each locally sorted.

    1. each shard samples P-1 local quantile keys from its sorted run
    2. all_gather the samples; global splitters = quantiles of the pool
    3. bucket rows by splitter interval; ICI all-to-all (slack-sized)
    4. local sort of the received rows

    Returns per-shard (keys, vals, valid, overflow); overflow means a shard
    received more than cap rows (skewed keys) — retry with bigger slack.

    The single-int64-key case of distributed_sort_keyed (one word, no
    specs), kept as the plain-array front door."""
    (w,), ov, valid, overflow = distributed_sort_keyed(
        mesh, [keys], None, vals, slack=slack, axis=axis)
    return w, ov, valid, overflow


def distributed_sort_keyed(mesh: Mesh, key_words: Sequence[jnp.ndarray],
                           key_specs, vals, slack: float = 2.0,
                           axis: str = "data", alive=None):
    """Global sort over typed keys (word lists from keys.encode_key_columns,
    so string/decimal128/float/nullable keys all sort) — sample-sort as one
    jitted SPMD program, the multi-word generalization of distributed_sort.
    The word encoding is order-preserving (tuple lexicographic order == the
    column's sort order, nulls first), so splitters are word TUPLES and the
    partition id is a vectorized lexicographic rank against them.

    `key_specs` is accepted for API symmetry with the other keyed ops and
    for the caller's later decode; the sort itself needs only the
    order-preserving words (pass None when sorting raw arrays).

    `vals` may be one payload array or a list (a whole table side rides the
    sort); `alive` (optional sharded (n,) bool) marks live rows of a padded
    sharded relation — dead rows sink out of the sampled runs, route to the
    out-of-range partition, and never reach any shard's output.

    Returns per-shard ([key words], vals (matching the input shape), valid,
    overflow); shard 0 ends with the smallest keys. overflow means a shard
    received more than its slack-sized capacity (skewed keys) — retry with
    bigger slack."""
    del key_specs  # symmetry/decode-side only
    n_peers = mesh.shape[axis]
    key_words = list(key_words)
    nw = len(key_words)
    multi_vals = isinstance(vals, (list, tuple))
    val_list = list(vals) if multi_vals else [vals]
    nv = len(val_list)
    has_alive = alive is not None

    def local(*arrs):
        ws, vs = list(arrs[:nw]), list(arrs[nw:nw + nv])
        live = arrs[-1] if has_alive else jnp.ones(ws[0].shape, bool)
        nloc = ws[0].shape[0]
        cap = max(1, math.ceil(nloc / n_peers * slack))
        iota = jnp.arange(nloc, dtype=jnp.int32)
        # dead rows take the sentinel and sink to the end of the local run,
        # so the live prefix is exactly the shard's real rows
        ks = [jnp.where(live, w, _DEAD_KEY) for w in ws]
        out = jax.lax.sort([*ks, iota], num_keys=nw, is_stable=True)
        sws, order = list(out[:-1]), out[-1]
        svs = [jnp.take(v, order, axis=0) for v in vs]
        salive = jnp.take(live, order, axis=0)
        nlive = jnp.sum(salive.astype(jnp.int32))
        # P-1 evenly spaced local sample TUPLES from the LIVE prefix of the
        # sorted run (sampling over nloc would pull dead-sentinel tuples
        # into the splitter pool and skew every splitter high)
        pos = (jnp.arange(1, n_peers, dtype=jnp.int32) * nlive) // n_peers
        pools = []
        for w in sws:
            samples = jnp.take(w, pos, axis=0, mode="clip")
            pools.append(jax.lax.all_gather(samples, axis).reshape(-1))
        pool_sorted = jax.lax.sort(pools, num_keys=nw, is_stable=True)
        m = pool_sorted[0].shape[0]
        spl_pos = (jnp.arange(1, n_peers, dtype=jnp.int32) * m) // n_peers
        spl = [jnp.take(p, spl_pos, axis=0, mode="clip")
               for p in pool_sorted]                       # W x (P-1,)

        # partition id = #splitters strictly below the row tuple:
        # lexicographic splitter<row over words, vectorized (n, P-1)
        lt = jnp.zeros((nloc, n_peers - 1), bool)
        eq = jnp.ones((nloc, n_peers - 1), bool)
        for w, s in zip(sws, spl):
            lt = lt | (eq & (s[None, :] < w[:, None]))
            eq = eq & (s[None, :] == w[:, None])
        # strict splitter<row mirrors distributed_sort's `row > splitter`:
        # rows equal to a splitter stay in the lower bucket
        part = jnp.sum(lt, axis=1).astype(jnp.int32)
        part = jnp.where(salive, part, jnp.int32(n_peers))  # drop dead rows
        recv, ralive_, spilled = _bucket_exchange(
            axis, n_peers, cap, part,
            [(w, _DEAD_KEY) for w in sws] + [(sv, 0) for sv in svs])
        spilled = jax.lax.all_gather(spilled.reshape(1), axis).any()
        rws, rvs = recv[:nw], recv[nw:]
        # final local sort; dead slots carry the sentinel and sink last
        dead_flag = jnp.where(ralive_, jnp.int32(0), jnp.int32(1))
        keyed = [jnp.where(ralive_, w, _DEAD_KEY) for w in rws]
        out2 = jax.lax.sort([*keyed, dead_flag, *rvs], num_keys=nw + 1,
                            is_stable=True)
        out_vs = tuple(out2[nw + 1:])
        return (tuple(out2[:nw]), out_vs if multi_vals else out_vs[0],
                out2[nw] == 0, spilled.reshape(1))

    spec = P(axis)
    val_out_spec = tuple(spec for _ in val_list) if multi_vals else spec
    fn = shard_map(local, mesh=mesh,
                   in_specs=(spec,) * (nw + nv + int(has_alive)),
                   out_specs=(tuple(spec for _ in key_words), val_out_spec,
                              spec, spec))
    args = key_words + val_list + ([alive] if has_alive else [])
    return fn(*args)


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _local_join_tail(lk, lv, lalive, rk, rv, ralive, row_cap: int,
                     outer: bool = False, lmatch=None, rmatch=None):
    """Shard-local (inner or left-outer) join into a fixed row_cap: union
    rank + sort-merge spans + padded expansion (ops/join.py machinery on
    shard-local shapes). Key sides may be single arrays or word lists
    (typed keys encoded by parallel/keys.py): rows match when ALL words are
    equal. `lmatch`/`rmatch` (default: the alive masks) restrict MATCHING
    without affecting emission — a null-keyed left row under `outer` is
    still emitted, just never matched (Spark equi-join NULL semantics).
    Returns (lkeys list, lvals list, rvals list, rmatched, live,
    overflow-scalar); rmatched is False on left-outer rows with no match
    (their rval slots are 0 and must be read as null)."""
    lks, rks = _as_list(lk), _as_list(rk)
    lvs, rvs = _as_list(lv), _as_list(rv)
    lmatch = lalive if lmatch is None else lmatch
    rmatch = ralive if rmatch is None else rmatch
    nl = lks[0].shape[0]
    operands = tuple(jnp.concatenate([a, b]) for a, b in zip(lks, rks))
    counts, lo, rorder = join_spans(operands, lmatch, rmatch, nl=nl)
    if outer:
        # dead (padded) rows emit NOTHING: a zero emit count keeps live
        # output slots a prefix with no dead-rows-last permute
        eff = jnp.where(lalive, jnp.maximum(counts, 1), 0)
        total = jnp.sum(eff)
    else:
        eff = None
        total = jnp.sum(counts)
    lsel, rsel = expand_spans(counts, lo, rorder, total=row_cap, outer=outer,
                              eff=eff)
    live = jnp.arange(row_cap, dtype=jnp.int32) < total
    rmatched = rsel >= 0 if outer else jnp.ones((row_cap,), bool)
    # dead-slot zeros keep each payload's dtype (a weak-typed python 0
    # would promote bool validity payloads to int)
    out_lks = [jnp.where(live, jnp.take(k, lsel, axis=0),
                         jnp.asarray(0, k.dtype)) for k in lks]
    out_lvs = [jnp.where(live, jnp.take(v, lsel, axis=0),
                         jnp.asarray(0, v.dtype)) for v in lvs]
    safe_rsel = jnp.maximum(rsel, 0)
    out_rvs = [jnp.where(live & rmatched, jnp.take(v, safe_rsel, axis=0),
                         jnp.asarray(0, v.dtype))
               for v in rvs]
    return out_lks, out_lvs, out_rvs, rmatched & live, live, total > row_cap


def _hash_exchange(axis: str, n_peers: int, slack: float,
                   keys, vals, hash_fn=None, alive=None,
                   hash_keys=None, key_fills=None):
    """Hash-partition by Spark murmur pmod and all-to-all one table side
    (the shared shuffle wiring of every distributed join). `keys` may be a
    single int64 array or a word list (typed keys); `vals` may be None
    (key-only sides, e.g. semi/anti build side), one array, or a list.
    `alive` (optional (n,) bool) marks live rows: dead rows route to the
    out-of-range partition id `n_peers` and are silently dropped by the
    bucketing — the padded-relation contract of the plan tier's sharded
    relations. `hash_keys` (default: `keys`) is the array list the hash
    runs over — the narrowed-key exchange ships narrow planes but hashes
    their widened 64-bit word form (plan/transport.narrow_words), so the
    wire and the hash input may legitimately differ. `key_fills` gives
    each key plane's dead-slot fill (default `_DEAD_KEY`; narrowed
    planes fill 0 — int64.max would wrap in a narrow dtype, and dead
    slots are never read anyway). Returns (key outs, val outs, alive,
    spilled)."""
    key_list = _as_list(keys)
    val_list = [] if vals is None else _as_list(vals)
    nloc = key_list[0].shape[0]
    cap = max(1, math.ceil(nloc / n_peers * slack))
    hash_list = key_list if hash_keys is None else _as_list(hash_keys)
    part = partition_ids((hash_fn or _spark_murmur_i64)(hash_list), n_peers)
    if alive is not None:
        part = jnp.where(alive, part, jnp.int32(n_peers))
    fills = ([_DEAD_KEY] * len(key_list) if key_fills is None
             else list(key_fills))
    payloads = [(k, f) for k, f in zip(key_list, fills)] \
        + [(v, 0) for v in val_list]
    outs, alive, spilled = _bucket_exchange(axis, n_peers, cap, part, payloads)
    # a spill anywhere means some shard RECEIVED an incomplete side: agree on
    # the flag across the mesh (same contract as distributed_sort) so the
    # shard whose output is wrong also reports overflow
    spilled = jax.lax.all_gather(spilled.reshape(1), axis).any()
    nk = len(key_list)
    return outs[:nk], outs[nk:], alive, spilled


def distributed_inner_join(mesh: Mesh, lkeys: jnp.ndarray, lvals: jnp.ndarray,
                           rkeys: jnp.ndarray, rvals: jnp.ndarray,
                           row_cap: int, slack: float = 2.0,
                           axis: str = "data"):
    """Inner equi-join of two mesh-sharded int64-keyed tables — one jitted
    SPMD program: hash-partition both sides (slack-sized buckets, NOT the
    whole table per shard), all-to-all, shard-local sort-merge join into a
    fixed row_cap output.

    Returns per-shard padded (lkey, lval, rval, valid, overflow); overflow
    covers both bucket spill during the shuffle and join-output spill past
    row_cap — retry with bigger slack/row_cap (SplitAndRetry contract)."""
    n_peers = mesh.shape[axis]

    def local(lk, lv, rk, rv):
        (Lk,), (Lv,), Lalive, lspill = _hash_exchange(
            axis, n_peers, slack, lk, lv)
        (Rk,), (Rv,), Ralive, rspill = _hash_exchange(
            axis, n_peers, slack, rk, rv)
        out_lk, out_lv, out_rv, _, live, joverflow = _local_join_tail(
            Lk, Lv, Lalive, Rk, Rv, Ralive, row_cap)
        overflow = joverflow | lspill | rspill
        return out_lk[0], out_lv[0], out_rv[0], live, overflow.reshape(1)

    spec = P(axis)
    fn = shard_map(local, mesh=mesh, in_specs=(spec,) * 4,
                   out_specs=(spec,) * 5)
    return fn(lkeys, lvals, rkeys, rvals)


def _check_word_counts(l_words, r_words):
    if len(r_words) != len(l_words):
        # encode both sides with the SAME static max_bytes — auto-derived
        # widths differ per side and would silently mis-slice the arg tuple
        raise ValueError(
            f"join key word counts differ: left {len(l_words)} vs right "
            f"{len(r_words)}; encode both sides with identical KeySpecs")


def _distributed_join_keyed(mesh, l_words, lvals, r_words, rvals, key_specs,
                            row_cap, slack, axis, outer, broadcast=False):
    """Shared typed-key equi-join body (inner / left-outer / broadcast):
    move the build side — hash-exchange BOTH sides by the Spark-exact hash
    of the words, or (`broadcast`) all_gather the small right side onto
    every shard while the left never moves — then join shard-locally. NULL
    keys never match (keys.keys_null_mask feeds the match masks), matching
    Spark's `l.k = r.k` semantics — under `outer` a null-keyed left row is
    emitted null-extended."""
    from .keys import keys_null_mask, spark_partition_hash
    n_peers = mesh.shape[axis]
    hash_fn = lambda ws: spark_partition_hash(ws, key_specs)  # noqa: E731
    l_words, lvals = list(l_words), list(lvals)
    r_words, rvals = list(r_words), list(rvals)
    _check_word_counts(l_words, r_words)
    nw, nlv = len(l_words), len(lvals)

    def local(*arrs):
        lw = list(arrs[:nw])
        lv = list(arrs[nw:nw + nlv])
        rw = list(arrs[nw + nlv:nw + nlv + nw])
        rv = list(arrs[nw + nlv + nw:])
        if broadcast:
            # build side replicated over ICI; probe side stays in place
            Lw, Lv = lw, lv
            Rw = [jax.lax.all_gather(w, axis, tiled=True) for w in rw]
            Rv = [jax.lax.all_gather(v, axis, tiled=True) for v in rv]
            Lalive = jnp.ones((Lw[0].shape[0],), jnp.bool_)
            Ralive = jnp.ones((Rw[0].shape[0],), jnp.bool_)
            lspill = rspill = jnp.zeros((), jnp.bool_)
        else:
            Lw, Lv, Lalive, lspill = _hash_exchange(
                axis, n_peers, slack, lw, lv, hash_fn)
            Rw, Rv, Ralive, rspill = _hash_exchange(
                axis, n_peers, slack, rw, rv, hash_fn)
        lmatch = Lalive & ~keys_null_mask(Lw, key_specs)
        rmatch = Ralive & ~keys_null_mask(Rw, key_specs)
        out_lw, out_lv, out_rv, rvalid, live, joverflow = _local_join_tail(
            Lw, Lv, Lalive, Rw, Rv, Ralive, row_cap, outer=outer,
            lmatch=lmatch, rmatch=rmatch)
        overflow = joverflow | lspill | rspill
        outs = (tuple(out_lw), tuple(out_lv), tuple(out_rv))
        if outer:
            return outs + (rvalid, live, overflow.reshape(1))
        return outs + (live, overflow.reshape(1))

    spec = P(axis)
    n_flags = 3 if outer else 2
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(spec,) * (2 * nw + nlv + len(rvals)),
        out_specs=(tuple(spec for _ in l_words), tuple(spec for _ in lvals),
                   tuple(spec for _ in rvals)) + (spec,) * n_flags)
    return fn(*l_words, *lvals, *r_words, *rvals)


def distributed_inner_join_keyed(mesh: Mesh, l_words: Sequence[jnp.ndarray],
                                 lvals: Sequence[jnp.ndarray],
                                 r_words: Sequence[jnp.ndarray],
                                 rvals: Sequence[jnp.ndarray],
                                 key_specs, row_cap: int, slack: float = 2.0,
                                 axis: str = "data"):
    """Typed-key inner join: key sides are word lists from
    keys.encode_key_columns (string/decimal128/float/nullable keys all ride
    the same machinery); placement is Spark-exact via
    keys.spark_partition_hash; NULL keys never match. Returns per-shard
    padded ([l key words], [lvals], [rvals], valid, overflow) — decode the
    key words back to typed columns with keys.decode_key_columns."""
    return _distributed_join_keyed(mesh, l_words, lvals, r_words, rvals,
                                   key_specs, row_cap, slack, axis,
                                   outer=False)


def distributed_broadcast_join(mesh: Mesh, lkeys: jnp.ndarray,
                               lvals: jnp.ndarray, rkeys: jnp.ndarray,
                               rvals: jnp.ndarray, row_cap: int,
                               axis: str = "data"):
    """Broadcast inner equi-join: `jax.lax.all_gather` replicates the (small)
    right side onto every shard over ICI — XLA lowers the gather to a ring of
    ICI hops — and each left shard joins locally. The probe side never moves,
    so collective traffic is O(|right| x peers) instead of reshuffling both
    sides: the TPU analogue of the BroadcastHashJoin the reference's plugin
    accelerates one level up (SURVEY.md §2.4's UCX-shuffle slot; here the
    broadcast IS the collective).

    `row_cap` bounds the per-shard join output (static shapes); returns
    per-shard padded (lkey, lval, rval, valid, overflow) exactly like
    distributed_inner_join, so callers reuse the same SplitAndRetry contract.
    """
    def local(lk, lv, rk, rv):
        Rk = jax.lax.all_gather(rk, axis, tiled=True)
        Rv = jax.lax.all_gather(rv, axis, tiled=True)
        all_l = jnp.ones((lk.shape[0],), jnp.bool_)
        all_r = jnp.ones((Rk.shape[0],), jnp.bool_)
        out_lk, out_lv, out_rv, _, live, overflow = _local_join_tail(
            lk, lv, all_l, Rk, Rv, all_r, row_cap)
        return out_lk[0], out_lv[0], out_rv[0], live, overflow.reshape(1)

    spec = P(axis)
    fn = shard_map(local, mesh=mesh, in_specs=(spec,) * 4,
                   out_specs=(spec,) * 5)
    return fn(lkeys, lvals, rkeys, rvals)


def distributed_broadcast_join_keyed(mesh: Mesh,
                                     l_words: Sequence[jnp.ndarray],
                                     lvals: Sequence[jnp.ndarray],
                                     r_words: Sequence[jnp.ndarray],
                                     rvals: Sequence[jnp.ndarray],
                                     key_specs, row_cap: int,
                                     axis: str = "data"):
    """Typed-key broadcast inner join: the word-encoded (small) build side
    is replicated onto every shard with `all_gather` over ICI and each left
    shard joins locally — the typed sibling of distributed_broadcast_join,
    completing the broadcast path for string/decimal128/float/nullable keys
    (the reference's BroadcastHashJoin handles any key type). NULL keys
    never match (keys.keys_null_mask). Returns per-shard padded
    ([l key words], [lvals], [rvals], valid, overflow)."""
    return _distributed_join_keyed(mesh, l_words, lvals, r_words, rvals,
                                   key_specs, row_cap, slack=1.0, axis=axis,
                                   outer=False, broadcast=True)


def distributed_left_join_keyed(mesh: Mesh, l_words: Sequence[jnp.ndarray],
                                lvals: Sequence[jnp.ndarray],
                                r_words: Sequence[jnp.ndarray],
                                rvals: Sequence[jnp.ndarray],
                                key_specs, row_cap: int, slack: float = 2.0,
                                axis: str = "data"):
    """Typed-key left-outer join (see distributed_inner_join_keyed).
    Returns per-shard padded ([l key words], [lvals], [rvals], rvalid,
    valid, overflow); rvalid is False on unmatched left rows — including
    null-keyed left rows, which never match but are still emitted."""
    return _distributed_join_keyed(mesh, l_words, lvals, r_words, rvals,
                                   key_specs, row_cap, slack, axis,
                                   outer=True)


def distributed_left_join(mesh: Mesh, lkeys: jnp.ndarray, lvals: jnp.ndarray,
                          rkeys: jnp.ndarray, rvals: jnp.ndarray,
                          row_cap: int, slack: float = 2.0,
                          axis: str = "data"):
    """Left-outer equi-join, same shuffle as distributed_inner_join.

    Returns per-shard padded (lkey, lval, rval, rvalid, valid, overflow):
    rvalid is False on unmatched left rows (their rval slot must be read as
    null)."""
    n_peers = mesh.shape[axis]

    def local(lk, lv, rk, rv):
        (Lk,), (Lv,), Lalive, lspill = _hash_exchange(
            axis, n_peers, slack, lk, lv)
        (Rk,), (Rv,), Ralive, rspill = _hash_exchange(
            axis, n_peers, slack, rk, rv)
        out_lk, out_lv, out_rv, rvalid, live, joverflow = _local_join_tail(
            Lk, Lv, Lalive, Rk, Rv, Ralive, row_cap, outer=True)
        overflow = joverflow | lspill | rspill
        return out_lk[0], out_lv[0], out_rv[0], rvalid, live, overflow.reshape(1)

    spec = P(axis)
    fn = shard_map(local, mesh=mesh, in_specs=(spec,) * 4,
                   out_specs=(spec,) * 6)
    return fn(lkeys, lvals, rkeys, rvals)


def _distributed_semi_anti(mesh, lkeys, lvals, rkeys, semi, slack, axis):
    """Shared body: mark each left row matched/unmatched after the exchange;
    output stays left-shaped (no expansion, no row_cap)."""
    n_peers = mesh.shape[axis]

    def local(lk, lv, rk):
        (Lk,), (Lv,), Lalive, lspill = _hash_exchange(
            axis, n_peers, slack, lk, lv)
        (Rk,), _, Ralive, rspill = _hash_exchange(
            axis, n_peers, slack, rk, None)
        nl = Lk.shape[0]
        counts, _, _ = join_spans((jnp.concatenate([Lk, Rk]),),
                                  Lalive, Ralive, nl=nl, need_rorder=False)
        hit = counts > 0
        keep = Lalive & (hit if semi else ~hit)
        out_lk = jnp.where(keep, Lk, 0)
        out_lv = jnp.where(keep, Lv, 0)
        overflow = lspill | rspill
        return out_lk, out_lv, keep, overflow.reshape(1)

    spec = P(axis)
    fn = shard_map(local, mesh=mesh, in_specs=(spec,) * 3,
                   out_specs=(spec,) * 4)
    return fn(lkeys, lvals, rkeys)


def _distributed_semi_anti_keyed(mesh, l_words, lvals, r_words, key_specs,
                                 semi, slack, axis):
    """Typed-key shared body: keys as word lists, same marking logic.
    NULL keys never match (Spark equi-join semantics): a null-keyed left
    row is dropped by semi and kept by anti."""
    from .keys import keys_null_mask, spark_partition_hash
    n_peers = mesh.shape[axis]
    hash_fn = lambda ws: spark_partition_hash(ws, key_specs)  # noqa: E731
    l_words, lvals = list(l_words), list(lvals)
    r_words = list(r_words)
    _check_word_counts(l_words, r_words)
    nw, nlv = len(l_words), len(lvals)

    def local(*arrs):
        lw = list(arrs[:nw])
        lv = list(arrs[nw:nw + nlv])
        rw = list(arrs[nw + nlv:])
        Lw, Lv, Lalive, lspill = _hash_exchange(
            axis, n_peers, slack, lw, lv, hash_fn)
        Rw, _, Ralive, rspill = _hash_exchange(
            axis, n_peers, slack, rw, None, hash_fn)
        lmatch = Lalive & ~keys_null_mask(Lw, key_specs)
        rmatch = Ralive & ~keys_null_mask(Rw, key_specs)
        nl = Lw[0].shape[0]
        operands = tuple(jnp.concatenate([a, b]) for a, b in zip(Lw, Rw))
        counts, _, _ = join_spans(operands, lmatch, rmatch, nl=nl,
                                  need_rorder=False)
        hit = counts > 0
        keep = Lalive & (hit if semi else ~hit)
        out_lw = [jnp.where(keep, w, 0) for w in Lw]
        out_lv = [jnp.where(keep, v, 0) for v in Lv]
        overflow = lspill | rspill
        return tuple(out_lw), tuple(out_lv), keep, overflow.reshape(1)

    spec = P(axis)
    fn = shard_map(
        local, mesh=mesh, in_specs=(spec,) * (2 * nw + nlv),
        out_specs=(tuple(spec for _ in l_words), tuple(spec for _ in lvals),
                   spec, spec))
    return fn(*l_words, *lvals, *r_words)


def distributed_left_semi_join_keyed(mesh, l_words, lvals, r_words,
                                     key_specs, slack: float = 2.0,
                                     axis: str = "data"):
    """Typed-key left-semi join: left rows with at least one match.
    Returns per-shard padded ([l key words], [lvals], valid, overflow)."""
    return _distributed_semi_anti_keyed(mesh, l_words, lvals, r_words,
                                        key_specs, True, slack, axis)


def distributed_left_anti_join_keyed(mesh, l_words, lvals, r_words,
                                     key_specs, slack: float = 2.0,
                                     axis: str = "data"):
    """Typed-key left-anti join: left rows with no match."""
    return _distributed_semi_anti_keyed(mesh, l_words, lvals, r_words,
                                        key_specs, False, slack, axis)


def distributed_left_semi_join(mesh: Mesh, lkeys: jnp.ndarray,
                               lvals: jnp.ndarray, rkeys: jnp.ndarray,
                               slack: float = 2.0, axis: str = "data"):
    """Left rows with at least one match. Returns per-shard padded
    (lkey, lval, valid, overflow); output is left-sized, no row_cap."""
    return _distributed_semi_anti(mesh, lkeys, lvals, rkeys, True, slack, axis)


def distributed_left_anti_join(mesh: Mesh, lkeys: jnp.ndarray,
                               lvals: jnp.ndarray, rkeys: jnp.ndarray,
                               slack: float = 2.0, axis: str = "data"):
    """Left rows with no match. Same contract as the semi join."""
    return _distributed_semi_anti(mesh, lkeys, lvals, rkeys, False, slack, axis)
