"""Distributed relational ops over the device mesh.

The reference's distributed story is Spark's: the plugin partial-aggregates
per task, shuffles by key hash (UCX), and final-aggregates (SURVEY.md §2.4).
Here the same physical plan runs as ONE jitted SPMD program per op —
`shard_map` over the mesh with the ICI all-to-all from shuffle.py in the
middle, XLA static shapes throughout:

    distributed_groupby:  local sorted partial agg (padded, key_cap groups)
        → murmur-pmod partition of the group keys → all-to-all (capacity =
        key_cap: a source sends ≤ key_cap groups total, so no bucket can
        overflow) → local final merge agg.
    distributed_inner_join: both sides hash-partitioned by key → all-to-all
        (slack-sized buckets, like shuffle.repartition_table) → shard-local
        sort-merge join into a fixed row_cap output.

Every stage reports overflow instead of corrupting: the returned flag is
the SplitAndRetry signal (retry with bigger caps / smaller batch), the same
detect-then-retry contract as the arbiter (SURVEY.md §5).

Everything is device-resident end to end; the only host interaction is the
caller-supplied static capacities, exactly like exchange()'s slack model.
"""
from __future__ import annotations

import math
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .shuffle import build_partition_map, partition_ids

_AGGS = ("sum", "count", "min", "max")

# key int64.max is the dead-slot sentinel throughout (padded all-to-all
# slots); a real key with that exact value would merge with padding
_DEAD_KEY = jnp.iinfo(jnp.int64).max


def _spark_murmur_i64(keys: jnp.ndarray) -> jnp.ndarray:
    """Spark murmur3_32 (seed 42, like GpuHashPartitioning) of int64 keys."""
    from ..ops.hash import murmur_hash3_32
    from ..columnar import Column, Table
    from .. import dtypes
    col = Column(dtype=dtypes.INT64, length=keys.shape[0],
                 data=keys.astype(jnp.int64))
    return murmur_hash3_32(Table([col]), seed=42).data


def _fit(x: jnp.ndarray, cap: int, fill) -> jnp.ndarray:
    """Slice or pad a (n,) array to exactly (cap,)."""
    n = x.shape[0]
    if n >= cap:
        return x[:cap]
    return jnp.concatenate([x, jnp.full((cap - n,), fill, x.dtype)])


def _identity(op: str) -> int:
    info = jnp.iinfo(jnp.int64)
    return {"sum": 0, "min": info.max, "max": info.min}[op]


def _merge_groups(keys: jnp.ndarray, alive: jnp.ndarray,
                  cols: Sequence[Tuple[jnp.ndarray, str]], key_cap: int):
    """Shard-local merge of rows with equal keys (the shared kernel behind
    both the partial and final stages; same sorted-span machinery as
    ops/aggregate.py's scatter-free groupby).

    cols: [(int64 column, merge op in sum|min|max)]. Dead rows (alive False)
    are excluded. Returns (keys (key_cap,), outs [(key_cap,)], valid
    (key_cap,), n_real_groups) — padded/sliced to exactly key_cap.
    """
    n = keys.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    k = jnp.where(alive, keys, _DEAD_KEY)     # dead rows sort last
    sk, order = jax.lax.sort([k, iota], num_keys=1, is_stable=True)
    salive = jnp.take(alive, order, axis=0)

    neq = sk != jnp.roll(sk, 1)
    boundary = neq.at[0].set(True) if n else neq
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    # boundary-compaction sort for group starts (see ops/aggregate.py)
    flag = jnp.where(boundary, jnp.int32(0), jnp.int32(1))
    payload = jnp.where(boundary, iota, jnp.int32(n))
    starts = jax.lax.sort([flag, payload], num_keys=1, is_stable=True)[1]
    if n:
        ends = jnp.concatenate([starts[1:], jnp.full((1,), n, jnp.int32)])
    else:
        ends = starts
    last = jnp.clip(ends - 1, 0, max(n - 1, 0))
    prev = starts - 1

    def span_sum(x):
        c = jnp.cumsum(x)
        hi = jnp.take(c, last, axis=0)
        lo = jnp.where(prev >= 0, jnp.take(c, jnp.maximum(prev, 0), axis=0), 0)
        return hi - lo

    alive_cnt = span_sum(salive.astype(jnp.int32))
    outs: List[jnp.ndarray] = []
    for col, op in cols:
        sc = jnp.take(col, order, axis=0)
        if op == "sum":
            outs.append(span_sum(jnp.where(salive, sc.astype(jnp.int64), 0)))
        else:
            ident = jnp.int64(_identity(op))
            masked = jnp.where(salive, sc.astype(jnp.int64), ident)

            def combine(a, b, op=op):
                ab, av = a
                bb, bv = b
                m = jnp.minimum(av, bv) if op == "min" else jnp.maximum(av, bv)
                return ab | bb, jnp.where(bb, bv, m)
            _, res = jax.lax.associative_scan(combine, (boundary, masked))
            outs.append(jnp.take(res, last, axis=0))

    n_groups = (gid[-1] + 1) if n else jnp.int32(0)
    # real groups only: the dead-key sentinel group (if any padding existed)
    # sorts last and has alive_cnt == 0 — it must not trip overflow
    in_range = iota < n_groups
    n_real = jnp.sum((alive_cnt > 0) & in_range).astype(jnp.int32)

    gkeys = jnp.take(sk, starts, axis=0, mode="clip")
    valid = (_fit(alive_cnt, key_cap, 0) > 0) & \
        (jnp.arange(key_cap, dtype=jnp.int32) < n_groups)
    return (_fit(gkeys, key_cap, _DEAD_KEY),
            [_fit(o, key_cap, 0) for o in outs],
            valid, n_real)


def distributed_groupby(mesh: Mesh, keys: jnp.ndarray, vals: jnp.ndarray,
                        aggs: Sequence[str], key_cap: int,
                        axis: str = "data"):
    """Groupby over mesh-sharded int64 key/value columns — ONE jitted SPMD
    program (partial agg → ICI all-to-all by key hash → final agg).

    `key_cap` bounds the distinct keys per shard at both stages (static
    shapes); the returned per-shard `overflow` flag means results are
    incomplete — retry with a bigger key_cap (SplitAndRetry contract).
    Returns per-shard padded (keys, [agg arrays], valid, overflow)."""
    for a in aggs:
        if a not in _AGGS:
            raise ValueError(f"unsupported distributed agg {a!r}")
    n_peers = mesh.shape[axis]
    aggs = tuple(aggs)

    def partial_cols(vals, alive):
        ones = jnp.ones(vals.shape, jnp.int64)
        return [(ones if a == "count" else vals,
                 "sum" if a in ("sum", "count") else a) for a in aggs]

    def merge_cols(partials):
        return [(p, "sum" if a in ("sum", "count") else a)
                for p, a in zip(partials, aggs)]

    def local(keys, vals):
        alive = jnp.ones(keys.shape, bool)
        gk, partials, gvalid, n_real = _merge_groups(
            keys, alive, partial_cols(vals, alive), key_cap)
        overflow = n_real > key_cap

        # route each surviving group to its owner peer; dead slots to the
        # out-of-range partition so they never land in a bucket
        part = partition_ids(_spark_murmur_i64(gk), n_peers)
        part = jnp.where(gvalid, part, jnp.int32(n_peers))
        gather_idx, bvalid, _ = build_partition_map(part, n_peers, key_cap)

        def bucket(x, fill):
            b = jnp.take(x, gather_idx, axis=0)          # (peers, cap)
            return jnp.where(bvalid, b, fill)

        recv_k = jax.lax.all_to_all(bucket(gk, _DEAD_KEY), axis, 0, 0,
                                    tiled=True).reshape(-1)
        recv_alive = jax.lax.all_to_all(bucket(gvalid, False), axis, 0, 0,
                                        tiled=True).reshape(-1)
        recv_p = [jax.lax.all_to_all(
            bucket(p, jnp.int64(_identity(op))), axis, 0, 0,
            tiled=True).reshape(-1) for p, op in merge_cols(partials)]

        fk, fouts, fvalid, fn_real = _merge_groups(
            recv_k, recv_alive, merge_cols(recv_p), key_cap)
        overflow = overflow | (fn_real > key_cap)
        return fk, tuple(fouts), fvalid, overflow.reshape(1)  # rank-1 spec

    spec = P(axis)
    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec),
                   out_specs=(spec, tuple(spec for _ in aggs), spec, spec))
    return fn(keys, vals)


def distributed_inner_join(mesh: Mesh, lkeys: jnp.ndarray, lvals: jnp.ndarray,
                           rkeys: jnp.ndarray, rvals: jnp.ndarray,
                           row_cap: int, slack: float = 2.0,
                           axis: str = "data"):
    """Inner equi-join of two mesh-sharded int64-keyed tables — one jitted
    SPMD program: hash-partition both sides (slack-sized buckets, NOT the
    whole table per shard), all-to-all, shard-local sort-merge join into a
    fixed row_cap output.

    Returns per-shard padded (lkey, lval, rval, valid, overflow); overflow
    covers both bucket spill during the shuffle and join-output spill past
    row_cap — retry with bigger slack/row_cap (SplitAndRetry contract)."""
    n_peers = mesh.shape[axis]

    def local(lk, lv, rk, rv):
        def reshuffle(keys, vals):
            nloc = keys.shape[0]
            cap = max(1, math.ceil(nloc / n_peers * slack))
            part = partition_ids(_spark_murmur_i64(keys), n_peers)
            gi, bvalid, counts = build_partition_map(part, n_peers, cap)
            spilled = jnp.any(counts > cap)
            bk = jnp.where(bvalid, jnp.take(keys, gi, axis=0), _DEAD_KEY)
            bv_ = jnp.where(bvalid, jnp.take(vals, gi, axis=0), 0)
            rk_ = jax.lax.all_to_all(bk, axis, 0, 0, tiled=True).reshape(-1)
            rv_ = jax.lax.all_to_all(bv_, axis, 0, 0, tiled=True).reshape(-1)
            ralive = jax.lax.all_to_all(bvalid, axis, 0, 0,
                                        tiled=True).reshape(-1)
            return rk_, rv_, ralive, spilled

        Lk, Lv, Lalive, lspill = reshuffle(lk, lv)
        Rk, Rv, Ralive, rspill = reshuffle(rk, rv)

        # shard-local join via union rank + sort-merge spans (ops/join.py
        # machinery, shard-local shapes)
        from ..ops.join import _match_spans, _union_ranks
        nl, nr = Lk.shape[0], Rk.shape[0]
        ranks = _union_ranks((jnp.concatenate([Lk, Rk]),), n_ops=1)
        counts, lo, rorder = _match_spans(ranks[:nl], Lalive,
                                          ranks[nl:], Ralive)
        starts = jnp.cumsum(counts) - counts
        lsel = jnp.repeat(jnp.arange(nl, dtype=jnp.int32), counts,
                          total_repeat_length=row_cap)
        j = jnp.arange(row_cap, dtype=jnp.int32)
        total = jnp.sum(counts)
        live = j < total
        k = j - jnp.take(starts, lsel, axis=0)
        rpos = jnp.take(lo, lsel, axis=0) + k
        rsel = jnp.take(rorder, jnp.clip(rpos, 0, max(nr - 1, 0)), axis=0)
        out_lk = jnp.where(live, jnp.take(Lk, lsel, axis=0), 0)
        out_lv = jnp.where(live, jnp.take(Lv, lsel, axis=0), 0)
        out_rv = jnp.where(live, jnp.take(Rv, rsel, axis=0), 0)
        overflow = (total > row_cap) | lspill | rspill
        return out_lk, out_lv, out_rv, live, overflow.reshape(1)

    spec = P(axis)
    fn = shard_map(local, mesh=mesh, in_specs=(spec,) * 4,
                   out_specs=(spec,) * 5)
    return fn(lkeys, lvals, rkeys, rvals)
