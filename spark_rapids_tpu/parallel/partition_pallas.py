"""Pallas TPU kernel for the shuffle bucket histogram.

The explicit-kernel tier of parallel/partition.py's compare-reduce
histogram (the reference computes this with an atomic-add CUDA kernel; TPU
has no atomics, so the kernel streams row blocks through VMEM and keeps the
(P,) accumulator resident across grid steps — the output block is revisited
by every step, so each input byte crosses HBM exactly once and the counts
never round-trip).

Layout: rows arrive as (TM, 128) int32 planes (natural tiling). Buckets are
capped at 128 (one lane plane); a real shuffle's peer count fits. Each grid
step unrolls a per-bucket compare+reduce — P block-wide reduces on the VPU,
~P ops/row total, vs the 930 ms emulated scatter-add the round-1
measurement flagged at 10M rows.

A/B status: CPU-validated (interpret mode) against partition_histogram;
chip numbers pending device time this round (the axon tunnel has been
hanging at backend init — see PARITY.md). benchmarks/bench_partition.py
captures sort-based vs scan vs this kernel when run on hardware.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128


def _hist_kernel(P: int, TM: int):
    def kernel(part_ref, counts_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            counts_ref[...] = jnp.zeros_like(counts_ref)

        blk = part_ref[...]                                  # (TM, 128) i32
        sub = jax.lax.broadcasted_iota(jnp.int32, (8, _LANES), 0)
        lane = jax.lax.broadcasted_iota(jnp.int32, (8, _LANES), 1)
        acc = counts_ref[...]                                # (8, 128) i32
        # bucket b lives at (sublane 0, lane b); P block-reduces, unrolled
        for b in range(P):
            # dtype pinned: some jax versions promote sum(int32) to int64
            # under x64, and a Pallas ref store rejects the widened value
            c = jnp.sum(jnp.where(blk == b, jnp.int32(1), jnp.int32(0)),
                        dtype=jnp.int32)
            acc = acc + jnp.where((sub == 0) & (lane == b), c, jnp.int32(0))
        counts_ref[...] = acc

    return kernel


def histogram_pallas(part: jnp.ndarray, num_partitions: int,
                     block_rows: int = 4096,
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """(P,) int32 bucket counts; P <= 128 (one lane plane)."""
    if num_partitions > _LANES:
        raise ValueError(f"histogram_pallas supports up to {_LANES} buckets")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = part.shape[0]
    TM = max(8, block_rows // _LANES)
    per_block = TM * _LANES
    m = max(1, math.ceil(n / per_block))
    pad = m * per_block - n
    p32 = part.astype(jnp.int32)
    if pad:
        # out-of-range id: never matches a bucket
        p32 = jnp.concatenate(
            [p32, jnp.full((pad,), num_partitions, jnp.int32)])
    planes = p32.reshape(m * TM, _LANES)

    counts = pl.pallas_call(
        _hist_kernel(num_partitions, TM),
        out_shape=jax.ShapeDtypeStruct((8, _LANES), jnp.int32),
        in_specs=[pl.BlockSpec((TM, _LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, _LANES), lambda i: (0, 0)),
        grid=(m,), interpret=interpret)(planes)
    return counts[0, :num_partitions]
