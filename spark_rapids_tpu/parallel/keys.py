"""Typed key codec for the distributed relational ops.

Round-1 limitation: the mesh ops shipped int64 keys only, while the local
path (`ops/sort.py::_key_operands`) already ordered any dtype. This module
closes that gap the TPU way — not by teaching every SPMD body about string
layouts, but by encoding ANY key column into a fixed tuple of (n,) int64
**key words** that flow through the existing exchange machinery unchanged:

- equality:  two rows are equal ⇔ their word tuples are equal
- ordering:  lexicographic int64 order over the tuple == the column's
             sort order (nulls first), so `_merge_groups`' sort-based
             grouping and the sort-merge join spans work verbatim
- decodable: the original column (values + validity) is reconstructible
             from the words — group keys / join keys come back typed

Spark-exact placement: `spark_partition_hash` reconstructs each column's
logical bytes from the words *inside the traced SPMD body* and runs the
same murmur3_32(seed 42) chain as `ops.murmur_hash3_32`, so distributed
placement matches GpuHashPartitioning exactly (Hash.java:40-58), strings
and decimal128 included.

Width rules (static, SPMD-friendly):

| dtype | words |
|---|---|
| bool/int8..64/date/timestamp/decimal32/64 | 1 (sign-extended value) |
| float32/float64 | 1 (total-order bits; NaN canonical, -0.0 → +0.0) |
| decimal128 | 2 (signed hi, bias-flipped lo) |
| string | max_bytes/8 (+1 length word), big-endian bias-flipped |
| any nullable column | +1 leading null-flag word (nulls first, data zeroed) |

Strings require a static `max_bytes` (the SPMD program shape); pick it per
pipeline the way the local string kernels pick `pad_to` buckets
(columnar/column.py `padded_chars`).
"""
from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from ..columnar.column import Column, strings_from_padded
from ..dtypes import DType, Kind

# XOR with the sign bit turns unsigned u64 order into signed int64 order
_SIGN64 = jnp.uint64(1 << 63)

_ONE_WORD_KINDS = (Kind.BOOL, Kind.INT8, Kind.INT16, Kind.INT32, Kind.INT64,
                   Kind.DATE32, Kind.TIMESTAMP_US, Kind.TIMESTAMP_S,
                   Kind.TIMESTAMP_MS, Kind.DECIMAL32, Kind.DECIMAL64)


@dataclasses.dataclass(frozen=True)
class KeySpec:
    """Static per-column encoding recipe (part of the SPMD program shape)."""
    dtype: DType
    n_words: int          # data words (excluding the null-flag word)
    nullable: bool
    max_bytes: int = 0    # strings only: padded byte width (multiple of 8)

    @property
    def total_words(self) -> int:
        return self.n_words + (1 if self.nullable else 0)


def _u64_to_word(u: jnp.ndarray) -> jnp.ndarray:
    """uint64 → int64 whose signed order equals the unsigned order."""
    return (u ^ _SIGN64).astype(jnp.int64)


def _word_to_u64(w: jnp.ndarray) -> jnp.ndarray:
    return w.astype(jnp.uint64) ^ _SIGN64


def _words_from_limbs(limbs: jnp.ndarray) -> List[jnp.ndarray]:
    """(n, 4) LE u32 decimal128 limbs → [signed hi word, bias-flipped lo]."""
    u = limbs.astype(jnp.uint64)
    hi = (u[:, 3] << jnp.uint64(32)) | u[:, 2]
    lo = (u[:, 1] << jnp.uint64(32)) | u[:, 0]
    return [hi.astype(jnp.int64), _u64_to_word(lo)]


def _limbs_from_words(hi_word: jnp.ndarray, lo_word: jnp.ndarray) -> jnp.ndarray:
    hi = hi_word.astype(jnp.uint64)
    lo = _word_to_u64(lo_word)
    return jnp.stack(
        [(lo & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32),
         (lo >> jnp.uint64(32)).astype(jnp.uint32),
         (hi & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32),
         (hi >> jnp.uint64(32)).astype(jnp.uint32)], axis=1)


def _float_order_word(col: Column) -> jnp.ndarray:
    """Total-order int64 word for float columns: NaNs canonical (one group),
    -0.0 folded into +0.0 (Spark groupby equality), order-preserving."""
    from ..ops.hash import _canonical_nan, _normalize_zeros, f64_bits_u64
    x = _normalize_zeros(_canonical_nan(col.data))
    if col.dtype.kind == Kind.FLOAT32:
        bits = jax.lax.bitcast_convert_type(x, jnp.uint32).astype(jnp.uint64) \
            << jnp.uint64(32)
    else:
        # f64_bits_u64 needs NaN bits substituted in the integer domain
        # (same contract as ops/hash.py's murmur encoding)
        bits = jnp.where(jnp.isnan(x), jnp.uint64(0x7FF8000000000000),
                         f64_bits_u64(x))
    # IEEE total order: negative floats reverse, positive floats offset
    neg = (bits >> jnp.uint64(63)) != 0
    tot = jnp.where(neg, ~bits, bits | _SIGN64)
    return _u64_to_word(tot)


def _float_from_word(w: jnp.ndarray, kind: Kind) -> jnp.ndarray:
    tot = _word_to_u64(w)
    neg = (tot >> jnp.uint64(63)) == 0
    bits = jnp.where(neg, ~tot, tot & ~_SIGN64)
    if kind == Kind.FLOAT32:
        return jax.lax.bitcast_convert_type(
            (bits >> jnp.uint64(32)).astype(jnp.uint32), jnp.float32)
    from ..ops.hash import f64_bits_u64  # noqa: F401 (encode counterpart)
    return _f64_from_bits(bits)


def _f64_from_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """Arithmetic IEEE-754 reconstruction (no f64 bitcast on TPU — the
    inverse of ops/hash.py's f64_bits_u64)."""
    sign = (bits >> jnp.uint64(63)) != 0
    expf = ((bits >> jnp.uint64(52)) & jnp.uint64(0x7FF)).astype(jnp.int32)
    mant = (bits & jnp.uint64((1 << 52) - 1)).astype(jnp.float64)
    normal = expf >= 1
    frac = jnp.where(normal, 1.0 + mant * 2.0 ** -52, mant * 2.0 ** -52)
    e = jnp.where(normal, expf - 1023, -1022)
    # exact two-step scaling (integer exponents only — exp2 of an integer is
    # exact; a fractional exponent would round) keeps intermediates in range
    h = (e // 2).astype(jnp.float64)
    mag = frac * jnp.exp2(h) * jnp.exp2(e.astype(jnp.float64) - h)
    is_inf = (expf == 0x7FF) & (mant == 0)
    is_nan = (expf == 0x7FF) & (mant != 0)
    mag = jnp.where(is_inf, jnp.inf, mag)
    mag = jnp.where(is_nan, jnp.nan, mag)
    return jnp.where(sign, -mag, mag)


def encode_key_column(col: Column,
                      max_bytes: Optional[int] = None,
                      spec: Optional[KeySpec] = None
                      ) -> Tuple[List[jnp.ndarray], KeySpec]:
    """Encode one key column into its int64 word list + static spec.

    Pass `spec` (e.g. the other join side's) to force the layout: a
    non-null column encoded under a nullable spec gets an all-valid flag
    word, so both sides of a join produce identical word counts even when
    only one side carries nulls."""
    k = col.dtype.kind
    valid = col.null_mask
    nullable = col.validity is not None
    if spec is not None:
        if spec.dtype.kind != k:
            raise TypeError(f"spec dtype {spec.dtype} != column {col.dtype}")
        if nullable and not spec.nullable:
            raise ValueError(
                "column has nulls but the target spec is non-nullable; "
                "encode the nullable side first (its specs then force the "
                "flag word on the other side)")
        nullable = spec.nullable
        if k == Kind.STRING:
            max_bytes = spec.max_bytes
    words: List[jnp.ndarray] = []

    if k in _ONE_WORD_KINDS:
        words = [col.data.astype(jnp.int64)]
        spec = KeySpec(col.dtype, 1, nullable)
    elif k in (Kind.FLOAT32, Kind.FLOAT64):
        words = [_float_order_word(col)]
        spec = KeySpec(col.dtype, 1, nullable)
    elif k == Kind.DECIMAL128:
        words = _words_from_limbs(col.data)
        spec = KeySpec(col.dtype, 2, nullable)
    elif k == Kind.STRING:
        if max_bytes is None:
            max_bytes = max(8, col.max_string_length())
        M = 8 * math.ceil(max_bytes / 8)
        padded, lens = col.padded_chars(pad_to=M)
        padded = jnp.where(valid[:, None], padded, jnp.uint8(0))
        lens = jnp.where(valid, lens, 0)
        b = padded.reshape(padded.shape[0], M // 8, 8).astype(jnp.uint64)
        w = jnp.zeros(b.shape[:2], jnp.uint64)
        for i in range(8):                        # big-endian pack
            w = (w << jnp.uint64(8)) | b[:, :, i]
        words = [_u64_to_word(w[:, i]) for i in range(M // 8)]
        words.append(lens.astype(jnp.int64))      # prefix-equal tiebreak
        spec = KeySpec(col.dtype, M // 8 + 1, nullable, max_bytes=M)
    else:
        raise TypeError(f"unsupported distributed key dtype {col.dtype}")

    if nullable:
        # nulls first (flag 0) and their data words zeroed so all nulls are
        # one equal tuple, like the local sort's null handling
        words = [jnp.where(valid, w, jnp.int64(0)) for w in words]
        words.insert(0, valid.astype(jnp.int64))
    return words, spec


def encode_key_columns(cols: Sequence[Column],
                       max_bytes: Union[None, int, Sequence[Optional[int]]] = None,
                       specs: Optional[Sequence[KeySpec]] = None
                       ) -> Tuple[List[jnp.ndarray], List[KeySpec]]:
    """Encode several key columns; returns the flat word list + specs.

    For joins, encode one side first and pass its `specs` when encoding
    the other so both sides share one static layout:

        lw, specs = encode_key_columns(lcols, max_bytes=16)
        rw, _     = encode_key_columns(rcols, specs=specs)
    """
    if max_bytes is None or isinstance(max_bytes, int):
        max_bytes = [max_bytes] * len(cols)
    if specs is None:
        specs = [None] * len(cols)
    words: List[jnp.ndarray] = []
    out_specs: List[KeySpec] = []
    for c, mb, sp in zip(cols, max_bytes, specs):
        w, s = encode_key_column(c, mb, spec=sp)
        words.extend(w)
        out_specs.append(s)
    return words, out_specs


def decode_key_columns(words: Sequence[jnp.ndarray], specs: Sequence[KeySpec],
                       alive: Optional[jnp.ndarray] = None) -> List[Column]:
    """Rebuild typed key columns from word arrays (the inverse of encode).

    `alive` (optional bool mask, e.g. the distributed op's `valid` output)
    is folded into each column's validity so padded slots read as null —
    and their words (which carry the exchange's dead-slot sentinel) are
    zeroed first so reassembly math (string offsets) never sees them."""
    if alive is not None:
        words = [jnp.where(alive, w, jnp.int64(0)) for w in words]
    cols: List[Column] = []
    i = 0
    for spec in specs:
        validity = None
        if spec.nullable:
            validity = words[i].astype(jnp.bool_)
            i += 1
        if alive is not None:
            base = validity if validity is not None else True
            validity = jnp.logical_and(base, alive)
        data_words = words[i:i + spec.n_words]
        i += spec.n_words
        n = data_words[0].shape[0]
        k = spec.dtype.kind
        if k in _ONE_WORD_KINDS:
            data = data_words[0].astype(spec.dtype.storage_dtype())
            cols.append(Column(dtype=spec.dtype, length=n, data=data,
                               validity=validity))
        elif k in (Kind.FLOAT32, Kind.FLOAT64):
            cols.append(Column(dtype=spec.dtype, length=n,
                               data=_float_from_word(data_words[0], k),
                               validity=validity))
        elif k == Kind.DECIMAL128:
            limbs = _limbs_from_words(data_words[0], data_words[1])
            cols.append(Column(dtype=spec.dtype, length=n, data=limbs,
                               validity=validity))
        elif k == Kind.STRING:
            W = spec.n_words - 1
            lens = jnp.clip(data_words[-1], 0, spec.max_bytes).astype(jnp.int32)
            padded = _unpack_string_words(data_words[:W], spec.max_bytes)
            v = validity
            cols.append(strings_from_padded(padded, lens, v))
        else:
            raise TypeError(f"unsupported key spec {spec}")
    return cols


def _unpack_string_words(wordlist: Sequence[jnp.ndarray],
                         M: int) -> jnp.ndarray:
    """Word list → (n, M) uint8 padded char matrix (big-endian unpack)."""
    cols8 = []
    for w in wordlist:
        u = _word_to_u64(w)
        for shift in range(56, -1, -8):
            cols8.append(((u >> jnp.uint64(shift)) &
                          jnp.uint64(0xFF)).astype(jnp.uint8))
    return jnp.stack(cols8, axis=1)[:, :M]


def keys_null_mask(words: Sequence[jnp.ndarray],
                   specs: Sequence[KeySpec]) -> jnp.ndarray:
    """(n,) bool, True where ANY key column is null. Equi-join semantics:
    a NULL key never matches (Spark `l.k = r.k` is never true on NULL), so
    the keyed joins exclude these rows from matching — unlike groupby,
    where nulls form one group. Dead exchange slots carry non-zero
    sentinel words and read as not-null; they are excluded by the alive
    masks instead."""
    null = None
    i = 0
    for spec in specs:
        if spec.nullable:
            col_null = words[i] == 0
            null = col_null if null is None else (null | col_null)
        i += spec.total_words
    if null is None:
        return jnp.zeros(words[0].shape, jnp.bool_)
    return null


def spark_partition_hash(words: Sequence[jnp.ndarray],
                         specs: Sequence[KeySpec]) -> jnp.ndarray:
    """Spark murmur3_32(seed 42) of the key tuple, straight off the words —
    the exact GpuHashPartitioning hash (Hash.java:40-58), computable inside
    a traced SPMD body (all shapes static). Placement therefore matches what
    the Spark plugin would compute on the same rows. (One documented
    deviation: float keys were normalized at encode per Spark's SPARK-26021
    grouping rule, so -0.0 hashes as +0.0 here.)

    Null rows pass the seed through unchanged, like `_murmur_element`."""
    from ..ops import hash as H
    # seed derived from the data (not jnp.full) so that under shard_map it
    # carries the same varying mesh axis as the words — a replicated
    # constant seed trips fori_loop's carry-type check inside _mm_var
    h = (words[0] * 0).astype(jnp.uint32) + jnp.uint32(42)
    i = 0
    for spec in specs:
        valid = None
        if spec.nullable:
            valid = words[i] != 0
            i += 1
        dw = words[i:i + spec.n_words]
        i += spec.n_words
        k = spec.dtype.kind
        if k == Kind.STRING:
            padded = _unpack_string_words(dw[:-1], spec.max_bytes)
            lens = dw[-1].astype(jnp.int32)
            hv = H._mm_var(h, padded, lens)
        elif k == Kind.DECIMAL128:
            be, lens = H.java_bigdecimal_bytes(_limbs_from_words(dw[0], dw[1]))
            hv = H._mm_var(h, be, lens)
        else:
            col = decode_key_columns(dw, [dataclasses.replace(spec,
                                                              nullable=False)])[0]
            u64, nbytes = H._encode_fixed_u64(col, normalize_zero=False)
            hv = H._mm_fixed(h, H._words_u32(u64, nbytes), nbytes)
        h = hv if valid is None else jnp.where(valid, hv, h)
    return h.astype(jnp.int32)
