from .shuffle import (partition_ids, build_partition_map, exchange,
                      repartition_table, make_mesh)
from .relational import (distributed_broadcast_join, distributed_groupby,
                         distributed_groupby_keyed, distributed_groupby_multi,
                         distributed_inner_join, distributed_inner_join_keyed,
                         distributed_left_anti_join,
                         distributed_left_join, distributed_left_semi_join,
                         distributed_sort)
from .keys import (KeySpec, encode_key_column, encode_key_columns,
                   decode_key_columns, spark_partition_hash)
from .autoretry import (CapacityOverflowError, auto_retry_overflow,
                        distributed_groupby_auto,
                        distributed_groupby_keyed_auto,
                        distributed_inner_join_auto,
                        distributed_inner_join_keyed_auto,
                        distributed_left_join_auto, distributed_sort_auto)

__all__ = ["partition_ids", "build_partition_map", "exchange",
           "repartition_table", "make_mesh",
           "distributed_groupby", "distributed_groupby_multi",
           "distributed_groupby_keyed", "distributed_inner_join_keyed",
           "KeySpec", "encode_key_column", "encode_key_columns",
           "decode_key_columns", "spark_partition_hash",
           "CapacityOverflowError", "auto_retry_overflow",
           "distributed_groupby_auto", "distributed_groupby_keyed_auto",
           "distributed_inner_join_auto", "distributed_inner_join_keyed_auto",
           "distributed_left_join_auto", "distributed_sort_auto",
           "distributed_inner_join",
           "distributed_broadcast_join", "distributed_left_join",
           "distributed_left_semi_join", "distributed_left_anti_join",
           "distributed_sort"]
