from .shuffle import (partition_ids, build_partition_map, exchange,
                      repartition_table, make_mesh)

__all__ = ["partition_ids", "build_partition_map", "exchange",
           "repartition_table", "make_mesh"]
