"""Driver-side SplitAndRetry for the distributed ops.

Every `distributed_*` op returns an overflow flag instead of corrupting
when a static capacity (key_cap / row_cap / slack) is exceeded — the mesh
analogue of the arbiter's SplitAndRetryOOM (SURVEY.md §5: "split its input
batch and retry"). Round 1 left acting on that flag to the caller; these
wrappers close the loop: run the op, and on overflow grow the capacities
and re-run. Capacities are static shapes, so each retry compiles a new SPMD
program — the retry cost is a compile, never wrong data, and the doubled
caps are remembered by jit's cache for the rest of the job (exactly how a
Spark task that hit SplitAndRetryOOM keeps its smaller batch size).

The growth is geometric (×2 per attempt, like halve_table's halving in
reverse); `max_attempts` bounds the escalation the way the arbiter's
retry limit bounds livelock (SparkResourceAdaptorJni.cpp:984-995).
"""
from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax.numpy as jnp

from .relational import (distributed_broadcast_join,
                         distributed_broadcast_join_keyed,
                         distributed_groupby, distributed_groupby_keyed,
                         distributed_inner_join, distributed_inner_join_keyed,
                         distributed_left_join, distributed_left_join_keyed,
                         distributed_sort)


class CapacityOverflowError(RuntimeError):
    """Retries exhausted with the overflow flag still set."""


def _grown(caps: Dict, grow: float) -> Dict:
    out = {}
    for k, v in caps.items():
        if isinstance(v, int):
            out[k] = max(v + 1, int(v * grow))
        else:
            out[k] = v * grow
    return out


def auto_retry_overflow(attempt: Callable[..., Tuple], caps: Dict,
                        max_attempts: int = 6, grow: float = 2.0,
                        ceil: Dict = None):
    """Run `attempt(**caps)` until its overflow flag (last element of the
    result tuple) clears, growing every capacity geometrically.

    `ceil` (per-capacity upper bounds — the resource certifier's sound
    hi-bounds, analysis/footprint.py) clamps the growth: escalating past
    a PROVEN bound is wasted memory, so a grown capacity stops at its
    ceiling. The ceiling is advisory, never load-bearing for progress: if
    an attempt that ran with a clamped capacity still overflows, the
    bound was wrong for this run (a certifier bug — soundness says this
    cannot happen) and the ceiling is dropped, restoring the pure
    geometric ladder rather than turning a recoverable overflow into a
    CapacityOverflowError.

    Returns (result_tuple, final_caps). The overflow check is a host sync —
    this is a driver-level loop by design, like the plugin's catch-retry."""
    ceil = dict(ceil or {})
    clamped_last = False
    for i in range(max_attempts):
        out = attempt(**caps)
        if not bool(jnp.any(out[-1])):
            return out, caps
        if clamped_last:
            ceil = {}           # distrust: a clamped attempt overflowed
            clamped_last = False
        if i + 1 < max_attempts:
            grown = _grown(caps, grow)
            if ceil:
                capped = {k: max(caps[k], min(v, ceil[k]))
                          if k in ceil and isinstance(v, int) else v
                          for k, v in grown.items()}
                if capped == caps:
                    # the ceiling blocks ALL growth: re-attempting
                    # byte-identical caps would deterministically
                    # overflow again, burning a ladder rung for nothing
                    # — drop the (evidently wrong) ceiling NOW and
                    # regrow, preserving the full geometric ladder
                    ceil = {}
                    caps = grown
                else:
                    clamped_last = capped != grown
                    caps = capped
            else:
                caps = grown
    raise CapacityOverflowError(
        f"overflow persisted after {max_attempts} attempts; final caps {caps}")


def distributed_groupby_auto(mesh, keys, vals, aggs, key_cap: int,
                             axis: str = "data", max_attempts: int = 6):
    """distributed_groupby that retries with a doubled key_cap on overflow
    (more distinct keys per shard than the static shape allowed)."""
    out, _ = auto_retry_overflow(
        lambda key_cap: distributed_groupby(mesh, keys, vals, aggs,
                                            key_cap=key_cap, axis=axis),
        {"key_cap": key_cap}, max_attempts)
    return out


def distributed_groupby_keyed_auto(mesh, key_words, key_specs, vals, aggs,
                                   key_cap: int, axis: str = "data",
                                   max_attempts: int = 6):
    out, _ = auto_retry_overflow(
        lambda key_cap: distributed_groupby_keyed(
            mesh, key_words, key_specs, vals, aggs, key_cap=key_cap,
            axis=axis),
        {"key_cap": key_cap}, max_attempts)
    return out


def distributed_inner_join_auto(mesh, lkeys, lvals, rkeys, rvals,
                                row_cap: int, slack: float = 2.0,
                                axis: str = "data", max_attempts: int = 6):
    """distributed_inner_join that grows BOTH capacities on overflow: the
    merged flag covers bucket spill during the shuffle (fix: slack) and
    join-output spill past row_cap (fix: row_cap); growing both converges
    on skew of either kind."""
    out, _ = auto_retry_overflow(
        lambda row_cap, slack: distributed_inner_join(
            mesh, lkeys, lvals, rkeys, rvals, row_cap=row_cap, slack=slack,
            axis=axis),
        {"row_cap": row_cap, "slack": slack}, max_attempts)
    return out


def distributed_inner_join_keyed_auto(mesh, l_words, lvals, r_words, rvals,
                                      key_specs, row_cap: int,
                                      slack: float = 2.0, axis: str = "data",
                                      max_attempts: int = 6):
    out, _ = auto_retry_overflow(
        lambda row_cap, slack: distributed_inner_join_keyed(
            mesh, l_words, lvals, r_words, rvals, key_specs,
            row_cap=row_cap, slack=slack, axis=axis),
        {"row_cap": row_cap, "slack": slack}, max_attempts)
    return out


def distributed_left_join_auto(mesh, lkeys, lvals, rkeys, rvals,
                               row_cap: int, slack: float = 2.0,
                               axis: str = "data", max_attempts: int = 6):
    out, _ = auto_retry_overflow(
        lambda row_cap, slack: distributed_left_join(
            mesh, lkeys, lvals, rkeys, rvals, row_cap=row_cap, slack=slack,
            axis=axis),
        {"row_cap": row_cap, "slack": slack}, max_attempts)
    return out


def distributed_left_join_keyed_auto(mesh, l_words, lvals, r_words, rvals,
                                     key_specs, row_cap: int,
                                     slack: float = 2.0, axis: str = "data",
                                     max_attempts: int = 6):
    out, _ = auto_retry_overflow(
        lambda row_cap, slack: distributed_left_join_keyed(
            mesh, l_words, lvals, r_words, rvals, key_specs,
            row_cap=row_cap, slack=slack, axis=axis),
        {"row_cap": row_cap, "slack": slack}, max_attempts)
    return out


def distributed_broadcast_join_auto(mesh, lkeys, lvals, rkeys, rvals,
                                    row_cap: int, axis: str = "data",
                                    max_attempts: int = 6):
    """Broadcast joins have no shuffle spill (the build side is replicated
    whole), so only row_cap grows on overflow."""
    out, _ = auto_retry_overflow(
        lambda row_cap: distributed_broadcast_join(
            mesh, lkeys, lvals, rkeys, rvals, row_cap=row_cap, axis=axis),
        {"row_cap": row_cap}, max_attempts)
    return out


def distributed_broadcast_join_keyed_auto(mesh, l_words, lvals, r_words,
                                          rvals, key_specs, row_cap: int,
                                          axis: str = "data",
                                          max_attempts: int = 6):
    out, _ = auto_retry_overflow(
        lambda row_cap: distributed_broadcast_join_keyed(
            mesh, l_words, lvals, r_words, rvals, key_specs,
            row_cap=row_cap, axis=axis),
        {"row_cap": row_cap}, max_attempts)
    return out


def distributed_sort_auto(mesh, keys, vals, slack: float = 2.0,
                          axis: str = "data", max_attempts: int = 6):
    """distributed_sort that grows slack on overflow (key skew past the
    sample-sort's balance estimate)."""
    out, _ = auto_retry_overflow(
        lambda slack: distributed_sort(mesh, keys, vals, slack=slack,
                                       axis=axis),
        {"slack": slack}, max_attempts)
    return out
