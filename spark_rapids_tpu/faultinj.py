"""Fault injector for the device-call surface (reference: faultinj/faultinj.cu,
the CUPTI-based `libcufaultinj.so` loaded via CUDA_INJECTION64_PATH; config
schema from faultinj/README.md:61-170, SURVEY.md §2.3).

The CUDA tool subscribes to CUPTI callbacks for every Driver/Runtime API call
and injects faults by rule. The TPU-native interception point is the
framework's own device-call surface: every public op in
`spark_rapids_tpu.ops` (compute dispatch) and the arbiter-fronted memory
calls (`MemoryBudget.acquire`/`release`). Activation mirrors the reference's
env-var loading: set `TPU_FAULT_INJECTOR_CONFIG_PATH` before importing the
package (the analogue of CUDA_INJECTION64_PATH + FAULT_INJECTOR_CONFIG_PATH),
or call `install(path)` from tests.

Config (JSON; field names kept from faultinj/README.md):

    {
      "logLevel": 1,            # python logging level number, spdlog-style
      "seed": 12345,            # sampling RNG seed (reproducible runs)
      "dynamic": true,          # hot-reload on config-file mtime change
      "computeFaults":  { "<op name>|*": { rule } },   # cudaRuntimeFaults slot
      "runtimeFaults":  { "<call name>|*": { rule } }  # cudaDriverFaults slot
    }

    rule = {
      "percent": 50,              # injection probability per matched call
      "injectionType": 0|1|2,     # 0 fatal device fault (PTX-trap analogue:
                                  #   poisons the device; later calls fail),
                                  # 1 nonfatal device assert (recoverable),
                                  # 2 substitute return code
      "substituteReturnCode": 2,  # arbiter status code to surface (type 2)
      "interceptionCount": 1000   # how many matched calls remain eligible
    }

Fatal-vs-nonfatal is the point of the tool (faultinj/README.md:6-16): a
fatal injected fault must leave the "device" unusable so the framework's
failure-detection logic can prove it stops retrying on a dead device;
`reset_device()` is the test-harness analogue of restarting the executor.
"""
from __future__ import annotations

import json
import logging
import os
import random
import threading
from typing import Callable, Dict, Optional

log = logging.getLogger("spark_rapids_tpu.faultinj")

ENV_CONFIG_PATH = "TPU_FAULT_INJECTOR_CONFIG_PATH"

FAULT_FATAL = 0        # reference: PTX trap kernel (faultinj.cu:139)
FAULT_ASSERT = 1       # reference: device assert(0) kernel (faultinj.cu:141)
FAULT_SUBSTITUTE = 2   # reference: substitute CUresult (faultinj.cu:226-248)


class DeviceFatalError(RuntimeError):
    """Injected fatal fault: the device is unusable until reset_device().
    (Reference analogue: sticky CUDA_ERROR_ILLEGAL_INSTRUCTION after trap.)"""


class DeviceAssertError(RuntimeError):
    """Injected nonfatal fault: this call failed; the device is still good."""


class InjectedReturnCode(RuntimeError):
    """Injected substitute return code (injectionType 2)."""

    def __init__(self, api_name: str, code: int):
        super().__init__(f"injected return code {code} from {api_name}")
        self.code = code


class _Rule:
    def __init__(self, spec: Dict):
        self.percent = float(spec.get("percent", 0))
        self.injection_type = int(spec.get("injectionType", FAULT_ASSERT))
        self.substitute_code = int(spec.get("substituteReturnCode", 0))
        # remaining matched calls eligible for sampling
        self.count = int(spec.get("interceptionCount", 0x7FFFFFFF))
        self.lock = threading.Lock()

    def draw(self, rng: random.Random) -> bool:
        """One matched call: consume eligibility, sample the percent."""
        with self.lock:
            if self.count <= 0:
                return False
            self.count -= 1
        return rng.uniform(0, 100) < self.percent


class FaultInjector:
    """One loaded config + its interception state."""

    def __init__(self, config_path: str):
        self.config_path = config_path
        self._mtime = 0.0
        self._lock = threading.Lock()
        self._device_poisoned = False
        self._injected = 0
        self._load()

    # ---- config ------------------------------------------------------------

    def _load(self) -> None:
        with open(self.config_path) as f:
            cfg = json.load(f)
        self._mtime = os.stat(self.config_path).st_mtime
        self.dynamic = bool(cfg.get("dynamic", False))
        self.rng = random.Random(cfg.get("seed"))
        if "logLevel" in cfg:
            # spdlog numeric levels 0..6 ~ trace..off; map onto logging's 0..50
            log.setLevel(min(int(cfg["logLevel"]), 5) * 10)
        self.compute_rules = {k: _Rule(v)
                              for k, v in cfg.get("computeFaults", {}).items()}
        self.runtime_rules = {k: _Rule(v)
                              for k, v in cfg.get("runtimeFaults", {}).items()}
        log.info("faultinj config loaded from %s (dynamic=%s)",
                 self.config_path, self.dynamic)

    def _maybe_reload(self) -> None:
        if not self.dynamic:
            return
        try:
            m = os.stat(self.config_path).st_mtime
        except OSError:
            return
        if m != self._mtime:
            with self._lock:
                if m != self._mtime:
                    try:
                        self._load()
                    except (OSError, ValueError) as e:
                        log.warning("faultinj config reload failed: %s", e)

    # ---- interception ------------------------------------------------------

    def reset_device(self) -> None:
        """Clear the poisoned-device state (executor-restart analogue)."""
        with self._lock:
            self._device_poisoned = False

    @property
    def device_poisoned(self) -> bool:
        return self._device_poisoned

    def get_and_reset_injected(self) -> int:
        """Faults fired since the last drain (arbiter-style get-and-reset;
        the chaos-soak stage records this per benchmark run)."""
        with self._lock:
            n = self._injected
            self._injected = 0
        return n

    def on_call(self, api_name: str, which: str) -> None:
        """Interception callback — the CUPTI callback-handler analogue
        (faultinj.cu:158-260). Raises when a fault fires."""
        if getattr(_suppress, "on", False):
            return      # degraded CPU tier: no device, no device faults
        self._maybe_reload()
        if self._device_poisoned:
            raise DeviceFatalError(
                f"device is in a failed state (earlier injected fatal fault); "
                f"{api_name} refused")
        rules = getattr(self, which)  # looked up AFTER a possible hot reload
        rule = rules.get(api_name) or rules.get("*")
        if rule is None or not rule.draw(self.rng):
            return
        log.debug("injecting fault type %d into %s", rule.injection_type, api_name)
        with self._lock:
            self._injected += 1
            if rule.injection_type == FAULT_FATAL:
                # poison INSIDE the lock: under concurrent sessions a racing
                # reset_device() must observe either the un-poisoned or the
                # fully-poisoned state, never a torn interleaving where the
                # fatal was counted but the device stayed healthy
                self._device_poisoned = True
        if rule.injection_type == FAULT_FATAL:
            raise DeviceFatalError(f"injected fatal device fault in {api_name}")
        if rule.injection_type == FAULT_ASSERT:
            raise DeviceAssertError(f"injected device assert in {api_name}")
        if rule.injection_type == FAULT_SUBSTITUTE:
            raise InjectedReturnCode(api_name, rule.substitute_code)

    def on_compute(self, api_name: str) -> None:
        self.on_call(api_name, "compute_rules")

    def on_runtime(self, api_name: str) -> None:
        self.on_call(api_name, "runtime_rules")


# ---- thread-local suppression ----------------------------------------------

_suppress = threading.local()


class suppressed:
    """Context manager: disable interception on this thread.

    The degraded CPU tier (plan/executor.py, docs/robustness.md) runs
    device-free, so NO device-call interception — compute shims, the
    arbiter-fronted MemoryBudget shims, or a poisoned-device fail-fast —
    may fire inside it; a dead device must not be able to kill the
    fallback that exists to survive it."""

    def __enter__(self):
        self._prev = getattr(_suppress, "on", False)
        _suppress.on = True
        return self

    def __exit__(self, *exc):
        _suppress.on = self._prev
        return False


# ---- global install / uninstall --------------------------------------------

_active: Optional[FaultInjector] = None
_saved_ops: Dict[str, Callable] = {}
_saved_budget_methods: Dict[str, Callable] = {}
# install/uninstall swap module-global interception state (the shims AND
# the saved originals); two racing installs would save each other's shims
# as "originals" and uninstall could never restore the real ops (the
# unguarded-module-global-mutation lint rule machine-checks this)
_install_lock = threading.Lock()


def active() -> Optional[FaultInjector]:
    return _active


def _wrap_op(name: str, fn: Callable) -> Callable:
    def shim(*args, **kwargs):
        inj = _active
        if inj is not None:
            inj.on_compute(name)
        return fn(*args, **kwargs)
    shim.__name__ = fn.__name__
    shim.__doc__ = fn.__doc__
    shim.__wrapped__ = fn
    shim.__faultinj_shim__ = True
    return shim


def install(config_path: Optional[str] = None) -> FaultInjector:
    """Load the config and intercept the device-call surface.

    Idempotent per-process like the reference's cuInit-time load; call
    uninstall() first to swap interception points.
    """
    with _install_lock:
        return _install_locked(config_path)


def _install_locked(config_path: Optional[str]) -> FaultInjector:
    global _active
    from . import config as _config
    path = config_path or _config.faultinj_config_path()
    if not path:
        raise ValueError(f"no config path given and ${ENV_CONFIG_PATH} unset")
    if _active is not None:
        # same interception points; just swap the config
        _active = FaultInjector(path)
        return _active
    _active = FaultInjector(path)

    from . import ops
    for name in ops.__all__:
        fn = getattr(ops, name)
        # skip non-callables and our own shims (admission wrappers set
        # __wrapped__ too, so that attr is no longer a valid skip marker)
        if callable(fn) and not hasattr(fn, "__faultinj_shim__"):
            _saved_ops[name] = fn
            setattr(ops, name, _wrap_op(name, fn))

    from .runtime import pool

    def patched(method_name):
        orig = getattr(pool.MemoryBudget, method_name)
        _saved_budget_methods[method_name] = orig

        def shim(self, *args, **kwargs):
            inj = _active
            if inj is not None:
                inj.on_runtime(f"MemoryBudget.{method_name}")
            return orig(self, *args, **kwargs)
        shim.__name__ = method_name
        shim.__wrapped__ = orig
        return shim

    for m in ("acquire", "try_acquire", "release"):
        setattr(pool.MemoryBudget, m, patched(m))
    log.info("faultinj installed over %d ops + MemoryBudget", len(_saved_ops))
    return _active


def uninstall() -> None:
    """Remove interception and restore the original callables."""
    with _install_lock:
        _uninstall_locked()


def _uninstall_locked() -> None:
    global _active
    _active = None
    if _saved_ops:
        from . import ops
        for name, fn in _saved_ops.items():
            setattr(ops, name, fn)
        _saved_ops.clear()
    if _saved_budget_methods:
        from .runtime import pool
        for name, fn in _saved_budget_methods.items():
            setattr(pool.MemoryBudget, name, fn)
        _saved_budget_methods.clear()


def maybe_install_from_env() -> None:
    """Package-import hook: activate when the env var is set, exactly like
    the reference loading libcufaultinj.so via CUDA_INJECTION64_PATH."""
    from . import config as _config
    if _config.faultinj_config_path():
        try:
            install()
        except (OSError, ValueError) as e:
            log.warning("faultinj auto-install failed: %s", e)
