"""Static resource certifier: abstract-interpretation bounds on cardinality,
memory footprint, and exchange bytes (docs/analysis.md).

The capped tier historically discovered footprints by OOM-escalation and
admission had no sizing at all — the arbitration story (PAPER.md §0:
many tasks share one device without deadlocking) needs to know *before*
admitting a plan whether it can possibly fit. This module walks the typed
plan DAG once, in toposort order, propagating a SOUND interval ``[lo, hi]``
on row count per operator plus derived byte footprints, and packages the
result as a :class:`ResourceCert`:

- **rows**: ``hi`` is an upper bound that holds for every execution over
  the bound inputs (filters collapse ``lo`` to 0, never ``hi``; an inner
  join's ``hi`` is the full cross product of its sides' ``hi`` — loose but
  sound, there are no key statistics to do better with statically);
- **bytes**: per-row widths come from the SAME dtype propagation the
  verifier's typing layer runs (`verifier.column_types`) — fixed-width
  columns certify ``itemsize + 1`` bytes/row (the +1 is a validity plane,
  assumed present because the certifier may not know nullability), while
  string/nested/unknown columns make the operator's byte bound UNBOUNDED
  (their buffer length is not a function of the row count);
- **working sets**: a join's build (right) table and a keyed aggregate's
  hash-table accumulators are resident while the operator runs, on top of
  its inputs and output — `resident_bytes_hi` sums them;
- **exchange bytes**: hash edges move each row at most once, broadcast
  replicates the relation onto every other peer, gather collects it —
  `exchange_bytes_hi` bounds the payload per planned Exchange edge
  (ROADMAP item 5's honest bytes-on-wire accounting, statically). The
  bound models the WIRE form the distributed tier actually ships
  (plan/transport.py): a hash edge's key columns ride their 64-bit
  order-preserving word encoding (8 B per word, plus a null-flag word
  when nullable) while value columns ship at most their unpacked
  column width; a hash edge whose sole consumer is a keyed aggregate
  fuses into the two-phase groupby and ships per-group int64 partials
  instead, so such edges bound by the larger of the two payload models.
  The runtime's observed `exchange_bytes` (wire) must stay at or under
  this bound on every edge — `check_observed` enforces the inequality.

Soundness contract (machine-checked): for every operator of every
executed plan, ``rows_lo <= observed rows_out <= rows_hi``, and on the
eager tier ``observed bytes_out <= out_bytes_hi`` (the capped tier pads
buffers to its caps, and the distributed tier's exchange buffers carry
slack, so their byte observations measure padding, not live data — rows
remain comparable everywhere). The fuzzer's property 5
(`analysis/fuzz.py`) asserts this on every seeded random DAG, cold and
warm, plus MONOTONICITY: an optimizer rewrite may only keep or tighten
the root's certified bound. `benchmarks/footprint_bench.py` asserts it
nightly on NDS q5/q72 and reports the bound-tightness ratio
(certified/observed) to JSONL.

Three consumers (docs/analysis.md#resource-certifier):

1. the executor's admission path (`PlanExecutor.execute`) rejects — or
   downgrades to the CPU tier — a plan whose certified hi-bound exceeds
   the configured device budget, BEFORE any compilation, raising a
   `ResourceAdmissionError` (PlanVerificationError family) that names the
   offending operator;
2. the optimizer consults certified row bounds where no observed stats
   or static estimates exist (decision source ``certified:<bound>``), and
   `exchange_planning` proves broadcast-join legality as a BYTE bound
   (`SPARK_RAPIDS_TPU_BROADCAST_BYTES`) instead of trusting the row
   heuristic alone;
3. the capped tier, on cold adaptive runs, tightens starting capacities
   to the certified hi (a sound bound can never overflow) and ceilings
   the escalation ladder at it — warm runs keep the observed high-water,
   which must always be <= the certified bound: that inequality IS the
   soundness check.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from .. import dtypes
from ..plan.nodes import (Exchange, Filter, FusedSelect, HashAggregate,
                          HashJoin, Limit, PlanNode, Project, Scan, Sort,
                          TopK, Union)
from .verifier import (PlanVerificationError, Violation, _propagate_schemas,
                       column_types)

__all__ = ["OpBound", "ResourceCert", "ResourceAdmissionError",
           "certify", "certify_nodes", "table_metadata",
           "check_observed", "quota_charge"]

_VALIDITY_BYTES = 1        # one bool plane byte per row per column
_ACC_BYTES = 8             # aggregate accumulators widen to 64-bit


class ResourceAdmissionError(PlanVerificationError):
    """A plan's certified footprint exceeds the device budget — raised at
    admission, before any compilation, with the offending operator's label
    in the structured violations (same `Violation` vocabulary as every
    other static-analysis gate)."""


def _add(a: Optional[int], b: Optional[int]) -> Optional[int]:
    """None-propagating sum: an unbounded term poisons the bound."""
    if a is None or b is None:
        return None
    return a + b


def _mul(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None or b is None:
        return None
    return a * b


def _col_width(dt: Optional[dtypes.DType]) -> Optional[int]:
    """Certified bytes per row for one column's buffers, or None when the
    buffer length is not a function of the row count (strings/nested) or
    the dtype is unknown. DECIMAL128 is fixed-width (16 bytes of limbs)."""
    if dt is None or dt.is_string or dt.is_nested:
        return None
    return dt.itemsize() + _VALIDITY_BYTES


@dataclasses.dataclass(frozen=True)
class OpBound:
    """Certified bounds for one operator. `rows_hi`/byte fields are None
    when UNBOUNDED (an unknown input cardinality or a non-fixed-width
    column reached this operator) — the certifier is sound-but-incomplete
    and never guesses."""
    label: str
    kind: str
    index: int                        # toposort index (the capped tier's
    #                                   per-node cap-key space)
    rows_lo: int
    rows_hi: Optional[int]
    row_bytes: Optional[int]          # certified output bytes per row
    out_bytes_hi: Optional[int]       # rows_hi x row_bytes
    working_bytes_hi: Optional[int]   # join build table / agg hash table
    exchange_bytes_hi: Optional[int]  # planned movement (Exchange nodes)
    resident_bytes_hi: Optional[int]  # child outputs + working + output

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


class ResourceCert:
    """One plan's certified resource bounds, toposort-ordered. `by_label`
    and `by_index` address the same `OpBound`s; `peak_bytes_hi` is the
    largest certified per-operator residency (the admission comparand);
    `unbounded` lists operators the certifier could not bound (they pass
    admission — rejecting them would reject every string plan — but are
    visible so the operator knows the cert is partial)."""

    def __init__(self, ops: List[OpBound], n_peers: int = 1):
        self.ops = list(ops)
        self.n_peers = n_peers
        self.by_label: Dict[str, OpBound] = {b.label: b for b in self.ops}
        self.by_index: Dict[int, OpBound] = {b.index: b for b in self.ops}
        self.unbounded: List[str] = [
            b.label for b in self.ops
            if b.rows_hi is None or b.out_bytes_hi is None]
        finite = [b.resident_bytes_hi for b in self.ops
                  if b.resident_bytes_hi is not None]
        self.peak_bytes_hi: Optional[int] = max(finite) if finite else None
        ex = [b.exchange_bytes_hi for b in self.ops
              if b.exchange_bytes_hi is not None]
        self.exchange_bytes_hi: Optional[int] = sum(ex) if ex else 0

    @property
    def root(self) -> OpBound:
        return self.ops[-1]

    def over_budget(self, budget_bytes: int) -> List[Violation]:
        """Operators whose certified residency provably exceeds
        `budget_bytes` — DEFINITE findings only: an unbounded operator is
        reported on the cert, not rejected (sound-but-incomplete, same
        philosophy as the verifier)."""
        out = []
        for b in self.ops:
            if b.resident_bytes_hi is not None and \
                    b.resident_bytes_hi > budget_bytes:
                out.append(Violation(
                    "footprint.over-budget", b.label,
                    f"{b.label}: certified residency hi-bound "
                    f"{b.resident_bytes_hi} B (rows<= "
                    f"{b.rows_hi}, output<={b.out_bytes_hi} B, working<="
                    f"{b.working_bytes_hi or 0} B) exceeds the device "
                    f"budget of {budget_bytes} B — the plan cannot be "
                    "proven to fit"))
        return out

    def to_dict(self) -> Dict:
        return {"peak_bytes_hi": self.peak_bytes_hi,
                "exchange_bytes_hi": self.exchange_bytes_hi,
                "root_rows_hi": self.root.rows_hi,
                "root_bytes_hi": self.root.out_bytes_hi,
                "unbounded": list(self.unbounded),
                "ops": [b.to_dict() for b in self.ops]}

    def render(self) -> str:
        """explain()-style block: one line per operator."""
        def fmt(v, unit=""):
            return "unbounded" if v is None else f"{v}{unit}"
        lines = ["resource cert (certified hi-bounds, "
                 f"peak {fmt(self.peak_bytes_hi, ' B')} resident, "
                 f"exchange {fmt(self.exchange_bytes_hi, ' B')}):"]
        for b in self.ops:
            parts = [f"rows [{b.rows_lo}, {fmt(b.rows_hi)}]",
                     f"out<={fmt(b.out_bytes_hi, ' B')}"]
            if b.working_bytes_hi:
                parts.append(f"working<={b.working_bytes_hi} B")
            if b.exchange_bytes_hi:
                parts.append(f"exchange<={b.exchange_bytes_hi} B")
            lines.append(f"  {b.label}: " + ", ".join(parts))
        return "\n".join(lines)

    def __repr__(self):
        return (f"ResourceCert({len(self.ops)} ops, peak="
                f"{self.peak_bytes_hi}, unbounded={len(self.unbounded)})")

    def peak_op_label(self) -> str:
        """Label of the operator that set `peak_bytes_hi` ("" when every
        operator is unbounded) — the name an over-quota serving
        diagnostic carries (docs/serving.md)."""
        for b in self.ops:
            if b.resident_bytes_hi is not None and \
                    b.resident_bytes_hi == self.peak_bytes_hi:
                return b.label
        return ""


def quota_charge(cert: Optional["ResourceCert"],
                 default_bytes: int) -> Tuple[int, str, str]:
    """Bytes one plan admission charges against a serving session's
    memory quota (serving/scheduler.py, docs/serving.md).

    The certified `peak_bytes_hi` is the charge when the certifier
    bounded the plan — it is SOUND (the plan provably stays inside that
    many resident bytes), so quota accounting inherits the same
    no-guessing contract as the admission gate. A plan the certifier
    could not bound (strings/nested columns, unbound scans, an internal
    certifier decline) charges the flat `default_bytes` instead
    (`SPARK_RAPIDS_TPU_SERVING_DEFAULT_CHARGE_BYTES`): unbounded plans
    neither ride the quota for free nor get rejected outright.

    Returns ``(bytes, source, op_label)``: source is ``"certified"`` or
    ``"default"``; op_label names the operator that set the certified
    peak ("" under the default) — the label an over-quota diagnostic
    should carry."""
    if cert is None or cert.peak_bytes_hi is None:
        return int(default_bytes), "default", ""
    return int(cert.peak_bytes_hi), "certified", cert.peak_op_label()


# ---- the abstract interpreter ----------------------------------------------

def _scan_rows(node: Scan, bound_rows) -> Optional[int]:
    """Source cardinality: the bound table/source's row count wins; a scan
    carrying its own parquet binding knows its footer count; otherwise
    unbounded (est_rows is a HINT, never a sound bound)."""
    v = (bound_rows or {}).get(node.source)
    if v is not None:
        return int(v)
    if node.parquet is not None:
        try:
            return int(node.parquet.num_rows)
        except (AttributeError, TypeError):
            return None
    return None


def _rows_interval(node: PlanNode, kids: List[Tuple[int, Optional[int]]],
                   bound_rows, nullable_keys: bool
                   ) -> Tuple[int, Optional[int]]:
    """The transfer function: [lo, hi] of this operator's output rows from
    its children's intervals. Sound for every tier: filters/semijoins
    collapse lo to 0 and never raise hi; inner joins bound by the cross
    product; keyed aggregates by their input (distinct groups <= rows)."""
    if isinstance(node, Scan):
        n = _scan_rows(node, bound_rows)
        if n is None:
            return 0, None
        # a pruning predicate may skip row groups: lo collapses, hi holds
        return (0 if node.predicate is not None else n), n
    los = [lo for lo, _ in kids]
    his = [hi for _, hi in kids]
    if isinstance(node, (Filter, FusedSelect)):
        return 0, his[0]
    if isinstance(node, (Project, Sort, Exchange)):
        return los[0], his[0]
    if isinstance(node, (Limit, TopK)):
        return (min(node.n, los[0]),
                None if his[0] is None else min(node.n, his[0]))
    if isinstance(node, Union):
        hi = 0
        for h in his:
            hi = _add(hi, h)
        return sum(los), hi
    if isinstance(node, HashJoin):
        if node.how == "inner":
            return 0, _mul(his[0], his[1])
        return 0, his[0]                     # semi/anti: left-row subset
    if isinstance(node, HashAggregate):
        if not node.keys:
            return 1, 1                      # one row, even over empty input
        # distinct groups <= input rows; at least one group when the input
        # provably has a row AND no key column can be null (a null-keyed
        # row's grouping is kernel policy the certifier must not assume)
        lo = 1 if (los[0] > 0 and not nullable_keys) else 0
        return lo, his[0]
    return los[0] if los else 0, his[0] if his else None


def _key_words(dt: Optional[dtypes.DType], nullable: bool) -> Optional[int]:
    """64-bit words one key column rides through a hash exchange
    (parallel/keys.py encoding: decimal128 = 2 data words, every other
    fixed-width kind = 1, plus a null-flag word when nullable); None for
    kinds with no distributed key encoding (strings/nested/unknown)."""
    if dt is None or dt.is_string or dt.is_nested:
        return None
    words = 2 if dt.kind == dtypes.Kind.DECIMAL128 else 1
    return words + (1 if nullable else 0)


def _hash_edge_row_bytes(node: Exchange, schema, ctypes,
                         cnull) -> Optional[int]:
    """Wire bytes per row of a standalone hash exchange: key columns as
    8-byte order-preserving words, every other column at most its
    unpacked width. The transport may FOR-narrow the shipped key planes
    (transport.narrow_words) and widen them back for the partition hash
    — a strict shrink, so pricing keys at full width stays a sound
    upper bound."""
    total = 0
    keyset = set(node.keys)
    for k in node.keys:
        w = _key_words(ctypes.get(k), cnull.get(k, True))
        if w is None:
            return None
        total += 8 * w
    for cname in (schema or ()):
        if cname in keyset:
            continue
        w = _col_width(ctypes.get(cname))
        if w is None:
            return None
        total += w
    return total


def _partial_row_bytes(agg: HashAggregate, ctypes, cnull) -> Optional[int]:
    """Wire bytes per shipped GROUP of a fused aggregate exchange: the
    two-phase program's all-to-all moves one int64 per key word and per
    agg partial (groups <= input rows, so rows_hi x this width is a
    sound payload bound)."""
    total_words = 0
    for k in agg.keys:
        w = _key_words(ctypes.get(k), cnull.get(k, True))
        if w is None:
            return None
        total_words += w
    return 8 * (total_words + len(agg.aggs))


def _agg_widths(node: HashAggregate, child_types) -> Optional[int]:
    """Output bytes/row of a HashAggregate: group keys keep their column
    widths; aggregate outputs certify at the 64-bit accumulator width
    (sums/counts/means accumulate in 64-bit regardless of the input
    column's width — certifying the typed width would under-bound)."""
    total = 0
    for k in node.keys:
        w = _col_width(child_types.get(k))
        if w is None:
            return None
        total += w
    return total + len(node.aggs) * (_ACC_BYTES + _VALIDITY_BYTES)


def certify_nodes(nodes: List[PlanNode], *, bound=None, bound_rows=None,
                  input_dtypes=None, input_nullable=None,
                  n_peers: int = 1) -> Dict[int, OpBound]:
    """Core walk over an already-toposorted node list; returns node-id ->
    OpBound. `bound` maps scan source -> column names (schema resolution
    falls back to declared schemas), `bound_rows` -> row counts,
    `input_dtypes` -> {column: DType} (enables byte bounds),
    `input_nullable` -> {column: bool} (tightens keyed-aggregate lo;
    unknown columns are assumed nullable). `n_peers` sizes exchange
    payloads (1 = single chip, exchanges move nothing)."""
    schemas, _ = _propagate_schemas(nodes, bound, strict=False)
    types = column_types(nodes, schemas, input_dtypes or {})
    parents: Dict[int, List[PlanNode]] = {}
    for nd in nodes:
        for ch in nd.children:
            parents.setdefault(id(ch), []).append(nd)
    # nullability walk, conservative: unknown -> True (nullable)
    nullable: Dict[int, Dict[str, bool]] = {}
    for node in nodes:
        kids_n = [nullable.get(id(c), {}) for c in node.children]
        if isinstance(node, Scan):
            src = dict((input_nullable or {}).get(node.source) or {})
            nullable[id(node)] = {
                c: src.get(c, True) for c in schemas.get(id(node), ())}
        elif isinstance(node, (Project, FusedSelect)):
            from ..plan.expr import ColumnRef
            nullable[id(node)] = {
                n: (kids_n[0].get(e.name, True)
                    if isinstance(e, ColumnRef) else False)
                for n, e in node.exprs}
        elif isinstance(node, HashJoin):
            out = dict(kids_n[0])
            if node.how == "inner":
                out.update(kids_n[1])
            nullable[id(node)] = out
        elif isinstance(node, HashAggregate):
            out = {k: kids_n[0].get(k, True) for k in node.keys}
            out.update({n: True for _, _, n in node.aggs})
            nullable[id(node)] = out
        elif isinstance(node, Union):
            merged = {}
            for c in schemas.get(id(node), ()):
                merged[c] = any(k.get(c, True) for k in kids_n)
            nullable[id(node)] = merged
        else:
            nullable[id(node)] = dict(kids_n[0]) if kids_n else {}

    out: Dict[int, OpBound] = {}
    for i, node in enumerate(nodes):
        kid_bounds = [out[id(c)] for c in node.children]
        kid_rows = [(b.rows_lo, b.rows_hi) for b in kid_bounds]
        keys_nullable = True
        if isinstance(node, HashAggregate) and node.keys and kid_bounds:
            cn = nullable.get(id(node.children[0]), {})
            keys_nullable = any(cn.get(k, True) for k in node.keys)
        lo, hi = _rows_interval(node, kid_rows, bound_rows, keys_nullable)

        # output bytes/row from the typed schema
        schema = schemas.get(id(node))
        ntypes = types.get(id(node)) or {}
        row_bytes: Optional[int] = None
        if schema is not None:
            if isinstance(node, HashAggregate):
                ctypes = (types.get(id(node.children[0])) or {}
                          if node.children else {})
                row_bytes = _agg_widths(node, ctypes)
            else:
                total = 0
                for c in schema:
                    w = _col_width(ntypes.get(c))
                    if w is None:
                        total = None
                        break
                    total += w
                row_bytes = total
        out_bytes = _mul(hi, row_bytes)

        # operator working sets beyond inputs + output
        working: Optional[int] = 0
        if isinstance(node, HashJoin):
            # the build (right) table is resident while probing — even for
            # semi/anti, where it never reaches the output
            working = kid_bounds[1].out_bytes_hi
        elif isinstance(node, HashAggregate) and node.keys:
            ctypes = types.get(id(node.children[0])) or {}
            w = _agg_widths(node, ctypes)
            working = _mul(kid_bounds[0].rows_hi, w)

        # exchange payload per planned edge (docs/distributed.md): hash
        # moves each row at most once; broadcast lands one extra copy on
        # every other peer; gather collects the whole relation. The
        # model is the WIRE form (module docstring): hash edges price
        # key columns as their 8-byte word encoding, and a hash edge
        # fused into the keyed aggregate above it ships per-group int64
        # partials — bound by the larger payload model, covering both
        # runtime paths.
        exchange: Optional[int] = 0
        if isinstance(node, Exchange) and n_peers > 1:
            child_out = kid_bounds[0].out_bytes_hi
            if node.how == "hash":
                cid = id(node.children[0])
                ctypes = types.get(cid) or {}
                cnull = nullable.get(cid, {})
                width = _hash_edge_row_bytes(node, schemas.get(id(node)),
                                             ctypes, cnull)
                par = parents.get(id(node), [])
                if width is not None and len(par) == 1 and \
                        isinstance(par[0], HashAggregate) and par[0].keys:
                    pw = _partial_row_bytes(par[0], ctypes, cnull)
                    width = None if pw is None else max(width, pw)
                exchange = _mul(hi, width)
            elif node.how == "gather":
                exchange = child_out
            elif node.how == "broadcast":
                exchange = _mul(child_out, n_peers - 1)

        resident = out_bytes
        for b in kid_bounds:
            resident = _add(resident, b.out_bytes_hi)
        resident = _add(resident, working)
        out[id(node)] = OpBound(
            label=node.label, kind=node.kind, index=i, rows_lo=lo,
            rows_hi=hi, row_bytes=row_bytes, out_bytes_hi=out_bytes,
            working_bytes_hi=working, exchange_bytes_hi=exchange,
            resident_bytes_hi=resident)
    return out


def table_metadata(inputs) -> Tuple[Dict, Dict]:
    """(input_dtypes, input_nullable) for the Table bindings of an
    execute()-style `inputs` dict — THE extraction every certify caller
    (executor, fuzzer, nightly gate) shares, so the metadata the bounds
    are proven over can never drift between them. Non-Table bindings
    (streaming sources) contribute nothing: their dtypes stay unknown
    and their columns conservatively nullable."""
    from ..columnar.table import Table
    dts = {name: {cn: c.dtype for cn, c in zip(t.names, t.columns)}
           for name, t in inputs.items() if isinstance(t, Table)}
    nul = {name: {cn: c.validity is not None
                  for cn, c in zip(t.names, t.columns)}
           for name, t in inputs.items() if isinstance(t, Table)}
    return dts, nul


def check_observed(cert: ResourceCert, result) -> Optional[str]:
    """THE soundness inequality, single-sourced: every executed
    operator's observed rows inside the certified ``[lo, hi]`` (all
    tiers), observed bytes at or under the certified byte bound on the
    eager tier for non-degraded ops (capped buffers pad to caps;
    degraded ops re-ran on a different tier than the cert sized).
    On a distributed run, every planned Exchange edge's observed WIRE
    bytes (the packed payload the edge shipped, plan/transport.py) must
    also sit at or under the certified per-edge payload bound — the
    `wire <= certified hi` inequality the transport layer is audited
    against (the cert must have been built with the run's n_peers, as
    `PlanExecutor.execute` does for the cert it stamps on the result).
    Returns the first violation as a string, None when sound — fuzz
    property 5, the nightly footprint gate, and the exchange-transport
    gate (benchmarks/exchange_bench.py) all call this."""
    for lbl, m in result.metrics.items():
        b = cert.by_label.get(lbl)
        if b is None:
            return f"{lbl}: executed op has no cert entry"
        if m.rows_out < b.rows_lo or (
                b.rows_hi is not None and m.rows_out > b.rows_hi):
            return (f"{lbl}: observed rows {m.rows_out} outside "
                    f"certified [{b.rows_lo}, {b.rows_hi}]")
        # mesh-resident ops (n_peers stamped) pad buffers to the mesh
        # width and exchange slack, so their bytes_out measures padding,
        # not live data (module docstring) — rows and WIRE bytes remain
        # comparable there
        if result.mode == "eager" and not m.degraded and not m.n_peers \
                and b.out_bytes_hi is not None \
                and m.bytes_out > b.out_bytes_hi:
            return (f"{lbl}: observed bytes {m.bytes_out} > certified "
                    f"{b.out_bytes_hi}")
        if m.kind == "Exchange" and not m.degraded \
                and m.exchange_bytes \
                and b.exchange_bytes_hi is not None \
                and m.exchange_bytes > b.exchange_bytes_hi:
            return (f"{lbl}: observed wire bytes {m.exchange_bytes} > "
                    f"certified exchange bound {b.exchange_bytes_hi}")
    return None


def certify(plan, *, bound=None, bound_rows=None, input_dtypes=None,
            input_nullable=None, n_peers: int = 1) -> ResourceCert:
    """Certify one Plan; see `certify_nodes` for the parameter contract.
    The returned cert's ops are in the plan's toposort order, so
    `by_index` keys line up with the capped tier's per-node cap-key
    space and the stats store's per-op records."""
    by_id = certify_nodes(plan.nodes, bound=bound, bound_rows=bound_rows,
                          input_dtypes=input_dtypes,
                          input_nullable=input_nullable, n_peers=n_peers)
    return ResourceCert([by_id[id(n)] for n in plan.nodes],
                        n_peers=n_peers)
