"""Property-based plan fuzzer: seeded random DAGs over all 11 node kinds.

The verifier (analysis/verifier.py) machine-checks invariants; this module
machine-GENERATES the plans to check them on. A `FuzzCase` is a seeded
random operator DAG (Scan, Filter, Project, FusedSelect, HashJoin,
HashAggregate, Sort, TopK, Limit, Union, Exchange — the full node set,
including the optimizer-produced kinds, authored directly) plus the bound
tables it runs over. Every case must satisfy six properties:

1. the authored plan VERIFIES (generator correctness — schema, typing and
   pruning layers clean);
2. the optimizer's rewrite verifies (`verify_rewrite`: schema preserved,
   swap legality, rule side conditions) and never falls back;
3. (small plans — which all of these are) the optimized and unoptimized
   EAGER executions agree bit-for-bit, compacted row for row; a case
   whose unoptimized run raises must raise the same error class
   optimized (semantics preserved means errors too);
4. the plan executed TWICE under a fresh per-case stats store
   (plan/stats.py) agrees bit-for-bit between the cold and warm runs,
   error class included — adaptivity (observed-cardinality build sides,
   cap seeding, kernel tie-breaks) may change *how*, never *what*;
5. the resource certifier (analysis/footprint.py) is SOUND and
   MONOTONE: for every operator of every successful execution —
   unoptimized, optimized, cold AND warm — the observed row count lies
   inside the certified `[lo, hi]` interval and the observed eager
   bytes stay at or under the certified byte bound; and the optimizer
   may only keep or tighten the root's certified bounds (a rewrite
   that loosens a proof is a bug even when results agree);
6. the plan executed with the co-placement rule ON
   (SPARK_RAPIDS_TPU_PLACEMENT, plan/optimizer.py placement rule)
   agrees bit-for-bit with the placement-OFF run, error class included
   — moving a subtree onto a host worker thread overlapped with device
   execution may change *where* it runs, never *what* it returns
   (docs/optimizer.md#placement).

Determinism is a contract: `gen_case(seed)` builds the same DAG (same
fingerprint) and the same table bytes every time — `random.Random(seed)`
only, no global RNG, no time — so the premerge corpus (fixed seeds, see
ci/premerge.sh) is reproducible and a nightly failure replays from its
seed alone. CI knobs: `python -m spark_rapids_tpu.analysis.fuzz --start S
--count N [--max-ops K] [--no-exec] [--cpu]`; the nightly deep sweep
(benchmarks/plan_fuzz.py) runs >=200 seeds and emits a JSONL summary.
"""
from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Tuple

from ..plan.expr import Expr, col, lit, scalar_max, scalar_min, scalar_sum
from ..plan.nodes import (Exchange, Filter, FusedSelect, HashAggregate,
                          HashJoin, Limit, PlanNode, Scan, Sort, TopK,
                          Union)

ALL_KINDS = ("Scan", "Filter", "Project", "FusedSelect", "HashJoin",
             "HashAggregate", "Sort", "TopK", "Limit", "Union", "Exchange")

_GLOBAL_AGGS = ("sum", "count", "size")      # empty-relation-safe
_KEYED_AGGS = ("sum", "count", "min", "max", "mean", "size")


@dataclasses.dataclass
class FuzzCase:
    seed: int
    plan: object                 # plan.builder.Plan
    tables: Dict[str, object]    # source -> columnar.Table
    kinds: Tuple[str, ...]       # node kinds present, for coverage stats


@dataclasses.dataclass
class FuzzResult:
    seed: int
    verified: bool = True
    optimized_verified: bool = True
    executed: bool = False
    parity: Optional[bool] = None
    # property 4 (docs/adaptive.md): cold-vs-warm bit-exact parity under
    # the stats store — adaptivity may change HOW, never WHAT (errors
    # included)
    adaptive_parity: Optional[bool] = None
    # property 5 (docs/analysis.md): certifier soundness (observed rows/
    # bytes inside the certified bounds, every op, every run) and
    # monotonicity (optimized root bound <= authored root bound)
    cert_sound: Optional[bool] = None
    # property 6 (docs/optimizer.md#placement): placement-on vs
    # placement-off bit-exact parity, error class included — co-placement
    # may change WHERE a subtree runs, never what it returns
    placement_parity: Optional[bool] = None
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return (self.verified and self.optimized_verified
                and self.error is None and self.parity is not False
                and self.adaptive_parity is not False
                and self.cert_sound is not False
                and self.placement_parity is not False)


# ---- deterministic relation/expression generation ---------------------------

class _Rel:
    """Generator-side relation: the node plus its (name -> tag) schema,
    where tag is 'i' (int64), 'f' (float64) or 'b' (bool), and a crude
    row estimate to keep join products bounded."""

    __slots__ = ("node", "schema", "est")

    def __init__(self, node: PlanNode, schema: List[Tuple[str, str]],
                 est: float):
        self.node = node
        self.schema = list(schema)
        self.est = est

    def cols(self, tag=None) -> List[str]:
        return [n for n, t in self.schema if tag is None or t == tag]


def _gen_table(rng: random.Random, schema: List[Tuple[str, str]],
               n_rows: int):
    """Deterministic Table over the tagged schema. Int values are small
    (0..7) so joins and groupbys hit duplicates; floats are quarter-
    integers (exactly representable — parity comparisons stay exact)."""
    import jax.numpy as jnp
    import numpy as np
    from .. import dtypes
    from ..columnar import Column, Table
    cols, names = [], []
    for name, tag in schema:
        if tag == "i":
            data = np.asarray([rng.randrange(8) for _ in range(n_rows)],
                              dtype=np.int64)
            dt = dtypes.INT64
        elif tag == "f":
            data = np.asarray([rng.randrange(32) / 4.0
                               for _ in range(n_rows)], dtype=np.float64)
            dt = dtypes.FLOAT64
        else:
            data = np.asarray([rng.randrange(2) == 1
                               for _ in range(n_rows)], dtype=np.bool_)
            dt = dtypes.BOOL
        cols.append(Column(dtype=dt, length=n_rows, data=jnp.asarray(data)))
        names.append(name)
    return Table(cols, names=names)


def _gen_predicate(rng: random.Random, rel: _Rel, depth: int = 0) -> Expr:
    """Random boolean expression over the relation: comparisons of int/
    float columns against in-range literals, conjunctions/disjunctions/
    negations, the odd scalar-aggregate subquery."""
    numeric = rel.cols("i") + rel.cols("f")
    if not numeric:
        return lit(True)
    if depth < 2 and rng.random() < 0.35:
        op = rng.choice(("&", "|", "~"))
        a = _gen_predicate(rng, rel, depth + 1)
        if op == "~":
            return ~a
        b = _gen_predicate(rng, rel, depth + 1)
        return (a & b) if op == "&" else (a | b)
    name = rng.choice(numeric)
    c = col(name)
    cmp = rng.choice(("<", "<=", ">", ">=", "==", "!="))
    if rng.random() < 0.12:
        sagg = rng.choice((scalar_max, scalar_min, scalar_sum))
        rhs: Expr = sagg(col(rng.choice(numeric)))
    else:
        is_f = name in rel.cols("f")
        rhs = lit(rng.randrange(32) / 4.0 if is_f else rng.randrange(8))
    return {"<": c < rhs, "<=": c <= rhs, ">": c > rhs, ">=": c >= rhs,
            "==": c == rhs, "!=": c != rhs}[cmp]


def _gen_exprs(rng: random.Random, rel: _Rel, fresh) -> Tuple[
        List[Tuple[str, Expr]], List[Tuple[str, str]]]:
    """Projection list: a random column subset (kept under their own
    names) plus up to one derived arithmetic column."""
    keep = [nt for nt in rel.schema if rng.random() < 0.75]
    if not keep:
        keep = [rng.choice(rel.schema)]
    exprs = [(n, col(n)) for n, _ in keep]
    schema = list(keep)
    numeric = rel.cols("i")
    if numeric and rng.random() < 0.5:
        name = fresh("d")
        a, b = rng.choice(numeric), rng.choice(numeric)
        op = rng.choice(("+", "-", "*"))
        e = {"+": col(a) + col(b), "-": col(a) - col(b),
             "*": col(a) * lit(rng.randrange(1, 4))}[op]
        exprs.append((name, e))
        schema.append((name, "i"))
    return exprs, schema


def gen_case(seed: int, *, max_ops: int = 8,
             allow_floats: bool = True) -> FuzzCase:
    """Build one deterministic random case. The generator composes only
    schema-correct operators (the property under test is the OPTIMIZER
    and the engine, not the builder's rejection paths), but draws from
    the full node vocabulary, including DAG-shared subtrees (self-union,
    shared join inputs)."""
    from ..plan.builder import Plan
    rng = random.Random(seed)
    counter = [0]

    def fresh(prefix: str) -> str:
        counter[0] += 1
        return f"{prefix}{counter[0]}"

    n_sources = rng.randrange(1, 4)
    tables: Dict[str, object] = {}
    rels: List[_Rel] = []
    for i in range(n_sources):
        src = f"s{i}"
        n_cols = rng.randrange(2, 5)
        schema = []
        for j in range(n_cols):
            r = rng.random()
            tag = ("f" if allow_floats and r < 0.18 else
                   "b" if r < 0.28 else "i")
            schema.append((f"{src}_c{j}", tag))
        n_rows = rng.randrange(6, 40)
        tables[src] = _gen_table(rng, schema, n_rows)
        # est_rows hint on some scans feeds the build_side rule
        est = n_rows if rng.random() < 0.5 else None
        rels.append(_Rel(Scan(src, tuple(n for n, _ in schema),
                              est_rows=est), schema, float(n_rows)))

    for _ in range(rng.randrange(3, max_ops + 1)):
        op = rng.choices(
            ("filter", "project", "fused", "aggregate", "sort", "topk",
             "limit", "union", "join", "exchange"),
            weights=(18, 14, 8, 12, 8, 5, 7, 7, 14, 7))[0]
        idx = rng.randrange(len(rels))
        rel = rels[idx]
        if op == "filter":
            pred = _gen_predicate(rng, rel)
            out = _Rel(Filter(rel.node, pred), rel.schema,
                       max(rel.est * 0.6, 1.0))
        elif op == "project":
            from ..plan.nodes import Project
            exprs, schema = _gen_exprs(rng, rel, fresh)
            out = _Rel(Project(rel.node, tuple(exprs)), schema, rel.est)
        elif op == "fused":
            exprs, schema = _gen_exprs(rng, rel, fresh)
            out = _Rel(FusedSelect(rel.node, _gen_predicate(rng, rel),
                                   tuple(exprs)), schema,
                       max(rel.est * 0.6, 1.0))
        elif op == "aggregate":
            numeric = rel.cols("i") + rel.cols("f")
            if not numeric:
                continue
            keyed = rel.cols("i") and rng.random() < 0.8
            keys = (tuple(rng.sample(rel.cols("i"),
                                     rng.randrange(1, min(3, len(
                                         rel.cols("i"))) + 1)))
                    if keyed else ())
            ops = _KEYED_AGGS if keys else _GLOBAL_AGGS
            aggs, schema = [], [(k, dict(rel.schema)[k]) for k in keys]
            for _ in range(rng.randrange(1, 3)):
                c = rng.choice(numeric)
                o = rng.choice(ops)
                name = fresh("a")
                aggs.append((c, o, name))
                tag = ("i" if o in ("count", "size") else
                       "f" if o == "mean" or dict(rel.schema)[c] == "f"
                       else "i")
                schema.append((name, tag))
            out = _Rel(HashAggregate(rel.node, keys, tuple(aggs)),
                       schema, max(rel.est / 4, 1.0) if keys else 1.0)
        elif op in ("sort", "topk"):
            sortable = rel.cols("i") + rel.cols("f")
            if not sortable:
                continue
            keys = tuple(rng.sample(sortable,
                                    rng.randrange(1, min(2, len(sortable))
                                                  + 1)))
            asc = tuple(rng.random() < 0.7 for _ in keys)
            if op == "sort":
                out = _Rel(Sort(rel.node, keys, asc), rel.schema, rel.est)
            else:
                out = _Rel(TopK(rel.node, keys, asc, rng.randrange(0, 12)),
                           rel.schema, 12.0)
        elif op == "limit":
            out = _Rel(Limit(rel.node, rng.randrange(0, 24)), rel.schema,
                       24.0)
        elif op == "union":
            # self-union through two different filters: same schema by
            # construction, and the child is DAG-SHARED (executes once)
            p1 = _gen_predicate(rng, rel)
            p2 = _gen_predicate(rng, rel)
            out = _Rel(Union((Filter(rel.node, p1),
                              Filter(rel.node, p2))), rel.schema,
                       rel.est * 1.2)
        elif op == "join":
            partners = [r for r in rels
                        if r is not rel and r.cols("i")
                        and not (set(r.cols()) & set(rel.cols()))]
            if not partners or not rel.cols("i"):
                continue
            other = rng.choice(partners)
            if rel.est * other.est > 4000:
                continue
            lk = (rng.choice(rel.cols("i")),)
            rk = (rng.choice(other.cols("i")),)
            how = rng.choices(("inner", "left_semi", "left_anti"),
                              weights=(3, 1, 1))[0]
            schema = (rel.schema + other.schema if how == "inner"
                      else list(rel.schema))
            est = (rel.est * other.est / 4 if how == "inner"
                   else rel.est * 0.6)
            out = _Rel(HashJoin(rel.node, other.node, lk, rk, how=how),
                       schema, max(est, 1.0))
        else:   # exchange: hash on an int column, or the identity marker
            if rel.cols("i") and rng.random() < 0.7:
                out = _Rel(Exchange(rel.node,
                                    (rng.choice(rel.cols("i")),)),
                           rel.schema, rel.est)
            else:
                out = _Rel(Exchange(rel.node, ()), rel.schema, rel.est)
        rels[idx] = out

    root = rng.choice(rels)
    plan = Plan(root.node)
    return FuzzCase(seed=seed, plan=plan, tables=dict(tables),
                    kinds=tuple(sorted({n.kind for n in plan.nodes})))


# ---- properties -------------------------------------------------------------

def _cert_soundness(case: FuzzCase, res, bound, input_dtypes,
                    input_nullable) -> Optional[str]:
    """Property 5's per-run half: certify the EXECUTED plan and hold
    every operator's observed metrics inside the certified bounds via
    the single-sourced inequality (`footprint.check_observed` — the
    nightly gate runs the SAME check). Returns the first violation as a
    string, None when sound."""
    from .footprint import certify, check_observed
    cert = certify(res.plan, bound=bound,
                   bound_rows={n: t.num_rows
                               for n, t in case.tables.items()},
                   input_dtypes=input_dtypes,
                   input_nullable=input_nullable)
    return check_observed(cert, res)


def _cert_monotonicity(case: FuzzCase, opt, bound, input_dtypes,
                       input_nullable) -> Optional[str]:
    """Property 5's rewrite half: the optimized plan's certified ROOT
    bounds must not exceed the authored plan's — every rule preserves or
    shrinks the relation it proves things about, so a looser optimized
    proof means a certifier or rule bug."""
    from .footprint import certify
    kw = dict(bound=bound,
              bound_rows={n: t.num_rows for n, t in case.tables.items()},
              input_dtypes=input_dtypes, input_nullable=input_nullable)
    a = certify(case.plan, **kw).root
    o = certify(opt, **kw).root
    if a.rows_hi is not None and (
            o.rows_hi is None or o.rows_hi > a.rows_hi):
        return (f"optimized root rows hi {o.rows_hi} exceeds authored "
                f"{a.rows_hi}")
    # None-after-finite is a LOOSENED proof, same as the rows branch: a
    # rewrite that makes the root's bytes uncertifiable weakens the
    # admission and broadcast-legality gates even when results agree
    if a.out_bytes_hi is not None and (
            o.out_bytes_hi is None or o.out_bytes_hi > a.out_bytes_hi):
        return (f"optimized root bytes hi {o.out_bytes_hi} exceeds "
                f"authored {a.out_bytes_hi}")
    return None


def run_case(case: FuzzCase, *, execute: bool = True) -> FuzzResult:
    """Check the five fuzz properties on one case (see module doc).
    Never raises for a property FAILURE (the result carries it); raises
    only on generator bugs like unbuildable plans."""
    from ..plan.executor import PlanExecutor, _input_has_floats
    from ..plan.optimizer import optimize
    from .verifier import verify, verify_rewrite
    res = FuzzResult(seed=case.seed)
    bound = {n: tuple(t.names) for n, t in case.tables.items()}
    input_dtypes = {
        n: {cn: c.dtype for cn, c in zip(t.names, t.columns)}
        for n, t in case.tables.items()}
    floats = any(_input_has_floats(t) for t in case.tables.values())

    rep = verify(case.plan, bound=bound, input_dtypes=input_dtypes,
                 float_inputs=floats)
    if not rep.ok:
        res.verified = False
        res.error = f"authored plan failed verify: {rep.violations[0]}"
        return res

    bound_rows = {n: t.num_rows for n, t in case.tables.items()}
    opt, report = optimize(case.plan, bound, bound_rows,
                           float_inputs=floats, verify_rules=True)
    if report.fell_back:
        res.optimized_verified = False
        res.error = f"optimizer fell back: {report.fallback}"
        return res
    rep = verify_rewrite(case.plan, opt, bound=bound,
                         input_dtypes=input_dtypes, float_inputs=floats,
                         report=report)
    if not rep.ok:
        res.optimized_verified = False
        res.error = f"optimized plan failed verify: {rep.violations[0]}"
        return res

    # property 5 (rewrite half): the optimizer may only keep or tighten
    # the root's certified bounds
    from .footprint import table_metadata
    _, input_nullable = table_metadata(case.tables)
    mono = _cert_monotonicity(case, opt, bound, input_dtypes,
                              input_nullable)
    if mono is not None:
        res.cert_sound = False
        res.error = f"cert monotonicity broke: {mono}"
        return res
    res.cert_sound = True

    if not execute:
        return res
    res.executed = True
    from ..plan import stats as stats_mod
    outs = {}
    cert_runs = []               # successful PlanResults for property 5
    # properties 1-3 measure the STATIC engine: scope adaptivity off, or
    # a premerge/nightly corpus run (no pytest conftest, stats default
    # ON) would record seed N's plans into the process-default store and
    # run later parity checks warm — a failing seed replayed standalone
    # would then see different optimizer decisions and not reproduce
    with stats_mod.scoped_store(None):
        for optimized in (False, True):
            ex = PlanExecutor(mode="eager", optimize=optimized)
            try:
                r = ex.execute(case.plan, dict(case.tables))
                outs[optimized] = ("ok", r.compact().to_pydict())
                cert_runs.append(r)
            except Exception as e:     # parity includes error parity
                outs[optimized] = ("err", type(e).__name__)
    res.parity = outs[False] == outs[True]
    if not res.parity:
        res.error = (f"eager parity broke: unoptimized={outs[False]!r} "
                     f"optimized={outs[True]!r}")
        return res

    # property 4: the same plan twice under a FRESH stats store — the
    # first run records, the second consumes (cap seeds, observed
    # cardinalities, kernel tie-breaks). Bit-exact parity, error class
    # included: adaptivity may change how a plan executes, never what it
    # returns (docs/adaptive.md). A fresh scoped store per case keeps
    # the corpus deterministic regardless of what ran before.
    runs = []
    # path="": never inherit SPARK_RAPIDS_TPU_STATS_PATH — a persisted
    # file would pre-warm the "cold" run and collect fuzz-plan garbage
    with stats_mod.scoped_store(stats_mod.StatsStore(capacity=32,
                                                     path="")):
        for _ in range(2):
            ex = PlanExecutor(mode="eager", optimize=True)
            try:
                r = ex.execute(case.plan, dict(case.tables))
                runs.append(("ok", r.compact().to_pydict()))
                cert_runs.append(r)
            except Exception as e:
                runs.append(("err", type(e).__name__))
    res.adaptive_parity = runs[0] == runs[1]
    if not res.adaptive_parity:
        res.error = (f"adaptive parity broke: cold={runs[0]!r} "
                     f"warm={runs[1]!r}")
        return res

    # property 6: the same plan with the co-placement rule off and on —
    # the ON run takes the rule's certified cold path (fuzz tables are
    # tiny, so eligible build sides place readily) and must agree
    # bit-for-bit, error class included. Fresh static scope per run: the
    # knob is read at use time (config.py's monkeypatch contract), and a
    # stats store would make the second run warm, entangling this with
    # property 4. Join-free plans skip the A/B — the rule fires only on
    # HashJoin build sides, so on==off is vacuous there and the paired
    # executions would double corpus cost for zero discrimination.
    import os
    if "HashJoin" not in case.kinds:
        res.placement_parity = True
        return _finish_cert_soundness(case, res, cert_runs, bound,
                                      input_dtypes, input_nullable)
    pouts = {}
    prev = os.environ.get("SPARK_RAPIDS_TPU_PLACEMENT")
    try:
        for pon in (False, True):
            os.environ["SPARK_RAPIDS_TPU_PLACEMENT"] = \
                "on" if pon else "off"
            with stats_mod.scoped_store(None):
                ex = PlanExecutor(mode="eager", optimize=True)
                try:
                    r = ex.execute(case.plan, dict(case.tables))
                    pouts[pon] = ("ok", r.compact().to_pydict())
                    cert_runs.append(r)
                except Exception as e:
                    pouts[pon] = ("err", type(e).__name__)
    finally:
        if prev is None:
            os.environ.pop("SPARK_RAPIDS_TPU_PLACEMENT", None)
        else:
            os.environ["SPARK_RAPIDS_TPU_PLACEMENT"] = prev
    res.placement_parity = pouts[False] == pouts[True]
    if not res.placement_parity:
        res.error = (f"placement parity broke: off={pouts[False]!r} "
                     f"on={pouts[True]!r}")
        return res

    return _finish_cert_soundness(case, res, cert_runs, bound,
                                  input_dtypes, input_nullable)


def _finish_cert_soundness(case, res, cert_runs, bound, input_dtypes,
                           input_nullable):
    """Property 5 (soundness half): every successful run — unoptimized,
    optimized, cold and warm, placement off and on — stays inside the
    certified bounds of ITS executed plan (cold and warm may have
    rewritten differently)."""
    for r in cert_runs:
        bad = _cert_soundness(case, r, bound, input_dtypes,
                              input_nullable)
        if bad is not None:
            res.cert_sound = False
            res.error = f"cert soundness broke: {bad}"
            return res
    return res


def run_corpus(seeds, *, execute: bool = True, max_ops: int = 8,
               verbose: bool = False) -> Dict:
    """Run gen+check over a seed list; summary dict with per-seed
    failures and the node-kind coverage of the corpus."""
    results: List[FuzzResult] = []
    kinds = set()
    for seed in seeds:
        case = gen_case(seed, max_ops=max_ops)
        kinds.update(case.kinds)
        r = run_case(case, execute=execute)
        results.append(r)
        if verbose:
            status = "ok" if r.ok else f"FAIL ({r.error})"
            print(f"  seed {seed}: {len(case.plan.nodes)} nodes "
                  f"[{', '.join(case.kinds)}] -> {status}")
    failures = [r for r in results if not r.ok]
    return {
        "cases": len(results),
        "executed": sum(1 for r in results if r.executed),
        "kinds_covered": tuple(sorted(kinds)),
        "failures": [{"seed": r.seed, "error": r.error} for r in failures],
    }


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="plan fuzzer: verify + optimize + eager-parity over "
                    "seeded random DAGs (docs/analysis.md)")
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument("--count", type=int, default=24)
    ap.add_argument("--max-ops", type=int, default=8)
    ap.add_argument("--no-exec", action="store_true",
                    help="verify/optimize only (skip the parity runs)")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend before jax initializes")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    seeds = range(args.start, args.start + args.count)
    summary = run_corpus(seeds, execute=not args.no_exec,
                         max_ops=args.max_ops, verbose=args.verbose)
    print(f"plan fuzz: {summary['cases']} case(s), "
          f"{summary['executed']} executed, kinds covered: "
          f"{', '.join(summary['kinds_covered'])}")
    if summary["failures"]:
        for f in summary["failures"]:
            print(f"  FAIL seed {f['seed']}: {f['error']}")
        return 1
    print("plan fuzz OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
