"""Static plan verifier: symbolic invariant checks over a Plan DAG.

Every check here answers one question WITHOUT executing the plan: could
this DAG — authored or optimizer-rewritten — produce something other than
the Spark-exact answer? Three layers, each independently skippable when
its inputs are unknown (the verifier is sound-but-incomplete: it flags
only DEFINITE violations, so it can gate every test execution without
false alarms):

1. **Schema propagation** — every node's output schema must be derivable
   from its children under the `output_names` contract in `plan/nodes.py`.
   This layer IS the builder's validation (`Plan.__init__` and
   `Plan.resolve_schemas` route through it), so build-time and
   execute-time diagnostics share one error vocabulary: a `Violation`
   with an invariant code and the offending operator's label.

2. **Dtype typing** — with bound-input dtypes known, expressions type
   bottom-up (`plan/expr.py` semantics: comparisons yield BOOL, `&`/`|`
   on floats is a jnp error, STRING/LIST/DECIMAL128 columns are not
   expression-addressable because `Expr.evaluate` reads the raw data
   buffer), predicates must type to BOOL, and aggregates must reduce
   scalar columns.

3. **Partitioning soundness** (`planned=True`, i.e. the plan went through
   the optimizer's `exchange_planning`) — re-derive every node's
   hash-partitioning claim bottom-up with the SAME `transfer_part`
   transfer function `plan/distributed.py` uses at runtime, then prove:
   every shuffle-join's sides co-located (`join_alignment`), every keyed
   aggregate's input co-located or hash-exchanged, no sharded relation
   flowing into an operator with no distributed form, exactly one gather
   at the sink (the PR 5 stale-partitioning-claim bug becomes a verifier
   error here, not a review comment).

`verify_rewrite` adds the pair checks mirroring optimizer-rule side
conditions that a single plan cannot witness: root-schema preservation,
and join build-side swaps only in order-unobservable regions and never
under floating-point inputs (fp reductions are not reorder-exact — the
other PR 5 review finding).

See docs/analysis.md for the invariant catalogue and how the executor's
`SPARK_RAPIDS_TPU_VERIFY_PLANS` gate and the optimizer's fall-back
diagnostics consume this module.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from .. import dtypes
from ..plan.expr import (BinOp, ColumnRef, Expr, Literal, ScalarAgg,
                         UnaryOp)
from ..plan.nodes import (Exchange, Filter, FusedSelect, HashAggregate,
                          HashJoin, Limit, PlanNode, PlanValidationError,
                          Project, Scan, Sort, TopK, Union)

__all__ = ["Violation", "VerifyReport", "PlanVerificationError",
           "verify", "verify_rewrite", "check_build", "resolve_schemas",
           "column_types"]


# ---- error vocabulary -------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Violation:
    """One broken invariant: a machine-readable code, the offending
    operator's label, and the human diagnostic."""
    invariant: str          # e.g. "partitioning.join-not-colocated"
    node: str               # node label, e.g. "HashJoin#12"
    message: str

    def __str__(self):
        return f"[{self.invariant}] {self.message}"


class PlanVerificationError(PlanValidationError):
    """A plan failed static verification. Subclasses the builder's
    `PlanValidationError` so every existing `except`/`raises` contract
    holds; carries the structured `violations` so callers (the optimizer's
    fall-back diagnostic, the bench JSONL) can name the invariant and node
    instead of parsing message text."""

    def __init__(self, violations: List[Violation], context: str = ""):
        self.violations = list(violations)
        head = f"plan verification failed ({context}):\n" if context else ""
        super().__init__(head + "\n".join(str(v) for v in self.violations))


class VerifyReport:
    """Outcome of one verification: the violations found (empty = the plan
    is provably consistent with every checked invariant)."""

    def __init__(self, violations: Optional[List[Violation]] = None):
        self.violations: List[Violation] = list(violations or [])

    @property
    def ok(self) -> bool:
        return not self.violations

    def add(self, invariant: str, node: PlanNode, message: str):
        self.violations.append(Violation(invariant, node.label, message))

    def raise_if_failed(self, context: str = ""):
        if self.violations:
            raise PlanVerificationError(self.violations, context)

    def __repr__(self):
        return f"VerifyReport({len(self.violations)} violation(s))"


# ---- layer 1: schema propagation (the builder's validation backend) ---------

def _propagate_schemas(nodes, bound, strict
                       ) -> Tuple[Dict[int, Tuple[str, ...]],
                                  List[Violation]]:
    """node-id -> output names over a toposorted node list, collecting
    violations instead of raising. Mirrors the historical
    `Plan.resolve_schemas` exactly (same messages — tests match on them);
    a node whose schema cannot be derived poisons its subtree silently so
    one authoring mistake yields one violation, not a cascade."""
    bound = bound or {}
    out: Dict[int, Tuple[str, ...]] = {}
    vs: List[Violation] = []
    broken = set()
    for node in nodes:
        if isinstance(node, Scan):
            schema = bound.get(node.source, node.schema)
            if schema is None and not strict:
                broken.add(id(node))
                continue
            if schema is None:
                vs.append(Violation(
                    "schema.unbound-scan", node.label,
                    f"{node.label}: input {node.source!r} is not bound "
                    f"and no schema was declared"))
                broken.add(id(node))
                continue
            schema = tuple(schema)
            if node.schema is not None and tuple(node.schema) != schema:
                vs.append(Violation(
                    "schema.binding-mismatch", node.label,
                    f"{node.label}: bound table schema {list(schema)} "
                    f"does not match declared {list(node.schema)}"))
                broken.add(id(node))
                continue
            try:
                # the declared/bound cross-check above ran on the full
                # schema; the pruned projection narrows the OUTPUT
                out[id(node)] = node.apply_projection(schema)
            except PlanValidationError as e:
                vs.append(Violation("schema", node.label, str(e)))
                broken.add(id(node))
            continue
        child_schemas = []
        ok = True
        for c in node.children:
            if id(c) not in out:
                ok = False
                break
            child_schemas.append(out[id(c)])
        if not ok:
            if strict and not any(id(c) in broken for c in node.children):
                vs.append(Violation(
                    "schema.unresolved", node.label,
                    f"{node.label}: child schema unresolved"))
            broken.add(id(node))
            continue
        try:
            out[id(node)] = tuple(node.output_names(child_schemas))
        except PlanValidationError as e:
            vs.append(Violation("schema", node.label, str(e)))
            broken.add(id(node))
    return out, vs


def resolve_schemas(nodes, bound=None, strict: bool = True
                    ) -> Dict[int, Tuple[str, ...]]:
    """Raising form of the schema layer — `Plan.resolve_schemas` delegates
    here, so a schema error surfaces as a `PlanVerificationError` (still a
    `PlanValidationError`) whether it is caught at build time or at
    execute()'s bind-time re-resolution."""
    out, vs = _propagate_schemas(nodes, bound, strict)
    if vs:
        raise PlanVerificationError(vs)
    return out


def check_build(plan) -> Dict[int, Tuple[str, ...]]:
    """Build-time validation for `Plan.__init__`: duplicate-source check +
    non-strict schema propagation, one error vocabulary with everything
    else in this module. Returns the resolvable schemas."""
    sources = [s.source for s in plan.scans]
    dup = {s for s in sources if sources.count(s) > 1}
    if dup:
        raise PlanVerificationError([Violation(
            "schema.duplicate-source", plan.root.label,
            f"multiple Scan nodes bind the same input(s) {sorted(dup)}; "
            "reuse one Scan node (the DAG executes it once)")])
    schemas, vs = _propagate_schemas(plan.nodes, None, strict=False)
    if vs:
        raise PlanVerificationError(vs)
    return schemas


# ---- layer 2: expression / operator dtype typing ----------------------------

_BOOL = dtypes.BOOL
_INT64 = dtypes.INT64
_FLOAT64 = dtypes.FLOAT64


def _expr_addressable(dt: Optional[dtypes.DType]) -> bool:
    """Whether `Expr.evaluate` can read the column: it reads the raw
    `data` buffer, so STRING (chars buffer), nested and DECIMAL128
    ((n, 4) limbs) columns are out — their buffer length/shape is not the
    row count."""
    if dt is None:
        return True
    return not (dt.is_string or dt.is_nested
                or dt.kind == dtypes.Kind.DECIMAL128)


def _lit_dtype(v) -> Optional[dtypes.DType]:
    if isinstance(v, bool):
        return _BOOL
    if isinstance(v, int):
        return _INT64
    if isinstance(v, float):
        return _FLOAT64
    return None


_CMP_OPS = ("==", "!=", "<", "<=", ">", ">=")


def type_expr(e: Expr, coltypes: Dict[str, Optional[dtypes.DType]],
              node: PlanNode, report: VerifyReport
              ) -> Optional[dtypes.DType]:
    """Bottom-up dtype of `e` under `plan/expr.py` evaluation semantics
    (pure jnp under x64). Returns None when unknowable; appends a
    violation only for expressions that DEFINITELY fail or corrupt at
    runtime — unknown dtypes never flag."""
    if isinstance(e, ColumnRef):
        dt = coltypes.get(e.name)
        if not _expr_addressable(dt):
            report.add("typing.column-not-expr-addressable", node,
                       f"{node.label}: column {e.name!r} is {dt!r} — "
                       "expressions read the raw data buffer, which for "
                       "string/nested/decimal128 columns is not "
                       "row-shaped")
            return None
        return dt
    if isinstance(e, Literal):
        return _lit_dtype(e.value)
    if isinstance(e, BinOp):
        lt = type_expr(e.left, coltypes, node, report)
        rt = type_expr(e.right, coltypes, node, report)
        if e.op in _CMP_OPS:
            return _BOOL
        if e.op in ("&", "|"):
            for side in (lt, rt):
                if side is not None and side.is_floating:
                    report.add("typing.bitwise-on-float", node,
                               f"{node.label}: {e.op!r} over a "
                               f"floating-point operand in {e!r} — jnp "
                               "bitwise ops reject floats at runtime")
                    return None
            if lt is not None and rt is not None:
                if lt.kind == dtypes.Kind.BOOL and \
                        rt.kind == dtypes.Kind.BOOL:
                    return _BOOL
                if lt.is_integer and rt.is_integer:
                    return _INT64
            return None
        # + - * arithmetic: x64 promotion — any float makes float
        if lt is not None and rt is not None:
            if lt.is_floating or rt.is_floating:
                return _FLOAT64
            if lt.is_integer and rt.is_integer:
                return _INT64
        return None
    if isinstance(e, UnaryOp):
        ct = type_expr(e.child, coltypes, node, report)
        if e.op == "~":
            if ct is not None and ct.is_floating:
                report.add("typing.invert-on-float", node,
                           f"{node.label}: ~ over a floating-point "
                           f"operand in {e!r} — jnp rejects it at "
                           "runtime")
                return None
            return ct
        if ct is not None and ct.kind == dtypes.Kind.BOOL:
            return None          # -bool: promotion is backend-subtle
        return ct
    if isinstance(e, ScalarAgg):
        ct = type_expr(e.child, coltypes, node, report)
        if ct is None:
            return None
        if e.op == "sum":
            return ct if ct.is_floating else _INT64
        return ct               # min/max preserve
    return None


def _agg_out_dtype(op: str, child_dt: Optional[dtypes.DType]
                   ) -> Optional[dtypes.DType]:
    if op in ("count", "size"):
        return _INT64
    if op == "mean":
        return _FLOAT64
    if child_dt is None:
        return None
    if op == "sum":
        return child_dt if child_dt.is_floating else _INT64
    return child_dt             # min/max


def _check_predicate(pred: Expr, coltypes, node, report: VerifyReport):
    t = type_expr(pred, coltypes, node, report)
    if t is not None and t.kind != dtypes.Kind.BOOL:
        report.add("typing.predicate-not-bool", node,
                   f"{node.label}: predicate {pred!r} types to {t!r}, "
                   "not BOOL — a non-boolean mask silently corrupts the "
                   "capped tier's alive set")


def _check_types(nodes, schemas, input_dtypes, report: VerifyReport
                 ) -> Dict[int, Dict[str, Optional[dtypes.DType]]]:
    """Walk node dtypes bottom-up; unknown columns stay unknown and never
    flag. `input_dtypes` maps scan source -> {column: DType}. Returns the
    per-node column-dtype map — the resource certifier
    (analysis/footprint.py) reuses this exact propagation for its byte
    widths, so typing and sizing can never disagree about a column."""
    types: Dict[int, Dict[str, Optional[dtypes.DType]]] = {}
    for node in nodes:
        if id(node) not in schemas:
            continue            # schema layer already poisoned this subtree
        if any(id(c) not in types for c in node.children):
            types[id(node)] = {}
            continue
        kids = [types[id(c)] for c in node.children]
        if isinstance(node, Scan):
            src = dict(input_dtypes.get(node.source) or {})
            types[id(node)] = {n: src.get(n) for n in schemas[id(node)]}
            continue
        if isinstance(node, Filter):
            _check_predicate(node.predicate, kids[0], node, report)
            types[id(node)] = kids[0]
            continue
        if isinstance(node, (Project, FusedSelect)):
            if isinstance(node, FusedSelect):
                _check_predicate(node.predicate, kids[0], node, report)
            # bare ColumnRefs ZERO-COPY through the executor's _project
            # (never Expr.evaluate), so string/nested columns pass
            # untouched — and the column_pruning rule inserts exactly
            # such bare-ref selects; only computed expressions type-check
            types[id(node)] = {
                n: (kids[0].get(e.name) if isinstance(e, ColumnRef)
                    else type_expr(e, kids[0], node, report))
                for n, e in node.exprs}
            continue
        if isinstance(node, HashJoin):
            out = dict(kids[0])
            if node.how == "inner":
                out.update(kids[1])
            types[id(node)] = out
            continue
        if isinstance(node, HashAggregate):
            out = {k: kids[0].get(k) for k in node.keys}
            for c, o, n in node.aggs:
                cdt = kids[0].get(c) if o != "size" else None
                # flag only ops that READ the data buffer as a scalar
                # array: sum/mean always; min/max only in the keyless
                # global path (the grouped kernel handles string
                # extremes via its value-ordered-sort path, and count
                # consumes validity only)
                reads_data = o in ("sum", "mean") or (
                    not node.keys and o in ("min", "max"))
                if reads_data and not _expr_addressable(cdt):
                    report.add(
                        "typing.agg-over-non-scalar", node,
                        f"{node.label}: {o}({c}) reduces a {cdt!r} "
                        "column's data buffer, which is not row-shaped "
                        "for string/nested/decimal128 layouts")
                out[n] = _agg_out_dtype(o, cdt)
            types[id(node)] = out
            continue
        if isinstance(node, Union):
            first = kids[0]
            for other in kids[1:]:
                for name in schemas[id(node)]:
                    a, b = first.get(name), other.get(name)
                    if a is None or b is None:
                        continue
                    if _expr_addressable(a) != _expr_addressable(b):
                        report.add(
                            "typing.union-dtype-mismatch", node,
                            f"{node.label}: column {name!r} is {a!r} on "
                            f"one input and {b!r} on another — UNION ALL "
                            "cannot concatenate scalar and non-scalar "
                            "layouts")
            types[id(node)] = dict(first)
            continue
        # Sort/TopK/Limit/Exchange: pass-through
        types[id(node)] = dict(kids[0]) if kids else {}
    return types


def column_types(nodes, schemas, input_dtypes
                 ) -> Dict[int, Dict[str, Optional[dtypes.DType]]]:
    """Public face of the typing walk for non-gating consumers: node-id ->
    {column name -> DType or None (unknown)} under the same bottom-up
    semantics the typing layer verifies. Violations found along the way are
    discarded here — callers that want them gate through verify()."""
    return _check_types(nodes, schemas, input_dtypes, VerifyReport())


# ---- layer 3: pruning-predicate legality ------------------------------------

def _conjunct_triples(pred: Expr):
    """(name, op, repr(value)) triples of the min/max-provable top-level
    AND conjuncts, plus the count of non-provable conjuncts."""
    from ..plan.optimizer import _as_comparison, split_conjuncts
    triples, unprovable = set(), 0
    for c in split_conjuncts(pred):
        cmp = _as_comparison(c)
        if cmp is None:
            unprovable += 1
        else:
            triples.add((cmp[0], cmp[1], repr(cmp[2])))
    return triples, unprovable


def _check_scan_pruning(nodes, report: VerifyReport):
    """A `Scan.predicate` is a PRUNING-ONLY hint: legality requires the
    enforcing Filter/FusedSelect to still sit directly above (retained
    semantics), the scan to be single-consumer (a DAG-shared scan feeds
    parents that did not author the filter — the scan_pruning rule's
    shared-scan guard, promoted to a verifier invariant), and every
    lowered conjunct to be min/max-provable AND implied by the retained
    predicate."""
    parents: Dict[int, List[PlanNode]] = {}
    for n in nodes:
        for c in n.children:
            parents.setdefault(id(c), []).append(n)
    for node in nodes:
        if not isinstance(node, Scan) or node.predicate is None:
            continue
        ps = parents.get(id(node), [])
        if len(ps) != 1:
            report.add("pruning.shared-scan", node,
                       f"{node.label}: carries a pruning predicate but "
                       f"has {len(ps)} consumers — pruning a DAG-shared "
                       "scan starves the parents that did not author "
                       "the filter")
            continue
        parent = ps[0]
        if not isinstance(parent, (Filter, FusedSelect)):
            report.add("pruning.unenforced-predicate", node,
                       f"{node.label}: pruning predicate "
                       f"{node.predicate!r} has no enforcing Filter/"
                       f"FusedSelect directly above (parent is "
                       f"{parent.label}) — pruned row groups would "
                       "change the result")
            continue
        scan_triples, unprovable = _conjunct_triples(node.predicate)
        if unprovable:
            report.add("pruning.unprovable-conjunct", node,
                       f"{node.label}: pruning predicate "
                       f"{node.predicate!r} contains conjunct(s) row-"
                       "group min/max statistics cannot prove — the "
                       "scan would over-prune")
            continue
        parent_triples, _ = _conjunct_triples(parent.predicate)
        missing = scan_triples - parent_triples
        if missing:
            report.add("pruning.unretained-conjunct", node,
                       f"{node.label}: pruning conjunct(s) "
                       f"{sorted(missing)} are not conjuncts of the "
                       f"retained predicate on {parent.label} — rows "
                       "the plan still wants could be pruned")


# ---- layer 4: sharding/partitioning soundness -------------------------------

def _check_partitioning(nodes, root, schemas, float_inputs: bool,
                        report: VerifyReport):
    """Re-derive each node's sharded/local state and hash-partitioning
    claim bottom-up — the same `transfer_part` transfer function the
    runtime `ShardedRel`s and the optimizer's `exchange_planning` follow —
    and prove the plan's exchange structure sound: co-located shuffle-join
    and keyed-aggregate inputs, gathers wherever a sharded relation meets
    an operator with no distributed form, exactly one gather at the sink.
    Only meaningful for exchange-PLANNED plans (`verify(planned=True)`);
    an unplanned plan legitimately relies on the runtime's implicit
    repartition."""
    from ..plan.distributed import (join_alignment, part_satisfies,
                                    transfer_part)
    from ..plan.optimizer import _statically_distributable
    sharded: Dict[int, bool] = {}
    part: Dict[int, frozenset] = {}
    for node in nodes:
        if id(node) not in schemas:
            continue
        kids = list(node.children)
        kid_sharded = [sharded.get(id(c), False) for c in kids]
        kid_parts = [part.get(id(c), frozenset()) for c in kids]
        if isinstance(node, Exchange):
            base = kid_sharded[0]
            if node.how == "gather":
                if not base:
                    report.add("partitioning.redundant-gather", node,
                               f"{node.label}: gathers an input that is "
                               "already local — the sink must gather "
                               "exactly once")
                sharded[id(node)] = False
                part[id(node)] = frozenset()
            elif node.how == "broadcast":
                # replicates a sharded rel — or lifts a local build side
                sharded[id(node)] = True
                part[id(node)] = frozenset()
            else:               # hash / identity: no-op over a local child
                sharded[id(node)] = base
                part[id(node)] = (transfer_part(node, kid_parts)
                                  if base else frozenset())
            continue
        on_mesh = _statically_distributable(node, float_inputs) and (
            isinstance(node, Scan) or (bool(kids) and all(kid_sharded)))
        sharded[id(node)] = on_mesh
        part[id(node)] = (transfer_part(node, kid_parts)
                          if on_mesh else frozenset())
        if not on_mesh:
            for c, s in zip(kids, kid_sharded):
                if s:
                    report.add(
                        "partitioning.ungathered-input", node,
                        f"{node.label}: has no distributed form for "
                        f"this binding but consumes sharded {c.label} "
                        "without a gather boundary")
            continue
        if isinstance(node, HashJoin):
            l, r = kids
            r_broadcast = isinstance(r, Exchange) and r.how == "broadcast"
            if isinstance(l, Exchange) and l.how == "broadcast":
                report.add("partitioning.broadcast-probe", node,
                           f"{node.label}: probe (left) side is a "
                           "broadcast exchange — only the build side "
                           "may replicate")
            if not r_broadcast and join_alignment(
                    kid_parts[0], kid_parts[1],
                    node.left_keys, node.right_keys) is None:
                report.add(
                    "partitioning.join-not-colocated", node,
                    f"{node.label}: sides are partitioned by "
                    f"{sorted(map(list, kid_parts[0])) or 'rows'} vs "
                    f"{sorted(map(list, kid_parts[1])) or 'rows'} — "
                    f"matching keys ({', '.join(node.left_keys)}) = "
                    f"({', '.join(node.right_keys)}) are not provably "
                    "co-located; the elided shuffle would duplicate/"
                    "drop matches")
        elif isinstance(node, HashAggregate) and node.keys:
            (c,) = kids
            fused = isinstance(c, Exchange) and c.how == "hash"
            if not fused and not part_satisfies(kid_parts[0], node.keys):
                report.add(
                    "partitioning.agg-not-colocated", node,
                    f"{node.label}: groups by ({', '.join(node.keys)}) "
                    f"over an input partitioned by "
                    f"{sorted(map(list, kid_parts[0])) or 'rows'} — no "
                    "claim co-locates every group and no hash exchange "
                    "re-places them; a shard-local merge would emit "
                    "duplicate groups")
    if sharded.get(id(root), False):
        report.add("partitioning.unsunk-root", root,
                   f"{root.label}: plan root is still sharded — the "
                   "planned sink gather is missing")


# ---- public entry points ----------------------------------------------------

def verify(plan, *, bound=None,
           input_dtypes: Optional[Dict[str, Dict]] = None,
           float_inputs: Optional[bool] = None,
           planned: bool = False) -> VerifyReport:
    """Verify one plan. `bound` maps scan source -> actual column names
    (schema layer runs strict when given); `input_dtypes` maps source ->
    {column: DType} and enables the typing layer; `planned=True` enables
    the partitioning layer (the plan claims a complete exchange plan —
    the optimizer's `exchange_planning` output). Returns a VerifyReport;
    callers gate with `.raise_if_failed()`."""
    report = VerifyReport()
    schemas, schema_vs = _propagate_schemas(plan.nodes, bound,
                                            strict=bound is not None)
    report.violations.extend(schema_vs)
    if float_inputs is None:
        float_inputs = bool(input_dtypes) and any(
            dt is not None and dt.is_floating
            for cols in input_dtypes.values() for dt in cols.values())
    if input_dtypes:
        _check_types(plan.nodes, schemas, input_dtypes, report)
    _check_scan_pruning(plan.nodes, report)
    if planned and not schema_vs:
        _check_partitioning(plan.nodes, plan.root, schemas,
                            bool(float_inputs), report)
    return report


def _plan_has_mean(nodes) -> bool:
    return any(isinstance(n, HashAggregate)
               and any(o == "mean" for _, o, _ in n.aggs) for n in nodes)


def verify_rewrite(authored, optimized, *, bound=None,
                   input_dtypes: Optional[Dict[str, Dict]] = None,
                   float_inputs: Optional[bool] = None,
                   planned: bool = False, report=None) -> VerifyReport:
    """Verify an optimizer rewrite: the optimized plan standalone, plus
    the pair invariants a single plan cannot witness — the root schema is
    preserved, and any join build-side swap honors the `build_side` rule's
    side conditions (only inside order-unobservable regions, never under
    floating-point inputs or a `mean` aggregate, whose reductions are not
    reorder-exact). `report` (the OptimizeReport) scopes the swap check to
    executions where the rule actually fired — and supplies the per-join
    decision source (hint / observed:<runs> / default, docs/adaptive.md),
    so a violation on a STATS-DRIVEN swap names the observations that
    picked it. This gate is not optional for adaptive rewrites: the
    executor runs it on every observed-driven rewrite even with
    SPARK_RAPIDS_TPU_VERIFY_PLANS off (PlanExecutor._optimized), because
    the stats store may change WHICH rewrites fire but must never weaken
    the invariants they are checked against."""
    out = verify(optimized, bound=bound, input_dtypes=input_dtypes,
                 float_inputs=float_inputs, planned=planned)
    if float_inputs is None:
        float_inputs = bool(input_dtypes) and any(
            dt is not None and dt.is_floating
            for cols in input_dtypes.values() for dt in cols.values())
    # root schema preservation (violations already reported by the
    # verify() call above; only the resolved root schemas matter here)
    a_schemas, _ = _propagate_schemas(authored.nodes, bound, strict=False)
    o_schemas, _ = _propagate_schemas(optimized.nodes, bound,
                                      strict=False)
    a_root = a_schemas.get(id(authored.root))
    o_root = o_schemas.get(id(optimized.root))
    if a_root is not None and o_root is not None and a_root != o_root:
        out.add("rewrite.schema-drift", optimized.root,
                f"{optimized.root.label}: rewrite changed the plan's "
                f"output schema {list(a_root)} -> {list(o_root)}")
    # build-side swap legality (diff-based: the pair witnesses the swap).
    # MULTISET comparison of inner-join key pairs, not set membership: a
    # plan that authors both (x)/(y) and (y)/(x) joins would otherwise
    # alias — the swapped join's reversed pair already "exists" and the
    # swap hides. An optimized pair occurring MORE times than authored,
    # with the reversed pair authored, witnesses a swap.
    if report is not None and not report.rules.get("build_side", 0):
        return out
    from collections import Counter

    def _pairs(nodes):
        return Counter((tuple(n.left_keys), tuple(n.right_keys))
                       for n in nodes
                       if isinstance(n, HashJoin) and n.how == "inner")

    a_cnt = _pairs(authored.nodes)
    excess = {p: c - a_cnt.get(p, 0)
              for p, c in _pairs(optimized.nodes).items()}
    swapped = []
    for n in optimized.nodes:
        if not (isinstance(n, HashJoin) and n.how == "inner"):
            continue
        p = (tuple(n.left_keys), tuple(n.right_keys))
        if excess.get(p, 0) > 0 and (p[1], p[0]) in a_cnt:
            excess[p] -= 1
            swapped.append(n)
    if not swapped:
        return out

    def _src(n) -> str:
        """Decision-source suffix for a swap violation: which estimate
        tier picked a swap. Only `swap (...)` stamps qualify — the
        fixpoint pass re-stamps the SWAPPED node's own label with a
        `keep` (its reversed sides never re-qualify under the 2x
        hysteresis), which describes the post-swap confirmation, not the
        decision under scrutiny. Diagnostic only — legality never
        depends on where the cardinalities came from."""
        sources = getattr(report, "decision_sources", None) or {}
        got = sources.get(f"{n.label}/build_side")
        if got is None or not got.startswith("swap"):
            swaps = [v for k, v in sorted(sources.items())
                     if k.endswith("/build_side")
                     and v.startswith("swap")]
            got = swaps[0] if len(swaps) == 1 else None
        return f" (decision source: {got})" if got else ""

    if float_inputs or _plan_has_mean(optimized.nodes) \
            or _plan_has_mean(authored.nodes):
        for n in swapped:
            out.add("rewrite.fp-build-side", n,
                    f"{n.label}: build-side swap under floating-point "
                    "inputs (or a mean aggregate) — fp reductions are "
                    "not reorder-exact on m:n joins, so the swapped "
                    f"pair enumeration changes the bits{_src(n)}")
        return out
    from ..plan.optimizer import _order_safe_ids
    safe = _order_safe_ids(optimized.root)
    for n in swapped:
        if id(n) not in safe:
            out.add("rewrite.order-unsafe-swap", n,
                    f"{n.label}: build-side swap where the join's output "
                    "row order is observable (not every path to the root "
                    "crosses a HashAggregate) — results would no longer "
                    f"be row-for-row identical{_src(n)}")
    return out
