"""Static analysis over physical plans (docs/analysis.md).

The engine's value proposition is Spark-exact semantics, yet two of the
last PRs shipped soundness bugs only human review caught: a stale
partitioning claim that let `exchange_planning` elide a required shuffle
(silently duplicating/dropping groups), and a bound-method capture in a
process-global jitted-primitive cache that pinned dead executors. This
package turns those one-off review findings into a permanent machine
check that gates every optimizer rule, executor tier and plan:

- `verifier`: the static plan verifier — symbolic schema/dtype
  propagation, sharding/partitioning soundness (re-derived bottom-up with
  the SAME `transfer_part` transfer function the runtime uses), and
  rewrite-pair legality checks mirroring each optimizer rule's side
  conditions. Wired as the builder's validation backend, a debug-mode
  pre-execution gate (`SPARK_RAPIDS_TPU_VERIFY_PLANS`, on in tests), and
  the optimizer's per-rule fall-back diagnostic.
- `fuzz`: the property-based plan fuzzer — a seeded random DAG generator
  over all 11 operator kinds whose cases must verify, optimize cleanly,
  and (being small) execute with optimized-vs-unoptimized eager parity.
  A fixed corpus runs premerge; a deep seeded sweep runs nightly.

The AST-level sibling is `tools/lint_hazards.py`: the codebase linter for
the known JAX hazard patterns (self capture in jit closure caches,
host-sync on traced values, tracer branches, env reads outside config.py,
nondeterministic iteration feeding fingerprints).
"""
from .verifier import (PlanVerificationError, VerifyReport, Violation,
                       verify, verify_rewrite)

__all__ = ["PlanVerificationError", "VerifyReport", "Violation",
           "verify", "verify_rewrite"]
