"""Static analysis over physical plans (docs/analysis.md).

The engine's value proposition is Spark-exact semantics, yet two of the
last PRs shipped soundness bugs only human review caught: a stale
partitioning claim that let `exchange_planning` elide a required shuffle
(silently duplicating/dropping groups), and a bound-method capture in a
process-global jitted-primitive cache that pinned dead executors. This
package turns those one-off review findings into a permanent machine
check that gates every optimizer rule, executor tier and plan:

- `verifier`: the static plan verifier — symbolic schema/dtype
  propagation, sharding/partitioning soundness (re-derived bottom-up with
  the SAME `transfer_part` transfer function the runtime uses), and
  rewrite-pair legality checks mirroring each optimizer rule's side
  conditions. Wired as the builder's validation backend, a debug-mode
  pre-execution gate (`SPARK_RAPIDS_TPU_VERIFY_PLANS`, on in tests), and
  the optimizer's per-rule fall-back diagnostic.
- `footprint`: the static resource certifier — an abstract interpreter
  propagating sound `[lo, hi]` row intervals and byte footprints
  (columnar widths, validity planes, join/aggregate working sets,
  exchange payloads) per operator, consumed by the executor's admission
  gate, the optimizer's broadcast byte-legality proof, and the capped
  tier's cold-run cap seeding. Its soundness inequality (certified hi >=
  observed, per op) is fuzz property 5 and a nightly NDS gate.
- `fuzz`: the property-based plan fuzzer — a seeded random DAG generator
  over all 11 operator kinds whose cases must verify, optimize cleanly,
  and (being small) execute with optimized-vs-unoptimized eager parity.
  A fixed corpus runs premerge; a deep seeded sweep runs nightly.

The AST-level sibling is `tools/lint_hazards.py`: the codebase linter for
the known JAX hazard patterns (self capture in jit closure caches,
host-sync on traced values, tracer branches, env reads outside config.py,
nondeterministic iteration feeding fingerprints, unlocked shared-state
mutation), plus `tools/lint_metrics.py` for the bench-JSONL stamp rule.
"""
from .footprint import (ResourceAdmissionError, ResourceCert, certify,
                        certify_nodes)
from .verifier import (PlanVerificationError, VerifyReport, Violation,
                       verify, verify_rewrite)

__all__ = ["PlanVerificationError", "VerifyReport", "Violation",
           "verify", "verify_rewrite", "ResourceAdmissionError",
           "ResourceCert", "certify", "certify_nodes"]
