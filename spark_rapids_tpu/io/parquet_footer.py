"""Parquet footer parse / prune / filter / re-serialize.

Python facade over native/parquet_footer.cpp, mirroring the reference's
ParquetFooter.java surface: a schema DSL (StructElement/ListElement/
MapElement/ValueElement, ParquetFooter.java:34-118) flattened depth-first
into names/num_children/tags arrays (tags 0=VALUE 1=STRUCT 2=LIST 3=MAP,
:139-179), readAndFilter(buffer, partOffset, partLength, schema,
ignoreCase) (:204), and serializeThriftFile returning the
[thrift][4-byte length][PAR1] framing (NativeParquetJni.cpp:793-830).
"""
from __future__ import annotations

import ctypes
import threading
from typing import List, Sequence, Tuple

from ..native.build import build


class ValueElement:
    """A primitive leaf column."""


class StructElement:
    def __init__(self, **children):
        self.children: List[Tuple[str, object]] = list(children.items())

    @staticmethod
    def of(children: Sequence[Tuple[str, object]]) -> "StructElement":
        s = StructElement()
        s.children = list(children)
        return s


class ListElement:
    def __init__(self, item):
        self.item = item


class MapElement:
    def __init__(self, key, value):
        self.key = key
        self.value = value


def _flatten(element, name: str, lower: bool, names, num_children, tags):
    if lower:
        name = name.lower()
    if isinstance(element, ValueElement):
        names.append(name)
        num_children.append(0)
        tags.append(0)
    elif isinstance(element, StructElement):
        names.append(name)
        num_children.append(len(element.children))
        tags.append(1)
        for child_name, child in element.children:
            _flatten(child, child_name, lower, names, num_children, tags)
    elif isinstance(element, ListElement):
        names.append(name)
        num_children.append(1)
        tags.append(2)
        _flatten(element.item, "element", lower, names, num_children, tags)
    elif isinstance(element, MapElement):
        names.append(name)
        num_children.append(2)
        tags.append(3)
        _flatten(element.key, "key", lower, names, num_children, tags)
        _flatten(element.value, "value", lower, names, num_children, tags)
    else:
        raise TypeError(f"{element!r} is not a supported schema element")


_lib = None
_lib_lock = threading.Lock()


def _native():
    global _lib
    if _lib is None:
        with _lib_lock:
            if _lib is None:
                lib = ctypes.CDLL(build("parquet_footer"))
                lib.pqf_parse.restype = ctypes.c_void_p
                lib.pqf_parse.argtypes = [ctypes.c_char_p, ctypes.c_int64]
                lib.pqf_last_error.restype = ctypes.c_char_p
                lib.pqf_filter_groups.argtypes = [ctypes.c_void_p,
                                                  ctypes.c_int64,
                                                  ctypes.c_int64]
                lib.pqf_prune.argtypes = [
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
                    ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                    ctypes.c_int, ctypes.c_int]
                lib.pqf_num_rows.restype = ctypes.c_int64
                lib.pqf_num_rows.argtypes = [ctypes.c_void_p]
                lib.pqf_num_row_groups.argtypes = [ctypes.c_void_p]
                lib.pqf_num_columns.argtypes = [ctypes.c_void_p]
                lib.pqf_serialize.restype = ctypes.c_int64
                lib.pqf_serialize.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                              ctypes.c_int64]
                lib.pqf_free.argtypes = [ctypes.c_void_p]
                _lib = lib
    return _lib


class ParquetFooter:
    """A parsed, filtered parquet footer (reference ParquetFooter.java)."""

    def __init__(self, handle: int):
        self._lib = _native()
        self._h = handle

    @staticmethod
    def read_and_filter(buffer: bytes, part_offset: int, part_length: int,
                        schema: StructElement,
                        ignore_case: bool) -> "ParquetFooter":
        """Parse a footer thrift buffer, prune to `schema`, and keep only
        the row groups whose byte midpoint falls inside
        [part_offset, part_offset + part_length)."""
        lib = _native()
        h = lib.pqf_parse(buffer, len(buffer))
        if not h:
            raise ValueError(lib.pqf_last_error().decode())
        footer = ParquetFooter(h)
        try:
            footer._filter_groups(part_offset, part_length)
            footer._prune(schema, ignore_case)
        except Exception:
            footer.close()
            raise
        return footer

    def _filter_groups(self, part_offset: int, part_length: int) -> None:
        if self._lib.pqf_filter_groups(self._h, part_offset, part_length):
            raise ValueError(self._lib.pqf_last_error().decode())

    def _prune(self, schema: StructElement, ignore_case: bool) -> None:
        names: List[str] = []
        num_children: List[int] = []
        tags: List[int] = []
        for child_name, child in schema.children:
            _flatten(child, child_name, ignore_case, names, num_children,
                     tags)
        n = len(names)
        c_names = (ctypes.c_char_p * n)(*[s.encode() for s in names])
        c_nc = (ctypes.c_int * n)(*num_children)
        c_tags = (ctypes.c_int * n)(*tags)
        if self._lib.pqf_prune(self._h, c_names, c_nc, c_tags, n,
                               int(ignore_case)):
            raise ValueError(self._lib.pqf_last_error().decode())

    def get_num_rows(self) -> int:
        return self._lib.pqf_num_rows(self._h)

    def get_num_columns(self) -> int:
        return self._lib.pqf_num_columns(self._h)

    def get_num_row_groups(self) -> int:
        return self._lib.pqf_num_row_groups(self._h)

    def serialize_thrift_file(self) -> bytes:
        """Filtered footer as [thrift][4-byte LE length]["PAR1"]."""
        size = self._lib.pqf_serialize(self._h, None, 0)
        if size < 0:
            raise ValueError(self._lib.pqf_last_error().decode())
        buf = ctypes.create_string_buffer(size)
        got = self._lib.pqf_serialize(self._h, buf, size)
        if got < 0:
            raise ValueError(self._lib.pqf_last_error().decode())
        return buf.raw[:got]

    def close(self) -> None:
        if self._h:
            self._lib.pqf_free(self._h)
            self._h = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
