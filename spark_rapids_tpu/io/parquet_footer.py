"""Parquet footer parse / prune / filter / re-serialize / statistics.

Python facade over native/parquet_footer.cpp, mirroring the reference's
ParquetFooter.java surface: a schema DSL (StructElement/ListElement/
MapElement/ValueElement, ParquetFooter.java:34-118) flattened depth-first
into names/num_children/tags arrays (tags 0=VALUE 1=STRUCT 2=LIST 3=MAP,
:139-179), readAndFilter(buffer, partOffset, partLength, schema,
ignoreCase) (:204), and serializeThriftFile returning the
[thrift][4-byte length][PAR1] framing (NativeParquetJni.cpp:793-830).

`read_footer_stats()` additionally exposes per-row-group, per-column-chunk
min/max statistics (decoded from the footer's Statistics structs) — the
input to the streaming scan's row-group pruning (docs/io.md). Columns
lacking statistics, and physical types whose plain encoding this module
does not decode (INT96, FLBA), surface as `min is None / max is None`:
the None-safe path pruning must treat as "cannot prove anything".
"""
from __future__ import annotations

import ctypes
import dataclasses
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

from ..native.build import build


class ValueElement:
    """A primitive leaf column."""


class StructElement:
    def __init__(self, **children):
        self.children: List[Tuple[str, object]] = list(children.items())

    @staticmethod
    def of(children: Sequence[Tuple[str, object]]) -> "StructElement":
        s = StructElement()
        s.children = list(children)
        return s


class ListElement:
    def __init__(self, item):
        self.item = item


class MapElement:
    def __init__(self, key, value):
        self.key = key
        self.value = value


def _flatten(element, name: str, lower: bool, names, num_children, tags):
    if lower:
        name = name.lower()
    if isinstance(element, ValueElement):
        names.append(name)
        num_children.append(0)
        tags.append(0)
    elif isinstance(element, StructElement):
        names.append(name)
        num_children.append(len(element.children))
        tags.append(1)
        for child_name, child in element.children:
            _flatten(child, child_name, lower, names, num_children, tags)
    elif isinstance(element, ListElement):
        names.append(name)
        num_children.append(1)
        tags.append(2)
        _flatten(element.item, "element", lower, names, num_children, tags)
    elif isinstance(element, MapElement):
        names.append(name)
        num_children.append(2)
        tags.append(3)
        _flatten(element.key, "key", lower, names, num_children, tags)
        _flatten(element.value, "value", lower, names, num_children, tags)
    else:
        raise TypeError(f"{element!r} is not a supported schema element")


_lib = None
_lib_lock = threading.Lock()


def _native():
    global _lib
    if _lib is None:
        with _lib_lock:
            if _lib is None:
                lib = ctypes.CDLL(build("parquet_footer"))
                lib.pqf_parse.restype = ctypes.c_void_p
                lib.pqf_parse.argtypes = [ctypes.c_char_p, ctypes.c_int64]
                lib.pqf_last_error.restype = ctypes.c_char_p
                lib.pqf_filter_groups.argtypes = [ctypes.c_void_p,
                                                  ctypes.c_int64,
                                                  ctypes.c_int64]
                lib.pqf_prune.argtypes = [
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
                    ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                    ctypes.c_int, ctypes.c_int]
                lib.pqf_num_rows.restype = ctypes.c_int64
                lib.pqf_num_rows.argtypes = [ctypes.c_void_p]
                lib.pqf_num_row_groups.argtypes = [ctypes.c_void_p]
                lib.pqf_num_columns.argtypes = [ctypes.c_void_p]
                lib.pqf_serialize.restype = ctypes.c_int64
                lib.pqf_serialize.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                              ctypes.c_int64]
                lib.pqf_rg_num_rows.restype = ctypes.c_int64
                lib.pqf_rg_num_rows.argtypes = [ctypes.c_void_p, ctypes.c_int]
                lib.pqf_rg_num_chunks.argtypes = [ctypes.c_void_p,
                                                  ctypes.c_int]
                lib.pqf_chunk_info.argtypes = [
                    ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                    ctypes.c_char_p, ctypes.c_int64,
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_int64)]
                lib.pqf_chunk_stat.restype = ctypes.c_int64
                lib.pqf_chunk_stat.argtypes = [
                    ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                    ctypes.c_void_p, ctypes.c_int64]
                lib.pqf_free.argtypes = [ctypes.c_void_p]
                _lib = lib
    return _lib


class ParquetFooter:
    """A parsed, filtered parquet footer (reference ParquetFooter.java)."""

    def __init__(self, handle: int):
        self._lib = _native()
        self._h = handle

    @staticmethod
    def read_and_filter(buffer: bytes, part_offset: int, part_length: int,
                        schema: StructElement,
                        ignore_case: bool) -> "ParquetFooter":
        """Parse a footer thrift buffer, prune to `schema`, and keep only
        the row groups whose byte midpoint falls inside
        [part_offset, part_offset + part_length)."""
        lib = _native()
        h = lib.pqf_parse(buffer, len(buffer))
        if not h:
            raise ValueError(lib.pqf_last_error().decode())
        footer = ParquetFooter(h)
        try:
            footer._filter_groups(part_offset, part_length)
            footer._prune(schema, ignore_case)
        except Exception:
            footer.close()
            raise
        return footer

    def _filter_groups(self, part_offset: int, part_length: int) -> None:
        if self._lib.pqf_filter_groups(self._h, part_offset, part_length):
            raise ValueError(self._lib.pqf_last_error().decode())

    def _prune(self, schema: StructElement, ignore_case: bool) -> None:
        names: List[str] = []
        num_children: List[int] = []
        tags: List[int] = []
        for child_name, child in schema.children:
            _flatten(child, child_name, ignore_case, names, num_children,
                     tags)
        n = len(names)
        c_names = (ctypes.c_char_p * n)(*[s.encode() for s in names])
        c_nc = (ctypes.c_int * n)(*num_children)
        c_tags = (ctypes.c_int * n)(*tags)
        if self._lib.pqf_prune(self._h, c_names, c_nc, c_tags, n,
                               int(ignore_case)):
            raise ValueError(self._lib.pqf_last_error().decode())

    def get_num_rows(self) -> int:
        return self._lib.pqf_num_rows(self._h)

    def get_num_columns(self) -> int:
        return self._lib.pqf_num_columns(self._h)

    def get_num_row_groups(self) -> int:
        return self._lib.pqf_num_row_groups(self._h)

    def serialize_thrift_file(self) -> bytes:
        """Filtered footer as [thrift][4-byte LE length]["PAR1"]."""
        size = self._lib.pqf_serialize(self._h, None, 0)
        if size < 0:
            raise ValueError(self._lib.pqf_last_error().decode())
        buf = ctypes.create_string_buffer(size)
        got = self._lib.pqf_serialize(self._h, buf, size)
        if got < 0:
            raise ValueError(self._lib.pqf_last_error().decode())
        return buf.raw[:got]

    def close(self) -> None:
        if self._h:
            self._lib.pqf_free(self._h)
            self._h = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---- per-row-group min/max statistics ---------------------------------------

# parquet physical types (parquet.thrift Type enum)
PHYS_BOOLEAN, PHYS_INT32, PHYS_INT64, PHYS_INT96 = 0, 1, 2, 3
PHYS_FLOAT, PHYS_DOUBLE, PHYS_BYTE_ARRAY, PHYS_FLBA = 4, 5, 6, 7


@dataclasses.dataclass(frozen=True)
class ColumnChunkStats:
    """One column chunk's footer statistics. `min`/`max` are decoded python
    values (int/float/bool/bytes) or None when the chunk carries no usable
    statistics — the None-safe "cannot prove anything" state pruning must
    honor. `null_count` is None when the writer omitted it."""
    path: str                       # dotted leaf path ("a", "s.x", ...)
    physical_type: int              # PHYS_* code
    min: object
    max: object
    null_count: Optional[int]
    total_compressed_size: int

    @property
    def column(self) -> str:
        """Top-level column this leaf belongs to."""
        return self.path.split(".", 1)[0]


@dataclasses.dataclass(frozen=True)
class RowGroupStats:
    """Statistics of one row group: num_rows plus per-leaf chunk stats
    keyed by the dotted leaf path."""
    index: int
    num_rows: int
    columns: Dict[str, ColumnChunkStats]


def _decode_stat(raw: Optional[bytes], phys: int):
    """Plain-encoded statistics value -> python value; None when the type
    has no decodable plain form here (INT96, FLBA) or the width is off."""
    if raw is None:
        return None
    try:
        if phys == PHYS_INT32 and len(raw) == 4:
            return int.from_bytes(raw, "little", signed=True)
        if phys == PHYS_INT64 and len(raw) == 8:
            return int.from_bytes(raw, "little", signed=True)
        if phys == PHYS_FLOAT and len(raw) == 4:
            return struct.unpack("<f", raw)[0]
        if phys == PHYS_DOUBLE and len(raw) == 8:
            return struct.unpack("<d", raw)[0]
        if phys == PHYS_BOOLEAN and len(raw) >= 1:
            return raw[0] != 0
        if phys == PHYS_BYTE_ARRAY:
            return raw                  # compare as bytes (UTF8 order ==
            #                             unsigned byte order)
    except (struct.error, ValueError):
        return None
    return None


def footer_thrift_bytes(data: bytes) -> bytes:
    """The raw thrift FileMetaData buffer from a whole-file byte string
    ([...data...][thrift][4-byte LE length][PAR1])."""
    if len(data) < 12 or data[-4:] != b"PAR1":
        raise ValueError("not a parquet file (missing PAR1 trailer)")
    n = int.from_bytes(data[-8:-4], "little")
    if n <= 0 or n + 8 > len(data):
        raise ValueError("corrupt parquet footer length")
    return data[-8 - n:-8]


def _read_footer_tail(source: Union[str, bytes]) -> bytes:
    if isinstance(source, (bytes, bytearray, memoryview)):
        return footer_thrift_bytes(bytes(source))
    with open(source, "rb") as f:
        import os
        size = os.fstat(f.fileno()).st_size
        if size < 12:
            raise ValueError("not a parquet file (too small)")
        f.seek(-8, 2)
        trailer = f.read(8)
        if trailer[-4:] != b"PAR1":
            raise ValueError("not a parquet file (missing PAR1 trailer)")
        n = int.from_bytes(trailer[:4], "little")
        if n <= 0 or n + 8 > size:
            raise ValueError("corrupt parquet footer length")
        f.seek(-(8 + n), 2)
        return f.read(n)


def read_footer_stats(source: Union[str, bytes]) -> List[RowGroupStats]:
    """Per-row-group, per-column-chunk min/max statistics of a parquet file
    (path or whole-file bytes). Reads ONLY the footer — no page data is
    touched, which is what makes stats-driven row-group pruning cheaper
    than decoding ("Do GPUs Really Need New Tabular File Formats?")."""
    lib = _native()
    buf = _read_footer_tail(source)
    h = lib.pqf_parse(buf, len(buf))
    if not h:
        raise ValueError(lib.pqf_last_error().decode())
    try:
        out: List[RowGroupStats] = []
        for rg in range(lib.pqf_num_row_groups(h)):
            n_rows = lib.pqf_rg_num_rows(h, rg)
            if n_rows < 0:
                raise ValueError(lib.pqf_last_error().decode())
            cols: Dict[str, ColumnChunkStats] = {}
            n_chunks = lib.pqf_rg_num_chunks(h, rg)
            if n_chunks < 0:
                raise ValueError(lib.pqf_last_error().decode())
            for c in range(n_chunks):
                path_buf = ctypes.create_string_buffer(2048)
                phys = ctypes.c_int64()
                compressed = ctypes.c_int64()
                null_count = ctypes.c_int64()
                if lib.pqf_chunk_info(h, rg, c, path_buf, 2048,
                                      ctypes.byref(phys),
                                      ctypes.byref(compressed),
                                      ctypes.byref(null_count)):
                    raise ValueError(lib.pqf_last_error().decode())

                def stat(which: int) -> Optional[bytes]:
                    size = lib.pqf_chunk_stat(h, rg, c, which, None, 0)
                    if size == -1:
                        return None             # absent: the None-safe path
                    if size < 0:
                        raise ValueError(lib.pqf_last_error().decode())
                    if size == 0:
                        return b""
                    vbuf = (ctypes.c_uint8 * size)()
                    got = lib.pqf_chunk_stat(h, rg, c, which, vbuf, size)
                    if got < 0:
                        raise ValueError(lib.pqf_last_error().decode())
                    return bytes(vbuf[:got])

                p = int(phys.value)
                st = ColumnChunkStats(
                    path=path_buf.value.decode(),
                    physical_type=p,
                    min=_decode_stat(stat(0), p),
                    max=_decode_stat(stat(1), p),
                    null_count=(None if null_count.value < 0
                                else int(null_count.value)),
                    total_compressed_size=int(compressed.value))
                cols[st.path] = st
            out.append(RowGroupStats(index=rg, num_rows=int(n_rows),
                                     columns=cols))
        return out
    finally:
        lib.pqf_free(h)
