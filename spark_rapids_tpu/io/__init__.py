from .parquet_footer import (ParquetFooter, StructElement, ListElement,
                             MapElement, ValueElement)
from .parquet import ParquetChunkedReader, read_parquet

__all__ = ["ParquetFooter", "StructElement", "ListElement", "MapElement",
           "ValueElement", "ParquetChunkedReader", "read_parquet"]
