from .parquet_footer import (ParquetFooter, StructElement, ListElement,
                             MapElement, ValueElement)

__all__ = ["ParquetFooter", "StructElement", "ListElement", "MapElement",
           "ValueElement"]
