from .parquet_footer import (ParquetFooter, StructElement, ListElement,
                             MapElement, ValueElement, ColumnChunkStats,
                             RowGroupStats, read_footer_stats)
from .parquet import (ParquetChunkedReader, ParquetSource, read_parquet,
                      select_row_groups)

# IO admission: a parquet read has no resident input buffers, so the
# working-set estimate comes from the source size (encoded bytes × a
# decompression/decode expansion factor) — the same pre-dispatch-estimate
# contract as the op boundary (runtime/admission.py).
from ..runtime.admission import admitted_op as _admitted_op


def _parquet_read_estimate(source, *args, **kwargs) -> int:
    import os
    if isinstance(source, (bytes, bytearray, memoryview)):
        return 3 * len(source)
    try:
        return 3 * os.path.getsize(source)
    except (OSError, TypeError):
        return 0


read_parquet = _admitted_op(read_parquet, estimator=_parquet_read_estimate)

__all__ = ["ParquetFooter", "StructElement", "ListElement", "MapElement",
           "ValueElement", "ParquetChunkedReader", "ParquetSource",
           "read_parquet", "read_footer_stats", "select_row_groups",
           "ColumnChunkStats", "RowGroupStats"]
