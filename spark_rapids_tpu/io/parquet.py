"""Chunked parquet reader → Arrow-layout Tables.

The reference jar feeds its filtered footer to the cudf *chunked parquet
reader* (SURVEY.md §3.4 last line, §2.1 #17); this module is that reader for
the TPU engine. The bitstream decode (thrift page headers, RLE/bit-packed
levels, dictionaries, codecs) runs in native host code
(native/parquet_reader.cpp) — branchy byte-chasing a TPU can't vectorize —
and hands back dense buffers that become device-resident Columns.

Usage:
    t = read_parquet("part-0.parquet", columns=["a", "b"])     # whole file
    with ParquetChunkedReader("big.parquet") as r:             # chunked
        while r.has_next():
            table = r.read_chunk()          # one row group per chunk

Type mapping (parquet physical + converted → engine dtype):
  BOOLEAN→BOOL, INT32→INT32 (DATE→DATE32, DECIMAL→DECIMAL32),
  INT64→INT64 (TIMESTAMP_MICROS→TIMESTAMP_US, TIMESTAMP_MILLIS→TIMESTAMP_MS,
  DECIMAL→DECIMAL64), INT96→TIMESTAMP_US (legacy Impala timestamps),
  FLOAT→FLOAT32, DOUBLE→FLOAT64, BYTE_ARRAY→STRING,
  FIXED_LEN_BYTE_ARRAY(DECIMAL)→DECIMAL128.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from collections import OrderedDict
from typing import NamedTuple

from .. import dtypes
from ..columnar import Column, Table
from ..native.build import build


class _Node(NamedTuple):
    """One generalized-ancestry node (kind-4 leaves): the Python image of
    the native 4-int descriptor records. MAP records are expanded at parse
    time into (list, implicit struct) so the builder only ever sees
    'struct' and 'list' — a map IS LIST<STRUCT<key,value>> in this engine
    (the same representation ops/map_utils.py produces)."""
    kind: str      # "struct" | "list"
    a: int         # struct: def of the group if optional else -1; list: dar
    b: int         # list: def of the (optional) LIST group else -1
    segs: int      # dotted path segments this node consumes

_lib = None
_lib_lock = threading.Lock()

# parquet physical types
_PT_BOOLEAN, _PT_INT32, _PT_INT64, _PT_INT96 = 0, 1, 2, 3
_PT_FLOAT, _PT_DOUBLE, _PT_BYTE_ARRAY, _PT_FLBA = 4, 5, 6, 7
# converted types we honor
_CT_UTF8, _CT_DECIMAL, _CT_DATE = 0, 5, 6
_CT_TIMESTAMP_MILLIS, _CT_TIMESTAMP_MICROS = 9, 10


def _native():
    global _lib
    if _lib is None:
        with _lib_lock:
            if _lib is None:
                lib = ctypes.CDLL(build("parquet_reader"))
                lib.pqr_open.restype = ctypes.c_void_p
                lib.pqr_open.argtypes = [ctypes.c_char_p, ctypes.c_int64]
                lib.pqr_open_ex.restype = ctypes.c_void_p
                lib.pqr_open_ex.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                            ctypes.c_int32]
                lib.pqr_last_error.restype = ctypes.c_char_p
                lib.pqr_num_rows.restype = ctypes.c_int64
                lib.pqr_num_rows.argtypes = [ctypes.c_void_p]
                lib.pqr_num_row_groups.argtypes = [ctypes.c_void_p]
                lib.pqr_num_leaves.argtypes = [ctypes.c_void_p]
                lib.pqr_row_group_num_rows.restype = ctypes.c_int64
                lib.pqr_row_group_num_rows.argtypes = [ctypes.c_void_p,
                                                       ctypes.c_int32]
                lib.pqr_leaf_info.argtypes = [
                    ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p,
                    ctypes.c_int32] + [ctypes.POINTER(ctypes.c_int32)] * 7
                lib.pqr_read_column.argtypes = [
                    ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
                    ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.POINTER(ctypes.c_int64)]
                lib.pqr_leaf_kind.argtypes = [ctypes.c_void_p, ctypes.c_int32]
                lib.pqr_leaf_struct_info.argtypes = [
                    ctypes.c_void_p, ctypes.c_int32,
                    ctypes.POINTER(ctypes.c_int32),
                    ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
                lib.pqr_read_def_levels.argtypes = [
                    ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
                    ctypes.c_void_p]
                lib.pqr_read_list_column.argtypes = [
                    ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
                    ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.POINTER(ctypes.c_int64)]
                lib.pqr_leaf_ancestry.argtypes = [
                    ctypes.c_void_p, ctypes.c_int32,
                    ctypes.POINTER(ctypes.c_int32),
                    ctypes.POINTER(ctypes.c_int32),
                    ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
                lib.pqr_read_nested_column.argtypes = [
                    ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_int64)]
                lib.pqr_free.argtypes = [ctypes.c_void_p]
                _lib = lib
    return _lib


class _Leaf:
    def __init__(self, idx, name, phys, type_length, converted, scale,
                 precision, optional, flat, is_list=False,
                 is_struct_member=False, ancestor_defs=(), max_def=0):
        self.idx, self.name, self.phys = idx, name, phys
        self.type_length, self.converted = type_length, converted
        self.scale, self.precision = scale, precision
        self.optional, self.flat = optional, flat
        self.is_list = is_list
        self.is_struct_member = is_struct_member
        self.ancestor_defs = tuple(ancestor_defs)  # per ancestor group,
                                                   # -1 = required
        self.max_def = max_def
        self.max_rep = 0
        self.nodes = ()        # kind-4 generalized ancestry (_Node records)
        # LIST leaves carry the 3-level dotted path (f.list.element) and
        # STRUCT members their field path; the user-facing column name is
        # the outer field
        self.display = name.split(".")[0] if (is_list or is_struct_member) \
            else name

    def dtype(self) -> dtypes.DType:
        if self.phys == _PT_BOOLEAN:
            return dtypes.BOOL
        if self.phys == _PT_INT32:
            if self.converted == _CT_DATE:
                return dtypes.DATE32
            if self.converted == _CT_DECIMAL:
                return dtypes.DType(dtypes.Kind.DECIMAL32,
                                    precision=self.precision, scale=self.scale)
            return dtypes.INT32
        if self.phys == _PT_INT64:
            if self.converted == _CT_TIMESTAMP_MICROS:
                return dtypes.TIMESTAMP_US
            if self.converted == _CT_TIMESTAMP_MILLIS:
                return dtypes.TIMESTAMP_MS
            if self.converted == _CT_DECIMAL:
                return dtypes.DType(dtypes.Kind.DECIMAL64,
                                    precision=self.precision, scale=self.scale)
            return dtypes.INT64
        if self.phys == _PT_INT96:
            return dtypes.TIMESTAMP_US
        if self.phys == _PT_FLOAT:
            return dtypes.FLOAT32
        if self.phys == _PT_DOUBLE:
            return dtypes.FLOAT64
        if self.phys == _PT_BYTE_ARRAY:
            return dtypes.STRING
        if self.phys == _PT_FLBA and self.converted == _CT_DECIMAL:
            return dtypes.DType(dtypes.Kind.DECIMAL128,
                                precision=self.precision, scale=self.scale)
        raise TypeError(f"unsupported parquet column {self.name!r} "
                        f"(physical type {self.phys})")


class ParquetChunkedReader:
    """Reads a parquet file one row group at a time (cudf chunked-reader
    contract: bounded memory regardless of file size).

    `columns=` is SELECTIVE decode: non-requested leaves are dropped from
    the schema walk before any page is touched, so their column chunks are
    never decompressed or assembled (not a post-select). `row_groups=`
    restricts the chunk sequence to the given group indices — the hook
    min/max footer pruning (parquet_footer.read_footer_stats) drives."""

    def __init__(self, source: Union[str, bytes],
                 columns: Optional[Sequence[str]] = None,
                 row_groups: Optional[Sequence[int]] = None):
        self._lib = _native()
        # zero-copy open: mmap files (pages fault in lazily, so decode
        # memory stays bounded per row group) / borrow bytes buffers; the
        # buffer is kept alive on self for the handle's lifetime
        if isinstance(source, (str, os.PathLike)):
            import mmap
            with open(source, "rb") as f:
                # ACCESS_COPY: private CoW pages, required by from_buffer
                self._buf = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_COPY)
        else:
            self._buf = source
        n = len(self._buf)
        if isinstance(self._buf, bytes):
            addr = ctypes.cast(ctypes.c_char_p(self._buf), ctypes.c_void_p)
        else:
            addr = ctypes.c_void_p(
                ctypes.addressof(ctypes.c_char.from_buffer(self._buf)))
        self._h = self._lib.pqr_open_ex(addr, n, 0)
        if not self._h:
            raise ValueError(self._lib.pqr_last_error().decode())
        self._leaves = self._read_schema()
        # top-level fields that assemble via the generalized nested builder
        # (any kind-4 leaf pulls its whole display group through it)
        self._nested_displays = {l.display for l in self._leaves
                                 if l.kind == 4}
        if columns is not None:
            wanted = set(columns)
            present = {l.display for l in self._leaves}
            missing = [c for c in columns if c not in present]
            if missing:
                raise KeyError(f"columns not in file: {missing}")
            self._leaves = [l for l in self._leaves if l.display in wanted]
            # preserve the requested order (by first occurrence)
            order = {c: k for k, c in enumerate(columns)}
            self._leaves.sort(key=lambda l: order[l.display])
        self.num_row_groups = self._lib.pqr_num_row_groups(self._h)
        self.num_rows = self._lib.pqr_num_rows(self._h)
        if row_groups is None:
            self._groups = list(range(self.num_row_groups))
        else:
            bad = [g for g in row_groups
                   if not 0 <= int(g) < self.num_row_groups]
            if bad:
                raise IndexError(
                    f"row group(s) {bad} out of range "
                    f"(file has {self.num_row_groups})")
            self._groups = [int(g) for g in row_groups]
        self._next_group = 0        # position in self._groups

    def _read_schema(self) -> List[_Leaf]:
        n = self._lib.pqr_num_leaves(self._h)
        out = []
        ints = [ctypes.c_int32() for _ in range(7)]
        for i in range(n):
            buf = ctypes.create_string_buffer(1024)
            rc = self._lib.pqr_leaf_info(self._h, i, buf, 1024,
                                         *[ctypes.byref(x) for x in ints])
            if rc != 0:
                raise ValueError("schema read failed")
            phys, tl, conv, scale, prec, opt, flat = (x.value for x in ints)
            kind = self._lib.pqr_leaf_kind(self._h, i)
            anc, max_def = (), 0
            nodes, max_rep = (), 0
            anc_overflow = False
            if kind == 2:
                md = ctypes.c_int32()
                buf_anc = (ctypes.c_int32 * 16)()
                n_anc = self._lib.pqr_leaf_struct_info(
                    self._h, i, ctypes.byref(md), buf_anc, 16)
                if n_anc < 0 or n_anc > 16:
                    kind = 3            # too deep / inconsistent: skip
                else:
                    anc, max_def = tuple(buf_anc[:n_anc]), md.value
            if kind in (2, 4):
                # kind-2 leaves need the generalized descriptor too: a mixed
                # top-level field (STRUCT with both plain and list-bearing
                # members) assembles every member through the nested builder
                md, mr = ctypes.c_int32(), ctypes.c_int32()
                buf_desc = (ctypes.c_int32 * 64)()
                n_ints = self._lib.pqr_leaf_ancestry(
                    self._h, i, ctypes.byref(md), ctypes.byref(mr),
                    buf_desc, 64)
                if n_ints < 0 or n_ints > 64 or n_ints % 4 != 0:
                    if kind == 4:
                        kind = 3
                    else:
                        # a kind-2 member without a descriptor cannot join a
                        # mixed nested group: poison the field below rather
                        # than crash the builder mid-tree
                        anc_overflow = True
                else:
                    max_def, max_rep = md.value, mr.value
                    parsed = []
                    for k in range(n_ints // 4):
                        t, a, b, segs = (buf_desc[4 * k], buf_desc[4 * k + 1],
                                         buf_desc[4 * k + 2],
                                         buf_desc[4 * k + 3])
                        if t == 2:      # MAP -> list + implicit element struct
                            parsed.append(_Node("list", a, b, segs))
                            parsed.append(_Node("struct", -1, -1, 0))
                        else:
                            parsed.append(_Node("struct" if t == 0 else "list",
                                                a, b, segs))
                    nodes = tuple(parsed)
            leaf = _Leaf(i, buf.value.decode(), phys, tl, conv, scale,
                         prec, bool(opt), bool(flat), kind == 1,
                         kind == 2, anc, max_def)
            leaf.kind = kind
            leaf.anc_overflow = anc_overflow
            if kind in (2, 4):
                leaf.nodes = nodes
                leaf.max_rep = max_rep
            if kind == 4:
                leaf.display = leaf.name.split(".")[0]
            out.append(leaf)
        # an unsupported leaf poisons its whole top-level field: surfacing a
        # struct with silently missing members would misrepresent the schema
        bad = {l.name.split(".")[0] for l in out if l.kind == 3}
        # a kind-2 member without an ancestry descriptor cannot assemble
        # inside a mixed nested field — poison that field too
        nested4 = {l.display for l in out if l.kind == 4}
        bad |= {l.display for l in out
                if l.anc_overflow and l.display in nested4}
        return [l for l in out
                if (l.flat or l.is_list or l.is_struct_member or l.kind == 4)
                and l.display not in bad]

    @property
    def column_names(self) -> List[str]:
        names, seen = [], set()
        for l in self._leaves:
            if l.display not in seen:
                seen.add(l.display)
                names.append(l.display)
        return names

    def has_next(self) -> bool:
        return self._next_group < len(self._groups)

    def read_chunk(self) -> Table:
        """Decode the next (selected) row group into a Table."""
        if not self.has_next():
            raise StopIteration("no more row groups")
        rg = self._groups[self._next_group]
        self._next_group += 1
        return self._read_group(rg)

    def read_all(self) -> Table:
        """Decode every remaining row group into one Table."""
        chunks = []
        while self.has_next():
            chunks.append(self.read_chunk())
        if len(chunks) == 1:
            return chunks[0]
        if not chunks:
            return Table(self._empty_columns(), names=self.column_names)
        return _concat_tables(chunks)

    def _empty_column(self, leaf: _Leaf) -> Column:
        import jax.numpy as jnp
        elem = _assemble(leaf, np.zeros(0, np.uint8), np.zeros(0, np.int32),
                         np.ones(0, np.uint8), 0, 0)
        if leaf.is_list:
            return Column.make_list(jnp.asarray(np.zeros(1, np.int32)), elem)
        return elem

    def _empty_columns(self) -> List[Column]:
        cols, done = [], set()
        for leaf in self._leaves:
            if leaf.kind == 4 or leaf.display in self._nested_displays:
                if leaf.display not in done:
                    done.add(leaf.display)
                    group = [l for l in self._leaves
                             if l.display == leaf.display]
                    decoded = [_NLeaf(l, l.name.split("."),
                                      np.zeros(0, np.uint8),
                                      np.zeros(0, np.int32),
                                      np.zeros(0, np.int16),
                                      np.zeros(0, np.int16), 0)
                               for l in group]
                    cols.append(_build_nested(
                        decoded, 0, 0,
                        [np.zeros(0, np.int64)] * len(group), 0))
                continue
            if leaf.is_struct_member:
                if leaf.display not in done:
                    done.add(leaf.display)
                    members = [(l, self._empty_column(l), np.zeros(0, np.uint8))
                               for l in self._leaves
                               if l.is_struct_member and l.display == leaf.display]
                    cols.append(_build_struct_tree(members, 1, 0))
                continue
            cols.append(self._empty_column(leaf))
        return cols

    def _read_group(self, rg: int) -> Table:
        import jax.numpy as jnp  # noqa: F401  (Column builds device arrays)
        n_rows = self._lib.pqr_row_group_num_rows(self._h, rg)
        cols = []
        done_structs = set()
        for leaf in self._leaves:
            if leaf.kind == 4 or leaf.display in self._nested_displays:
                # generalized nesting: assemble the whole top-level field
                # (a mixed struct pulls its plain members through this path
                # too, so every member shares one slot-stream model)
                if leaf.display not in done_structs:
                    done_structs.add(leaf.display)
                    group = [l for l in self._leaves
                             if l.display == leaf.display]
                    cols.append(self._read_nested_chunk(rg, group, n_rows))
                continue
            if leaf.is_struct_member:
                if leaf.display not in done_structs:
                    done_structs.add(leaf.display)
                    members = [l for l in self._leaves
                               if l.is_struct_member and l.display == leaf.display]
                    cols.append(self._read_struct_chunk(rg, members, n_rows))
                continue
            if leaf.is_list:
                cols.append(self._read_list_chunk(rg, leaf, n_rows))
                continue
            nbytes = ctypes.c_int64()
            present = ctypes.c_int64()
            rc = self._lib.pqr_read_column(self._h, rg, leaf.idx, None,
                                           ctypes.byref(nbytes), None, None,
                                           ctypes.byref(present))
            if rc != 0:
                raise ValueError(self._lib.pqr_last_error().decode())
            values = np.zeros(max(nbytes.value, 1), np.uint8)
            lengths = np.zeros(max(present.value, 1), np.int32)
            defined = np.zeros(max(n_rows, 1), np.uint8)
            rc = self._lib.pqr_read_column(
                self._h, rg, leaf.idx,
                values.ctypes.data_as(ctypes.c_void_p), ctypes.byref(nbytes),
                lengths.ctypes.data_as(ctypes.c_void_p),
                defined.ctypes.data_as(ctypes.c_void_p),
                ctypes.byref(present))
            if rc != 0:
                raise ValueError(self._lib.pqr_last_error().decode())
            cols.append(_assemble(leaf, values[:nbytes.value],
                                  lengths[:present.value],
                                  defined[:n_rows], n_rows, present.value))
        return Table(cols, names=self.column_names)

    def _read_struct_chunk(self, rg: int, members: List[_Leaf],
                           n_rows: int) -> Column:
        """Assemble one STRUCT column from its member leaves: each member
        decodes like a flat column plus its raw def levels; a struct node at
        def threshold D is null on rows where def < D (any member's levels
        give identical ancestor validity)."""
        import jax.numpy as jnp
        decoded = []
        for leaf in members:
            nbytes = ctypes.c_int64()
            present = ctypes.c_int64()
            rc = self._lib.pqr_read_column(self._h, rg, leaf.idx, None,
                                           ctypes.byref(nbytes), None, None,
                                           ctypes.byref(present))
            if rc != 0:
                raise ValueError(self._lib.pqr_last_error().decode())
            defs = np.zeros(max(n_rows, 1), np.uint8)
            if leaf.max_def > 0:
                rc = self._lib.pqr_read_def_levels(
                    self._h, rg, leaf.idx,
                    defs.ctypes.data_as(ctypes.c_void_p))
                if rc != 0:
                    raise ValueError(self._lib.pqr_last_error().decode())
            else:
                defs[:] = leaf.max_def
            values = np.zeros(max(nbytes.value, 1), np.uint8)
            lengths = np.zeros(max(present.value, 1), np.int32)
            defined = np.zeros(max(n_rows, 1), np.uint8)
            rc = self._lib.pqr_read_column(
                self._h, rg, leaf.idx,
                values.ctypes.data_as(ctypes.c_void_p), ctypes.byref(nbytes),
                lengths.ctypes.data_as(ctypes.c_void_p),
                defined.ctypes.data_as(ctypes.c_void_p),
                ctypes.byref(present))
            if rc != 0:
                raise ValueError(self._lib.pqr_last_error().decode())
            col = _assemble(leaf, values[:nbytes.value],
                            lengths[:present.value], defined[:n_rows],
                            n_rows, present.value)
            decoded.append((leaf, col, defs[:n_rows]))
        return _build_struct_tree(decoded, level=1, n_rows=n_rows)

    def _read_nested_buffers(self, rg: int, leaf: _Leaf, n_rows: int):
        """(values, lengths, defs, reps, present) for one leaf of a nested
        field. Kind-4 leaves export raw level streams; kind-2 members of a
        mixed struct synthesize reps == 0 over n_rows slots so both plug
        into the same Dremel builder."""
        if leaf.kind == 4:
            nbytes = ctypes.c_int64()
            present = ctypes.c_int64()
            slots = ctypes.c_int64()

            def call(values, lengths, defs, reps):
                return self._lib.pqr_read_nested_column(
                    self._h, rg, leaf.idx, values, ctypes.byref(nbytes),
                    lengths, defs, reps, ctypes.byref(slots),
                    ctypes.byref(present))

            if call(None, None, None, None) != 0:
                raise ValueError(self._lib.pqr_last_error().decode())
            values = np.zeros(max(nbytes.value, 1), np.uint8)
            lengths = np.zeros(max(present.value, 1), np.int32)
            defs = np.zeros(max(slots.value, 1), np.uint8)
            reps = np.zeros(max(slots.value, 1), np.uint8)
            if call(values.ctypes.data_as(ctypes.c_void_p),
                    lengths.ctypes.data_as(ctypes.c_void_p),
                    defs.ctypes.data_as(ctypes.c_void_p),
                    reps.ctypes.data_as(ctypes.c_void_p)) != 0:
                raise ValueError(self._lib.pqr_last_error().decode())
            s = slots.value
            return (values[:nbytes.value], lengths[:present.value],
                    defs[:s].astype(np.int16), reps[:s].astype(np.int16),
                    int(present.value))
        # kind-2 member: dense read + raw def levels, reps all zero
        nbytes = ctypes.c_int64()
        present = ctypes.c_int64()
        rc = self._lib.pqr_read_column(self._h, rg, leaf.idx, None,
                                       ctypes.byref(nbytes), None, None,
                                       ctypes.byref(present))
        if rc != 0:
            raise ValueError(self._lib.pqr_last_error().decode())
        defs = np.full(max(n_rows, 1), leaf.max_def, np.int16)
        if leaf.max_def > 0:
            d8 = np.zeros(max(n_rows, 1), np.uint8)
            rc = self._lib.pqr_read_def_levels(
                self._h, rg, leaf.idx, d8.ctypes.data_as(ctypes.c_void_p))
            if rc != 0:
                raise ValueError(self._lib.pqr_last_error().decode())
            defs = d8.astype(np.int16)
        values = np.zeros(max(nbytes.value, 1), np.uint8)
        lengths = np.zeros(max(present.value, 1), np.int32)
        defined = np.zeros(max(n_rows, 1), np.uint8)
        rc = self._lib.pqr_read_column(
            self._h, rg, leaf.idx,
            values.ctypes.data_as(ctypes.c_void_p), ctypes.byref(nbytes),
            lengths.ctypes.data_as(ctypes.c_void_p),
            defined.ctypes.data_as(ctypes.c_void_p), ctypes.byref(present))
        if rc != 0:
            raise ValueError(self._lib.pqr_last_error().decode())
        return (values[:nbytes.value], lengths[:present.value],
                defs[:n_rows], np.zeros(n_rows, np.int16),
                int(present.value))

    def _read_nested_chunk(self, rg: int, group: List[_Leaf],
                           n_rows: int) -> Column:
        """Assemble one generalized-nested top-level field: read every
        leaf's dense values + (def, rep) streams, then run the multi-level
        Dremel reassembly (numpy, vectorized over level slots)."""
        decoded = []
        for leaf in group:
            values, lengths, defs, reps, present = \
                self._read_nested_buffers(rg, leaf, n_rows)
            decoded.append(_NLeaf(leaf, leaf.name.split("."), values,
                                  lengths, defs, reps, present))
        ctxs = [np.nonzero(nl.reps == 0)[0] for nl in decoded]
        for nl, ctx in zip(decoded, ctxs):
            if len(ctx) != n_rows:
                raise ValueError(
                    f"nested column {nl.leaf.display!r}: row count mismatch "
                    f"({len(ctx)} vs {n_rows})")
        return _build_nested(decoded, 0, 0, ctxs, 0)

    def _read_list_chunk(self, rg: int, leaf: _Leaf, n_rows: int) -> Column:
        import jax.numpy as jnp
        nbytes = ctypes.c_int64()
        present = ctypes.c_int64()
        slots = ctypes.c_int64()
        rows = ctypes.c_int64()

        def call(values, lengths, defined, counts, valid):
            return self._lib.pqr_read_list_column(
                self._h, rg, leaf.idx, values, ctypes.byref(nbytes),
                lengths, defined, ctypes.byref(slots), ctypes.byref(present),
                counts, valid, ctypes.byref(rows))

        if call(None, None, None, None, None) != 0:
            raise ValueError(self._lib.pqr_last_error().decode())
        values = np.zeros(max(nbytes.value, 1), np.uint8)
        lengths = np.zeros(max(present.value, 1), np.int32)
        defined = np.zeros(max(slots.value, 1), np.uint8)
        counts = np.zeros(max(rows.value, 1), np.int32)
        valid = np.zeros(max(rows.value, 1), np.uint8)
        rc = call(values.ctypes.data_as(ctypes.c_void_p),
                  lengths.ctypes.data_as(ctypes.c_void_p),
                  defined.ctypes.data_as(ctypes.c_void_p),
                  counts.ctypes.data_as(ctypes.c_void_p),
                  valid.ctypes.data_as(ctypes.c_void_p))
        if rc != 0:
            raise ValueError(self._lib.pqr_last_error().decode())
        if rows.value != n_rows:
            raise ValueError(
                f"list column {leaf.display!r}: row count mismatch "
                f"({rows.value} vs {n_rows})")
        elem = _assemble(leaf, values[:nbytes.value],
                         lengths[:present.value], defined[:slots.value],
                         int(slots.value), int(present.value))
        offsets = np.zeros(n_rows + 1, np.int32)
        np.cumsum(counts[:n_rows], out=offsets[1:])
        validity = (jnp.asarray(valid[:n_rows] != 0)
                    if (valid[:n_rows] == 0).any() else None)
        return Column.make_list(jnp.asarray(offsets), elem, validity)

    def close(self) -> None:
        if self._h:
            self._lib.pqr_free(self._h)
            self._h = 0
        buf = getattr(self, "_buf", None)
        if buf is not None and hasattr(buf, "close"):
            buf.close()
        self._buf = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _spread(dense: np.ndarray, defined: np.ndarray, fill=0) -> np.ndarray:
    """Scatter `dense` present-values into full-length rows (nulls = fill)."""
    n = defined.shape[0]
    out = np.full((n,) + dense.shape[1:], fill, dense.dtype)
    out[defined != 0] = dense
    return out


def _assemble(leaf: _Leaf, values: np.ndarray, lengths: np.ndarray,
              defined: np.ndarray, n_rows: int, present: int) -> Column:
    import jax.numpy as jnp

    dt = leaf.dtype()
    validity = None
    # struct members: a required member under an optional ancestor still has
    # undefined rows (the ancestor was null) — its child column must carry
    # that validity so direct child consumers see nulls, like cudf; kind-4
    # elements likewise (null list/struct ancestors surface as def<max_def)
    nullable = (leaf.optional or getattr(leaf, "is_struct_member", False)
                or getattr(leaf, "kind", 0) == 4)
    if nullable and (defined == 0).any():
        validity = jnp.asarray(defined != 0)

    if dt.kind == dtypes.Kind.STRING:
        full_lens = _spread(lengths, defined)
        offsets = np.zeros(n_rows + 1, np.int32)
        np.cumsum(full_lens, out=offsets[1:])
        return Column(dtype=dt, length=n_rows, data=jnp.asarray(values),
                      offsets=jnp.asarray(offsets), validity=validity)

    if dt.kind == dtypes.Kind.DECIMAL128:
        # FLBA big-endian two's-complement → (n, 4) uint32 LE limbs
        w = leaf.type_length
        raw = values.reshape(present, w)
        ext = np.zeros((present, 16), np.uint8)
        sign = (raw[:, 0] & 0x80) != 0
        ext[sign] = 0xFF
        ext[:, 16 - w:] = raw
        le = ext[:, ::-1].copy()                      # little-endian bytes
        limbs = le.view(np.uint32).reshape(present, 4)
        data = jnp.asarray(_spread(limbs, defined))
        return Column(dtype=dt, length=n_rows, data=data, validity=validity)

    if leaf.phys == _PT_INT96:
        # 12-byte legacy timestamp: u64 nanos-of-day + u32 julian day
        raw = values.reshape(present, 12)
        nanos = raw[:, :8].copy().view(np.int64).reshape(present)
        jday = raw[:, 8:].copy().view(np.int32).reshape(present).astype(np.int64)
        micros = (jday - 2440588) * 86400_000_000 + nanos // 1000
        data = jnp.asarray(_spread(micros, defined))
        return Column(dtype=dt, length=n_rows, data=data, validity=validity)

    np_dt = {dtypes.Kind.BOOL: np.uint8, dtypes.Kind.INT32: np.int32,
             dtypes.Kind.DATE32: np.int32, dtypes.Kind.DECIMAL32: np.int32,
             dtypes.Kind.INT64: np.int64, dtypes.Kind.TIMESTAMP_US: np.int64,
             dtypes.Kind.TIMESTAMP_MS: np.int64,
             dtypes.Kind.DECIMAL64: np.int64,
             dtypes.Kind.FLOAT32: np.float32,
             dtypes.Kind.FLOAT64: np.float64}[dt.kind]
    dense = values.view(np_dt) if dt.kind != dtypes.Kind.BOOL else values
    dense = dense.reshape(present)
    full = _spread(dense, defined)
    if dt.kind == dtypes.Kind.BOOL:
        full = full != 0
    return Column(dtype=dt, length=n_rows, data=jnp.asarray(full),
                  validity=validity)


def _build_struct_tree(decoded, level: int, n_rows: int) -> Column:
    """decoded: [(leaf, element Column, def_levels)]; group by the path
    segment at `level` (level 0 is the struct column itself's name)."""
    import jax.numpy as jnp

    first_leaf, _, first_defs = decoded[0]
    segs = first_leaf.name.split(".")
    # validity of THIS node (ancestor index level-1): -1 = required group
    thresh = first_leaf.ancestor_defs[level - 1]
    validity = None
    if thresh >= 0 and (first_defs < thresh).any():
        validity = jnp.asarray(first_defs >= thresh)

    fields = {}
    for leaf, col, defs in decoded:
        parts = leaf.name.split(".")
        key = parts[level]
        if len(parts) == level + 1:
            fields[key] = col              # direct member
        else:                              # deeper nesting: recurse per key
            fields.setdefault(key, []).append((leaf, col, defs))
    out_fields = {}
    for key, val in fields.items():
        if isinstance(val, list):
            out_fields[key] = _build_struct_tree(val, level + 1, n_rows)
        else:
            out_fields[key] = val
    dt = dtypes.DType(dtypes.Kind.STRUCT,
                      children=tuple(c.dtype for c in out_fields.values()),
                      field_names=tuple(out_fields.keys()))
    return Column(dtype=dt, length=n_rows, validity=validity,
                  children=tuple(out_fields.values()))


class _NLeaf(NamedTuple):
    """One decoded leaf of a nested field: dense present values plus the
    full (def, rep) level streams."""
    leaf: "_Leaf"
    parts: List[str]          # dotted path segments
    values: np.ndarray
    lengths: np.ndarray
    defs: np.ndarray          # (slots,) int16
    reps: np.ndarray          # (slots,) int16
    present: int


def _build_nested(group: List[_NLeaf], ni: int, si: int,
                  ctxs: List[np.ndarray], depth: int) -> Column:
    """Multi-level Dremel reassembly (numpy over level slots, not rows).

    The classic level semantics: a slot with repetition level r continues
    the depth-r list, so it starts a new element at every depth > r; an
    element of the depth-k list exists iff rep <= k AND def >= dar_k (def
    below dar_k is an empty/null list placeholder). Offsets at each depth
    fall out of one boolean mask + np.add.reduceat over the parent entry
    boundaries; struct/list validity is one def-threshold compare. This is
    the whole reference cudf preprocess_levels pipeline as ~60 lines of
    vectorized host code.

    group: sibling leaves of one subtree (identical nodes[0..ni)).
    ni/si: next ancestry node / next unconsumed path segment.
    ctxs:  per-leaf slot indices of the current context entries (all the
           same logical entries, one index array per leaf's own stream).
    depth: repetition depth consumed so far (k of the next list = depth+1).
    """
    import jax.numpy as jnp
    rep0 = group[0]
    nodes = rep0.leaf.nodes
    n_entries = len(ctxs[0])

    if ni == len(nodes):
        # element leaf
        assert len(group) == 1, [nl.leaf.name for nl in group]
        nl, ctx = group[0], ctxs[0]
        defined = (nl.defs[ctx] == nl.leaf.max_def).astype(np.uint8)
        return _assemble(nl.leaf, nl.values, nl.lengths, defined,
                         n_entries, int(defined.sum()))

    node = nodes[ni]
    if node.kind == "struct":
        validity = None
        if node.a >= 0:
            dv = rep0.defs[ctxs[0]] >= node.a
            if not dv.all():
                validity = jnp.asarray(dv)
        fields: "OrderedDict[str, tuple]" = OrderedDict()
        for nl, ctx in zip(group, ctxs):
            key = nl.parts[si + node.segs]
            fields.setdefault(key, ([], []))
            fields[key][0].append(nl)
            fields[key][1].append(ctx)
        children = OrderedDict(
            (k, _build_nested(nls, ni + 1, si + node.segs, cx, depth))
            for k, (nls, cx) in fields.items())
        dt = dtypes.DType(dtypes.Kind.STRUCT,
                          children=tuple(c.dtype for c in children.values()),
                          field_names=tuple(children.keys()))
        return Column(dtype=dt, length=n_entries, validity=validity,
                      children=tuple(children.values()))

    # list node at repetition depth k
    k = depth + 1
    ctx0 = ctxs[0]
    elem_mask = (rep0.reps <= k) & (rep0.defs >= node.a)
    if n_entries:
        counts = np.add.reduceat(elem_mask.astype(np.int32), ctx0)
    else:
        counts = np.zeros(0, np.int32)
    offsets = np.zeros(n_entries + 1, np.int32)
    np.cumsum(counts, out=offsets[1:])
    validity = None
    if node.b >= 0:
        dv = rep0.defs[ctx0] >= node.b
        if not dv.all():
            validity = jnp.asarray(dv)
    new_ctxs = [np.nonzero((nl.reps <= k) & (nl.defs >= node.a))[0]
                for nl in group]
    child = _build_nested(group, ni + 1, si + node.segs, new_ctxs, k)
    return Column.make_list(jnp.asarray(offsets), child, validity)


def _concat_tables(tables: List[Table]) -> Table:
    from ..ops.copying import concat_tables
    return concat_tables(tables)


def read_parquet(source: Union[str, bytes],
                 columns: Optional[Sequence[str]] = None,
                 row_groups: Optional[Sequence[int]] = None) -> Table:
    """Read a whole parquet file into a Table (selective decode via
    `columns`, row-group selection via `row_groups` — stats-driven pruning
    composes through parquet_footer.read_footer_stats + select_row_groups;
    the reference flow's ParquetFooter.read_and_filter splice also still
    works upstream)."""
    with ParquetChunkedReader(source, columns=columns,
                              row_groups=row_groups) as r:
        return r.read_all()


# ---- stats-driven row-group pruning -----------------------------------------

def _proves_empty(st, op: str, val) -> bool:
    """True iff `col <op> val` matches NO row of a chunk with stats `st` —
    provable, never guessed: any missing/undecodable stat, any null in the
    chunk (null rows carry fill values the row-wise Filter above still
    sees), or any type mismatch returns False (keep the group)."""
    if st is None or st.min is None or st.max is None:
        return False
    if st.null_count != 0:          # None (unknown) or > 0: cannot prove
        return False
    if isinstance(val, str):
        val = val.encode()          # UTF8 stats order == byte order
    if isinstance(val, (bytes, bytearray)) != isinstance(st.min, bytes):
        return False
    try:
        if op == "<":
            return not st.min < val
        if op == "<=":
            return not st.min <= val
        if op == ">":
            return not st.max > val
        if op == ">=":
            return not st.max >= val
        if op == "==":
            return val < st.min or val > st.max
    except TypeError:
        return False
    return False


def select_row_groups(stats, conjuncts,
                      num_row_groups: int) -> Tuple[List[int], int]:
    """(kept row-group indices, pruned count) under min/max pruning.

    `conjuncts` is a list of (column, op, literal) triples that are ANDed
    above the scan (plan/optimizer.pruning_conjuncts extracts them); a
    group is dropped only when some conjunct PROVES it holds no matching
    row, so pruning is parity-exact with the retained Filter. `stats` of
    None (unparseable footer) keeps everything."""
    if stats is None or not conjuncts:
        return list(range(num_row_groups)), 0
    kept = []
    for rg in stats:
        if any(_proves_empty(rg.columns.get(name), op, val)
               for name, op, val in conjuncts):
            continue
        kept.append(rg.index)
    return kept, num_row_groups - len(kept)


class ParquetSource:
    """A parquet file/bytes source a plan `Scan` binds to INSTEAD of a
    materialized Table (`PlanBuilder.scan(..., parquet=...)`, or passed as
    an `inputs=` value at execute()). Schema is read from the footer at
    construction, so plans over sources validate at build time; data stays
    on disk until the executor streams it — the streamable prefix of a
    plan runs morsel-at-a-time (docs/io.md), so bigger-than-budget tables
    feed the spill/admission machinery instead of materializing up front.
    """

    is_streaming_source = True

    def __init__(self, source: Union[str, bytes],
                 chunk_rows: Optional[int] = None):
        self.source = source
        self.chunk_rows = chunk_rows      # per-source override of
        #                                   SPARK_RAPIDS_TPU_IO_CHUNK_ROWS
        with ParquetChunkedReader(source) as r:
            self.names = tuple(r.column_names)
            self.num_rows = int(r.num_rows)
            self.num_row_groups = int(r.num_row_groups)
            dts = {}
            for leaf in r._leaves:
                if leaf.display not in dts:
                    try:
                        dts[leaf.display] = leaf.dtype()
                    except TypeError:
                        dts[leaf.display] = None
            self._dtypes = dts
        self._stats = False               # lazy; None = unparseable footer

    def __repr__(self):
        name = self.source if isinstance(self.source, str) else "<bytes>"
        return (f"ParquetSource({name!r}, rows={self.num_rows}, "
                f"row_groups={self.num_row_groups})")

    @property
    def has_floats(self) -> bool:
        """Any floating column — gates reductions whose result depends on
        accumulation order (streaming partial aggregation, build_side)."""
        return any(dt is not None and dt.is_floating
                   for dt in self._dtypes.values())

    @property
    def stats(self):
        """Per-row-group footer statistics, read once; None when the footer
        stats cannot be parsed (pruning then keeps every group)."""
        if self._stats is False:
            from .parquet_footer import read_footer_stats
            try:
                self._stats = read_footer_stats(self.source)
            except Exception:
                self._stats = None
        return self._stats

    def select_groups(self, conjuncts=(),
                      columns: Optional[Sequence[str]] = None):
        """(kept group indices, pruned count, bytes skipped). Bytes skipped
        counts compressed column-chunk bytes never decoded: pruned groups
        entirely, plus non-projected columns of kept groups."""
        stats = self.stats
        kept, pruned = select_row_groups(stats, list(conjuncts or ()),
                                         self.num_row_groups)
        skipped = 0
        if stats is not None:
            sel = None if columns is None else set(columns)
            kept_set = set(kept)
            for rg in stats:
                for st in rg.columns.values():
                    if rg.index in kept_set and (sel is None
                                                 or st.column in sel):
                        continue
                    skipped += st.total_compressed_size
        return kept, pruned, skipped

    def chunks(self, columns: Optional[Sequence[str]] = None,
               row_groups: Optional[Sequence[int]] = None,
               chunk_rows: Optional[int] = None):
        """Generator of morsel Tables: one decoded row group per chunk,
        split into <= chunk_rows slices when a bound is given. An empty
        selection yields the typed empty table once, so downstream
        operators always see the scan's schema."""
        from ..ops.copying import slice_table
        with ParquetChunkedReader(self.source, columns=columns,
                                  row_groups=row_groups) as r:
            if not r.has_next():
                yield r.read_all()        # typed empty (_empty_columns)
                return
            while r.has_next():
                t = r.read_chunk()
                if chunk_rows and t.num_rows > chunk_rows:
                    for off in range(0, t.num_rows, chunk_rows):
                        yield slice_table(t, off,
                                          min(off + chunk_rows, t.num_rows))
                else:
                    yield t

    def read_all(self, columns: Optional[Sequence[str]] = None,
                 row_groups: Optional[Sequence[int]] = None) -> Table:
        """Materialize (a selection of) the source as one Table, through
        the admitted read path — the working-set estimate crosses the
        active DeviceSession's budget like any other op, so an over-budget
        materialization surfaces as the arbiter's OOM contract instead of
        an allocator crash."""
        from ..io import read_parquet as admitted_read
        return admitted_read(self.source, columns=columns,
                             row_groups=row_groups)
