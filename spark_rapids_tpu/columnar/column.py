"""HBM-resident columnar substrate (Arrow layout) for the TPU engine.

Equivalent role to cudf's `column`/`column_view` + the JNI handle surface in the
reference (/root/reference/src/main/java/.../CastStrings.java:155-165 passes
`long` view handles; ownership contract described in SURVEY.md §1). Here a
column is a JAX pytree of dense device arrays, so whole tables flow through
`jax.jit`/`shard_map` unchanged:

- fixed-width column:  data (n,) storage-dtype, validity (n,) bool or None
- string column:       chars (total,) uint8, offsets (n+1,) int32, validity
- decimal128 column:   data (n, 4) uint32 little-endian limbs, validity
- list column:         offsets (n+1,) int32, one child column, validity
- struct column:       children columns, validity

Validity is an unpacked bool vector (vectorizes on the VPU; pack/unpack to
Arrow bitmask lives in utils/bitmask.py for wire parity — the reference ORs
packed bitmasks in utilities.cu:32).

Strings on a fixed-shape-loving XLA stack: every string kernel here is the
two-pass (measure → gather) pattern the reference uses for its strings output
(parse_uri.cu:774/854), and *input* parsing uses a padded (n, max_len) uint8
matrix built with one gather (`padded_chars`), with max_len rounded to a
bucket so jit recompiles are bounded.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes
from ..dtypes import DType, Kind


def _round_bucket(n: int, minimum: int = 8) -> int:
    """Round up to a power of two so padded-string jit shapes are bounded."""
    b = minimum
    while b < n:
        b *= 2
    return b


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Column:
    """One logical column. Immutable; all mutation returns new columns."""
    dtype: DType
    length: int
    data: Optional[jnp.ndarray] = None       # primary buffer (absent for struct/list)
    validity: Optional[jnp.ndarray] = None   # (n,) bool; None == all valid
    offsets: Optional[jnp.ndarray] = None    # (n+1,) int32 for string/list
    children: Tuple["Column", ...] = ()

    # ---- pytree protocol --------------------------------------------------------
    def tree_flatten(self):
        leaves = (self.data, self.validity, self.offsets, self.children)
        aux = (self.dtype, self.length)
        return leaves, aux

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        data, validity, offsets, children = leaves
        dtype, length = aux
        return cls(dtype=dtype, length=length, data=data, validity=validity,
                   offsets=offsets, children=children)

    # ---- basic accessors --------------------------------------------------------
    def __len__(self) -> int:
        return self.length

    @property
    def null_mask(self) -> jnp.ndarray:
        """(n,) bool, True where valid."""
        if self.validity is None:
            return jnp.ones((self.length,), dtype=jnp.bool_)
        return self.validity

    def null_count(self) -> int:
        if self.validity is None:
            return 0
        return int(self.length - jnp.sum(self.validity))

    def has_nulls(self) -> bool:
        return self.null_count() > 0

    def with_validity(self, validity: Optional[jnp.ndarray]) -> "Column":
        return dataclasses.replace(self, validity=validity)

    # ---- string helpers ---------------------------------------------------------
    def string_lengths(self) -> jnp.ndarray:
        assert self.dtype.is_string
        return (self.offsets[1:] - self.offsets[:-1]).astype(jnp.int32)

    def max_string_length(self) -> int:
        """Host-side max row length (concrete; forces a sync)."""
        assert self.dtype.is_string
        if self.length == 0:
            return 0
        return int(jnp.max(self.string_lengths()))

    def padded_chars(self, pad_to: Optional[int] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Return ((n, L) uint8 padded char matrix, (n,) int32 lengths).

        L is `pad_to` or the power-of-two bucket >= max row length. Rows are
        zero-padded. This is the canonical input form for the vectorized
        parsing kernels (the TPU-native analogue of the reference's
        thread-per-row char loops, cast_string.cu:171).
        """
        assert self.dtype.is_string
        lens = self.string_lengths()
        if pad_to is None:
            pad_to = _round_bucket(max(1, self.max_string_length()))
        elif not isinstance(lens, jax.core.Tracer):
            # a too-small pad silently truncates rows, corrupting every
            # downstream kernel - reject when we can see concrete lengths
            m = self.max_string_length()
            if m > pad_to:
                raise ValueError(
                    f"pad_to={pad_to} is smaller than the longest string ({m})")
        starts = self.offsets[:-1]
        idx = starts[:, None] + jnp.arange(pad_to, dtype=jnp.int32)[None, :]
        in_range = jnp.arange(pad_to, dtype=jnp.int32)[None, :] < lens[:, None]
        chars = self.data if self.data.shape[0] > 0 else jnp.zeros((1,), jnp.uint8)
        gathered = jnp.take(chars, jnp.clip(idx, 0, chars.shape[0] - 1), axis=0)
        return jnp.where(in_range, gathered, jnp.uint8(0)), lens

    # ---- host interop -----------------------------------------------------------
    @staticmethod
    def from_numpy(arr: np.ndarray, dtype: Optional[DType] = None,
                   validity: Optional[np.ndarray] = None) -> "Column":
        if dtype is None:
            dtype = _np_to_dtype(arr.dtype)
        data = jnp.asarray(arr, dtype=dtype.storage_dtype())
        v = None if validity is None else jnp.asarray(validity, dtype=jnp.bool_)
        return Column(dtype=dtype, length=int(arr.shape[0]), data=data, validity=v)

    @staticmethod
    def from_pylist(values: Sequence, dtype: DType) -> "Column":
        """Build a column from a Python list; None entries become nulls."""
        n = len(values)
        valid = np.array([v is not None for v in values], dtype=bool)
        has_nulls = not valid.all()
        if dtype.is_string:
            encoded = [(v.encode() if isinstance(v, str) else (v or b"")) if v is not None else b""
                       for v in values]
            offs = np.zeros(n + 1, dtype=np.int32)
            np.cumsum([len(e) for e in encoded], out=offs[1:])
            chars = np.frombuffer(b"".join(encoded), dtype=np.uint8).copy()
            return Column(
                dtype=dtype, length=n,
                data=jnp.asarray(chars),
                offsets=jnp.asarray(offs),
                validity=jnp.asarray(valid) if has_nulls else None)
        if dtype.kind == Kind.DECIMAL128:
            limbs = np.zeros((n, 4), dtype=np.uint32)
            for i, v in enumerate(values):
                if v is None:
                    continue
                iv = int(v) & ((1 << 128) - 1)
                for j in range(4):
                    limbs[i, j] = (iv >> (32 * j)) & 0xFFFFFFFF
            return Column(dtype=dtype, length=n, data=jnp.asarray(limbs),
                          validity=jnp.asarray(valid) if has_nulls else None)
        np_dt = np.dtype(dtype.storage_dtype().__name__ if not isinstance(
            dtype.storage_dtype(), np.dtype) else dtype.storage_dtype())
        filled = [0 if v is None else v for v in values]
        if dtype.kind == Kind.BOOL:
            arr = np.array([bool(v) for v in filled], dtype=np.bool_)
        else:
            arr = np.array(filled).astype(np_dt)
        return Column(dtype=dtype, length=n, data=jnp.asarray(arr),
                      validity=jnp.asarray(valid) if has_nulls else None)

    def to_pylist(self) -> List:
        """Materialize to host Python values (None for nulls). Testing aid."""
        valid = np.asarray(self.null_mask)
        if self.dtype.is_string:
            chars = np.asarray(self.data, dtype=np.uint8).tobytes()
            offs = np.asarray(self.offsets)
            out = []
            for i in range(self.length):
                if not valid[i]:
                    out.append(None)
                else:
                    out.append(chars[offs[i]:offs[i + 1]].decode("utf-8", errors="replace"))
            return out
        if self.dtype.kind == Kind.DECIMAL128:
            limbs = np.asarray(self.data, dtype=np.uint64)
            out = []
            for i in range(self.length):
                if not valid[i]:
                    out.append(None)
                else:
                    u = int(limbs[i, 0]) | (int(limbs[i, 1]) << 32) | \
                        (int(limbs[i, 2]) << 64) | (int(limbs[i, 3]) << 96)
                    if u >= (1 << 127):
                        u -= (1 << 128)
                    out.append(u)
            return out
        if self.dtype.kind == Kind.LIST:
            offs = np.asarray(self.offsets)
            child = self.children[0].to_pylist()
            return [None if not valid[i] else child[offs[i]:offs[i + 1]]
                    for i in range(self.length)]
        if self.dtype.kind == Kind.STRUCT:
            kids = [c.to_pylist() for c in self.children]
            names = self.dtype.field_names or tuple(str(i) for i in range(len(kids)))
            return [None if not valid[i] else {n: k[i] for n, k in zip(names, kids)}
                    for i in range(self.length)]
        arr = np.asarray(self.data)
        return [None if not valid[i] else arr[i].item() for i in range(self.length)]

    # ---- constructors for nested types -----------------------------------------
    @staticmethod
    def make_list(offsets: jnp.ndarray, child: "Column",
                  validity: Optional[jnp.ndarray] = None) -> "Column":
        n = int(offsets.shape[0]) - 1
        return Column(dtype=dtypes.list_(child.dtype), length=n,
                      offsets=offsets.astype(jnp.int32), children=(child,),
                      validity=validity)

    @staticmethod
    def make_struct(validity: Optional[jnp.ndarray] = None, **fields: "Column") -> "Column":
        cols = tuple(fields.values())
        n = cols[0].length
        dt = dtypes.struct(**{k: c.dtype for k, c in fields.items()})
        return Column(dtype=dt, length=n, children=cols, validity=validity)


def _np_to_dtype(np_dtype) -> DType:
    m = {
        np.dtype(np.bool_): dtypes.BOOL,
        np.dtype(np.int8): dtypes.INT8,
        np.dtype(np.int16): dtypes.INT16,
        np.dtype(np.int32): dtypes.INT32,
        np.dtype(np.int64): dtypes.INT64,
        np.dtype(np.float32): dtypes.FLOAT32,
        np.dtype(np.float64): dtypes.FLOAT64,
    }
    try:
        return m[np.dtype(np_dtype)]
    except KeyError:
        raise TypeError(f"no logical dtype for numpy {np_dtype}")


def make_string_column(chars: jnp.ndarray, offsets: jnp.ndarray,
                       validity: Optional[jnp.ndarray] = None) -> Column:
    return Column(dtype=dtypes.STRING, length=int(offsets.shape[0]) - 1,
                  data=chars.astype(jnp.uint8), offsets=offsets.astype(jnp.int32),
                  validity=validity)


def strings_from_padded(padded: jnp.ndarray, lengths: jnp.ndarray,
                        validity: Optional[jnp.ndarray] = None) -> Column:
    """Assemble a string column from an (n, L) padded char matrix + lengths.

    The gather half of the measure→gather pattern (reference two-kernel
    strings construction, parse_uri.cu:854-875): compute offsets by scan,
    then scatter each row's live chars into the dense chars buffer.
    """
    n, L = padded.shape
    lengths = lengths.astype(jnp.int32)
    offsets = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(lengths)])
    if isinstance(offsets, jax.core.Tracer):
        # under jit the exact char total is not concrete: size the data
        # buffer by its static upper bound n*L (Arrow permits a data buffer
        # longer than offsets[-1]; every consumer indexes through offsets)
        total = n * L
    else:
        total = int(offsets[-1])  # host sync, but the buffer is exact-sized
    in_range = jnp.arange(L, dtype=jnp.int32)[None, :] < lengths[:, None]
    dest = offsets[:-1, None] + jnp.arange(L, dtype=jnp.int32)[None, :]
    dest = jnp.where(in_range, dest, total)  # out-of-range writes dropped
    chars = jnp.zeros((total + 1,), jnp.uint8).at[dest.reshape(-1)].set(
        padded.reshape(-1).astype(jnp.uint8), mode="drop")[:total]
    return make_string_column(chars, offsets, validity)
