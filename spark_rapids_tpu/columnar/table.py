"""Table: an ordered collection of equal-length columns.

Equivalent of `cudf::table_view` handles crossing the reference's JNI surface
(SURVEY.md §1: L5→L4 passes table handles; e.g. Hash.java:40-58 hashes a
table's column set). A Table is a pytree, so whole tables are jit/shard_map
arguments.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Sequence, Tuple

import jax
import numpy as np

from .column import Column


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Table:
    columns: Tuple[Column, ...]
    names: Tuple[str, ...]

    def __init__(self, columns: Sequence[Column], names: Sequence[str] = None):
        columns = tuple(columns)
        if names is None:
            names = tuple(f"c{i}" for i in range(len(columns)))
        assert len(names) == len(columns), (
            f"{len(names)} names for {len(columns)} columns — a mismatched "
            "binding silently shifts every name-based lookup")
        if len(columns) > 1:
            n0 = columns[0].length
            for c in columns[1:]:
                assert c.length == n0, "all columns must have equal length"
        object.__setattr__(self, "columns", columns)
        object.__setattr__(self, "names", tuple(names))

    def tree_flatten(self):
        return (self.columns,), (self.names,)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        (columns,) = leaves
        (names,) = aux
        return cls(columns, names)

    # ---- accessors --------------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return self.columns[0].length if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def __len__(self) -> int:
        return self.num_rows

    def __getitem__(self, key) -> Column:
        if isinstance(key, int):
            return self.columns[key]
        return self.columns[self.names.index(key)]

    def __iter__(self) -> Iterator[Column]:
        return iter(self.columns)

    def column_dict(self) -> Dict[str, Column]:
        return dict(zip(self.names, self.columns))

    def select(self, names: Sequence[str]) -> "Table":
        return Table([self[n] for n in names], names)

    def with_column(self, name: str, col: Column) -> "Table":
        if name in self.names:
            i = self.names.index(name)
            cols = list(self.columns)
            cols[i] = col
            return Table(cols, self.names)
        return Table(list(self.columns) + [col], list(self.names) + [name])

    # ---- host interop -----------------------------------------------------------
    @staticmethod
    def from_pydict(data: Dict[str, Column]) -> "Table":
        return Table(list(data.values()), list(data.keys()))

    def to_pydict(self) -> Dict[str, List]:
        return {n: c.to_pylist() for n, c in zip(self.names, self.columns)}
