from .column import Column, make_string_column, strings_from_padded
from .table import Table

__all__ = ["Column", "Table", "make_string_column", "strings_from_padded"]
