"""Build/version stamping (reference: build/build-info generates
version-info.properties into the jar — pom.xml:467-492; read back via
`ai.rapids.cudf.NativeDepsLoader` consumers). Exposes the same fields:
version, user, revision, branch, date, url."""
from __future__ import annotations

import functools
import os
import subprocess

__version__ = "0.1.0"


def _git(*args: str) -> str:
    try:
        out = subprocess.run(["git", *args], capture_output=True, text=True,
                             cwd=os.path.dirname(os.path.dirname(__file__)),
                             timeout=5)
        return out.stdout.strip() if out.returncode == 0 else ""
    except Exception:
        return ""


@functools.lru_cache(None)
def version_info() -> dict:
    """The version-info.properties equivalent, computed once per process."""
    import datetime
    return {
        "version": __version__,
        "user": os.environ.get("USER", ""),
        "revision": _git("rev-parse", "HEAD"),
        "branch": _git("rev-parse", "--abbrev-ref", "HEAD"),
        "date": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "url": _git("config", "--get", "remote.origin.url"),
    }
