"""Multi-tenant serving layer: fair-share session scheduler with quota
admission, backpressure, and overload-graceful degradation
(docs/serving.md).

This is the paper's SparkResourceAdaptor story — many concurrent tasks
share one device without deadlock or starvation (PAPER.md §0) — promoted
to whole-plan traffic: the front door the `runtime/` arbitration
machinery (admission, retry budgets, breaker, spill) never had. N tenant
sessions submit plans; a bounded queue + a small dispatcher worker pool
execute them through ONE shared `PlanExecutor`, so the compiled-program
caches, the health monitor, and the stats store are genuinely shared
across tenants while every per-tenant bound stays per-tenant:

- **fair share** — weighted deficit round-robin over the sessions of
  each priority lane (interactive > normal > batch), one deficit credit
  per dispatched plan scaled by the session weight; an AGING bound
  (`SPARK_RAPIDS_TPU_SERVING_STARVATION_MS`) dispatches any plan that
  has waited too long regardless of lane or deficit, so weighted
  fairness can skew throughput but never unbound a session's queue wait.
  With `SPARK_RAPIDS_TPU_SERVING_FEEDBACK` on, each session's credit
  grant scales down by its decayed cumulative wall-ms + retry cost (the
  ROADMAP dispatch-fairness feedback loop) — half-life
  `_FEEDBACK_HALFLIFE_S`, floored at a quarter of the configured weight
  so one bad hour skews dispatch but can never starve a tenant;
- **quota admission** — every submission is charged against its
  session's device-memory quota: the OBSERVED high-water live bytes
  when the stats store has seen this fingerprint on this backend (what
  the plan DID — capped by the certified bound when both exist), else
  `footprint.quota_charge(cert, default)`: the PR 12 certifier's sound
  `peak_bytes_hi` when the plan is bounded, a flat configurable default
  when it is not. The winning source ("observed"/"certified"/"default")
  is stamped on the ticket (`charge_source`) and the soak's JSONL. A
  charge that can NEVER fit the session quota rejects (typed, naming
  session + the operator that set the certified peak, before any
  compilation), pins the plan to the CPU tier, or — under
  `SPARK_RAPIDS_TPU_SERVING_OVER_QUOTA=partial` — offloads certified
  join build-side subtrees to co-placement host threads until the
  device remainder fits, charging quota for the device footprint only
  (docs/serving.md#partial-placement, `charge_source="partial"`); a
  charge that fits but is currently crowded out just waits — the
  dispatcher skips the session until its in-flight charges drain;
- **backpressure** — the queue is bounded; a full queue blocks submit()
  (or fast-rejects, caller-selectable) instead of hiding overload until
  memory does the rejecting (StreamBox-HBM's bounded-pipeline
  discipline, PAPERS.md);
- **per-session retry budgets** — every job executes inside
  `sessionctx.session_scope`, so the health monitor's retry budgets and
  sticky windows key on the TENANT (runtime/health.py): one pathological
  session exhausts its own budget, never a neighbour's;
- **breaker-aware dispatch** — an open breaker never stalls the queue:
  the executor's admission gate routes each dispatched plan to the
  degraded CPU tier (parity-exact) until the half-open probe closes the
  breaker, at which point device dispatch resumes on the very next job;
- **result cache** — completed results key by canonical fingerprint +
  input-data digest (serving/cache.py, LRU + TTL); hits serve deep-
  copied results stamped `cached=True` without consuming queue, quota,
  or a worker.

Concurrency note: this layer is the first real multi-plan concurrency
the engine sees — one session's streaming-scan prefetch thread decoding
chunks while another session's plan executes on the device is the PR 4
overlap promoted across tenants.
"""
from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Deque, Dict, List, Optional

from . import cache as cache_mod

__all__ = ["ServingScheduler", "ServingSession", "Ticket",
           "ServingRejectedError", "PRIORITIES"]

# priority lanes, served strictly in order (aging outranks lanes)
PRIORITIES = {"interactive": 0, "normal": 1, "batch": 2}


class ServingRejectedError(RuntimeError):
    """Typed fast-reject from the serving layer. `reason` is machine-
    checkable ("queue_full" | "over_quota" | "closed" | "deadline" |
    "quarantined" — the last from the fleet's poison-fingerprint gate,
    serving/fleet.py); `session` and `operator` (the label that set the
    certified peak, over-quota only) make the diagnostic attributable
    without parsing the message."""

    def __init__(self, reason: str, detail: str, *,
                 session: Optional[str] = None, operator: str = ""):
        at = f" [session={session}]" if session else ""
        op = f" [operator={operator}]" if operator else ""
        super().__init__(f"{reason}{at}{op}: {detail}")
        self.reason = reason
        self.session = session
        self.operator = operator


class Ticket:
    """One submitted plan's handle: `result()` blocks for the outcome
    (re-raising the execution error, if any); `queue_wait_ms` and
    `cached` are the serving-side observability stamps."""

    def __init__(self, session_id: str):
        self.session = session_id
        self.queue_wait_ms: float = 0.0
        self.cached = False
        self.charge_source = ""   # "observed" | "certified" | "default"
        #                           | "partial" (over-quota split:
        #                           device-footprint charge only,
        #                           docs/serving.md#partial-placement)
        self.worker = ""          # fleet worker id ("" single-worker)
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        # completion callbacks (serving/fleet.py condition-notify
        # wakeup): own lock, never held while running a callback or
        # while any other lock is held — no lock-order edges
        self._cb_lock = threading.Lock()
        self._callbacks: List = []

    def done(self) -> bool:
        return self._event.is_set()

    def add_done_callback(self, fn) -> None:
        """Run `fn(self)` when the ticket completes — immediately if it
        already has. Callbacks run on the completing thread (or this
        one), outside every scheduler lock; exceptions are swallowed
        (a waiter's notification hook must never fail the job)."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        try:
            fn(self)
        except Exception:
            pass

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"serving ticket [session={self.session}] not complete "
                f"after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    def _complete(self, result=None, error: Optional[BaseException] = None):
        self._result = result
        self._error = error
        # set the event UNDER the callback lock: a concurrent
        # add_done_callback either appends before the set (drained
        # below) or observes it set and self-invokes — never neither
        with self._cb_lock:
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            try:
                fn(self)
            except Exception:
                pass


class _SessionState:
    """Dispatcher-side per-session bookkeeping (all fields guarded by the
    scheduler lock)."""

    def __init__(self, sid: str, weight: float, priority: str,
                 quota_bytes: int):
        self.id = sid
        self.weight = weight
        self.priority = priority
        self.lane = PRIORITIES[priority]
        self.quota_bytes = quota_bytes
        self.deficit = 0.0
        self.in_flight_bytes = 0
        # dispatch-fairness feedback (ISSUE 16): decayed cumulative cost
        # (wall-ms + retry penalty) this session has charged the device;
        # scales the WDRR credit grant down, bounded so one bad hour
        # can never starve a tenant forever
        self.cost_score = 0.0
        self.cost_at = 0.0        # clock of the last decay application
        self.queue: Deque["_Job"] = collections.deque()
        # accounting for metrics()/the soak's per-session assertions
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self.degraded = 0
        self.retries = 0
        self.cache_hits = 0
        self.deadline_rejects = 0            # expired-in-queue completions
        self.wait_ms: List[float] = []       # per-dispatch queue waits
        self.aged_dispatches = 0             # starvation-bound promotions
        self.active_jobs = 0                 # dispatched, not yet completed
        self.closed = False

    def wait_stats(self) -> Dict[str, float]:
        if not self.wait_ms:
            return {"max": 0.0, "p99": 0.0, "mean": 0.0}
        s = sorted(self.wait_ms)
        return {"max": s[-1],
                "p99": s[min(len(s) - 1, int(0.99 * len(s)))],
                "mean": sum(s) / len(s)}


class _Job:
    __slots__ = ("plan", "inputs", "state", "ticket", "charge",
                 "charge_source", "op_label", "tier", "cache_key",
                 "enqueued_at", "deadline", "placement")

    def __init__(self, plan, inputs, state: _SessionState, ticket: Ticket,
                 charge: int, charge_source: str, op_label: str, tier: str,
                 cache_key, enqueued_at: float,
                 deadline: Optional[float] = None,
                 placement=None):
        self.plan = plan
        self.inputs = inputs
        self.state = state
        self.ticket = ticket
        self.charge = charge
        self.charge_source = charge_source
        self.op_label = op_label
        self.tier = tier                  # "device" | "cpu" (quota-degraded)
        self.cache_key = cache_key
        self.enqueued_at = enqueued_at
        self.deadline = deadline          # submit-side deadline (clock units)
        self.placement = placement        # host-placed subtree labels under
        #                                   OVER_QUOTA=partial (None normal):
        #                                   `charge` covers the DEVICE
        #                                   remainder only


class ServingSession:
    """One tenant's handle onto the scheduler: `submit()` enqueues and
    returns a Ticket, `run()` is the submit+wait convenience. Closing a
    session only bars NEW submissions — queued work drains normally."""

    def __init__(self, scheduler: "ServingScheduler", state: _SessionState):
        self._scheduler = scheduler
        self._state = state
        self.id = state.id

    def submit(self, plan, inputs: Optional[Dict] = None, *,
               block: Optional[bool] = None,
               timeout: Optional[float] = None,
               pin_cpu: bool = False) -> Ticket:
        return self._scheduler._submit(self._state, plan, inputs,
                                       block=block, timeout=timeout,
                                       pin_cpu=pin_cpu)

    def run(self, plan, inputs: Optional[Dict] = None, *,
            block: Optional[bool] = None,
            timeout: Optional[float] = None,
            pin_cpu: bool = False):
        """submit + wait under ONE deadline: whatever the blocked submit
        consumed of `timeout` is not granted to the result wait again."""
        t0 = time.monotonic()
        ticket = self.submit(plan, inputs, block=block, timeout=timeout,
                             pin_cpu=pin_cpu)
        remaining = (None if timeout is None
                     else max(0.0, timeout - (time.monotonic() - t0)))
        return ticket.result(remaining)

    def close(self) -> None:
        self._scheduler._close_session(self._state)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class ServingScheduler:
    """The serving front door: N sessions, one device, bounded queue,
    fair-share dispatch (see the module docstring for the contract).

    Pass an existing `PlanExecutor` to share its health monitor and
    program caches with non-serving callers; by default the scheduler
    owns an eager-tier executor. All knob parameters default from the
    `SPARK_RAPIDS_TPU_SERVING_*` family (config.py), read once at
    construction (one policy per scheduler lifetime, the health-monitor
    convention)."""

    _ids = itertools.count(1)

    def __init__(self, executor=None, *,
                 workers: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 starvation_ms: Optional[float] = None,
                 cache_entries: Optional[int] = None,
                 cache_ttl_s: Optional[float] = None,
                 quota_bytes: Optional[int] = None,
                 default_charge_bytes: Optional[int] = None,
                 over_quota: Optional[str] = None,
                 backpressure: Optional[str] = None,
                 feedback: Optional[bool] = None,
                 feedback_halflife_s: Optional[float] = None,
                 stats_store=None,
                 clock=time.monotonic):
        from .. import config
        from ..plan.executor import PlanExecutor
        self.executor = executor if executor is not None \
            else PlanExecutor(mode="eager")
        # an explicit per-scheduler stats store (fleet workers isolate
        # theirs); None keeps the process-default active_store() wiring
        self.stats_store = stats_store
        self.feedback = (config.serving_feedback() if feedback is None
                         else bool(feedback))
        self.feedback_halflife_s = (
            config.serving_feedback_halflife_s()
            if feedback_halflife_s is None else float(feedback_halflife_s))
        self.workers = (config.serving_workers() if workers is None
                        else max(1, int(workers)))
        self.queue_depth = (config.serving_queue_depth()
                            if queue_depth is None
                            else max(1, int(queue_depth)))
        self.starvation_ms = (config.serving_starvation_ms()
                              if starvation_ms is None
                              else float(starvation_ms))
        self.default_quota_bytes = (config.serving_quota_bytes()
                                    if quota_bytes is None
                                    else int(quota_bytes))
        self.default_charge_bytes = (config.serving_default_charge_bytes()
                                     if default_charge_bytes is None
                                     else int(default_charge_bytes))
        self.over_quota = (config.serving_over_quota()
                           if over_quota is None else over_quota)
        if self.over_quota not in ("reject", "degrade", "partial"):
            raise ValueError(f"unknown over_quota policy "
                             f"{self.over_quota!r} (expected reject, "
                             "degrade, or partial)")
        bp = (config.serving_backpressure() if backpressure is None
              else backpressure)
        if bp not in ("block", "reject"):
            raise ValueError(f"unknown backpressure policy {bp!r} "
                             "(expected block or reject)")
        self.block_default = bp == "block"
        self.cache = cache_mod.ResultCache(entries=cache_entries,
                                           ttl_s=cache_ttl_s, clock=clock)
        self._clock = clock
        self._lock = threading.Lock()
        self._lock_cond = threading.Condition(self._lock)
        self._sessions: Dict[str, _SessionState] = {}
        self._rr: Dict[int, int] = {}     # per-lane round-robin cursor
        self._queued = 0
        self._queued_hiwater = 0
        self._active = 0                  # jobs dispatched, not yet done
        self._closed = False
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"srt-serving-{i}")
            for i in range(self.workers)]
        for t in self._threads:
            t.start()

    # ---- sessions ----------------------------------------------------------

    def open_session(self, session_id: Optional[str] = None, *,
                     weight: float = 1.0, priority: str = "normal",
                     quota_bytes: Optional[int] = None) -> ServingSession:
        if priority not in PRIORITIES:
            raise ValueError(f"unknown priority {priority!r} (expected "
                             f"one of {sorted(PRIORITIES)})")
        if weight <= 0:
            raise ValueError(f"session weight must be > 0, got {weight}")
        with self._lock:
            if self._closed:
                raise ServingRejectedError(
                    "closed", "scheduler is shut down")
            sid = session_id or f"s{next(self._ids)}"
            old = self._sessions.get(sid)
            if old is not None and not old.closed:
                raise ValueError(f"session id {sid!r} already open")
            if old is not None and old.queue:
                # reopening would orphan the old state's queued jobs: the
                # dispatcher discovers work only through self._sessions,
                # so replacing the entry now would strand those tickets
                # forever while _queued still counts them
                raise ValueError(f"session id {sid!r} is closed but still "
                                 f"draining {len(old.queue)} queued "
                                 "plan(s); reopen after they complete")
            state = _SessionState(
                sid, float(weight), priority,
                self.default_quota_bytes if quota_bytes is None
                else int(quota_bytes))
            self._sessions[sid] = state
        return ServingSession(self, state)

    def _close_session(self, state: _SessionState) -> None:
        with self._lock:
            state.closed = True
            self._maybe_reap_locked(state)

    def _maybe_reap_locked(self, state: _SessionState) -> None:
        """Drop a closed, fully-drained session from the map: a
        long-running scheduler serving short-lived tenants must not
        accumulate one _SessionState (deque + counters + wait samples)
        per tenant ever opened — _pick_locked iterates the map under the
        dispatch lock on every pick, so leaked sessions are latency, not
        just memory. Waits for queued AND dispatched work (a CPU-pinned
        job carries zero in-flight charge, so bytes alone cannot prove
        quiescence). Reaped ids disappear from metrics(); callers wanting
        a tenant's final numbers read them before close()."""
        if state.closed and not state.queue and \
                state.active_jobs == 0 and \
                self._sessions.get(state.id) is state:
            del self._sessions[state.id]

    # ---- submission --------------------------------------------------------

    def _bind(self, plan, inputs: Optional[Dict]) -> Dict:
        """The executor's OWN scan-binding prologue (one definition —
        plan/executor.bind_scan_sources), applied here so the cache
        digest and quota charge see exactly the binding execute() will."""
        from ..plan.executor import bind_scan_sources
        return bind_scan_sources(plan, inputs)

    def _certify(self, plan, inputs: Dict):
        """Certify the AUTHORED plan through the executor's memoized walk
        — quota must resolve BEFORE any optimization/compilation, so the
        charge is deliberately the authored plan's bound (the optimizer
        may only keep or tighten it — certifier monotonicity, docs/
        analysis.md); repeat submissions of the same (plan, binding)
        share the memo, execute()'s own cert of the REWRITTEN plan is a
        separate (also memoized) walk. Defensive None on any error:
        sizing must never fail a submission the executor would accept
        (missing inputs etc. surface at execution, against
        executor-owned diagnostics)."""
        try:
            bound = {name: tuple(t.names) for name, t in inputs.items()}
            return self.executor._certify(plan, inputs, bound)
        except Exception:
            return None

    def _observed_charge(self, plan) -> Optional[int]:
        """High-water OBSERVED live bytes for this authored plan on the
        current backend (plan/stats.py), or None when cold / stats off.
        Defensive None on any error — sizing must never fail a submit."""
        from ..plan import stats as stats_mod
        store = (self.stats_store if self.stats_store is not None
                 else stats_mod.active_store())
        if store is None:
            return None
        try:
            import jax
            obs = store.observed_peak_bytes(jax.default_backend(),
                                            plan.fingerprint)
        except Exception:
            return None
        return None if obs is None else int(obs[0])

    def _partial_placement(self, plan, inputs, cert, quota_bytes):
        """Over-quota split under SPARK_RAPIDS_TPU_SERVING_OVER_QUOTA=
        partial (docs/serving.md#partial-placement): offload certified
        join build-side subtrees of the AUTHORED plan to co-placement
        host worker threads — largest certified residency first — until
        the certified peak of the DEVICE-placed remainder fits the
        session quota. Returns (host subtree root labels, device
        charge) or None when no split fits (the caller falls back to
        the whole-plan CPU pin).

        The candidate shape mirrors the optimizer's placement rule
        (plan/optimizer.py): a HashJoin build (right) side of >= 2
        nodes, no Exchange, every Scan bound to a Table, exclusive (one
        consumer). The executor re-validates each label against the
        OPTIMIZED plan and skips any the rewrite renamed — execution
        stays correct either way; only the offload (and with it the
        accounting's tightness) is lost, so build-side roots that
        survive rewrites (Filter, HashAggregate) make the best
        candidates. Defensive None on any error: admission sizing must
        never fail a submission."""
        from ..columnar import Table
        from ..plan.nodes import Exchange, HashJoin, Scan
        try:
            if cert is None or cert.peak_bytes_hi is None:
                return None
            parents: Dict[int, List] = {}
            for n in plan.nodes:
                for c in n.children:
                    parents.setdefault(id(c), []).append(n)
            cands = []          # (root label, member labels, weight)
            claimed: set = set()
            for n in plan.nodes:
                if not isinstance(n, HashJoin):
                    continue
                cand = n.children[1]
                sub, seen = [], set()

                def walk(x):
                    if id(x) in seen:
                        return
                    seen.add(id(x))
                    for c in x.children:
                        walk(c)
                    sub.append(x)

                walk(cand)
                ids = {id(s) for s in sub}
                if len(sub) < 2 or ids & claimed or cand is plan.root:
                    continue
                ok = True
                for s in sub:
                    if isinstance(s, Exchange) or (
                            isinstance(s, Scan) and not isinstance(
                                inputs.get(s.source), Table)):
                        ok = False
                        break
                    ps = parents.get(id(s), [])
                    if (len(ps) != 1 if s is cand else
                            any(id(p) not in ids for p in ps)):
                        ok = False
                        break
                if not ok:
                    continue
                members = {s.label for s in sub}
                weight = max((cert.by_label[lbl].resident_bytes_hi or 0
                              for lbl in members
                              if lbl in cert.by_label), default=0)
                cands.append((cand.label, members, weight))
                claimed |= ids
            bounds = [b for b in cert.ops
                      if b.resident_bytes_hi is not None]
            offloaded: set = set()

            def device_peak():
                vals = [b.resident_bytes_hi for b in bounds
                        if b.label not in offloaded]
                return max(vals) if vals else 0

            chosen = []
            for root_label, members, _ in sorted(
                    cands, key=lambda c: -c[2]):
                if device_peak() <= quota_bytes:
                    break
                offloaded |= members
                chosen.append(root_label)
            peak = device_peak()
            if not chosen or peak > quota_bytes:
                return None
            return tuple(chosen), int(peak)
        except Exception:
            return None

    def _submit(self, state: _SessionState, plan, inputs: Optional[Dict],
                *, block: Optional[bool], timeout: Optional[float],
                pin_cpu: bool = False) -> Ticket:
        from ..analysis.footprint import quota_charge
        if self._closed or state.closed:
            # early unlocked read: a submit racing close() is still
            # caught by the locked re-check at enqueue below; this just
            # keeps cache hits from serving through a closed front door
            raise ServingRejectedError(
                "closed", "session or scheduler is shut down",
                session=state.id)
        if block is None:
            block = self.block_default
        inputs = self._bind(plan, inputs)
        ticket = Ticket(state.id)
        key = cache_mod.cache_key(plan, inputs) \
            if self.cache.entries > 0 else None
        hit = self.cache.get(key)
        if hit is not None:
            # a hit consumes nothing: no queue slot, no quota, no worker
            hit.session = state.id
            for m in hit.metrics.values():
                m.session = state.id
            ticket.cached = True
            with self._lock:
                state.submitted += 1
                state.completed += 1
                state.cache_hits += 1
            ticket._complete(result=hit)
            return ticket
        cert = self._certify(plan, inputs)
        charge, source, op_label = quota_charge(cert,
                                                self.default_charge_bytes)
        observed = self._observed_charge(plan)
        if observed:
            # warm fingerprint: what the plan DID is the better sizer
            # than the sound-but-loose certified cross-product bound —
            # but never charge above a certified ceiling (both bound the
            # same execution, the tighter one wins)
            charge = min(observed, charge) if source == "certified" \
                else observed
            source = "observed"
        ticket.charge_source = source
        tier = "device"
        placement = None
        if pin_cpu:
            # fleet quarantine degrade (serving/fleet.py): the device
            # never sees this plan, so the device quota does not bind —
            # the same contract as the over_quota degrade below
            tier, charge = "cpu", 0
        elif charge > state.quota_bytes:
            # can NEVER fit this session's quota: resolve now, before any
            # compilation — reject with an attributable diagnostic, pin
            # to the CPU tier where the device quota does not bind, or
            # (partial) offload enough certified subtrees to co-placement
            # host threads that the DEVICE remainder fits
            if self.over_quota == "reject":
                with self._lock:
                    state.submitted += 1
                    state.rejected += 1
                raise ServingRejectedError(
                    "over_quota",
                    f"plan charges {charge} B ({source}) against a "
                    f"{state.quota_bytes} B session quota",
                    session=state.id, operator=op_label)
            split = None
            if self.over_quota == "partial":
                split = self._partial_placement(plan, inputs, cert,
                                                state.quota_bytes)
            if split is not None:
                # quota is charged for the DEVICE footprint only — the
                # host-placed subtrees never occupy device memory
                # (docs/serving.md#partial-placement); the job stays on
                # the device tier instead of the whole-plan CPU pin
                placement, charge = split
                ticket.charge_source = source = "partial"
            else:
                tier, charge = "cpu", 0
        deadline = None if timeout is None else self._clock() + timeout
        with self._lock_cond:
            if self._closed or state.closed:
                raise ServingRejectedError(
                    "closed", "session or scheduler is shut down",
                    session=state.id)
            while self._queued >= self.queue_depth:
                if not block:
                    state.submitted += 1
                    state.rejected += 1
                    raise ServingRejectedError(
                        "queue_full",
                        f"{self._queued} plans queued (depth "
                        f"{self.queue_depth}); backpressure policy is "
                        "fast-reject", session=state.id)
                remaining = (None if deadline is None
                             else deadline - self._clock())
                if remaining is not None and remaining <= 0:
                    state.submitted += 1
                    state.rejected += 1
                    raise ServingRejectedError(
                        "queue_full",
                        f"queue stayed full past the {timeout}s submit "
                        "timeout", session=state.id)
                self._lock_cond.wait(timeout=0.05 if remaining is None
                                else min(0.05, remaining))
                if self._closed or state.closed:
                    raise ServingRejectedError(
                        "closed", "session or scheduler shut down while "
                        "submit was blocked", session=state.id)
            job = _Job(plan, inputs, state, ticket, charge, source,
                       op_label, tier, key, self._clock(),
                       deadline=deadline, placement=placement)
            state.queue.append(job)
            state.submitted += 1
            self._queued += 1
            self._queued_hiwater = max(self._queued_hiwater, self._queued)
            self._lock_cond.notify_all()
        return ticket

    # ---- dispatch ----------------------------------------------------------

    def _eligible(self, state: _SessionState) -> bool:
        """Head-of-line job can dispatch now: CPU-pinned jobs always (no
        device charge), device jobs when the session's in-flight charges
        leave room under its quota."""
        if not state.queue:
            return False
        job = state.queue[0]
        return job.tier == "cpu" or \
            state.in_flight_bytes + job.charge <= state.quota_bytes

    # cost normalizer: one second of accumulated wall halves a session's
    # effective weight; each retry charges like 100 ms of wall
    _FEEDBACK_NORM_MS = 1000.0
    _FEEDBACK_RETRY_MS = 100.0
    # the decayed penalty never cuts a session below a quarter of its
    # configured weight — feedback skews dispatch, it cannot starve
    _FEEDBACK_FLOOR = 0.25

    def _effective_weight_locked(self, s: _SessionState,
                                 now: float) -> float:
        """WDRR credit grant with the dispatch-fairness feedback loop
        (docs/serving.md#fairness): sessions that have recently burned
        disproportionate wall-ms / retries earn credit slower. The cost
        score decays with a configurable half-life (one bad hour fades)
        and the grant is floored at `_FEEDBACK_FLOOR x weight` (bounded
        skew, never starvation). Feedback off => exactly `s.weight`."""
        if not self.feedback:
            return s.weight
        if s.cost_score > 0.0 and self.feedback_halflife_s > 0:
            dt = now - s.cost_at
            if dt > 0:
                s.cost_score *= 0.5 ** (dt / self.feedback_halflife_s)
        s.cost_at = now
        scaled = s.weight / (1.0 + s.cost_score / self._FEEDBACK_NORM_MS)
        return max(scaled, self._FEEDBACK_FLOOR * s.weight)

    def _pick_locked(self) -> Optional[_Job]:
        """Next job to dispatch (scheduler lock held).

        1. Starvation aging: the oldest eligible head waiting past
           `starvation_ms` wins outright — bounded queue wait for every
           session, whatever the lanes/weights say.
        2. Priority lanes in order; weighted deficit round-robin within a
           lane: each pass over the lane's eligible sessions grants
           `weight` credit (scaled down by the feedback cost score when
           SPARK_RAPIDS_TPU_SERVING_FEEDBACK is on), a dispatch costs 1
           credit — over time a weight-2 session dispatches twice per
           weight-1 session's once.
        """
        eligible = [s for s in self._sessions.values() if self._eligible(s)]
        if not eligible:
            return None
        now = self._clock()
        if self.starvation_ms > 0:
            starved = [s for s in eligible
                       if (now - s.queue[0].enqueued_at) * 1e3
                       >= self.starvation_ms]
            if starved:
                s = min(starved, key=lambda s: s.queue[0].enqueued_at)
                s.aged_dispatches += 1
                return self._take_locked(s)
        lanes: Dict[int, List[_SessionState]] = {}
        for s in eligible:
            lanes.setdefault(s.lane, []).append(s)
        for lane in sorted(lanes):
            members = sorted(lanes[lane], key=lambda s: s.id)
            cursor = self._rr.get(lane, 0)
            # rotate so round-robin order persists across picks
            members = members[cursor % len(members):] + \
                members[:cursor % len(members)]
            for _ in range(64):     # bounded credit rounds (weights >= eps)
                for i, s in enumerate(members):
                    if s.deficit >= 1.0:
                        s.deficit -= 1.0
                        self._rr[lane] = (cursor + i + 1) % len(members)
                        return self._take_locked(s)
                for s in members:
                    s.deficit = min(
                        s.deficit + self._effective_weight_locked(s, now),
                        64.0)
        return None

    def _take_locked(self, state: _SessionState) -> _Job:
        job = state.queue.popleft()
        self._queued -= 1
        if job.tier != "cpu":
            state.in_flight_bytes += job.charge
        state.active_jobs += 1
        self._active += 1
        self._lock_cond.notify_all()
        return job

    def _worker(self) -> None:
        while True:
            with self._lock_cond:
                job = None
                while job is None:
                    if self._closed and self._queued == 0:
                        return
                    job = self._pick_locked()
                    if job is None:
                        # timed wait, not pure signal-driven: aging
                        # promotions and quota releases become pickable
                        # with time, and a missed notify must never
                        # strand a queued job
                        self._lock_cond.wait(timeout=0.05)
            self._run_job(job)

    def _run_job(self, job: _Job) -> None:
        from ..runtime import sessionctx
        state = job.state
        wait_ms = (self._clock() - job.enqueued_at) * 1e3
        job.ticket.queue_wait_ms = wait_ms
        result = error = None
        served_hit = False
        # EVERYTHING between dispatch and the finally must leave the
        # worker alive and the ticket completed: an unguarded raise here
        # (cache copy under memory pressure, say) would kill the
        # dispatcher thread, leak _active/in_flight accounting (close()
        # then never drains), and strand the submitter's result() forever
        try:
            # deadline enforcement at dispatch: a job whose submit-side
            # deadline expired while QUEUED completes with the typed
            # rejection before certification or compilation — nobody is
            # waiting for the result, and executing it anyway would
            # charge quota and burn a dispatcher slot for dead traffic.
            # queue_wait_ms is already stamped above: the wait that
            # killed the job is exactly the number worth reporting.
            if job.deadline is not None and self._clock() >= job.deadline:
                raise ServingRejectedError(
                    "deadline",
                    f"submit-side deadline expired after "
                    f"{wait_ms:.0f} ms queued", session=state.id)
            # dispatch-time cache consult: a repeat plan that QUEUED
            # behind its twin (both submitted before either completed —
            # the common shape of a burst of identical traffic) still
            # serves the first completion's result instead of
            # re-executing
            # count_miss=False: submit() already counted this key's
            # miss once — the dispatch-time re-consult is burst dedup,
            # not new traffic, and must not halve the reported hit rate
            hit = self.cache.get(job.cache_key, count_miss=False)
            if hit is not None:
                hit.session = state.id
                for m in hit.metrics.values():
                    m.session = state.id
                job.ticket.cached = True
                served_hit = True
                result = hit
            else:
                import contextlib
                from ..plan import stats as stats_mod
                scope = (stats_mod.scoped_store(self.stats_store)
                         if self.stats_store is not None
                         else contextlib.nullcontext())
                # attribution scope: a breaker trip fired by THIS
                # execution is stamped with this plan's fingerprint in
                # the health monitor's trip log, which is what lets the
                # fleet's poison-plan quarantine (serving/fleet.py)
                # attribute trips to fingerprints instead of guessing
                # placement= is only forwarded when a partial split is
                # actually armed: executor doubles (tests, shims) that
                # stub execute() keep working unchanged on the default
                # path, and the kwarg's absence IS the default anyway
                kw = ({"placement": job.placement}
                      if job.placement is not None else {})
                with sessionctx.session_scope(state.id), scope, \
                        self.executor.health.attribution(
                            job.plan.fingerprint):
                    result = self.executor.execute(
                        job.plan, job.inputs,
                        tier="cpu" if job.tier == "cpu" else None,
                        **kw)
                if job.cache_key is not None and not result.degraded:
                    # device-tier results only: a degraded result is a
                    # transient-condition artifact (breaker open, quota
                    # pin) whose degraded=True stamp would keep reporting
                    # CPU-tier completions to healthy-device traffic for
                    # the whole TTL. The cache is an optimization —
                    # failing to store must not fail the job.
                    try:
                        self.cache.put(job.cache_key, result)
                    except Exception:
                        pass
        except BaseException as e:
            error = e
        finally:
            with self._lock:
                if job.tier != "cpu":
                    state.in_flight_bytes -= job.charge
                state.active_jobs -= 1
                self._active -= 1
                state.wait_ms.append(wait_ms)
                if len(state.wait_ms) > 10_000:
                    del state.wait_ms[:5_000]     # bounded sample memory
                if error is None and result is not None:
                    state.completed += 1
                    if served_hit:
                        state.cache_hits += 1
                    else:
                        state.retries += result.retries
                        if result.degraded or job.tier == "cpu":
                            state.degraded += 1
                        if self.feedback:
                            if state.cost_score == 0.0:
                                # anchor the decay clock: an untouched
                                # cost_at of 0 would decay the first
                                # accrual away instantly
                                state.cost_at = self._clock()
                            state.cost_score += float(result.wall_ms) + \
                                self._FEEDBACK_RETRY_MS * result.retries
                elif (isinstance(error, ServingRejectedError)
                      and error.reason == "deadline"):
                    # expired-in-queue is an admission outcome, not an
                    # execution failure: count it with the rejects so
                    # `failed` keeps meaning "execution broke"
                    state.rejected += 1
                    state.deadline_rejects += 1
                else:
                    state.failed += 1
                self._maybe_reap_locked(state)
                self._lock_cond.notify_all()
            job.ticket._complete(result=result, error=error)

    # ---- lifecycle / observability -----------------------------------------

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Shut down: `drain=True` (default) serves everything already
        queued, then stops; `drain=False` fails queued jobs with a typed
        `ServingRejectedError("closed")` immediately. Either way no new
        submission is accepted from the moment of the call."""
        deadline = None if timeout is None else self._clock() + timeout
        doomed: List[_Job] = []
        with self._lock_cond:
            self._closed = True
            if not drain:
                for state in self._sessions.values():
                    while state.queue:
                        job = state.queue.popleft()
                        self._queued -= 1
                        doomed.append(job)
            self._lock_cond.notify_all()
        # complete OUTSIDE the scheduler lock: _complete runs done-
        # callbacks (fleet ticket wakeups), and callbacks under the
        # scheduler lock would hand arbitrary code a lock-order edge
        for job in doomed:
            job.ticket._complete(error=ServingRejectedError(
                "closed", "scheduler shut down before dispatch",
                session=job.state.id))
        with self._lock_cond:
            while self._queued > 0 or self._active > 0:
                remaining = (None if deadline is None
                             else deadline - self._clock())
                if remaining is not None and remaining <= 0:
                    break
                self._lock_cond.wait(timeout=0.05 if remaining is None
                                else min(0.05, remaining))
        for t in self._threads:
            t.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def metrics(self) -> Dict:
        """Snapshot: per-session accounting + queue/cache aggregates (the
        soak's assertion surface, docs/serving.md#observability)."""
        with self._lock:
            now = self._clock()
            sessions = {
                s.id: {"weight": s.weight, "priority": s.priority,
                       "quota_bytes": s.quota_bytes,
                       "in_flight_bytes": s.in_flight_bytes,
                       "queued": len(s.queue), "submitted": s.submitted,
                       "completed": s.completed, "failed": s.failed,
                       "rejected": s.rejected, "degraded": s.degraded,
                       "deadline_rejects": s.deadline_rejects,
                       "retries": s.retries, "cache_hits": s.cache_hits,
                       "aged_dispatches": s.aged_dispatches,
                       "cost_score": round(s.cost_score, 3),
                       "effective_weight": round(
                           self._effective_weight_locked(s, now), 4),
                       "queue_wait_ms": s.wait_stats()}
                for s in self._sessions.values()}
            queued, hiwater = self._queued, self._queued_hiwater
        return {"sessions": sessions,
                "queued": queued,
                "queue_hiwater": hiwater,
                "queue_depth": self.queue_depth,
                "workers": self.workers,
                "cache": self.cache.stats(),
                "breaker": self.executor.health.breaker.state}

    def pressure(self) -> Dict:
        """Cheap load signal for the fleet router (serving/fleet.py):
        queued + active work, total in-flight certified charge, and the
        breaker state — enough to rank workers for spillover without
        touching per-session detail."""
        with self._lock:
            queued, active = self._queued, self._active
            inflight = sum(s.in_flight_bytes
                           for s in self._sessions.values())
        return {"queued": queued, "active": active,
                "in_flight_bytes": inflight,
                "queue_depth": self.queue_depth,
                "workers": self.workers,
                "breaker": self.executor.health.breaker.state}
