"""Fleet serving tier: a router fronting N executor workers with
failover (docs/serving.md#fleet).

The reference deployment is one coordinator over many per-device JNI
executors (PAPER.md), and "Accelerating Presto with GPUs" converges on
the same two-level split for GPU SQL serving. PR 15's
`ServingScheduler` solved many-tenants-one-device; this module scales
it out: `FleetScheduler` owns N `FleetWorker`s — each a full
single-worker serving stack (its own `PlanExecutor` + device, its own
`DeviceHealthMonitor`/breaker, its own `StatsStore`, its own
`ResultCache`) — and routes every submission by three rules, in
precedence order:

1. **session affinity** — a session with work still in flight on its
   pinned worker stays there: retry budgets and sticky-failure windows
   key on (session, worker) and a mid-plan re-home would reset them;
2. **consistent hashing on the canonical plan fingerprint**
   (serving/router.py) — the same plan lands on the same worker run
   after run, so that worker's result cache / stats store / compiled
   programs stay warm for it, and the mapping survives worker
   join/leave with only ~1/n of the keyspace moving;
3. **load-aware spillover** — when the routed worker's pressure score
   (queued + active work, breaker state; `ServingScheduler.pressure()`)
   exceeds `SPARK_RAPIDS_TPU_FLEET_SPILL_RATIO` x the least-loaded
   worker's, the submission sheds to that worker instead of queueing
   unboundedly behind a hot spot — locality is a preference, overload
   is a fact.

**Failover.** `kill_worker()` (deliberate kill, the chaos soak's move)
and `reap_unhealthy()` (breaker stuck OPEN with no cooldown) mark a
worker dead, remove it from the ring, fail its queued jobs, and REPLAY
every incomplete tracked submission on a surviving worker. Execution is
deterministic and side-effect-free, so a replay returns the bit-exact
result the dead worker would have — the soak asserts per-session parity
against solo execution. `FleetTicket.result()` also self-heals: a
ticket that surfaces the dead worker's typed `closed` rejection
re-routes itself instead of failing the tenant.

**Cache promotion.** Affinity and spillover divert computations off
their ring home, so the home worker's cache can lack results the fleet
already paid for. On a routed submission the router checks the routed
worker's cache; on a would-miss it adopts a peer's frozen entry
(`ResultCache.peek_frozen`/`adopt` — a dict slot, not a table copy).
The served copy keeps the COMPUTING worker's stamp while the fleet
ticket names the SERVING worker — when they differ, consistent-hash
locality (not luck) produced the hit.

**Invalidation bus.** Worker caches are per-worker, so a source input
whose digest changes on resubmit would keep serving stale results from
OTHER workers' caches (the submitting worker naturally misses — its key
includes the digest). The fleet tracks the last digest seen per plan
fingerprint; on change it publishes an invalidation to every worker:
`ResultCache.invalidate_fingerprint` (old-digest entries only — the
new-digest entry stays sound) and `StatsStore.forget_plan` (observed
sizes describe data that no longer exists). The bus only runs with >1
live worker: one worker's digest-keyed cache is already coherent by
itself, and single-worker behavior must stay byte-identical to the
plain scheduler.

**Self-healing** (docs/serving.md#fleet-self-healing). Failover alone
shrinks the fleet: every kill/reap permanently loses a worker's
capacity. With `SPARK_RAPIDS_TPU_FLEET_RESPAWN=on` the fleet heals
itself back to its target size:

- **auto-respawn** — after a kill, reap, or drain the fleet spawns a
  replacement worker with a fresh isolated stack and a NEW monotonic id
  (ids are never reused: quarantine counts trips per worker
  *incarnation*, and a name-recycling respawn would alias the dead
  worker's history onto the newborn), gated by a lifetime budget
  (`_RESPAWN_MAX`) and an exponential backoff (`_RESPAWN_BACKOFF_MS`)
  so a crash-looping root cause cannot churn workers forever;
- **poison-plan quarantine** — breaker trips are attributed to the
  fingerprint that fired them (`DeviceHealthMonitor.attribution`); a
  fingerprint that tripped breakers on >= 2 DISTINCT workers is
  quarantined fleet-wide — rejected with a typed error or pinned to the
  CPU tier per `_FLEET_QUARANTINE`. This check runs BEFORE respawn
  logic on purpose: respawning workers under a poison plan without
  quarantining it is a crash amplifier (each newborn dies the same way);
- **graceful drain** — `drain_worker()` stops new routing immediately,
  lets in-flight work finish under a deadline, then removes the worker
  and replays only the stragglers (`failover_reason == "drained"`);
- **warm failover** — HOT fingerprints (>= 2 observed runs AND top-K by
  run count) replicate their frozen cache entries to the next
  `_FLEET_HOT_REPLICAS` distinct ring successors, and the stats stores
  gossip observed caps / high-water bytes to every survivor on worker
  death and to every newborn on respawn — so a failover rehome serves
  the replica (or compiles ONCE, `attempts == 1`) and charges observed
  bytes immediately instead of re-learning the plan from scratch.

A background sweep (`_FLEET_SWEEP_MS > 0`) runs reap + respawn
periodically so healing does not wait for the next submission.

With `SPARK_RAPIDS_TPU_FLEET_WORKERS=1` (the default) the fleet is one
worker and every routing rule degenerates to "that worker" — serving
behavior is the single-worker `ServingScheduler` path, regression-held
byte-identical by tests/test_fleet.py.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set

from . import cache as cache_mod
from .router import HashRing
from .scheduler import (PRIORITIES, ServingRejectedError, ServingScheduler,
                        Ticket)

__all__ = ["FleetScheduler", "FleetSession", "FleetTicket", "FleetWorker"]

# pressure-score penalty for a non-closed breaker: a worker whose device
# is quarantined can still serve (CPU-degraded), but routing NEW work at
# it when healthy replicas exist is self-harm
_BREAKER_PENALTY = 1000.0


class FleetWorker:
    """One executor worker: a full single-worker serving stack under a
    worker id. Every layer is worker-scoped on purpose — a breaker trip,
    a poisoned stats entry, or a cache eviction storm on one worker must
    never bleed into its replicas (failure isolation is the point of
    having replicas)."""

    def __init__(self, worker_id: str, *, scheduler_kwargs=None):
        from ..plan.executor import PlanExecutor
        from ..plan.stats import StatsStore
        from ..runtime.health import DeviceHealthMonitor
        self.id = worker_id
        self.health = DeviceHealthMonitor(worker_id=worker_id)
        self.executor = PlanExecutor(mode="eager", health=self.health,
                                     worker_id=worker_id)
        # path="": a worker's observations are its own — N workers
        # replaying one persisted JSONL would each double-count it
        self.stats = StatsStore(path="")
        self.scheduler = ServingScheduler(self.executor,
                                          stats_store=self.stats,
                                          **(scheduler_kwargs or {}))
        self.alive = True
        # draining: still alive (finishing in-flight work) but no NEW
        # routing — the half-state graceful drain needs that kill lacks
        self.draining = False

    # The gossip surface: every cross-worker stats reach goes through
    # these wrappers so the isolation linter (tools/lint_concurrency.py)
    # can sanction the worker's OWN surface instead of allowlisting raw
    # `w.stats.*` reaches all over fleet.py.

    def drain_trips(self):
        """Get-and-reset the health monitor's attributed trip log —
        (fingerprint, reason) pairs the quarantine logic consumes."""
        return self.health.drain_trips()

    def gossip_export(self, fps=None):
        """This worker's observed plan rows (caps, high-water bytes,
        run counts) for merging into peers on death/drain/respawn."""
        return self.stats.export_plans(fps)

    def gossip_merge(self, rows) -> int:
        """High-water merge of peer observations into this worker's
        stats store; idempotent, returns the number of rows changed."""
        return self.stats.merge_plans(rows)

    def pressure_score(self) -> float:
        """Scalar load rank for the router: queued + active work, plus a
        large penalty when the breaker is not closed. Cheap by contract
        — this runs on every routed submission."""
        p = self.scheduler.pressure()
        score = float(p["queued"] + p["active"])
        if p["breaker"] != "closed":
            score += _BREAKER_PENALTY
        return score


class FleetTicket:
    """A submission's fleet-level handle. Wraps the current worker-level
    `Ticket` and re-routes itself through `FleetScheduler._replay` when
    the worker serving it dies — the tenant sees one ticket with one
    result, whatever happened underneath. `worker` names the worker that
    SERVED the result; `result().worker` (stamped by the executor) names
    the one that COMPUTED it, which differs exactly when a consistent-
    hash cache hit served another worker's computation."""

    def __init__(self, fleet: "FleetScheduler", sid: str, plan, inputs):
        self._fleet = fleet
        self.session = sid
        self.plan = plan
        self.inputs = inputs
        self.worker = ""                # serving worker id
        self.replays = 0
        # why this ticket ever left its first worker: "" (never did),
        # "killed" / "reaped" / "drained" (proactive fleet failover) or
        # "self_heal" (result() discovered the death itself)
        self.failover_reason = ""
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._inner: Optional[Ticket] = None
        self._inner_worker = ""
        self._failed: Optional[BaseException] = None
        self._replaying = False

    def _bind(self, inner: Ticket, worker_id: str) -> None:
        with self._lock:
            self._inner = inner
            self._inner_worker = worker_id
            self.worker = worker_id
            inner.worker = worker_id
            self._cond.notify_all()
        # register OUTSIDE the ticket lock: an already-completed inner
        # invokes the callback inline, and _wake re-takes the lock
        inner.add_done_callback(self._wake)

    def _wake(self, _inner) -> None:
        """Done-callback from the CURRENT (or a superseded) inner
        ticket: wake result() waiters. Spurious wakeups from a stale
        inner are harmless — the waiter re-checks under the lock."""
        with self._lock:
            self._cond.notify_all()

    def _current(self):
        with self._lock:
            return self._inner, self._inner_worker

    def _fail(self, err: BaseException) -> None:
        """Terminal failure, under the ticket lock — `done()`/`result()`
        read `_failed` under the same lock, so a lock-free write here
        (the pre-lockdep bug) could be reordered past a concurrent
        `done()` that already answered False and will never re-poll."""
        with self._lock:
            self._failed = err
            self._cond.notify_all()

    def done(self) -> bool:
        with self._lock:
            if self._failed is not None:
                return True
            inner = self._inner
        return inner is not None and inner.done()

    @property
    def queue_wait_ms(self) -> float:
        inner, _ = self._current()
        return 0.0 if inner is None else inner.queue_wait_ms

    @property
    def cached(self) -> bool:
        inner, _ = self._current()
        return False if inner is None else inner.cached

    @property
    def charge_source(self) -> str:
        inner, _ = self._current()
        return "" if inner is None else inner.charge_source

    def result(self, timeout: Optional[float] = None):
        """Block for the outcome, transparently surviving worker death:
        a typed `closed` rejection from a worker the fleet knows is dead
        replays on a survivor instead of raising.

        Waits on a condition the inner ticket's done-callback notifies
        (`_wake`, re-armed on every re-bind) — completion wakes the
        waiter immediately instead of on the next slot of a fixed poll
        loop. The bounded wait slice below is insurance against a
        missed edge, not the wakeup mechanism."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            with self._lock:
                if self._failed is not None:
                    raise self._failed
                inner, wid = self._inner, self._inner_worker
                if inner is None or not inner.done():
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError(
                            f"fleet ticket [session={self.session}] not "
                            f"complete after {timeout}s")
                    self._cond.wait(1.0 if remaining is None
                                    else min(1.0, remaining))
                    continue
            # harvest OUTSIDE the ticket lock: result(0) cannot block
            # (inner.done() above), and the self-heal path below takes
            # fleet-level locks the ticket lock must never sit under
            try:
                return inner.result(0)
            except TimeoutError:
                continue        # raced with a re-bind: re-check
            except ServingRejectedError as e:
                if e.reason == "closed" and \
                        not self._fleet._worker_alive(wid):
                    with self._lock:
                        if not self.failover_reason:
                            self.failover_reason = "self_heal"
                    self._fleet._replay(self)
                    continue
                raise


class _SessRec:
    """Fleet-side per-session record (guarded by the fleet lock):
    open-session parameters (replayed onto every worker the session
    touches), the affinity pin, and the in-flight tickets failover must
    replay."""

    def __init__(self, sid: str, weight: float, priority: str,
                 quota_bytes: Optional[int]):
        self.id = sid
        self.weight = weight
        self.priority = priority
        self.quota_bytes = quota_bytes
        self.affinity: Optional[str] = None
        self.handles: Dict[str, object] = {}   # worker id -> ServingSession
        self.tickets: Set[FleetTicket] = set()
        self.closed = False


class FleetSession:
    """One tenant's handle onto the fleet — same surface as
    `ServingSession` (submit/run/close, context manager), with the
    routing hidden behind it."""

    def __init__(self, fleet: "FleetScheduler", rec: _SessRec):
        self._fleet = fleet
        self._rec = rec
        self.id = rec.id

    def submit(self, plan, inputs: Optional[Dict] = None, *,
               block: Optional[bool] = None,
               timeout: Optional[float] = None) -> FleetTicket:
        return self._fleet._submit(self._rec, plan, inputs,
                                   block=block, timeout=timeout)

    def run(self, plan, inputs: Optional[Dict] = None, *,
            block: Optional[bool] = None,
            timeout: Optional[float] = None):
        t0 = time.monotonic()
        ticket = self.submit(plan, inputs, block=block, timeout=timeout)
        remaining = (None if timeout is None
                     else max(0.0, timeout - (time.monotonic() - t0)))
        return ticket.result(remaining)

    def close(self) -> None:
        self._fleet._close_session(self._rec)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class FleetScheduler:
    """The router tier: N workers, one front door.

    `open_session()` mirrors `ServingScheduler.open_session` and returns
    a `FleetSession`; every knob parameter not listed here passes
    through to each worker's `ServingScheduler` via
    `scheduler_kwargs`."""

    def __init__(self, workers: Optional[int] = None, *,
                 ring_replicas: Optional[int] = None,
                 spill_ratio: Optional[float] = None,
                 respawn: Optional[bool] = None,
                 respawn_max: Optional[int] = None,
                 respawn_backoff_ms: Optional[float] = None,
                 quarantine: Optional[str] = None,
                 hot_replicas: Optional[int] = None,
                 hot_k: Optional[int] = None,
                 sweep_ms: Optional[float] = None,
                 scheduler_kwargs: Optional[Dict] = None):
        from .. import config
        n = (config.fleet_workers() if workers is None
             else max(1, int(workers)))
        self.spill_ratio = (config.fleet_spill_ratio() if spill_ratio
                            is None else float(spill_ratio))
        # self-healing knobs (docs/serving.md#fleet-self-healing)
        self.respawn = (config.fleet_respawn() if respawn is None
                        else bool(respawn))
        self.respawn_max = (config.fleet_respawn_max() if respawn_max
                            is None else max(0, int(respawn_max)))
        self.respawn_backoff_ms = (
            config.fleet_respawn_backoff_ms() if respawn_backoff_ms
            is None else max(0.0, float(respawn_backoff_ms)))
        self.quarantine_policy = (config.fleet_quarantine()
                                  if quarantine is None else quarantine)
        if self.quarantine_policy not in ("reject", "degrade"):
            raise ValueError(
                f"quarantine policy must be 'reject' or 'degrade', "
                f"got {self.quarantine_policy!r}")
        self.hot_replicas = (config.fleet_hot_replicas() if hot_replicas
                             is None else max(0, int(hot_replicas)))
        self.hot_k = (config.fleet_hot_k() if hot_k is None
                      else max(0, int(hot_k)))
        self.sweep_ms = (config.fleet_sweep_ms() if sweep_ms is None
                         else max(0.0, float(sweep_ms)))
        self._lock = threading.Lock()
        self._workers: Dict[str, FleetWorker] = {}
        self._ring = HashRing(replicas=ring_replicas)
        self._sessions: Dict[str, _SessRec] = {}
        self._closed = False
        # invalidation bus state: last input digest seen per fingerprint
        from ..utils.lru import LruDict
        self._digests: Dict[str, str] = LruDict(4096)
        # self-healing state: the size auto-respawn heals back to, the
        # monotonic worker-id counter (ids are NEVER reused — quarantine
        # counts trips per distinct worker incarnation), the poison map
        # (fingerprint -> worker ids whose breakers it tripped), the
        # quarantine set, the respawn rate-limit clock, and the router-
        # side run counter hot replication ranks fingerprints by
        self.target_workers = n
        self._widx = n
        self._poison: Dict[str, Set[str]] = LruDict(512)
        self._quarantined: Dict[str, str] = LruDict(256)
        self._respawn_last = 0.0
        self._respawn_streak = 0
        self._fp_runs: Dict[str, int] = LruDict(4096)
        # observability counters
        self.routes_affinity = 0
        self.routes_ring = 0
        self.routes_spill = 0
        self.failovers = 0
        self.replayed_jobs = 0
        self.bus_publishes = 0
        self.cache_promotions = 0
        self.killed = 0
        self.reaped = 0
        self.drained = 0
        self.respawned = 0
        self.respawn_deferred = 0
        self.replications = 0
        self.gossips = 0
        self.quarantine_hits = 0
        for i in range(n):
            self._add_worker_locked(f"w{i}",
                                    scheduler_kwargs=scheduler_kwargs)
        self._scheduler_kwargs = dict(scheduler_kwargs or {})
        # background health sweep: reap stuck-OPEN breakers and top the
        # fleet back up without waiting for the next submission to
        # trigger healing (0 = off; tests drive healing synchronously)
        self._sweep_stop = threading.Event()
        self._sweep_thread: Optional[threading.Thread] = None
        if self.sweep_ms > 0:
            self._sweep_thread = threading.Thread(
                target=self._sweep_loop, name="fleet-sweep", daemon=True)
            self._sweep_thread.start()

    # ---- membership --------------------------------------------------------

    def _add_worker_locked(self, wid: str, *, scheduler_kwargs=None):
        w = FleetWorker(wid, scheduler_kwargs=scheduler_kwargs)
        self._workers[wid] = w
        self._ring.add(wid)
        return w

    def _next_wid_locked(self) -> str:
        """Monotonic, never-reused worker id. Reusing a dead worker's
        name would alias its incarnation in the poison map — a respawn
        that 'inherits' the trips of the corpse it replaced would
        quarantine fingerprints off one worker's evidence."""
        wid = f"w{self._widx}"
        self._widx += 1
        return wid

    def add_worker(self) -> str:
        """Scale out by one worker (join): only ~1/n of the fingerprint
        keyspace re-homes onto it. Raises the self-healing target size
        — the fleet now heals back to the larger fleet."""
        with self._lock:
            if self._closed:
                raise ServingRejectedError("closed", "fleet is shut down")
            wid = self._next_wid_locked()
            self._add_worker_locked(
                wid, scheduler_kwargs=self._scheduler_kwargs)
            self.target_workers += 1
        return wid

    def _worker_alive(self, wid: str) -> bool:
        with self._lock:
            w = self._workers.get(wid)
            return w is not None and w.alive

    def _live_workers_locked(self) -> List[FleetWorker]:
        return [w for w in self._workers.values() if w.alive]

    def _routable_locked(self) -> List[FleetWorker]:
        """Workers new submissions may land on: alive and not draining
        (a draining worker still finishes its in-flight work — it is
        live for gossip and the invalidation bus, dead for routing)."""
        return [w for w in self._workers.values()
                if w.alive and not w.draining]

    def kill_worker(self, wid: str, *, _cause: str = "killed") -> int:
        """Deliberate worker death (the chaos soak's kill-mid-storm):
        remove from the ring, fail its queue, replay every incomplete
        tracked submission on a survivor. Returns the number of
        in-flight jobs failed over — a job that manages to FINISH on
        the dying worker during the drain keeps that result and is not
        re-submitted (`metrics()["replayed_jobs"]` counts actual
        re-submissions). In-execution jobs whose tickets were already
        re-bound discard the late result (first-completion-wins is
        safe: execution is deterministic, both completions are the
        same bytes).

        Before the worker disappears the fleet (1) absorbs its
        attributed breaker trips into the poison map — the incarnation
        dies, its evidence does not — and (2) gossips its stats-store
        observations to every survivor, so rehomed fingerprints charge
        observed bytes (and skip compile churn) wherever they land.
        With respawn enabled a replacement is spawned afterward."""
        with self._lock:
            w = self._workers.get(wid)
            if w is None or not w.alive:
                return 0
            routable = self._routable_locked()
            if w in routable and len(routable) <= 1:
                raise ValueError(
                    f"cannot kill {wid}: it is the last live worker")
            self._absorb_trips_locked(w)
            rows = w.gossip_export()
            if rows:
                for peer in self._live_workers_locked():
                    if peer is not w:
                        peer.gossip_merge(rows)
                        self.gossips += 1
            w.alive = False
            self._ring.remove(wid)
            self.failovers += 1
            if _cause == "reaped":
                self.reaped += 1
            else:
                self.killed += 1
            orphans: List[FleetTicket] = []
            for rec in self._sessions.values():
                if rec.affinity == wid:
                    rec.affinity = None
                rec.handles.pop(wid, None)
                for t in list(rec.tickets):
                    if t.done():
                        rec.tickets.discard(t)
                    elif t._current()[1] == wid:
                        t.failover_reason = t.failover_reason or _cause
                        orphans.append(t)
        # close OUTSIDE the fleet lock: drain=False completes queued
        # tickets with the typed "closed" rejection (self-heal path) and
        # waits on active jobs — holding the lock here would stall every
        # route until the dead worker's in-flight work unwinds
        w.scheduler.close(drain=False, timeout=30.0)
        for t in orphans:
            self._replay(t)
        self._maybe_respawn()
        return len(orphans)

    def reap_unhealthy(self) -> List[str]:
        """Kill workers whose breaker is stuck OPEN with no cooldown to
        self-arm (cooldown_s <= 0): that worker will refuse device work
        until operator intervention, so its sessions fail over now. A
        breaker WITH a cooldown is left alone — it will half-open and
        probe by itself, and the CPU-degraded tier keeps serving
        meanwhile. Never kills the last live worker. Reaps count under
        `metrics()["reaped"]` (not `killed`), and with respawn enabled
        each reap spawns a replacement."""
        doomed = []
        with self._lock:
            for w in self._live_workers_locked():
                br = w.health.breaker
                if w.alive and br.state == "open" and br.cooldown_s <= 0:
                    doomed.append(w.id)
        out = []
        for wid in doomed:
            try:
                self.kill_worker(wid, _cause="reaped")
                out.append(wid)
            except ValueError:
                break               # last live worker: keep serving
        return out

    def drain_worker(self, wid: str,
                     timeout: Optional[float] = None) -> int:
        """Graceful decommission: stop routing NEW work at `wid`
        immediately (ring removal + affinity unpin), let its in-flight
        and queued work FINISH under `timeout`, then remove it and
        replay only the stragglers the deadline cut off
        (`failover_reason == "drained"`). The polite sibling of
        `kill_worker` — a planned node rotation should not throw away
        work the worker was mid-way through. Returns the number of
        stragglers replayed; with respawn enabled a replacement is
        spawned afterward."""
        with self._lock:
            w = self._workers.get(wid)
            if w is None or not w.alive or w.draining:
                return 0
            routable = self._routable_locked()
            if w in routable and len(routable) <= 1:
                raise ValueError(
                    f"cannot drain {wid}: it is the last live worker")
            w.draining = True
            self._ring.remove(wid)
            self._absorb_trips_locked(w)
            rows = w.gossip_export()
            if rows:
                for peer in self._routable_locked():
                    peer.gossip_merge(rows)
                    self.gossips += 1
            for rec in self._sessions.values():
                if rec.affinity == wid:
                    rec.affinity = None
        # drain OUTSIDE the fleet lock: this BLOCKS until the worker's
        # queue and active jobs finish (or the deadline) — the whole
        # point of drain over kill, and exactly why the lock can't be
        # held (every route would stall behind the drain)
        w.scheduler.close(drain=True, timeout=timeout)
        stragglers: List[FleetTicket] = []
        with self._lock:
            w.alive = False
            self.failovers += 1
            self.drained += 1
            for rec in self._sessions.values():
                rec.handles.pop(wid, None)
                for t in list(rec.tickets):
                    if t.done():
                        rec.tickets.discard(t)
                    elif t._current()[1] == wid:
                        t.failover_reason = t.failover_reason or "drained"
                        stragglers.append(t)
        for t in stragglers:
            self._replay(t)
        self._maybe_respawn()
        return len(stragglers)

    # ---- self-healing ------------------------------------------------------

    def _absorb_trips_locked(self, w: FleetWorker) -> None:
        """Drain `w`'s attributed breaker-trip log into the poison map
        and quarantine any fingerprint that has now tripped breakers on
        >= 2 DISTINCT worker incarnations. One worker tripping could be
        that worker's hardware; the same fingerprint wrecking two
        isolated stacks is the plan's fault — and with auto-respawn on,
        NOT quarantining it turns the healer into a crash amplifier
        (every replacement worker dies the same death)."""
        for fp, reason in w.drain_trips():
            if not fp:
                continue            # trip outside any attribution scope
            trippers = self._poison.get(fp)
            if trippers is None:
                trippers = set()
            trippers.add(w.id)
            self._poison[fp] = trippers     # (re)insert refreshes LRU
            if len(trippers) >= 2 and fp not in self._quarantined:
                self._quarantined[fp] = reason or "breaker"

    def quarantined(self) -> Dict[str, str]:
        """Snapshot of quarantined fingerprints -> trip reason."""
        with self._lock:
            return dict(self._quarantined)

    def _maybe_respawn(self) -> List[str]:
        """Top the fleet back up to `target_workers` (if respawn is
        enabled), within the lifetime budget and the exponential
        backoff. Each newborn gets the full gossip of every live peer's
        stats observations — it joins knowing every observed cap and
        high-water byte count the fleet has ever measured — and hot
        fingerprints re-replicate so its ring arc is warm. Deferred
        (budget- or backoff-blocked) attempts count under
        `respawn_deferred`; the sweep retries them."""
        spawned: List[str] = []
        while True:
            with self._lock:
                if self._closed or not self.respawn:
                    break
                if len(self._routable_locked()) >= self.target_workers:
                    break
                if self.respawned >= self.respawn_max:
                    self.respawn_deferred += 1
                    break
                now = time.monotonic()
                base = self.respawn_backoff_ms / 1e3
                # _respawn_last == 0.0 is the "never respawned" sentinel
                # (monotonic's epoch is arbitrary): the first respawn is
                # never backoff-gated
                if base > 0 and self._respawn_last > 0.0:
                    # a quiet fleet forgets its crash streak; a churning
                    # one doubles its wait (capped) so a crash-looping
                    # root cause cannot spin workers at full speed
                    if now - self._respawn_last > 16 * base:
                        self._respawn_streak = 0
                    wait = base * (2 ** self._respawn_streak)
                    if now - self._respawn_last < wait:
                        self.respawn_deferred += 1
                        break
                wid = self._next_wid_locked()
                w = self._add_worker_locked(
                    wid, scheduler_kwargs=self._scheduler_kwargs)
                self.respawned += 1
                self._respawn_last = now
                self._respawn_streak = min(self._respawn_streak + 1, 8)
                rows = []
                for peer in self._live_workers_locked():
                    if peer is not w:
                        rows.extend(peer.gossip_export())
                if rows:
                    w.gossip_merge(rows)
                    self.gossips += 1
                self._replicate_hot_locked()
                spawned.append(wid)
        return spawned

    def _hot_fps_locked(self) -> Set[str]:
        """Fingerprints worth replicating: >= 2 observed runs AND in
        the top-`hot_k` by run count — one-shot plans are not worth a
        replica slot, and K bounds replication work on wide traffic."""
        import heapq
        cand = [(n, fp) for fp, n in self._fp_runs.items() if n >= 2]
        return {fp for _, fp in heapq.nlargest(self.hot_k, cand)}

    def _replicate_locked(self, fp: str, digest: str) -> None:
        """Warm failover: copy the frozen cache entry for (fp, digest)
        onto the next `hot_replicas` distinct ring successors of `fp`'s
        primary. When the primary dies, the ring rehomes `fp` to
        exactly its first successor — which already holds the entry, so
        the failover serves a hit instead of recompiling. Entries are
        adopted frozen (shared, immutable) and TTL'd/invalidated like
        any other entry: the bus drops primary AND replicas together."""
        owners = self._ring.route_multi(fp, 1 + self.hot_replicas)
        if len(owners) < 2:
            return
        key = (fp, digest)
        ent, src = None, None
        for w in self._live_workers_locked():
            ent = w.scheduler.cache.peek_frozen(key)
            if ent is not None:
                src = w
                break
        if ent is None:
            return                  # nothing computed/cached yet
        for wid in owners[1:]:
            w = self._workers.get(wid)
            if w is None or not w.alive or w is src:
                continue
            if w.scheduler.cache.peek_frozen(key) is None:
                w.scheduler.cache.adopt(key, ent[0], ent[1])
                self.replications += 1

    def _replicate_hot_locked(self) -> None:
        """Re-derive replica placement for every hot fingerprint —
        membership changed (join/respawn), so ring successor sets
        changed with it (minimally: route_multi's walk)."""
        if self.hot_replicas <= 0 or self.hot_k <= 0:
            return
        for fp in self._hot_fps_locked():
            digest = self._digests.get(fp)
            if digest is not None:
                self._replicate_locked(fp, digest)

    def _sweep_loop(self) -> None:
        """Background health sweep: absorb trip logs (quarantine does
        not wait for the next submission), reap stuck-open breakers,
        and retry deferred respawns. Best-effort by design — a sweep
        pass that loses a race with a concurrent kill just retries next
        period."""
        period = max(self.sweep_ms / 1e3, 1e-3)
        while not self._sweep_stop.wait(period):
            try:
                with self._lock:
                    if self._closed:
                        return
                    for w in self._live_workers_locked():
                        self._absorb_trips_locked(w)
                self.reap_unhealthy()
                self._maybe_respawn()
            except Exception:
                pass                # the sweep must outlive any one bug

    # ---- sessions ----------------------------------------------------------

    def open_session(self, session_id: Optional[str] = None, *,
                     weight: float = 1.0, priority: str = "normal",
                     quota_bytes: Optional[int] = None) -> FleetSession:
        if priority not in PRIORITIES:
            raise ValueError(f"unknown priority {priority!r} (expected "
                             f"one of {sorted(PRIORITIES)})")
        if weight <= 0:
            raise ValueError(f"session weight must be > 0, got {weight}")
        with self._lock:
            if self._closed:
                raise ServingRejectedError("closed", "fleet is shut down")
            sid = session_id or f"fs{len(self._sessions) + 1}"
            old = self._sessions.get(sid)
            if old is not None and not old.closed:
                raise ValueError(f"session id {sid!r} already open")
            rec = _SessRec(sid, float(weight), priority, quota_bytes)
            self._sessions[sid] = rec
        return FleetSession(self, rec)

    def _close_session(self, rec: _SessRec) -> None:
        with self._lock:
            rec.closed = True
            handles = list(rec.handles.values())
        for h in handles:
            try:
                h.close()
            except Exception:
                pass

    def _handle_locked(self, rec: _SessRec, w: FleetWorker):
        """The session's ServingSession on worker `w`, opened lazily
        with the fleet-level parameters — the SAME session id on every
        worker, so retry budgets and sticky windows key on the tenant
        wherever its plans land."""
        h = rec.handles.get(w.id)
        if h is None:
            h = w.scheduler.open_session(rec.id, weight=rec.weight,
                                         priority=rec.priority,
                                         quota_bytes=rec.quota_bytes)
            rec.handles[w.id] = h
        return h

    # ---- routing -----------------------------------------------------------

    def _route_locked(self, rec: _SessRec, plan) -> FleetWorker:
        live = self._routable_locked()
        if not live:
            raise ServingRejectedError(
                "closed", "no live workers", session=rec.id)
        if len(live) == 1:
            rec.affinity = live[0].id
            return live[0]
        # 1. affinity: in-flight work pins the session (retry budgets /
        # sticky windows key on (session, worker) — a mid-plan re-home
        # would reset them and un-bound the very storms they bound)
        if rec.affinity is not None:
            w = self._workers.get(rec.affinity)
            if w is not None and w.alive and not w.draining and \
                    any(not t.done() for t in rec.tickets):
                self.routes_affinity += 1
                return w
        # 2. consistent hash on the canonical fingerprint
        wid = self._ring.route(plan.fingerprint)
        w = self._workers.get(wid) if wid is not None else None
        if w is None or not w.alive:
            w = min(live, key=lambda x: x.pressure_score())
        chosen, how = w, "ring"
        # 3. load-aware spillover: locality yields to overload
        if self.spill_ratio > 0:
            best = min(live, key=lambda x: x.pressure_score())
            if best is not w and w.pressure_score() > \
                    self.spill_ratio * (best.pressure_score() + 1.0):
                chosen, how = best, "spill"
        if how == "spill":
            self.routes_spill += 1
        else:
            self.routes_ring += 1
        rec.affinity = chosen.id
        return chosen

    def _publish_invalidation_locked(self, fp: str, digest: str) -> None:
        """A fingerprint re-submitted over CHANGED data: every worker's
        result cache drops its old-digest entries (they answer a
        question nobody is asking anymore) and its stats store forgets
        the plan's observed sizes (measured over the old data). The new
        digest's entries stay — they are sound."""
        for w in self._live_workers_locked():
            try:
                w.scheduler.cache.invalidate_fingerprint(fp,
                                                         keep_digest=digest)
                w.stats.forget_plan(fp)
            except Exception:
                pass                # bus is best-effort: serving goes on
        self.bus_publishes += 1

    def _promote_locked(self, w: FleetWorker, key) -> None:
        """Cross-worker cache promotion: the routed worker would miss,
        but a peer computed this exact (fingerprint, digest) already —
        adopt the peer's frozen entry so the ring-home worker serves the
        hit. The adopted entry keeps its `worker` stamp, so the served
        copy still names the worker that COMPUTED it (the soak's
        locality proof: hit served by a different worker than computed
        it). Affinity and spillover divert computations off their ring
        home; promotion is what brings the results back."""
        if w.scheduler.cache.peek_frozen(key) is not None:
            return
        for other in self._live_workers_locked():
            if other is w:
                continue
            ent = other.scheduler.cache.peek_frozen(key)
            if ent is not None:
                w.scheduler.cache.adopt(key, ent[0], ent[1])
                self.cache_promotions += 1
                return

    # ---- submission --------------------------------------------------------

    def _submit(self, rec: _SessRec, plan, inputs: Optional[Dict], *,
                block: Optional[bool],
                timeout: Optional[float]) -> FleetTicket:
        if self._closed or rec.closed:
            raise ServingRejectedError(
                "closed", "session or fleet is shut down", session=rec.id)
        from ..plan.executor import bind_scan_sources
        ticket = FleetTicket(self, rec.id, plan, inputs)
        # same binding prologue the worker's scheduler applies — the bus
        # must see the digest the cache key will see, or it invalidates
        # on a phantom change
        digest = cache_mod.input_digest(bind_scan_sources(plan, inputs))
        fp = plan.fingerprint
        with self._lock:
            # quarantine arms WITH respawn (and only then): it exists
            # to keep the healer from feeding a crash-amplifying plan
            # to every replacement worker. A fleet without respawn
            # keeps the pre-self-healing admission behavior (breaker
            # trips degrade and recover per worker, nothing fleet-wide)
            pin_cpu = False
            if self.respawn:
                # absorb attributed breaker trips BEFORE admission: a
                # fingerprint that just earned its second distinct-
                # worker trip must not be admitted a third time
                for lw in self._live_workers_locked():
                    self._absorb_trips_locked(lw)
            if self.respawn and fp in self._quarantined:
                self.quarantine_hits += 1
                if self.quarantine_policy == "reject":
                    raise ServingRejectedError(
                        "quarantined",
                        f"fingerprint {fp[:12]} tripped breakers on "
                        f">= 2 distinct workers "
                        f"({self._quarantined[fp]})", session=rec.id)
                pin_cpu = True      # degrade: serve it, CPU tier only
            self._fp_runs[fp] = self._fp_runs.get(fp, 0) + 1
            # the bus is CROSS-worker coherence: with one live worker
            # its own digest-keyed cache is already coherent, and bus
            # eviction would diverge from the single-worker scheduler's
            # behavior (the workers=1 byte-identical regression)
            if digest is not None and len(self._live_workers_locked()) > 1:
                last = self._digests.get(fp)
                if last is not None and last != digest:
                    self._publish_invalidation_locked(fp, digest)
                self._digests[fp] = digest
            w = self._route_locked(rec, plan)
            if digest is not None and len(self._workers) > 1:
                self._promote_locked(w, (fp, digest))
                # warm failover: a fingerprint that just became (or
                # stays) hot keeps its frozen entry replicated on its
                # ring successors
                if (self.hot_replicas > 0 and self.hot_k > 0
                        and self._fp_runs.get(fp, 0) >= 2
                        and fp in self._hot_fps_locked()):
                    self._replicate_locked(fp, digest)
            handle = self._handle_locked(rec, w)
            rec.tickets.add(ticket)
            if len(rec.tickets) > 64:
                rec.tickets = {t for t in rec.tickets if not t.done()}
        try:
            inner = handle.submit(plan, inputs, block=block,
                                  timeout=timeout, pin_cpu=pin_cpu)
        except BaseException:
            # rejected at the worker's front door (queue_full /
            # over_quota / ...): the tenant sees the typed error — the
            # ticket must not linger as a failover-replayable orphan
            with self._lock:
                rec.tickets.discard(ticket)
            raise
        ticket._bind(inner, w.id)
        return ticket

    def _replay(self, ticket: FleetTicket) -> None:
        """Re-run one orphaned submission on a surviving worker
        (idempotent: a ticket already re-bound to a live worker is left
        alone — kill_worker's proactive replay and result()'s self-heal
        may race here)."""
        with ticket._lock:
            if ticket._replaying:
                return      # concurrent replay in flight: it will bind
            ticket._replaying = True
        try:
            self._replay_inner(ticket)
        finally:
            with ticket._lock:
                ticket._replaying = False

    def _replay_inner(self, ticket: FleetTicket) -> None:
        inner, _ = ticket._current()
        if inner is not None and inner.done():
            try:
                inner.result(0)
                return       # finished before the death: result stands
            except ServingRejectedError as e:
                if e.reason != "closed":
                    return   # typed front-door verdict: replay keeps it
            except BaseException:
                return       # execution error IS the answer (the worker
                #              scheduler already spent its retry budget)
        with self._lock:
            rec = self._sessions.get(ticket.session)
            if rec is None:
                ticket._fail(ServingRejectedError(
                    "closed", "session gone during failover",
                    session=ticket.session))
                return
            # already re-bound by a racing replay?
            cur_w = ticket._current()[1]
            w0 = self._workers.get(cur_w)
            if w0 is not None and w0.alive and not ticket.done():
                return
            # a fingerprint quarantined AFTER the original submission
            # replays under the quarantine policy — the whole point is
            # that a replay of a worker-killer must not kill again
            fp = ticket.plan.fingerprint
            pin_cpu = False
            if self.respawn and fp in self._quarantined:
                self.quarantine_hits += 1
                if self.quarantine_policy == "reject":
                    ticket._fail(ServingRejectedError(
                        "quarantined",
                        f"fingerprint {fp[:12]} quarantined during "
                        f"failover ({self._quarantined[fp]})",
                        session=ticket.session))
                    return
                pin_cpu = True
            try:
                w = self._route_locked(rec, ticket.plan)
            except ServingRejectedError as e:
                ticket._fail(e)
                return
            handle = self._handle_locked(rec, w)
            self.replayed_jobs += 1
            ticket.replays += 1
        try:
            inner = handle.submit(ticket.plan, ticket.inputs,
                                  pin_cpu=pin_cpu)
        except BaseException as e:
            ticket._fail(e)
            return
        ticket._bind(inner, w.id)

    # ---- lifecycle / observability -----------------------------------------

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        self._sweep_stop.set()
        with self._lock:
            self._closed = True
            workers = list(self._workers.values())
        if self._sweep_thread is not None:
            self._sweep_thread.join(timeout=5.0)
        for w in workers:
            if w.alive:
                w.scheduler.close(drain=drain, timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def metrics(self) -> Dict:
        """Fleet snapshot: per-worker serving metrics + pressure +
        liveness, ring membership, and the router's route/failover/bus
        counters (the multi-worker soak's assertion surface)."""
        with self._lock:
            workers = dict(self._workers)
            counters = {"routes_affinity": self.routes_affinity,
                        "routes_ring": self.routes_ring,
                        "routes_spill": self.routes_spill,
                        "failovers": self.failovers,
                        "replayed_jobs": self.replayed_jobs,
                        "bus_publishes": self.bus_publishes,
                        "cache_promotions": self.cache_promotions,
                        # self-healing: failovers split by cause, plus
                        # the healer's own bookkeeping
                        "killed": self.killed,
                        "reaped": self.reaped,
                        "drained": self.drained,
                        "respawned": self.respawned,
                        "respawn_deferred": self.respawn_deferred,
                        "replications": self.replications,
                        "gossips": self.gossips,
                        "quarantine_hits": self.quarantine_hits,
                        "quarantined": sorted(self._quarantined),
                        "target_workers": self.target_workers}
        out = {}
        for wid, w in workers.items():
            out[wid] = {"alive": w.alive,
                        "draining": w.draining,
                        "pressure": w.pressure_score() if w.alive else None,
                        "serving": w.scheduler.metrics() if w.alive
                        else None}
        return {"workers": out, "ring": list(self._ring.members()),
                **counters}
