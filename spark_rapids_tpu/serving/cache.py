"""Plan-result cache for the serving layer (docs/serving.md).

Identical traffic is the cheapest traffic: under multi-tenant load the
same dashboard/report plans arrive over and over against unchanged data,
and every repeat admission re-pays optimize + certify + execute. This
module keys a completed `PlanResult` by

    (canonical plan fingerprint, input-data digest)

— the same `optimizer.plan_fingerprint` canonical structural hash the
compiled-program cache shares (structurally identical plans built
independently hit together), crossed with a digest of the DATA the plan
was bound to. A fingerprint alone must never serve: the same plan over
new rows is a different answer, so the digest covers every input's
content (Table bindings hash their buffers; parquet-path sources hash
the path + size + mtime_ns identity — re-written files change identity;
in-memory byte sources hash the bytes). Any input the digest cannot
prove stable makes the plan UNCACHEABLE (sound-but-incomplete, the
certifier's philosophy) rather than cached on a guess. Table digests
memoize per object identity (weakref-guarded — Tables are immutable by
contract), so repeat submissions over the same binding pay the
device->host hash once, not per submit.

Only DEVICE-tier results enter the cache (the scheduler guards put):
a degraded result is a transient-condition artifact whose
`degraded=True` stamp would keep reporting CPU-tier completions to
healthy-device traffic for the whole TTL.

Served hits are COPIES (`cached_copy`): `cached=True` stamped on the
result, metrics deep-copied so a profile/bench consumer mutating or
summing per-op wall time can never double-attribute the original run's
numbers (and never mutate the cached entry itself). Eviction is LRU +
TTL; hits/misses/evictions/expirations drain to `stats()` and ride the
soak's JSONL `cache_hit` stamp.
"""
from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
import weakref
from typing import Dict, Optional, Tuple

import numpy as np


def _hash_array(h, a) -> None:
    if a is None:
        h.update(b"\x00none")
        return
    arr = np.asarray(a)
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(arr.tobytes())


def _hash_column(h, col) -> None:
    h.update(repr(col.dtype).encode())
    _hash_array(h, col.data)
    _hash_array(h, col.validity)
    _hash_array(h, col.offsets)
    for c in col.children:
        _hash_column(h, c)


# per-Table digest memo: hashing a Table's buffers costs a device->host
# copy of every buffer plus blake2b over the bytes — on every submit.
# Tables are immutable by contract, so the digest is a function of
# object identity; memoize it keyed by id() with a weakref guard (id()
# reuse after GC must not serve a dead table's digest) so repeat
# submissions over the same binding hash once, not per submit.
_table_digests: Dict[int, Tuple[object, str]] = {}
_digest_lock = threading.Lock()


def _table_digest(t) -> str:
    key = id(t)
    with _digest_lock:
        ent = _table_digests.get(key)
        if ent is not None and ent[0]() is t:
            return ent[1]
    h = hashlib.blake2b(digest_size=16)
    h.update(",".join(t.names).encode())
    for c in t.columns:
        _hash_column(h, c)
    digest = h.hexdigest()
    try:
        ref = weakref.ref(t, lambda _r, k=key: _evict_digest(k))
    except TypeError:
        return digest            # not weakref-able: correct, un-memoized
    with _digest_lock:
        _table_digests[key] = (ref, digest)
    return digest


def _evict_digest(key: int) -> None:
    with _digest_lock:
        _table_digests.pop(key, None)


def input_digest(inputs: Dict) -> Optional[str]:
    """Content digest of one input binding, or None when any input's
    stability cannot be proven (uncacheable — never guess)."""
    from ..columnar import Table
    h = hashlib.blake2b(digest_size=16)
    for name in sorted(inputs):
        v = inputs[name]
        h.update(name.encode())
        if isinstance(v, Table):
            h.update(b"table")
            h.update(_table_digest(v).encode())
            continue
        src = getattr(v, "source", None)
        if isinstance(src, str):
            # path identity: size + mtime_ns change when the file is
            # rewritten; a torn in-place append between stat and read is
            # the writer's race, same as any mmap consumer's
            try:
                st = os.stat(src)
            except OSError:
                return None
            h.update(b"path")
            h.update(src.encode())
            h.update(f"{st.st_size}:{st.st_mtime_ns}".encode())
        elif isinstance(src, bytes):
            h.update(b"bytes")
            h.update(src)
        else:
            return None         # unknown source kind: uncacheable
    return h.hexdigest()


def cache_key(plan, inputs: Dict) -> Optional[Tuple[str, str]]:
    """(canonical fingerprint, input digest), or None when uncacheable."""
    digest = input_digest(inputs)
    if digest is None:
        return None
    return (plan.fingerprint, digest)


def cached_copy(result):
    """A serve-safe copy of a cached PlanResult: `cached=True`, metrics
    and every mutable container deep-copied — the cache entry and all
    previously served copies stay untouched whatever the consumer does,
    and wall times remain attributed to the ORIGINAL run they measured
    (the cached stamp is how profile/bench consumers know not to count
    them again)."""
    from ..plan.executor import PlanResult
    metrics = {}
    for label, m in result.metrics.items():
        # dataclasses.replace copies every declared field; the
        # _kernel_sig side-channel intentionally does not survive — a
        # cached serve must never re-feed the stats store's timings
        metrics[label] = dataclasses.replace(m)
    copy = PlanResult(
        result.plan, result.table, result.valid, metrics, result.mode,
        result.wall_ms, attempts=result.attempts,
        caps=dict(result.caps) if result.caps else result.caps,
        retries=result.retries, degraded=result.degraded,
        breaker=dict(result.breaker) if result.breaker else result.breaker,
        backoff_ms=result.backoff_ms,
        jit_cache_hits=result.jit_cache_hits)
    copy.optimizer = (dict(result.optimizer)
                      if isinstance(result.optimizer, dict)
                      else result.optimizer)
    copy.cert = result.cert           # immutable bounds, shared by design
    copy.session = result.session
    # the worker stamp survives the copy ON PURPOSE: a hit names the
    # worker that COMPUTED the entry, not the one serving it — the
    # fleet soak's cross-worker cache-locality proof reads exactly this
    copy.worker = result.worker
    copy.cached = True
    return copy


class ResultCache:
    """Bounded LRU + TTL cache of completed PlanResults.

    `get` returns a `cached_copy` (never the entry), refreshes recency,
    and expires entries past the TTL; `put` stores a `cached_copy`-able
    original and evicts least-recently-used entries beyond `entries`.
    `entries=0` disables (get always misses, put drops)."""

    def __init__(self, entries: Optional[int] = None,
                 ttl_s: Optional[float] = None,
                 max_bytes: Optional[int] = None,
                 clock=time.monotonic):
        from .. import config
        self.entries = (config.serving_cache_entries() if entries is None
                        else max(0, int(entries)))
        self.ttl_s = (config.serving_cache_ttl_s() if ttl_s is None
                      else float(ttl_s))
        self.max_bytes = (config.serving_cache_bytes() if max_bytes is None
                          else max(1, int(max_bytes)))
        self._clock = clock
        self._lock = threading.Lock()
        # hand-rolled LRU (not utils/lru.LruDict): eviction here is
        # byte-weighted AND TTL'd, neither of which the shared bounded
        # dict models — entries are (stored_at, nbytes, result)
        self._data: Dict[Tuple[str, str], Tuple[float, int, object]] = {}
        self._resident_bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.expirations = 0
        self.oversize_skips = 0

    def get(self, key: Optional[Tuple[str, str]], *,
            count_miss: bool = True):
        """Serve a copy, refresh recency, expire past-TTL entries.
        `count_miss=False` keeps a re-consult of an already-counted key
        (the scheduler's dispatch-time burst dedup) out of the miss
        counter — stats must reflect traffic, not lookup plumbing."""
        if key is None or self.entries <= 0:
            return None
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                if count_miss:
                    self.misses += 1
                return None
            stored_at, nbytes, result = ent
            if self.ttl_s > 0 and self._clock() - stored_at > self.ttl_s:
                del self._data[key]
                self._resident_bytes -= nbytes
                self.expirations += 1
                if count_miss:
                    self.misses += 1
                return None
            # refresh recency (dict preserves insertion order)
            del self._data[key]
            self._data[key] = ent
            self.hits += 1
        # copy OUTSIDE the lock: concurrent hits (the burst shape the
        # dispatch-time consult exists for) must not serialize behind
        # one tenant's O(#ops) metric copies — the frozen entry is
        # immutable by contract, so the copy needs no exclusion
        return cached_copy(result)

    def put(self, key: Optional[Tuple[str, str]], result) -> None:
        if key is None or self.entries <= 0:
            return
        # resident-bytes accounting: cached tables are live buffers no
        # session quota charges (quotas cover in-flight execution, not
        # retention), so the cache bounds its own pin — and a single
        # result bigger than the whole budget never caches (a one-entry
        # cache that thrashes the budget serves nobody)
        from ..runtime.admission import operand_nbytes
        nbytes = operand_nbytes(result.table) + operand_nbytes(result.valid)
        if nbytes > self.max_bytes:
            with self._lock:
                self.oversize_skips += 1
            return
        # store a COPY, not the live result: the submitting caller still
        # holds the original and may mutate its metrics after completion
        # — the entry every future serve copies from must be frozen at
        # put time
        entry = cached_copy(result)
        with self._lock:
            self._insert_locked(key, nbytes, entry)

    def _insert_locked(self, key, nbytes: int, entry) -> None:
        old = self._data.pop(key, None)
        if old is not None:
            self._resident_bytes -= old[1]
        self._data[key] = (self._clock(), nbytes, entry)
        self._resident_bytes += nbytes
        while len(self._data) > self.entries or \
                self._resident_bytes > self.max_bytes:
            _, ev_bytes, _ = self._data.pop(next(iter(self._data)))
            self._resident_bytes -= ev_bytes
            self.evictions += 1

    def peek_frozen(self, key: Optional[Tuple[str, str]]):
        """The frozen entry as `(nbytes, result)` for cross-worker
        promotion (serving/fleet.py), or None. TTL-honored, but NO
        hit/miss accounting and no recency refresh — promotion is
        router plumbing, not tenant traffic, and must not skew the
        stats either cache reports."""
        if key is None or self.entries <= 0:
            return None
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                return None
            stored_at, nbytes, result = ent
            if self.ttl_s > 0 and self._clock() - stored_at > self.ttl_s:
                del self._data[key]
                self._resident_bytes -= nbytes
                self.expirations += 1
                return None
            return (nbytes, result)

    def adopt(self, key: Optional[Tuple[str, str]], nbytes: int,
              entry) -> None:
        """Insert an already-frozen entry promoted from a peer worker's
        cache. The frozen object is SHARED between the caches on
        purpose: entries are immutable by contract and every serve
        copies, so adoption costs a dict slot, not a table copy — and
        the entry keeps its original `worker` stamp, which is how a hit
        served here still names the worker that computed it."""
        if key is None or self.entries <= 0 or entry is None:
            return
        if nbytes > self.max_bytes:
            with self._lock:
                self.oversize_skips += 1
            return
        with self._lock:
            self._insert_locked(key, nbytes, entry)

    def invalidate_fingerprint(self, fingerprint: str,
                               keep_digest: Optional[str] = None) -> int:
        """Drop every entry for this plan fingerprint whose input digest
        differs from `keep_digest` (the fleet invalidation bus,
        serving/fleet.py: a source input changed, so results computed
        over the OLD data must stop serving everywhere — the entry for
        the new digest, if any, is still sound and survives). Returns
        the number of entries dropped; they count as evictions."""
        with self._lock:
            doomed = [k for k in self._data
                      if k[0] == fingerprint and k[1] != keep_digest]
            for k in doomed:
                _, nbytes, _ = self._data.pop(k)
                self._resident_bytes -= nbytes
                self.evictions += 1
            return len(doomed)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._data), "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions,
                    "expirations": self.expirations,
                    "resident_bytes": self._resident_bytes,
                    "oversize_skips": self.oversize_skips}
