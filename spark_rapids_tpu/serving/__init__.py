"""Multi-tenant serving layer (docs/serving.md).

`ServingScheduler` is the front door: N tenant sessions submit plans to
a bounded queue; a fair-share dispatcher (weighted deficit round-robin
over priority lanes, starvation-bounded) admits them through the health
monitor with per-session memory quotas sized by the static resource
certifier, exerts backpressure when the queue saturates, keys retry
budgets per tenant, and serves repeat traffic from a fingerprint +
data-digest result cache.

    from spark_rapids_tpu.serving import ServingScheduler

    with ServingScheduler() as sched:
        tenant = sched.open_session(priority="interactive")
        res = tenant.run(plan, {"t": table})
"""
from .cache import ResultCache, cache_key, cached_copy, input_digest
from .scheduler import (PRIORITIES, ServingRejectedError, ServingScheduler,
                        ServingSession, Ticket)

__all__ = ["ServingScheduler", "ServingSession", "Ticket",
           "ServingRejectedError", "ResultCache", "cache_key",
           "cached_copy", "input_digest", "PRIORITIES"]
