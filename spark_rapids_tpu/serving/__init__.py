"""Multi-tenant serving layer (docs/serving.md).

`ServingScheduler` is the front door: N tenant sessions submit plans to
a bounded queue; a fair-share dispatcher (weighted deficit round-robin
over priority lanes, starvation-bounded) admits them through the health
monitor with per-session memory quotas sized by the static resource
certifier, exerts backpressure when the queue saturates, keys retry
budgets per tenant, and serves repeat traffic from a fingerprint +
data-digest result cache.

    from spark_rapids_tpu.serving import ServingScheduler

    with ServingScheduler() as sched:
        tenant = sched.open_session(priority="interactive")
        res = tenant.run(plan, {"t": table})

`FleetScheduler` scales that out: a router tier fronting N such workers
— consistent-hash plan routing (serving/router.py), session affinity,
load spillover, failover replay when a worker dies, and a cross-worker
cache-invalidation bus (serving/fleet.py).

    from spark_rapids_tpu.serving import FleetScheduler

    with FleetScheduler(workers=4) as fleet:
        tenant = fleet.open_session(priority="interactive")
        res = tenant.run(plan, {"t": table})
"""
from .cache import ResultCache, cache_key, cached_copy, input_digest
from .fleet import FleetScheduler, FleetSession, FleetTicket, FleetWorker
from .router import HashRing
from .scheduler import (PRIORITIES, ServingRejectedError, ServingScheduler,
                        ServingSession, Ticket)

__all__ = ["ServingScheduler", "ServingSession", "Ticket",
           "ServingRejectedError", "ResultCache", "cache_key",
           "cached_copy", "input_digest", "PRIORITIES",
           "FleetScheduler", "FleetSession", "FleetTicket", "FleetWorker",
           "HashRing"]
