"""Consistent-hash routing ring for the fleet serving tier
(docs/serving.md#fleet).

The router's cache-locality promise is that the SAME plan fingerprint
lands on the SAME worker run after run — that worker's result cache,
stats store, and compiled-program caches stay warm for it — and that
promise must survive workers joining and leaving. A modulo assignment
(`hash(fp) % n`) reshuffles nearly every fingerprint when n changes; a
consistent-hash ring moves only the keys that mapped onto the departed
(or newly inserted) worker's arcs — about 1/n of the keyspace — which
is the textbook property the fleet's failover story leans on: killing
one worker re-homes that worker's fingerprints and NOBODY else's, so
the survivors' caches keep serving warm (Karger et al.; the same ring
every memcached/Dynamo-descended router ships).

Each worker owns `replicas` virtual points (blake2b of "name#i") so the
arc lengths even out; lookup is a bisect over the sorted point list —
O(log(workers x replicas)) per route, no per-key state. The ring is
deliberately dumb: membership changes and pressure-aware OVERRIDES of
the ring's answer (session affinity, load spillover) are fleet.py
policy, not ring mechanics.
"""
from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["HashRing"]


def _point(label: str) -> int:
    """Ring coordinate of one virtual node / key: the first 8 bytes of
    blake2b — stable across processes and Python hash randomization
    (`hash()` would re-home every fingerprint on restart)."""
    return int.from_bytes(
        hashlib.blake2b(label.encode(), digest_size=8).digest(), "big")


class HashRing:
    """Consistent-hash ring over named workers.

    `route(key)` returns the owning worker name (clockwise-next virtual
    point); `add`/`remove` change membership, moving only ~1/n of the
    keyspace each. Thread-safe — the fleet routes while membership
    changes under failover."""

    def __init__(self, replicas: Optional[int] = None):
        from .. import config
        self.replicas = (config.fleet_ring_replicas() if replicas is None
                         else max(1, int(replicas)))
        self._lock = threading.Lock()
        self._points: List[int] = []          # sorted virtual points
        self._owner: Dict[int, str] = {}      # point -> worker name
        self._members: Dict[str, List[int]] = {}

    def add(self, name: str) -> None:
        with self._lock:
            if name in self._members:
                return
            pts = []
            for i in range(self.replicas):
                p = _point(f"{name}#{i}")
                # vanishingly rare 64-bit collision: skip the point
                # rather than silently re-home another worker's arc
                if p in self._owner:
                    continue
                self._owner[p] = name
                bisect.insort(self._points, p)
                pts.append(p)
            self._members[name] = pts

    def remove(self, name: str) -> None:
        with self._lock:
            pts = self._members.pop(name, None)
            if not pts:
                return
            doomed = set(pts)
            for p in pts:
                del self._owner[p]
            self._points = [p for p in self._points if p not in doomed]

    def route(self, key: str) -> Optional[str]:
        """Owning worker for `key`, or None on an empty ring."""
        with self._lock:
            if not self._points:
                return None
            i = bisect.bisect_right(self._points, _point(key))
            if i == len(self._points):
                i = 0                          # wrap: the ring is a circle
            return self._owner[self._points[i]]

    def route_multi(self, key: str, n: int) -> List[str]:
        """The first `n` DISTINCT owners clockwise from `key`'s point —
        primary first, then the replica owners warm failover replicates
        hot entries to (serving/fleet.py). Same walk every quorum-style
        ring uses: membership changes re-derive replica sets with
        minimal remap (a join inserts itself into some sets, a leave
        drops itself — surviving members keep their relative order,
        which tests/test_fleet.py pins). Returns fewer than `n` names
        when the ring has fewer members."""
        with self._lock:
            if not self._points or n <= 0:
                return []
            out: List[str] = []
            start = bisect.bisect_right(self._points, _point(key))
            for off in range(len(self._points)):
                owner = self._owner[
                    self._points[(start + off) % len(self._points)]]
                if owner not in out:
                    out.append(owner)
                    if len(out) >= n:
                        break
            return out

    def members(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._members))

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._members
