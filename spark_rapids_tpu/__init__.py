"""spark_rapids_tpu — TPU-native columnar acceleration layer for Apache Spark.

A from-scratch re-design of the capabilities of NVIDIA's spark-rapids-jni
(reference at /root/reference; structural analysis in SURVEY.md) on an
idiomatic JAX/XLA/Pallas/PJRT stack:

- `columnar`: HBM-resident Arrow-layout Column/Table substrate (pytrees).
- `ops`: Spark-exact kernels — casts, hashes, bloom filter, decimal128
  arithmetic, datetime rebase, timezones, zorder, parse_uri, JSON→map,
  histogram/percentile, row↔columnar conversion, groupby/join/sort.
- `runtime`: host-side C++ task/memory arbitration state machine (retry,
  split-and-retry, BUFN, deadlock watchdog, OOM injection, metrics) — the
  TPU equivalent of SparkResourceAdaptor (SURVEY.md §2.2).
- `parallel`: device-mesh sharding + ICI/DCN all-to-all partition exchange
  (the slot the GPU stack fills with UCX shuffle).
- `plan`: physical-plan subsystem — typed operator DAG (Scan/Filter/…/
  HashJoin/HashAggregate/Exchange) over Table, validating builder, and an
  executor with eager / capped-jit / distributed tiers, per-operator
  metrics (explain/profile) and plan-granularity cap escalation.
- `serving`: multi-tenant front door — fair-share session scheduler with
  certified per-session memory quotas, bounded-queue backpressure,
  breaker-aware degradation, and a fingerprint+digest plan-result cache.
- `io`: native parquet footer parse/prune/filter + chunked page reader.
- `interop`: Arrow C Data Interface export/import (JVM-facing surface).
- `faultinj`: config-driven fault injection over the device-call surface.

int64 is pervasive in Spark data (timestamps, longs, xxhash64), so this
package enables jax x64 mode on import; XLA:TPU emulates s64/u64 with 32-bit
pairs, which is correct (full wrap-around) and off the hot matmul path.
"""
import jax

jax.config.update("jax_enable_x64", True)

from . import dtypes                                    # noqa: E402
from .columnar import Column, Table                     # noqa: E402

from .version import __version__, version_info

__all__ = ["dtypes", "Column", "Table", "api", "__version__", "version_info"]


_LAZY_SUBMODULES = ("api", "ops", "parallel", "io", "runtime", "interop",
                    "columnar", "faultinj", "config", "plan", "serving")


def __getattr__(name):
    # Subpackages import modules whose module-level jnp constants initialize
    # the JAX backend — lazy (PEP 562) so a bare `import spark_rapids_tpu`
    # stays side-effect-free and callers can pin a platform first (a dead
    # device tunnel would otherwise hang here).
    if name in _LAZY_SUBMODULES:
        import importlib
        return importlib.import_module("." + name, __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

# Fault-injector auto-load (reference: libcufaultinj.so via
# CUDA_INJECTION64_PATH at cuInit — faultinj/README.md:20-24).
from . import faultinj as _faultinj                     # noqa: E402

_faultinj.maybe_install_from_env()
