"""Spark-exact string→numeric casts (ANSI-aware), TPU-vectorized.

Re-design of the reference's cast kernels (cast_string.cu:158-244 string→int,
cast_string_to_float.cu:56-653 string→float, CastStringJni.cpp:159-258 base
conversions) for the XLA substrate: the reference marches one CUDA thread (or
warp) per row over the chars; here every rule is a dense boolean-matrix
computation over the padded (rows, max_len) char matrix, and digit
accumulation is a closed-form positional-weight multiply-reduce (each digit
times 10^rank-from-the-right in u64) rather than a sequential loop — one
fused XLA pass over the matrix instead of max_len dependent steps.

Spark semantics preserved:
- whitespace = {space, \\r, \\t, \\n} only (cast_string.cu:46-56);
- int casts: optional leading/trailing whitespace (strip), sign, truncation
  at the first '.' in non-ANSI mode with the tail still validated
  (cast_string.cu:210-213), digit-by-digit overflow detection against the
  target type's limits (cast_string.cu:100-143);
- ANSI mode errors carry the first failing row index and its string
  (cast_string.hpp:26-56, validate_ansi_column cast_string.cu:601-634);
- float casts: 'nan' only as the exact 3-char string, 'inf'/'infinity'
  (case-insensitive) must end the string, at most 19 significant digits
  accumulated into a uint64 with greedy 20th-digit absorption, 4-digit manual
  exponents, trailing f/F/d/D suffix allowed, value built as
  sign*digits*10^exp in double then cast (cast_string_to_float.cu:309-474);
  a zero mantissa skips trailing-suffix handling, so '0e5' and '0\\n' are
  valid zeros but '0f' is invalid (cast_string_to_float.cu:131-141) - a
  deliberate quirk kept for parity.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column
from ..dtypes import DType, Kind


class CastError(RuntimeError):
    """ANSI cast failure carrying the first bad row (cast_string.hpp:26-56)."""

    def __init__(self, row_number: int, string_with_error: str):
        super().__init__(
            f"Error casting data on row {row_number}: {string_with_error!r}")
        self.row_number = row_number
        self.string_with_error = string_with_error


_INT_LIMITS = {
    Kind.INT8: (-128, 127),
    Kind.INT16: (-32768, 32767),
    Kind.INT32: (-(2**31), 2**31 - 1),
    Kind.INT64: (-(2**63), 2**63 - 1),
}


def _is_ws(c):
    return (c == 32) | (c == 13) | (c == 9) | (c == 10)


def _first_idx(mask, default: int):
    """Per-row first True column index in (n, L) mask, `default` if none."""
    has = jnp.any(mask, axis=1)
    return jnp.where(has, jnp.argmax(mask, axis=1).astype(jnp.int32),
                     jnp.int32(default))


def _char_at(C, idx):
    """Per-row char at (clipped) dynamic index. C: (n, L) int32."""
    L = C.shape[1]
    return jnp.take_along_axis(C, jnp.clip(idx, 0, L - 1)[:, None], axis=1)[:, 0]


def _rank_in_mask(mask):
    """Exclusive per-row running count of True positions in an (n, L) mask:
    rank[i, j] = number of True entries strictly left of j in row i."""
    c = jnp.cumsum(mask, axis=1, dtype=jnp.int32)
    return c - mask.astype(jnp.int32)


# 10^k as u64 for k in [0, 19] (10^19 < 2^64); jnp.take per (n, L) exponent
# plane gives each digit its positional weight so a whole row's magnitude is
# one masked multiply-reduce instead of an L-step sequential accumulator
_POW10_U64 = np.array([10**k for k in range(20)], dtype=np.uint64)


def _raise_first_error(col: Column, error_mask):
    """ANSI contract: raise for the first flagged row with its content
    (validate_ansi_column, cast_string.cu:601-634)."""
    errors = np.asarray(error_mask)
    if errors.any():
        row = int(np.argmax(errors))
        strings = col.to_pylist()
        raise CastError(row, strings[row] if strings[row] is not None else "")


def string_to_integer(col: Column, out_type: DType, ansi_mode: bool = False,
                      strip: bool = True, pad_to: Optional[int] = None) -> Column:
    """Spark-exact string→INT8/16/32/64 (cast_string.cu:158-244).

    Returns a column of out_type; invalid rows null (or CastError in ANSI).
    """
    assert out_type.kind in _INT_LIMITS, f"not an integer type: {out_type}"
    tmin, tmax = _INT_LIMITS[out_type.kind]

    padded, lens = col.padded_chars(pad_to)
    C = padded.astype(jnp.int32)
    n, L = C.shape
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    lens2 = lens[:, None]
    in_str = pos < lens2
    ws = _is_ws(C)
    digit = (C >= 48) & (C <= 57)
    dot = C == 46

    valid_in = col.null_mask
    # leading whitespace skip
    if strip:
        i0 = _first_idx(~ws & in_str, 0)
        i0 = jnp.where(jnp.any(~ws & in_str, axis=1), i0, lens)
    else:
        i0 = jnp.zeros((n,), jnp.int32)
    # optional sign
    c0 = _char_at(C, i0)
    has_sign = ((c0 == 43) | (c0 == 45)) & (i0 < lens)
    neg = (c0 == 45) & has_sign
    istart = i0 + has_sign.astype(jnp.int32)

    valid = valid_in & (lens > 0) & (istart < lens)

    region = (pos >= istart[:, None]) & in_str
    # any char that is not digit / dot / whitespace is invalid
    valid &= ~jnp.any(region & ~digit & ~dot & ~ws, axis=1)
    # whitespace rules: with strip, the first ws begins the trailing region
    # (must not be the first char, everything after must be ws); without
    # strip any ws is invalid (cast_string.cu:207-222)
    ws_in = ws & region
    if strip:
        fw = _first_idx(ws_in, L)
        after_fw = region & (pos >= fw[:, None])
        valid &= ~jnp.any(after_fw & ~ws, axis=1)
        valid &= fw != istart
    else:
        valid &= ~jnp.any(ws_in, axis=1)
        fw = jnp.full((n,), L, jnp.int32)
    # dot rules: ANSI forbids; else truncate at the first, a second is invalid
    dot_in = dot & region
    if ansi_mode:
        valid &= ~jnp.any(dot_in, axis=1)
        first_dot = jnp.full((n,), L, jnp.int32)
    else:
        first_dot = _first_idx(dot_in, L)
        valid &= jnp.sum(dot_in, axis=1) <= 1

    dend = jnp.minimum(jnp.minimum(first_dot, fw), lens)

    # Closed-form digit accumulation (replaces an L-step sequential loop):
    # appending a digit never shrinks the magnitude, so the reference's
    # per-step overflow checks (cast_string.cu:100-143) fire iff the final
    # magnitude exceeds the type bound. Give each digit its positional
    # weight 10^(dend-1-pos) and reduce — exact in u64 once rows with more
    # than 19 significant digits (which always overflow every int type) are
    # flagged up front. Rows already invalid from the region checks may
    # compute garbage here; their validity is already false.
    dig_run = (pos >= istart[:, None]) & (pos < dend[:, None])
    nzrun = dig_run & (C != 48)
    first_nz = _first_idx(nzrun, 0)
    first_nz = jnp.where(jnp.any(nzrun, axis=1), first_nz, dend)
    nd_eff = dend - first_nz                  # digits after leading zeros
    e = dend[:, None] - 1 - pos
    w = jnp.take(jnp.asarray(_POW10_U64), jnp.clip(e, 0, 19))
    d_u = jnp.clip(C - 48, 0, 9).astype(jnp.uint64)
    dmask = dig_run & (pos >= first_nz[:, None])
    mag = jnp.sum(jnp.where(dmask, d_u * w, jnp.uint64(0)), axis=1)
    of = (nd_eff > 19) | jnp.where(neg, mag > jnp.uint64(-tmin),
                                   mag > jnp.uint64(tmax))
    valid &= ~of
    val = jax.lax.bitcast_convert_type(
        jnp.where(neg, jnp.uint64(0) - mag, mag), jnp.int64)

    out = Column(dtype=out_type, length=n,
                 data=val.astype(out_type.storage_dtype()),
                 validity=valid)
    if ansi_mode:
        _raise_first_error(col, valid_in & ~valid)
    return out


# ---------------------------------------------------------------------------
# string -> float
# ---------------------------------------------------------------------------
_MAX_HOLDING = (2**64 - 1 - 9) // 10  # cast_string_to_float.cu:396-404

# Correctly-rounded powers of ten (the reference uses device exp10; a constant
# table is exact on CPU and avoids the TPU f64-emulation's inexact pow)
_P10_MIN, _P10_MAX = -350, 350
_P10_TABLE = None


def _pow10(k):
    """10.0**k for integer array k via correctly-rounded table lookup."""
    global _P10_TABLE
    if _P10_TABLE is None:
        # cached as a HOST array: caching a jnp array created during a jit
        # trace would leak the tracer into later traces
        _P10_TABLE = np.asarray(
            [float(f"1e{i}") if -324 < i <= 308 else (0.0 if i <= -324 else np.inf)
             for i in range(_P10_MIN, _P10_MAX + 1)], dtype=np.float64)
    idx = jnp.clip(k - _P10_MIN, 0, _P10_MAX - _P10_MIN)
    return jnp.take(jnp.asarray(_P10_TABLE), idx)


def _ci_match(C, start, lens, word: bytes):
    """Case-insensitive match of `word` at per-row dynamic index `start`."""
    m = jnp.ones((C.shape[0],), jnp.bool_)
    for k, ch in enumerate(word):
        c = _char_at(C, start + k)
        m &= ((c == ch) | (c == ch - 32)) & (start + k < lens)
    return m


def string_to_float(col: Column, out_type: DType, ansi_mode: bool = False,
                    pad_to: Optional[int] = None) -> Column:
    """Spark-exact string→FLOAT32/64 (cast_string_to_float.cu:56-653)."""
    assert out_type.kind in (Kind.FLOAT32, Kind.FLOAT64)
    padded, lens = col.padded_chars(pad_to)
    C = padded.astype(jnp.int32)
    n, L = C.shape
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_str = pos < lens[:, None]
    ws = _is_ws(C)
    digit = (C >= 48) & (C <= 57)
    dot = C == 46

    valid_in = col.null_mask
    lens_i = lens.astype(jnp.int32)

    def skip_ws(start):
        """First non-ws index >= start (per row), else lens."""
        m = ~ws & in_str & (pos >= start[:, None])
        idx = _first_idx(m, 0)
        return jnp.where(jnp.any(m, axis=1), idx, lens_i)

    i0 = skip_ws(jnp.zeros((n,), jnp.int32))
    c0 = _char_at(C, i0)
    has_sign = ((c0 == 43) | (c0 == 45)) & (i0 < lens_i)
    neg = (c0 == 45) & has_sign
    sign = jnp.where(neg, -1.0, 1.0)
    p0 = i0 + has_sign.astype(jnp.int32)

    # --- nan: only the exact 3-char string is valid; 'nan'+junk raises in
    # ANSI (cast_string_to_float.cu:235-255)
    starts_nan = _ci_match(C, p0, lens_i, b"nan")
    nan_valid = starts_nan & (lens_i == 3)
    nan_except = starts_nan & (lens_i != 3)

    # --- inf / infinity: must end the string; junk after silently nulls
    # without an ANSI exception (cast_string_to_float.cu:257-306)
    inf3 = _ci_match(C, p0, lens_i, b"inf") & ~starts_nan
    inf8 = inf3 & _ci_match(C, p0 + 3, lens_i, b"inity")
    inf_valid = (inf3 & (p0 + 3 == lens_i)) | (inf8 & (p0 + 8 == lens_i))
    is_inf_path = inf3

    # --- digit parsing over [p0, term) where term is the first char that is
    # neither digit nor '.'
    reg = (pos >= p0[:, None]) & in_str
    nondig = reg & ~digit & ~dot
    term = _first_idx(nondig, 0)
    term = jnp.where(jnp.any(nondig, axis=1), term, lens_i)

    mant = reg & (pos < term[:, None])
    dots_in_mant = jnp.sum(dot & mant, axis=1)
    multi_dot = dots_in_mant > 1
    dot_idx = _first_idx(dot & mant, L)
    has_dot = dots_in_mant == 1
    # a '.' appearing at/after term ends up invalid (decimal_pos check,
    # cast_string_to_float.cu:372-376)
    stray_dot = jnp.any(dot & in_str & (pos >= term[:, None]), axis=1)

    predot_end = jnp.minimum(dot_idx, term)
    # leading zeros stripped while no decimal seen and value still zero
    pre_region = mant & (pos < predot_end[:, None])
    nonzero_pre = pre_region & (C != 48)
    first_nz = _first_idx(nonzero_pre, 0)
    first_nz = jnp.where(jnp.any(nonzero_pre, axis=1), first_nz, predot_end)
    z = first_nz - p0                                   # stripped zeros
    a1 = predot_end - first_nz                          # counted pre-dot digits
    a2 = jnp.where(has_dot, term - dot_idx - 1, 0)      # post-dot digits
    total_digits = a1 + a2
    seen_digit = (z > 0) | (total_digits > 0)

    # accumulate at most 19 digits + greedy 20th (cast_string_to_float.cu:390-440)
    # mask of counted digit positions: digits in [first_nz, term) excluding dot
    counted = (pos >= first_nz[:, None]) & (pos < term[:, None]) & digit

    # Closed form (replaces an L-step sequential accumulator): the loop
    # absorbs exactly min(total, 19) digits unconditionally, then at most ONE
    # guarded 20th (after a 20th digit the count passes 19 and nothing more
    # can ever absorb). So rank every counted digit, weight the first k19 by
    # 10^(k19-1-rank), reduce in u64 (k19 <= 19 keeps it exact), and apply
    # the single 20th-digit guard (check order of cast_string_to_float.cu:
    # 404-427: the <= max_holding test precedes the multiply so it can't wrap).
    r = _rank_in_mask(counted)
    total_counted = jnp.sum(counted, axis=1).astype(jnp.int32)
    k19 = jnp.minimum(total_counted, 19)
    e19 = k19[:, None] - 1 - r
    w19 = jnp.take(jnp.asarray(_POW10_U64), jnp.clip(e19, 0, 19))
    d_u = jnp.clip(C - 48, 0, 9).astype(jnp.uint64)
    take19 = counted & (r < k19[:, None])
    dval19 = jnp.sum(jnp.where(take19, d_u * w19, jnp.uint64(0)), axis=1)
    d20 = jnp.sum(jnp.where(counted & (r == 19), d_u, jnp.uint64(0)), axis=1)
    extra_ok = (total_counted >= 20) & (dval19 <= jnp.uint64(_MAX_HOLDING)) & \
        (dval19 * jnp.uint64(10) + d20 <= jnp.uint64(_MAX_HOLDING))
    dval = jnp.where(extra_ok, dval19 * jnp.uint64(10) + d20, dval19)
    absorbed = k19 + extra_ok.astype(jnp.int32)
    truncated = total_digits - absorbed
    exp_base = truncated - jnp.where(has_dot, total_digits - a1, 0)

    zero_mantissa = dval == jnp.uint64(0)

    # --- manual exponent (cast_string_to_float.cu:479-528)
    has_e = (term < lens_i) & ((_char_at(C, term) == 101) | (_char_at(C, term) == 69))
    ce = _char_at(C, term + 1)
    e_sign_char = ((ce == 43) | (ce == 45)) & has_e & (term + 1 < lens_i)
    e_neg = (ce == 45) & e_sign_char
    estart = term + 1 + e_sign_char.astype(jnp.int32)
    # count leading digits at estart, capped at 4
    nd = jnp.zeros((n,), jnp.int32)
    eval_ = jnp.zeros((n,), jnp.int32)
    for k in range(4):
        ck = _char_at(C, estart + k)
        is_d = (ck >= 48) & (ck <= 57) & (estart + k < lens_i) & (nd == k)
        eval_ = jnp.where(is_d, eval_ * 10 + (ck - 48), eval_)
        nd = nd + is_d.astype(jnp.int32)
    manual_exp = jnp.where(e_neg, -eval_, eval_)
    exp_invalid = has_e & (nd == 0)
    after_exp = jnp.where(has_e, estart + nd, term)

    # --- trailing: one optional f/F/d/D, then ws, then end
    # (cast_string_to_float.cu:530-553)
    cq = _char_at(C, after_exp)
    has_suffix = ((cq == 102) | (cq == 70) | (cq == 100) | (cq == 68)) & \
        (after_exp < lens_i)
    q = after_exp + has_suffix.astype(jnp.int32)
    after_ws = skip_ws(q)
    trailing_junk = after_ws < lens_i

    # zero-mantissa path: the manual exponent IS parsed first (operator()
    # order, cast_string_to_float.cu:119-141), then only ws may follow —
    # so '0e5' is valid 0 but '0f' is invalid (no suffix handling here)
    zero_after_ws = skip_ws(after_exp)
    zero_junk = zero_after_ws < lens_i

    # --- assemble validity
    number_valid = ~multi_dot & ~stray_dot & seen_digit & ~exp_invalid & \
        jnp.where(zero_mantissa, ~zero_junk, ~trailing_junk)
    valid = valid_in & jnp.where(
        starts_nan, nan_valid, jnp.where(is_inf_path, inf_valid, number_valid))

    # ANSI exception flag: inf-with-junk does NOT raise (quirk kept;
    # compute_validity only sees except from nan/digit paths); empty and
    # ws-only strings raise via the no-digit rule
    number_except = multi_dot | stray_dot | ~seen_digit | exp_invalid | \
        jnp.where(zero_mantissa, zero_junk, trailing_junk)
    except_flag = valid_in & jnp.where(
        starts_nan, nan_except,
        jnp.where(is_inf_path, jnp.zeros((n,), jnp.bool_), number_except))

    # --- construct the value in f64 (cast_string_to_float.cu:150-196)
    digitsf = sign * dval.astype(jnp.float64)
    exp_ten = (exp_base + manual_exp).astype(jnp.int32)
    overflow = exp_ten > 308
    subnormal_shift = -307 - exp_ten
    safe_dval = jnp.maximum(dval, jnp.uint64(1)).astype(jnp.float64)
    num_digits = jnp.floor(jnp.log10(safe_dval)).astype(jnp.int32) + 1
    # subnormal branch
    sub_digitsf = digitsf / _pow10(num_digits - 1 + subnormal_shift)
    sub_result = sub_digitsf * _pow10(exp_ten + num_digits - 1 + subnormal_shift)
    # normal branch
    expf = _pow10(jnp.abs(exp_ten))
    norm_result = jnp.where(exp_ten < 0, digitsf / expf, digitsf * expf)
    result = jnp.where(subnormal_shift > 0, sub_result, norm_result)
    result = jnp.where(overflow, sign * jnp.inf, result)
    result = jnp.where(zero_mantissa, sign * 0.0, result)
    result = jnp.where(is_inf_path, sign * jnp.inf, result)
    result = jnp.where(starts_nan, jnp.nan, result)

    out = Column(dtype=out_type, length=n,
                 data=result.astype(out_type.storage_dtype()), validity=valid)
    if ansi_mode:
        _raise_first_error(col, except_flag & ~valid)
    return out


# ---------------------------------------------------------------------------
# base conversion (Spark `conv`) - CastStringJni.cpp:159-258
# ---------------------------------------------------------------------------
def string_to_integer_with_base(col: Column, out_type: DType, base: int = 10,
                                ansi_mode: bool = False,
                                pad_to: Optional[int] = None) -> Column:
    """toIntegersWithBase: leading-token extraction with regex semantics
    ^\\s*(-?[0-9a-fA-F]+).* — non-matching rows become 0 (not null),
    whitespace-only rows become null, arithmetic wraps modulo 2^bits."""
    if base not in (10, 16):
        raise CastError(0, f"Bases supported 10, 16; Actual: {base}")
    padded, lens = col.padded_chars(pad_to)
    C = padded.astype(jnp.int32)
    n, L = C.shape
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_str = pos < lens[:, None]
    # regex \s class: the reference implements conv via cudf regexes
    # (CastStringJni.cpp:174-210), so \f and \v count here, unlike the
    # 4-char Spark set used by the int/float casts
    ws = _is_ws(C) | (C == 12) | (C == 11)

    i0 = _first_idx(~ws & in_str, 0)
    all_ws = ~jnp.any(~ws & in_str, axis=1)
    i0 = jnp.where(all_ws, lens, i0)
    c0 = _char_at(C, i0)
    neg = (c0 == 45) & (i0 < lens)
    istart = i0 + neg.astype(jnp.int32)

    if base == 10:
        is_dig = (C >= 48) & (C <= 57)
        dval = C - 48
    else:
        is_dig = ((C >= 48) & (C <= 57)) | ((C >= 97) & (C <= 102)) | \
            ((C >= 65) & (C <= 70))
        dval = jnp.where((C >= 48) & (C <= 57), C - 48,
                         jnp.where((C >= 97) & (C <= 102), C - 87, C - 55))
    run = (pos >= istart[:, None]) & in_str
    non_dig_in_run = run & ~is_dig
    run_end = _first_idx(non_dig_in_run, 0)
    run_end = jnp.where(jnp.any(non_dig_in_run, axis=1), run_end, lens)
    matched = run_end > istart  # at least one digit after optional sign

    # Closed form mod 2^64 (conv arithmetic wraps): weight each digit by
    # base^(run_end-1-pos) mod 2^64 — the wrapped power table is computed
    # host-side with exact bigints, so the masked multiply-reduce matches the
    # sequential val*base+d chain bit for bit.
    btbl = jnp.asarray(np.array([pow(base, k, 2**64) for k in range(max(L, 1))],
                                dtype=np.uint64))
    eb = run_end[:, None] - 1 - pos
    wb = jnp.take(btbl, jnp.clip(eb, 0, L - 1))
    brun = (pos >= istart[:, None]) & (pos < run_end[:, None])
    mag = jnp.sum(jnp.where(brun, dval.astype(jnp.uint64) * wb, jnp.uint64(0)),
                  axis=1)
    val = jax.lax.bitcast_convert_type(
        jnp.where(neg, jnp.uint64(0) - mag, mag), jnp.int64)
    val = jnp.where(matched, val, 0)
    validity = col.null_mask & ~all_ws & (lens > 0)
    return Column(dtype=out_type, length=n,
                  data=val.astype(out_type.storage_dtype()),
                  validity=validity)


def integer_to_string_with_base(col: Column, base: int = 10) -> Column:
    """fromIntegersWithBase: base 10 decimal strings; base 16 uppercase hex of
    the two's-complement value with leading zeros stripped."""
    from ..columnar.column import strings_from_padded

    if base not in (10, 16):
        raise CastError(0, f"Bases supported 10, 16; Actual: {base}")
    nbits = col.dtype.itemsize() * 8
    n = col.length
    if base == 16:
        u = col.data.astype(jnp.int64).astype(jnp.uint64)
        if nbits < 64:
            u = u & jnp.uint64((1 << nbits) - 1)
        ndig = nbits // 4
        shifts = jnp.arange(ndig - 1, -1, -1, dtype=jnp.uint64) * 4
        nibbles = ((u[:, None] >> shifts[None, :]) & jnp.uint64(0xF)).astype(jnp.int32)
        chars = jnp.where(nibbles < 10, nibbles + 48, nibbles + 55)  # uppercase
        nz = nibbles != 0
        first = _first_idx(nz, ndig - 1)  # value 0 -> single '0'
        lens_out = ndig - jnp.minimum(first, ndig - 1)
        # shift each row left so its first significant nibble is at column 0
        idx = jnp.minimum(first, ndig - 1)[:, None] + jnp.arange(ndig)[None, :]
        out = jnp.take_along_axis(chars, jnp.clip(idx, 0, ndig - 1), axis=1)
        return strings_from_padded(out.astype(jnp.uint8), lens_out, col.validity)
    # base 10
    if col.dtype.kind == Kind.UINT64:
        # Spark conv() prints the unsigned value ("-510" parsed base 10 comes
        # back as 18446744073709551106, CastStringsTest.baseDec2HexTestMixed)
        mag = col.data.astype(jnp.uint64)
        neg = jnp.zeros((n,), jnp.bool_)
    else:
        v = col.data.astype(jnp.int64)
        neg = v < 0
        mag = jnp.where(neg, -v.astype(jnp.uint64), v.astype(jnp.uint64))
        # careful: -INT64_MIN wraps to itself, the correct magnitude bits
        mag = jnp.where(v == jnp.int64(-(2**63)), jnp.uint64(2**63), mag)
    ndig = 20
    pows = jnp.asarray([10**k for k in range(ndig)], dtype=jnp.uint64)
    digs = ((mag[:, None] // pows[None, ::-1]) % jnp.uint64(10)).astype(jnp.int32)
    nzd = digs != 0
    first = _first_idx(nzd, ndig - 1)
    first = jnp.minimum(first, ndig - 1)
    mag_len = ndig - first
    lens_out = mag_len + neg.astype(jnp.int32)
    width = ndig + 1
    j = jnp.arange(width, dtype=jnp.int32)[None, :]
    # digit j of output (after optional '-') is digs[first + j - neg]
    src = first[:, None] + j - neg.astype(jnp.int32)[:, None]
    dchars = jnp.take_along_axis(digs, jnp.clip(src, 0, ndig - 1), axis=1) + 48
    out = jnp.where((j == 0) & neg[:, None], 45, dchars)
    return strings_from_padded(out.astype(jnp.uint8), lens_out, col.validity)
