"""Pallas TPU kernels for the fixed-width row-hash hot path.

The jnp implementations in ops/hash.py are semantically complete (strings,
nested types, decimal128); this module is the performance path for the case a
Spark plan hashes hardest — hash-partition / hash-join / hash-aggregate keys
over fixed-width columns (reference hot kernels: murmur_hash.cu:64-207,
xxhash64.cu:277-330, both one-thread-per-row CUDA).

TPU-first redesign rather than a translation:
- one `pallas_call` fuses the whole per-row chain (every column's rounds +
  finalization for BOTH hashes) in VMEM, so each input byte crosses HBM once;
- rows are laid out as (rows/128, 128) u32 *word planes* (lo/hi) so every
  step is an 8x128 VPU op — there is no 64-bit scalar unit to lean on;
- uint64 arithmetic is hand-built from u32 planes: adds via compare-carry,
  rotates via plane shifts, multiplies by the (constant) xxhash primes via
  16-bit limb partial products (TPU has no widening 32x32 multiply, so the
  limbs keep every partial product exact in u32);
- validity is a per-column u32 plane consumed as a select; columns with
  validity=None skip the plane and the select entirely (kernel specialization
  happens at trace time, like the reference's type_dispatcher but compiled
  per column-set).

Float columns are supported through the same bit-encoding helpers as the jnp
path (NaN canonicalization; xxhash additionally normalizes zeros,
hash.cuh:33-52), applied before the planes enter the kernel.

Measured (v5e-1, 10M rows x 2 int64 cols): ~3.2 ms vs ~2.8 ms for the fused
XLA path in ops/hash.py. The op is ALU-bound in u32-emulated u64 math, which
XLA already schedules well, and the pallas_call boundary forces the word
planes to materialize in HBM (Mosaic cannot de-interleave the raw little-
endian i64 pairs in-register: strided lane slices and minor-dim reshapes are
unsupported). Kept as the explicit-kernel path — it documents the layout and
wins when the planes are already split (e.g. reused across several hash
calls); the jnp path stays the default. ops/join_pallas.py is exactly that
reuse case: its hash-join build/probe kernels consume this module's word
planes (and round/fmix chain) in-kernel, with selection owned by the
kernel registry (ops/registry.py, docs/kernels.md).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import dtypes
from ..columnar import Column, Table
from ..dtypes import Kind
from .hash import (DEFAULT_XXHASH64_SEED, _canonical_nan, _normalize_zeros,
                   f64_bits_u64)

_LANES = 128
_U32 = jnp.uint32


def _u32c(v: int):
    return _U32(v & 0xFFFFFFFF)


# ---------------------------------------------------------------------------
# u64-as-two-u32-planes arithmetic
# ---------------------------------------------------------------------------
def _limbs16(c: int) -> Tuple[int, int, int, int]:
    return (c & 0xFFFF, (c >> 16) & 0xFFFF, (c >> 32) & 0xFFFF, (c >> 48) & 0xFFFF)


def _mul64_const(lo, hi, c: int):
    """(lo,hi) * c mod 2**64. Partial products of 16-bit limbs: each product
    is exact in u32, and each 16-bit accumulation column sums at most 7
    sixteen-bit terms (< 2**19), so no carry is ever lost."""
    a = (lo & _u32c(0xFFFF), lo >> _U32(16), hi & _u32c(0xFFFF), hi >> _U32(16))
    b = _limbs16(c)
    acc = [None, None, None, None]  # 16-bit columns of the result

    def add(k, term):
        acc[k] = term if acc[k] is None else acc[k] + term

    for i in range(4):
        for j in range(4 - i):
            if b[j] == 0:
                continue
            p = a[i] * _u32c(b[j])
            k = i + j
            add(k, p & _u32c(0xFFFF))
            if k + 1 < 4:
                add(k + 1, p >> _U32(16))
    z = jnp.zeros_like(lo)
    r0 = acc[0] if acc[0] is not None else z
    r1 = (acc[1] if acc[1] is not None else z) + (r0 >> _U32(16))
    r2 = (acc[2] if acc[2] is not None else z) + (r1 >> _U32(16))
    r3 = (acc[3] if acc[3] is not None else z) + (r2 >> _U32(16))
    out_lo = (r0 & _u32c(0xFFFF)) | (r1 << _U32(16))
    out_hi = (r2 & _u32c(0xFFFF)) | (r3 << _U32(16))
    return out_lo, out_hi


def _add64_const(lo, hi, c: int):
    blo, bhi = c & 0xFFFFFFFF, (c >> 32) & 0xFFFFFFFF
    s = lo + _u32c(blo)
    carry = (s < _u32c(blo)).astype(_U32)
    return s, hi + _u32c(bhi) + carry


def _rotl64(lo, hi, r: int):
    r &= 63
    if r == 0:
        return lo, hi
    if r == 32:
        return hi, lo
    if r < 32:
        return ((lo << _U32(r)) | (hi >> _U32(32 - r)),
                (hi << _U32(r)) | (lo >> _U32(32 - r)))
    r -= 32
    return ((hi << _U32(r)) | (lo >> _U32(32 - r)),
            (lo << _U32(r)) | (hi >> _U32(32 - r)))


def _xor_shr64(lo, hi, r: int):
    """h ^= h >> r for 32 <= r < 64 and 0 < r < 32."""
    if r >= 32:
        return lo ^ (hi >> _U32(r - 32)) if r > 32 else lo ^ hi, hi
    return lo ^ ((lo >> _U32(r)) | (hi << _U32(32 - r))), hi ^ (hi >> _U32(r))


# ---------------------------------------------------------------------------
# murmur3_32 (plain u32 planes)
# ---------------------------------------------------------------------------
def _mm_round(h, k1):
    k1 = k1 * _u32c(0xCC9E2D51)
    k1 = (k1 << _U32(15)) | (k1 >> _U32(17))
    k1 = k1 * _u32c(0x1B873593)
    h = h ^ k1
    h = (h << _U32(13)) | (h >> _U32(19))
    return h * _U32(5) + _u32c(0xE6546B64)


def _mm_fmix(h):
    h = h ^ (h >> _U32(16))
    h = h * _u32c(0x85EBCA6B)
    h = h ^ (h >> _U32(13))
    h = h * _u32c(0xC2B2AE35)
    return h ^ (h >> _U32(16))


# ---------------------------------------------------------------------------
# xxhash64 rounds on planes (constants match xxhash64.cu:42-56)
# ---------------------------------------------------------------------------
_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5


def _xx_fixed(seed_lo, seed_hi, wlo, whi, nbytes: int):
    """xxhash64 of one 4- or 8-byte value per row (xxhash64.cu:108-183)."""
    hlo, hhi = _add64_const(seed_lo, seed_hi, _P5 + nbytes)
    if nbytes == 8:
        klo, khi = _mul64_const(wlo, whi, _P2)
        klo, khi = _rotl64(klo, khi, 31)
        klo, khi = _mul64_const(klo, khi, _P1)
        hlo, hhi = hlo ^ klo, hhi ^ khi
        hlo, hhi = _rotl64(hlo, hhi, 27)
        hlo, hhi = _mul64_const(hlo, hhi, _P1)
        hlo, hhi = _add64_const(hlo, hhi, _P4)
    else:
        mlo, mhi = _mul64_const(wlo, jnp.zeros_like(wlo), _P1)
        hlo, hhi = hlo ^ mlo, hhi ^ mhi
        hlo, hhi = _rotl64(hlo, hhi, 23)
        hlo, hhi = _mul64_const(hlo, hhi, _P2)
        hlo, hhi = _add64_const(hlo, hhi, _P3)
    # finalize (avalanche)
    hlo, hhi = _xor_shr64(hlo, hhi, 33)
    hlo, hhi = _mul64_const(hlo, hhi, _P2)
    hlo, hhi = _xor_shr64(hlo, hhi, 29)
    hlo, hhi = _mul64_const(hlo, hhi, _P3)
    hlo, hhi = _xor_shr64(hlo, hhi, 32)
    return hlo, hhi


# ---------------------------------------------------------------------------
# plane encoding (host-of-kernel side, still inside jit)
# ---------------------------------------------------------------------------
def _planes(col: Column, normalize_zero: bool):
    """-> (lo_u32, hi_u32_or_None, nbytes). Encoding parity with
    hash.py _encode_fixed_u64 (Spark byte forms, murmur_hash.cuh:135-199)."""
    k = col.dtype.kind
    d = col.data
    if k in (Kind.BOOL, Kind.INT8, Kind.INT16, Kind.INT32, Kind.DATE32):
        return d.astype(jnp.int32).astype(_U32), None, 4
    if k in (Kind.INT64, Kind.TIMESTAMP_US, Kind.DECIMAL32, Kind.DECIMAL64):
        u = d.astype(jnp.int64).astype(jnp.uint64)
        return ((u & jnp.uint64(0xFFFFFFFF)).astype(_U32),
                (u >> jnp.uint64(32)).astype(_U32), 8)
    if k == Kind.FLOAT32:
        x = _canonical_nan(d)
        if normalize_zero:
            x = _normalize_zeros(x)
        return jax.lax.bitcast_convert_type(x, _U32), None, 4
    if k == Kind.FLOAT64:
        x = _normalize_zeros(d) if normalize_zero else d
        u = jnp.where(jnp.isnan(d), jnp.uint64(0x7FF8000000000000),
                      f64_bits_u64(x))
        return ((u & jnp.uint64(0xFFFFFFFF)).astype(_U32),
                (u >> jnp.uint64(32)).astype(_U32), 8)
    raise TypeError(f"pallas row hash: unsupported dtype {col.dtype}")


def _to_tiles(x, n_pad, lanes: int = _LANES, fill=0):
    """Pad a flat (n,) array to n_pad rows and tile it (n_pad/lanes,
    lanes) — the one word-plane layout transform shared by every Pallas
    module here (join_pallas, topk_pallas, select_pallas); `fill` is the
    padding value (topk pads with its sentinel)."""
    x = jnp.pad(x, (0, n_pad - x.shape[0]), constant_values=fill)
    return x.reshape(n_pad // lanes, lanes)


def _u16_halves(w) -> Tuple:
    """u32 word -> (lo16, hi16) as f32 — the split that keeps one-hot MXU
    gathers bit-exact (a single <=16-bit term per product fits the f32
    mantissa). Shared by the join/select compaction kernels."""
    return ((w & _u32c(0xFFFF)).astype(jnp.float32),
            (w >> _U32(16)).astype(jnp.float32))


def _pack_inputs(cols: Sequence[Column], normalize_zero: bool, n: int,
                 block_rows: int):
    """Flat list of (M, 128) u32 plane arrays (each its own ref — stacking
    them would cost an extra HBM copy of every input) + static layout of
    (nbytes, has_nulls, plane_count) per column."""
    n_pad = max(block_rows, ((n + block_rows - 1) // block_rows) * block_rows)
    arrays, layout = [], []
    for c in cols:
        lo, hi, nbytes = _planes(c, normalize_zero)
        planes = [_to_tiles(lo, n_pad)]
        if hi is not None:
            planes.append(_to_tiles(hi, n_pad))
        has_nulls = c.validity is not None
        if has_nulls:
            planes.append(_to_tiles(c.validity.astype(_U32), n_pad))
        arrays.extend(planes)
        layout.append((nbytes, has_nulls, len(planes)))
    return arrays, layout, n_pad


def _hash_kernel_body(layout, mm_seed, xx_seed, emit_mm, emit_xx,
                      in_refs, out_refs):
    shape = in_refs[0].shape  # (TM, 128)
    if emit_mm:
        mh = jnp.full(shape, _u32c(mm_seed))
    if emit_xx:
        xlo = jnp.full(shape, _u32c(xx_seed))
        xhi = jnp.full(shape, _u32c(xx_seed >> 32))
    p = 0
    for (nbytes, has_nulls, nplanes) in layout:
        lo = in_refs[p][...]
        hi = in_refs[p + 1][...] if nbytes == 8 else None
        valid = None
        if has_nulls:
            valid = in_refs[p + nplanes - 1][...] != _U32(0)
        p += nplanes
        if emit_mm:
            nh = _mm_round(mh, lo)
            if nbytes == 8:
                nh = _mm_round(nh, hi)
            nh = _mm_fmix(nh ^ _U32(nbytes))
            mh = jnp.where(valid, nh, mh) if has_nulls else nh
        if emit_xx:
            nlo, nhi = _xx_fixed(xlo, xhi, lo, hi, nbytes)
            if has_nulls:
                xlo = jnp.where(valid, nlo, xlo)
                xhi = jnp.where(valid, nhi, xhi)
            else:
                xlo, xhi = nlo, nhi
    i = 0
    if emit_mm:
        out_refs[i][...] = mh.astype(jnp.int32)
        i += 1
    if emit_xx:
        out_refs[i][0] = xlo
        out_refs[i][1] = xhi


def _as_columns(table) -> List[Column]:
    if isinstance(table, Table):
        return list(table.columns)
    if isinstance(table, Column):
        return [table]
    return list(table)


def supports(table) -> bool:
    """True if every column is a fixed-width type this kernel handles."""
    ok = (Kind.BOOL, Kind.INT8, Kind.INT16, Kind.INT32, Kind.DATE32,
          Kind.INT64, Kind.TIMESTAMP_US, Kind.DECIMAL32, Kind.DECIMAL64,
          Kind.FLOAT32, Kind.FLOAT64)
    return all(c.dtype.kind in ok for c in _as_columns(table))


def murmur_hash3_32_pallas(table, seed: int = 0, block_rows: int = 128 * 128,
                           interpret: Optional[bool] = None) -> Column:
    """Spark murmur3_32 row hash, fused Pallas path (fixed-width columns)."""
    cols = _as_columns(table)
    if not cols:
        raise ValueError("Murmur3 hashing requires at least 1 column of input")
    # murmur does NOT normalize float zeros (Spark < 3.2 behavior,
    # murmur_hash.cuh:112-133)
    [col] = _run_custom(cols, mm_seed=seed & 0xFFFFFFFF, xx_seed=None,
                        normalize_zero=False, block_rows=block_rows,
                        interpret=interpret)
    return col


def _run_custom(cols, mm_seed, xx_seed, normalize_zero, block_rows, interpret):
    # index_map constants are written `i - i` (not 0): under x64 a literal 0
    # traces as i64 and Mosaic rejects the mixed (i64, i32, i64) index tuple
    if block_rows < _LANES or block_rows % _LANES:
        raise ValueError(f"block_rows must be a multiple of {_LANES}, "
                         f"got {block_rows}")
    n = cols[0].length
    if any(c.length != n for c in cols):
        # plain-list inputs bypass Table validation; a short column would
        # otherwise silently hash its zero padding
        raise ValueError("all hashed columns must have equal length")
    arrays, layout, n_pad = _pack_inputs(cols, normalize_zero, n, block_rows)
    M = n_pad // _LANES
    TM = block_rows // _LANES
    emit_mm, emit_xx = mm_seed is not None, xx_seed is not None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def kernel(*refs):
        _hash_kernel_body(layout, mm_seed or 0, xx_seed or 0, emit_mm, emit_xx,
                          refs[:len(arrays)], refs[len(arrays):])

    in_specs = [pl.BlockSpec((TM, _LANES), lambda i: (i, i - i),
                             memory_space=pltpu.VMEM) for _ in arrays]
    out_shape, out_specs = [], []
    if emit_mm:
        out_shape.append(jax.ShapeDtypeStruct((M, _LANES), jnp.int32))
        out_specs.append(pl.BlockSpec((TM, _LANES), lambda i: (i, i - i),
                                      memory_space=pltpu.VMEM))
    if emit_xx:
        out_shape.append(jax.ShapeDtypeStruct((2, M, _LANES), _U32))
        out_specs.append(pl.BlockSpec((2, TM, _LANES), lambda i: (i - i, i, i - i),
                                      memory_space=pltpu.VMEM))
    outs = pl.pallas_call(
        kernel, out_shape=out_shape, in_specs=in_specs, out_specs=out_specs,
        grid=(M // TM,), interpret=interpret)(*arrays)
    res, i = [], 0
    if emit_mm:
        res.append(Column(dtype=dtypes.INT32, length=n,
                          data=outs[i].reshape(-1)[:n]))
        i += 1
    if emit_xx:
        xlo = outs[i][0].reshape(-1)[:n].astype(jnp.uint64)
        xhi = outs[i][1].reshape(-1)[:n].astype(jnp.uint64)
        res.append(Column(dtype=dtypes.INT64, length=n,
                          data=((xhi << jnp.uint64(32)) | xlo).astype(jnp.int64)))
    return res


def xxhash64_pallas(table, seed: int = DEFAULT_XXHASH64_SEED,
                    block_rows: int = 128 * 128,
                    interpret: Optional[bool] = None) -> Column:
    """Spark xxhash64 row hash, fused Pallas path (fixed-width columns)."""
    cols = _as_columns(table)
    if not cols:
        raise ValueError("xxhash64 hashing requires at least 1 column of input")
    [col] = _run_custom(cols, mm_seed=None, xx_seed=seed & (2**64 - 1),
                        normalize_zero=True, block_rows=block_rows,
                        interpret=interpret)
    return col


def fused_row_hash(table, mm_seed: int = 0,
                   xx_seed: int = DEFAULT_XXHASH64_SEED,
                   block_rows: int = 128 * 128,
                   interpret: Optional[bool] = None) -> Tuple[Column, Column]:
    """Both Spark row hashes in one HBM pass. Restricted to integer-family
    columns: float columns need different zero normalization per hash
    (hash.cuh:33-52), so mixed float tables must use the single-hash entry
    points."""
    cols = _as_columns(table)
    if any(c.dtype.kind in (Kind.FLOAT32, Kind.FLOAT64) for c in cols):
        raise TypeError("fused_row_hash: float columns need per-hash zero "
                        "normalization; use the single-hash pallas calls")
    mm, xx = _run_custom(cols, mm_seed=mm_seed & 0xFFFFFFFF,
                         xx_seed=xx_seed & (2**64 - 1), normalize_zero=False,
                         block_rows=block_rows, interpret=interpret)
    return mm, xx
