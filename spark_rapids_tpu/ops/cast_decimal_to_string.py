"""DECIMAL32/64/128 → STRING with Spark's non-ANSI formatting.

TPU-native re-design of the reference kernel
(src/main/cpp/src/cast_decimal_to_string.cu:53-175): follows Java
BigDecimal.toString() — plain `[-]integer.fraction` when java-scale >= 0 and
adjusted exponent >= -6, scientific `d.dddE±x` otherwise.

Where the reference runs a two-pass size/write functor per row, here the
digits of every row are extracted at once with a static unrolled divide-by-10
loop (limb-wise long division for DECIMAL128 — no native int128 on TPU), and
the output is assembled positionally over an (n, width) char plane, then
compacted with the standard measure→gather strings pattern.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..columnar import Column, strings_from_padded
from ..dtypes import Kind

_MINUS = jnp.uint8(ord("-"))
_POINT = jnp.uint8(ord("."))
_E = jnp.uint8(ord("E"))
_PLUS = jnp.uint8(ord("+"))
_ZERO = jnp.uint8(ord("0"))


def _digits_dec128(limbs: jnp.ndarray, ndigits: int):
    """(n,4) uint32 two's-complement limbs -> (neg, (n,D) uint8 digits MSB-first)."""
    neg = (limbs[:, 3] >> jnp.uint32(31)) != 0
    # two's complement negate: ~x + 1 limb-wise with carry
    inv = (~limbs).astype(jnp.uint32)
    carry = jnp.ones_like(inv[:, 0])
    abs_limbs = []
    for i in range(4):
        s = inv[:, i].astype(jnp.uint64) + carry.astype(jnp.uint64)
        abs_limbs.append((s & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32))
        carry = (s >> jnp.uint64(32)).astype(jnp.uint32)
    abs_l = jnp.where(neg[:, None], jnp.stack(abs_limbs, axis=1), limbs)

    digs = []
    cur = [abs_l[:, i].astype(jnp.uint64) for i in range(4)]
    for _ in range(ndigits):
        r = jnp.zeros_like(cur[0])
        new = [None] * 4
        for i in (3, 2, 1, 0):              # long division by 10, high→low limb
            acc = (r << jnp.uint64(32)) | cur[i]
            new[i] = acc // jnp.uint64(10)
            r = acc % jnp.uint64(10)
        cur = new
        digs.append(r.astype(jnp.uint8))
    # digs is LSB-first; flip to MSB-first
    return neg, jnp.stack(digs[::-1], axis=1)


def _digits_fixed(data: jnp.ndarray, ndigits: int):
    """(n,) int32/int64 -> (neg, (n,D) uint8 digits MSB-first)."""
    neg = data < 0
    mag = jnp.abs(data.astype(jnp.int64)).astype(jnp.uint64)
    digs = []
    for _ in range(ndigits):
        digs.append((mag % jnp.uint64(10)).astype(jnp.uint8))
        mag = mag // jnp.uint64(10)
    return neg, jnp.stack(digs[::-1], axis=1)


def decimal_to_non_ansi_string(col: Column) -> Column:
    """Spark non-ANSI decimal formatting (cast_decimal_to_string.cu:210)."""
    if not col.dtype.is_decimal:
        raise TypeError(
            "Values for decimal_to_non_ansi_string function must be a decimal type.")
    n = col.length
    s = int(col.dtype.scale or 0)            # java scale; fraction digits if > 0
    D = {Kind.DECIMAL32: 10, Kind.DECIMAL64: 19, Kind.DECIMAL128: 39}[col.dtype.kind]
    if col.dtype.kind == Kind.DECIMAL128:
        neg, dig = _digits_dec128(col.data, D)
    else:
        neg, dig = _digits_fixed(col.data, D)

    # significant digit count of |v| (count_digits(0) == 1)
    nz = dig != 0
    first_nz = jnp.argmax(nz, axis=1)                         # D if all zero → 0
    any_nz = jnp.any(nz, axis=1)
    ndig = jnp.where(any_nz, D - first_nz, 1).astype(jnp.int32)
    adjusted = ndig - 1 - s                                   # adjusted exponent

    plain = jnp.logical_and(s >= 0, adjusted >= -6)

    # ---- plain layout: [-] int . frac ------------------------------------------
    int_len = jnp.maximum(ndig - s, 1)                        # "0" when |v| < 10^s
    has_pt = jnp.int32(1 if s > 0 else 0)
    p_len = neg.astype(jnp.int32) + int_len + has_pt + (s if s > 0 else 0)

    # ---- scientific layout: [-] d [. rest] E sign exp --------------------------
    exp_abs = jnp.abs(adjusted)
    exp_ndig = jnp.where(exp_abs >= 100, 3, jnp.where(exp_abs >= 10, 2, 1))
    multi = ndig > 1
    s_len = (neg.astype(jnp.int32) + 1 + jnp.where(multi, 1 + (ndig - 1), 0)
             + 1 + 1 + exp_ndig)

    length = jnp.where(plain, p_len, s_len)
    W = 1 + max(D, s + 1) + 1 + (s if s > 0 else 0) + 6       # static width bound
    j = jnp.arange(W, dtype=jnp.int32)[None, :]               # (1, W)

    def dig_at(idx):
        """Row-wise gather dig[row, idx] with clipping; idx (n, W)."""
        return jnp.take_along_axis(dig, jnp.clip(idx, 0, D - 1), axis=1) + _ZERO

    negi = neg.astype(jnp.int32)[:, None]
    ndigc = ndig[:, None]
    int_lenc = int_len[:, None]

    # plain characters
    b0 = negi                      # end of sign
    b1 = b0 + int_lenc            # end of integer part
    b2 = b1 + has_pt              # end of point
    # integer digits: dig columns [D-s-int_len, D-s); when |v|<10^s that
    # window starts at a zero digit, giving the required "0"
    p_char = jnp.where(
        j < b0, _MINUS,
        jnp.where(j < b1, dig_at(D - s - int_lenc + (j - b0)),
                  jnp.where((j < b2) & (has_pt > 0), _POINT,
                            dig_at(D - s + (j - b2)))))

    # scientific characters
    exp_dig = jnp.stack([(exp_abs // 100) % 10, (exp_abs // 10) % 10,
                         exp_abs % 10], axis=1).astype(jnp.uint8)
    exp_ndigc = exp_ndig[:, None]
    c0 = negi                       # sign end
    c1 = c0 + 1                     # first digit end
    c2 = c1 + jnp.where(multi, 1, 0)[:, None]      # point end
    c3 = c2 + jnp.where(multi[:, None], ndigc - 1, 0)   # frac end
    c4 = c3 + 1                     # E end
    c5 = c4 + 1                     # exp sign end
    exp_at = jnp.take_along_axis(
        exp_dig, jnp.clip(3 - exp_ndigc + (j - c5), 0, 2), axis=1) + _ZERO
    s_char = jnp.where(
        j < c0, _MINUS,
        jnp.where(j < c1, dig_at(D - ndigc + (j - c0)),
                  jnp.where(j < c2, _POINT,
                            jnp.where(j < c3, dig_at(D - ndigc + 1 + (j - c2)),
                                      jnp.where(j < c4, _E,
                                                jnp.where(j < c5,
                                                          jnp.where(adjusted[:, None] >= 0,
                                                                    _PLUS, _MINUS),
                                                          exp_at))))))

    chars = jnp.where(plain[:, None], p_char, s_char)
    in_row = j < length[:, None]
    chars = jnp.where(in_row & col.null_mask[:, None], chars, jnp.uint8(0))
    length = jnp.where(col.null_mask, length, 0)
    return strings_from_padded(chars, length, validity=col.validity)
