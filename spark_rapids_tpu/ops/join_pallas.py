"""Pallas TPU hash-join build/probe over fixed-width key columns.

The engine's generic join (`ops/join.py`) is one union sort over both
sides' key operands — O((nl+nr) log) over the CONCATENATED relation, paid
even when the build side is a few hundred dimension rows. This module is
the classic build/probe split for that case, reusing `ops/hash_pallas`'s
u32 word-plane layout so the hash planes are split once and consumed
IN-KERNEL (the exact "planes already split" reuse case hash_pallas's
module docstring identifies as its win condition — no standalone hash
materialization pass):

- **build** (one `pallas_call`): murmur3 bucket hashes of the build keys
  computed from the word planes on the VPU, then a VMEM-resident open-
  addressing table (capacity = 2x rows rounded to a power of two, linear
  probing) filled by PARALLEL insertion rounds: every unplaced row
  proposes `(h + probe_distance) & (C-1)`, the winner per free slot is the
  minimum row id (a masked sublane reduction), winners' key words land in
  the table via one-hot matrix products on the MXU (u16 halves, one term
  per slot — bit-exact in f32), losers advance their probe distance.
  Insertion therefore lands equal keys in ascending-row chain order, which
  is what makes probe emission order match the sort-based fallback
  exactly.
- **probe** (two `pallas_call`s): per 128-row block, bucket hashes from
  the probe planes in-kernel, then a vectorized chain walk — each round
  gathers 128 slots in one one-hot matmul against the table matrix and
  compares raw key words; counting stops per-lane at the first empty slot
  (the linear-probing invariant). A count pass sizes the output exactly
  like the fallback's span kernel; an emit pass re-walks to the k-th match
  per output slot.

Nulls never match (Spark equi-join): invalid build rows are never
inserted, invalid probe rows count zero — the same lvalid/rvalid masks the
fallback applies. Registered as `hash_join`/"pallas" for the TPU backend;
declines (strings/decimal128/floats, build side > MAX_BUILD rows — the
table must fit VMEM) run the union-sort fallback. Parity is asserted
pair-for-pair IN ORDER against `ops.inner_join` / `inner_join_capped` by
the registry parity suite.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import dtypes
from ..columnar import Column, Table
from ..dtypes import Kind
from .hash_pallas import (_mm_fmix, _mm_round, _planes, _to_tiles, _u32c,
                          _u16_halves as _halves)
from .join import _require_x64

_LANES = 128
_U32 = jnp.uint32
_SEED = 42          # any fixed seed: build and probe share the chain

MAX_BUILD = 512     # table capacity tops out at 1024 slots; the (rows x
#                     capacity) insertion matrices and the per-block
#                     (128 x capacity) probe one-hots stay comfortably in
#                     VMEM. Bigger build sides decline to the union sort,
#                     which scales; this kernel is the small-dimension-
#                     table shape (the broadcast-join regime).

_SUPPORTED_KINDS = frozenset(k.value for k in (
    Kind.BOOL, Kind.INT8, Kind.INT16, Kind.INT32, Kind.DATE32,
    Kind.INT64, Kind.TIMESTAMP_US, Kind.DECIMAL32, Kind.DECIMAL64))


def _capacity(n_build: int) -> int:
    c = 256
    while c < 2 * n_build:
        c *= 2
    return c


def _layout_of(cols: Sequence[Column]) -> List[int]:
    """Per-key byte widths (the murmur chain's static layout). Static dtype
    facts only — no plane arrays are built (those are computed exactly once
    per side and threaded through the build/count/emit passes). MUST match
    hash_pallas._planes' nbytes per kind: decimals hash as longs (Spark),
    so DECIMAL32 is 8-byte despite its 4-byte storage."""
    eight = (Kind.INT64, Kind.TIMESTAMP_US, Kind.DECIMAL32, Kind.DECIMAL64)
    return [8 if c.dtype.kind in eight else 4 for c in cols]


def _key_planes(cols: Sequence[Column], n_pad: int):
    """Word planes of the key columns, shaped (1, n_pad) — the hash_pallas
    u32 words, minus validity planes (validity is an insert/probe mask
    here, not hashed). The build side keeps all rows in the LANE dimension
    (n_pad <= 2*MAX_BUILD, a multiple of 128) because the kernel transposes
    rows against the table's capacity axis; flattening (rows/128, 128)
    tiles in-kernel would be the minor-dim reshape Mosaic rejects
    (hash_pallas module docstring), so the host does it here in XLA."""
    planes, layout = [], []
    for c in cols:
        lo, hi, nbytes = _planes(c, normalize_zero=False)
        ws = [lo] if hi is None else [lo, hi]
        planes.extend(_to_tiles(w, n_pad, lanes=n_pad) for w in ws)
        layout.append(nbytes)
    return planes, layout


def _mm_hash(layout: List[int], words: List[jnp.ndarray]) -> jnp.ndarray:
    """Murmur3 bucket hash over per-column word tiles, in-kernel (the
    hash_pallas round/fmix chain, no validity selects — null exclusion is
    the caller's mask)."""
    h = jnp.full(words[0].shape, _u32c(_SEED))
    p = 0
    for nbytes in layout:
        nh = _mm_round(h, words[p])
        if nbytes == 8:
            nh = _mm_round(nh, words[p + 1])
        p += 2 if nbytes == 8 else 1
        h = _mm_fmix(nh ^ _U32(nbytes))
    return h


# ---- build kernel ------------------------------------------------------------

def _build_kernel_body(layout, C: int, n_pad: int, refs):
    n_words = sum(2 if b == 8 else 1 for b in layout)
    in_refs = refs[:n_words + 1]
    out_refs = refs[n_words + 1:]          # occ, rowid, 2*n_words halves
    words = [r[...] for r in in_refs[:n_words]]      # (1, n_pad) blocks
    valid = in_refs[n_words][...] != _U32(0)
    h = _mm_hash(layout, words)
    halves = [hh for w in words for hh in _halves(w)]

    r_col = jax.lax.broadcasted_iota(jnp.float32, (n_pad, C), 0)
    c_ids = jax.lax.broadcasted_iota(jnp.int32, (n_pad, C), 1)
    big = jnp.float32(1e9)

    occ = jnp.zeros((1, C), jnp.float32)
    rowid = jnp.zeros((1, C), jnp.float32)
    tbl = tuple(jnp.zeros((1, C), jnp.float32) for _ in range(2 * n_words))
    p = jnp.zeros((1, n_pad), jnp.int32)
    placed = ~valid                        # invalid rows never insert

    def cond(st):
        d, p, placed, occ, rowid, tbl = st
        return jnp.any(~placed) & (d < 2 * C + 2)

    def body(st):
        d, p, placed, occ, rowid, tbl = st
        slot = (h + p.astype(_U32)) & _u32c(C - 1)
        slot_col = jnp.transpose(slot.astype(jnp.int32))       # (n_pad, 1)
        unplaced_col = jnp.transpose((~placed).astype(jnp.float32)) > 0
        proposes = (slot_col == c_ids) & unplaced_col          # (n_pad, C)
        winner = jnp.min(jnp.where(proposes, r_col, big), axis=0,
                         keepdims=True)                        # (1, C)
        free = occ == 0
        won = free & (winner < big)
        onehot = (proposes & (r_col == winner) &
                  jnp.broadcast_to(free, proposes.shape)) \
            .astype(jnp.float32)                               # (n_pad, C)
        placed_now = jnp.transpose(
            jnp.sum(onehot, axis=1, keepdims=True)) > 0        # (1, n_pad)
        rowid = jnp.where(won, winner, rowid)
        tbl = tuple(
            jnp.where(won,
                      jnp.dot(half, onehot,
                              preferred_element_type=jnp.float32), t)
            for half, t in zip(halves, tbl))
        occ = jnp.where(won, jnp.float32(1), occ)
        placed = placed | placed_now
        p = p + jnp.where(placed, 0, 1)
        return d + 1, p, placed, occ, rowid, tbl

    _, _, _, occ, rowid, tbl = jax.lax.while_loop(
        cond, body, (jnp.int32(0), p, placed, occ, rowid, tbl))
    out_refs[0][...] = occ
    out_refs[1][...] = rowid
    for i, t in enumerate(tbl):
        out_refs[2 + i][...] = t


def _build_table(rcols: Sequence[Column], rvalid: jnp.ndarray, C: int,
                 interpret: bool) -> jnp.ndarray:
    """-> (C, 2 + 2*n_words) f32 table matrix: [occ, rowid, u16 halves of
    every key word]. Assembled from the build kernel's outputs; consumed by
    the probe kernels through one-hot matmul gathers."""
    n = rcols[0].length
    n_pad = max(_LANES, ((n + _LANES - 1) // _LANES) * _LANES)
    planes, layout = _key_planes(rcols, n_pad)
    n_words = len(planes)
    vplane = _to_tiles(rvalid.astype(_U32), n_pad, lanes=n_pad)

    def kernel(*refs):
        _build_kernel_body(layout, C, n_pad, refs)

    in_specs = [pl.BlockSpec((1, n_pad), lambda: (0, 0),
                             memory_space=pltpu.VMEM)
                for _ in range(n_words + 1)]
    out_shape = [jax.ShapeDtypeStruct((1, C), jnp.float32)
                 for _ in range(2 + 2 * n_words)]
    out_specs = [pl.BlockSpec((1, C), lambda: (0, 0),
                              memory_space=pltpu.VMEM)
                 for _ in range(2 + 2 * n_words)]
    outs = pl.pallas_call(
        kernel, out_shape=out_shape, in_specs=in_specs, out_specs=out_specs,
        interpret=interpret)(*planes, vplane)
    return jnp.stack([o.reshape(-1) for o in outs], axis=1)


# ---- probe kernels -----------------------------------------------------------

def _count_kernel_body(layout, C: int, refs):
    n_words = sum(2 if b == 8 else 1 for b in layout)
    words = [refs[i][...] for i in range(n_words)]
    valid = refs[n_words][...] != _U32(0)
    tbl = refs[n_words + 1][...]
    out = refs[n_words + 2]

    h = _mm_hash(layout, words)
    h_col = jnp.transpose(h.astype(jnp.int32) & jnp.int32(C - 1))
    halves = [jnp.transpose(hh) for w in words for hh in _halves(w)]
    c_ids = jax.lax.broadcasted_iota(jnp.int32, (_LANES, C), 1)
    active0 = jnp.transpose(valid)
    counts0 = jnp.zeros((_LANES, 1), jnp.int32)

    def cond(st):
        d, active, _ = st
        return jnp.any(active) & (d < C + 1)

    def body(st):
        d, active, counts = st
        slot = (h_col + d) & jnp.int32(C - 1)
        onehot = (slot == c_ids).astype(jnp.float32)
        g = jnp.dot(onehot, tbl, preferred_element_type=jnp.float32)
        occ = g[:, 0:1] > 0
        eq = jnp.ones((_LANES, 1), bool)
        for j, ph in enumerate(halves):
            eq = eq & (g[:, 2 + j:3 + j] == ph)
        counts = counts + (active & occ & eq).astype(jnp.int32)
        return d + 1, active & occ, counts

    _, _, counts = jax.lax.while_loop(cond, body,
                                      (jnp.int32(0), active0, counts0))
    out[...] = jnp.transpose(counts)


def _emit_kernel_body(layout, C: int, refs):
    n_words = sum(2 if b == 8 else 1 for b in layout)
    words = [refs[i][...] for i in range(n_words)]
    ktgt = jnp.transpose(
        jax.lax.bitcast_convert_type(refs[n_words][...], jnp.int32))
    tbl = refs[n_words + 1][...]
    out = refs[n_words + 2]

    h = _mm_hash(layout, words)
    h_col = jnp.transpose(h.astype(jnp.int32) & jnp.int32(C - 1))
    halves = [jnp.transpose(hh) for w in words for hh in _halves(w)]
    c_ids = jax.lax.broadcasted_iota(jnp.int32, (_LANES, C), 1)
    active0 = jnp.ones((_LANES, 1), bool)
    seen0 = jnp.zeros((_LANES, 1), jnp.int32)
    rmap0 = jnp.zeros((_LANES, 1), jnp.int32)

    def cond(st):
        d, active, seen, rmap, resolved = st
        return jnp.any(active & ~resolved) & (d < C + 1)

    def body(st):
        d, active, seen, rmap, resolved = st
        slot = (h_col + d) & jnp.int32(C - 1)
        onehot = (slot == c_ids).astype(jnp.float32)
        g = jnp.dot(onehot, tbl, preferred_element_type=jnp.float32)
        occ = g[:, 0:1] > 0
        rowid = g[:, 1:2].astype(jnp.int32)
        eq = jnp.ones((_LANES, 1), bool)
        for j, ph in enumerate(halves):
            eq = eq & (g[:, 2 + j:3 + j] == ph)
        match = active & occ & eq
        hit = match & ~resolved & (seen == ktgt)
        rmap = jnp.where(hit, rowid, rmap)
        resolved = resolved | hit
        seen = seen + match.astype(jnp.int32)
        return d + 1, active & occ, seen, rmap, resolved

    _, _, _, rmap, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), active0, seen0, rmap0,
                     jnp.zeros((_LANES, 1), bool)))
    out[...] = jnp.transpose(rmap)


def _run_probe(body_fn, layout, C, planes, extra_plane, tbl, out_dtype,
               n_pad, interpret):
    n_words = len(planes)
    B = n_pad // _LANES

    def kernel(*refs):
        body_fn(layout, C, refs)

    in_specs = [pl.BlockSpec((1, _LANES), lambda i: (i, i - i),
                             memory_space=pltpu.VMEM)
                for _ in range(n_words + 1)]
    in_specs.append(pl.BlockSpec(tbl.shape, lambda i: (i - i, i - i),
                                 memory_space=pltpu.VMEM))
    out = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((B, _LANES), out_dtype)],
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, _LANES), lambda i: (i, i - i),
                                memory_space=pltpu.VMEM)],
        grid=(B,), interpret=interpret)(*planes, extra_plane, tbl)[0]
    return out.reshape(-1)


def _probe_counts(flat_planes, n: int, lvalid, layout, C, tbl, interpret):
    n_pad = max(_LANES, ((n + _LANES - 1) // _LANES) * _LANES)
    planes = [_to_tiles(p, n_pad) for p in flat_planes]
    vplane = _to_tiles(lvalid.astype(_U32), n_pad)
    counts = _run_probe(_count_kernel_body, layout, C, planes, vplane, tbl,
                        jnp.int32, n_pad, interpret)
    return counts[:n]


def _probe_emit(sel_planes, ktgt, layout, C, tbl, interpret):
    total = ktgt.shape[0]
    n_pad = max(_LANES, ((total + _LANES - 1) // _LANES) * _LANES)
    planes = [_to_tiles(p, n_pad) for p in sel_planes]
    kplane = _to_tiles(jax.lax.bitcast_convert_type(ktgt.astype(jnp.int32),
                                                _U32), n_pad)
    rmap = _run_probe(_emit_kernel_body, layout, C, planes, kplane, tbl,
                      jnp.int32, n_pad, interpret)
    return rmap[:total]


# ---- public entry points -----------------------------------------------------

def _side_valid(cols, n, alive=None):
    v = jnp.ones((n,), bool)
    for c in cols:
        if c.validity is not None:
            v = v & c.validity
    if alive is not None:
        v = v & alive
    return v


def _flat_planes(cols):
    out = []
    for c in cols:
        lo, hi, _ = _planes(c, normalize_zero=False)
        out.append(lo)
        if hi is not None:
            out.append(hi)
    return out


def _prep_probe(lcols, rcols, lvalid, rvalid, interpret):
    """-> (counts, probe planes, layout, C, tbl, interpret). The probe-side
    word planes are built ONCE here and reused by the emit pass (gathered
    at lsel) — the same planes-split-once economics the build side gets
    from consuming them in-kernel."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    C = _capacity(rcols[0].length)
    tbl = _build_table(rcols, rvalid, C, interpret)
    layout = _layout_of(rcols)
    lplanes = _flat_planes(lcols)
    counts = _probe_counts(lplanes, lcols[0].length, lvalid, layout, C,
                           tbl, interpret)
    return counts, lplanes, layout, C, tbl, interpret


def inner_join_pallas(left_keys, right_keys,
                      interpret: Optional[bool] = None):
    """Eager inner equi-join via hash build/probe: gather maps
    (left_map, right_map), pair-for-pair identical to `ops.inner_join`."""
    from .join import _cols
    lcols, rcols = _cols(left_keys), _cols(right_keys)
    nl, nr = lcols[0].length, rcols[0].length
    if nl == 0 or nr == 0:
        e = jnp.zeros((0,), jnp.int32)
        return (Column(dtype=dtypes.INT32, length=0, data=e),
                Column(dtype=dtypes.INT32, length=0, data=e))
    lvalid = _side_valid(lcols, nl)
    rvalid = _side_valid(rcols, nr)
    counts, lplanes, layout, C, tbl, interpret = _prep_probe(
        lcols, rcols, lvalid, rvalid, interpret)
    total = int(jnp.sum(counts))            # the one host sync (same as the
    #                                         fallback's match-count sync)
    if total == 0:
        e = jnp.zeros((0,), jnp.int32)
        return (Column(dtype=dtypes.INT32, length=0, data=e),
                Column(dtype=dtypes.INT32, length=0, data=e))
    starts = jnp.cumsum(counts) - counts
    lsel = jnp.repeat(jnp.arange(nl, dtype=jnp.int32), counts,
                      total_repeat_length=total)
    ktgt = jnp.arange(total, dtype=jnp.int32) - jnp.take(starts, lsel,
                                                         axis=0)
    sel_planes = [jnp.take(p, lsel, axis=0) for p in lplanes]
    rmap = _probe_emit(sel_planes, ktgt, layout, C, tbl, interpret)
    return (Column(dtype=dtypes.INT32, length=total, data=lsel),
            Column(dtype=dtypes.INT32, length=total, data=rmap))


def inner_join_capped_pallas(left_keys, right_keys, row_cap: int, *,
                             lalive=None, ralive=None,
                             interpret: Optional[bool] = None):
    """Capped inner equi-join (jit-traceable): (lmap, rmap, valid,
    overflow) with `ops.inner_join_capped`'s exact contract."""
    from .join import _cols
    _require_x64("inner_join_capped (pallas)")
    lcols, rcols = _cols(left_keys), _cols(right_keys)
    nl, nr = lcols[0].length, rcols[0].length
    if nl == 0 or nr == 0:
        z = jnp.zeros((row_cap,), jnp.int32)
        return z, z, jnp.zeros((row_cap,), bool), jnp.asarray(False)
    lvalid = _side_valid(lcols, nl, lalive)
    rvalid = _side_valid(rcols, nr, ralive)
    counts, lplanes, layout, C, tbl, interpret = _prep_probe(
        lcols, rcols, lvalid, rvalid, interpret)
    total = jnp.sum(counts.astype(jnp.int64))
    starts = jnp.cumsum(counts) - counts
    lsel = jnp.repeat(jnp.arange(nl, dtype=jnp.int32), counts,
                      total_repeat_length=row_cap)
    ktgt = jnp.arange(row_cap, dtype=jnp.int32) - jnp.take(starts, lsel,
                                                           axis=0)
    sel_planes = [jnp.take(p, lsel, axis=0) for p in lplanes]
    rmap = _probe_emit(sel_planes, ktgt, layout, C, tbl, interpret)
    valid = jnp.arange(row_cap, dtype=jnp.int32) < total
    lmap = jnp.where(valid, lsel, 0)
    rmap = jnp.where(valid, jnp.clip(rmap, 0, max(nr - 1, 0)), 0)
    return lmap, rmap, valid, total > row_cap


# ---- registry wiring --------------------------------------------------------

def make_signature(lcols: Sequence[Column], rcols: Sequence[Column],
                   how: str, tier: str):
    from .registry import Signature
    kinds_match = all(a.dtype.kind == b.dtype.kind
                      for a, b in zip(lcols, rcols))
    return Signature.of(list(lcols) + list(rcols), how=how, tier=tier,
                        kinds_match=kinds_match,
                        build_rows=rcols[0].length if rcols else 0)


def _supports(sig) -> bool:
    return (sig.extra("how") == "inner"
            and sig.extra("tier") in ("eager", "capped")
            and bool(sig.extra("kinds_match"))
            and (sig.extra("build_rows") or 0) <= MAX_BUILD
            and all(k in _SUPPORTED_KINDS for k in sig.kinds))


from .registry import REGISTRY as _REGISTRY  # noqa: E402

_REGISTRY.register("hash_join", "xla", fallback=True)
_REGISTRY.register("hash_join", "pallas", fn=inner_join_pallas,
                   backends=("tpu",), supports=_supports)
