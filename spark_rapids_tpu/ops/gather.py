"""Row gather (`take`) over columns/tables — the cudf::gather equivalent the
reference leans on everywhere (e.g. map_utils' substring gather,
map_utils.cu:539-647; join gather maps in the plugin). TPU-first: one fused
`jnp.take` per buffer; strings go through the padded measure→gather pattern
(SURVEY.md §7 step 1).

An index of -1 (OOB_NULL policy, like cudf's out-of-bounds-policy
NULLIFY) yields a null output row — hash joins use this for outer-join
non-matches.
"""
from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp

from ..columnar import Column, Table
from ..columnar.column import strings_from_padded
from ..dtypes import Kind


def take(col: Column, idx: jnp.ndarray, check_bounds: bool = False,
         _has_negative: bool = None) -> Column:
    """New column with rows col[idx]. idx: (m,) int32/int64; -1 → null row.

    `_has_negative` lets table-level callers hoist the one device sync that
    decides whether a validity mask is needed; leave it None elsewhere.
    """
    idx = jnp.asarray(idx)
    if idx.ndim != 1:
        raise ValueError("gather map must be 1-D")
    m = int(idx.shape[0])
    if check_bounds and m:
        lo, hi = (int(x) for x in jax.device_get(
            (jnp.min(idx), jnp.max(idx))))        # one fused sync
        if hi >= col.length or lo < -1:
            raise IndexError(f"gather index out of bounds for {col.length} rows")
    if _has_negative is None:
        _has_negative = m > 0 and bool(jnp.any(idx < 0))
    nullify = idx < 0
    safe = jnp.where(nullify, 0, idx)

    if col.validity is not None:
        validity = jnp.take(col.validity, safe, axis=0) & ~nullify
    elif _has_negative:
        validity = ~nullify
    else:
        validity = None

    k = col.dtype.kind
    if k == Kind.STRING:
        padded, lens = col.padded_chars()
        out_padded = jnp.take(padded, safe, axis=0)
        out_lens = jnp.where(nullify, 0, jnp.take(lens, safe, axis=0))
        out = strings_from_padded(out_padded, out_lens, validity)
        return out
    if k == Kind.STRUCT:
        children = tuple(take(c, idx, _has_negative=_has_negative)
                         for c in col.children)
        return Column(dtype=col.dtype, length=m, validity=validity,
                      children=children)
    if k == Kind.LIST:
        # two-pass: gather per-row spans into a fresh dense child
        lens = col.offsets[1:] - col.offsets[:-1]
        out_lens = jnp.where(nullify, 0, jnp.take(lens, safe, axis=0))
        new_offsets = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                       jnp.cumsum(out_lens).astype(jnp.int32)])
        total = int(new_offsets[-1])
        L = int(jnp.max(lens)) if col.length else 0
        # child indexes: for output row i, element j -> old_start[idx[i]] + j
        starts = jnp.take(col.offsets[:-1], safe, axis=0)
        pos = jnp.arange(max(L, 1), dtype=jnp.int32)[None, :]
        child_idx = jnp.where(pos < out_lens[:, None], starts[:, None] + pos, -1)
        flat = child_idx.reshape(-1)
        keep_map = flat[flat >= 0] if total else jnp.zeros((0,), jnp.int32)
        # (host-synced total; facade-level op like the reference's JNI calls)
        child = take(col.children[0], keep_map.astype(jnp.int32),
                     _has_negative=False)
        return Column.make_list(new_offsets, child, validity)
    # fixed-width (incl. decimal128 limbs: take along axis 0 of (n,4))
    data = jnp.take(col.data, safe, axis=0)
    return Column(dtype=col.dtype, length=m, data=data, validity=validity)


def apply_boolean_mask(table_or_col, mask) -> Union[Table, Column]:
    """Keep rows where mask is True (cudf::apply_boolean_mask — the filter
    half of read → filter → project). Null mask entries drop the row, like
    Spark's WHERE over a nullable predicate."""
    if isinstance(mask, Column):
        m = mask.data
        if mask.validity is not None:
            m = m & mask.validity
    else:
        m = jnp.asarray(mask)
    n = (table_or_col.num_rows if isinstance(table_or_col, Table)
         else table_or_col.length)
    if m.shape != (n,):
        raise ValueError(f"mask length {m.shape} does not match {n} rows")
    keep = jnp.nonzero(m)[0].astype(jnp.int32)   # host sync: result size
    if isinstance(table_or_col, Table):
        return take_table(table_or_col, keep, _has_negative=False)
    return take(table_or_col, keep, _has_negative=False)


def take_table(table: Table, idx: jnp.ndarray,
               _has_negative: bool = None) -> Table:
    idx = jnp.asarray(idx)
    if _has_negative is None:
        _has_negative = int(idx.shape[0]) > 0 and bool(jnp.any(idx < 0))
    return Table([take(c, idx, _has_negative=_has_negative)
                  for c in table.columns], names=table.names)
