"""Timestamp <-> UTC timezone conversion (GpuTimeZoneDB equivalent).

Reference: /root/reference/src/main/java/com/nvidia/spark/rapids/jni/
GpuTimeZoneDB.java (transition-table construction, loadData :261-335; cached
singleton with async load :88-202; supported = fixed-offset or no recurring
DST rules :236-248; Spark zone-id normalization :251-258) and
/root/reference/src/main/cpp/src/timezones.cu (per-row upper_bound over the
zone's transition span, convert_timestamp_tz_functor :50-90).

TPU-native design: the host half parses TZif files (RFC 8536) directly from
the system tzdata — the role java.time.ZoneRules plays in the reference —
and builds, per supported zone, three dense arrays:

    utc_instants  int64 seconds   (search key when converting UTC -> zone)
    tz_instants   int64 seconds   (search key when converting zone -> UTC)
    offsets       int32 seconds   (offset *after* each transition)

Row 0 is the (INT64_MIN, INT64_MIN, first-standard-offset) sentinel exactly
like GpuTimeZoneDB.java:284-295.  Gap transitions store
(instant, instant + offsetAfter, offsetAfter); overlaps store
(instant, instant + offsetBefore, offsetAfter) — the Spark disambiguation
rule documented at GpuTimeZoneDB.java:296-318.

The device half is one fused XLA kernel: truncate the timestamp to epoch
seconds (duration_cast semantics, timezones.cu:74-76), vectorized
`jnp.searchsorted(side="right")` over the zone's span, gather the offset,
add/subtract.  Zone spans are padded to power-of-two buckets (INT64_MAX
sentinel) so jit recompiles stay bounded.
"""
from __future__ import annotations

import dataclasses
import os
import re
import struct
import threading
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes
from ..columnar.column import Column, _round_bucket

INT64_MIN = -(1 << 63)
INT64_MAX = (1 << 63) - 1

# java.time.ZoneId.SHORT_IDS — applied by the reference's getZoneId
# (GpuTimeZoneDB.java:257 passes ZoneId.SHORT_IDS).
SHORT_IDS = {
    "ACT": "Australia/Darwin", "AET": "Australia/Sydney",
    "AGT": "America/Argentina/Buenos_Aires", "ART": "Africa/Cairo",
    "AST": "America/Anchorage", "BET": "America/Sao_Paulo",
    "BST": "Asia/Dhaka", "CAT": "Africa/Harare", "CNT": "America/St_Johns",
    "CST": "America/Chicago", "CTT": "Asia/Shanghai",
    "EAT": "Africa/Addis_Ababa", "ECT": "Europe/Paris",
    "IET": "America/Indiana/Indianapolis", "IST": "Asia/Kolkata",
    "JST": "Asia/Tokyo", "MIT": "Pacific/Apia", "NET": "Asia/Yerevan",
    "NST": "Pacific/Auckland", "PLT": "Asia/Karachi",
    "PNT": "America/Phoenix", "PRT": "America/Puerto_Rico",
    "PST": "America/Los_Angeles", "SST": "Pacific/Guadalcanal",
    "VST": "Asia/Ho_Chi_Minh",
    "EST": "-05:00", "MST": "-07:00", "HST": "-10:00",
}

_TZPATHS = ("/usr/share/zoneinfo", "/usr/lib/zoneinfo",
            "/usr/share/lib/zoneinfo", "/etc/zoneinfo")


# ---------------------------------------------------------------------------
# TZif parsing (host side; RFC 8536)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _TzifData:
    trans_times: List[int]        # transition instants, UTC seconds
    trans_types: List[int]        # index into utoffs per transition
    utoffs: List[int]             # seconds east of UTC per local time type
    isdsts: List[bool]
    footer: str                   # POSIX TZ string ('' if none / v1)


def _parse_tzif(path: str) -> _TzifData:
    with open(path, "rb") as f:
        raw = f.read()

    def parse_block(buf, off, time_size):
        magic, version = struct.unpack_from(">4sc", buf, off)
        if magic != b"TZif":
            raise ValueError(f"{path}: not a TZif file")
        isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt = \
            struct.unpack_from(">6I", buf, off + 20)
        p = off + 44
        fmt = ">%d%s" % (timecnt, "q" if time_size == 8 else "l")
        trans = list(struct.unpack_from(fmt, buf, p)) if timecnt else []
        p += timecnt * time_size
        types = list(struct.unpack_from(">%dB" % timecnt, buf, p)) if timecnt else []
        p += timecnt
        utoffs, isdsts = [], []
        for i in range(typecnt):
            utoff, isdst, _desig = struct.unpack_from(">lBB", buf, p + 6 * i)
            utoffs.append(utoff)
            isdsts.append(bool(isdst))
        p += 6 * typecnt + charcnt
        p += leapcnt * (time_size + 4) + isstdcnt + isutcnt
        return version, trans, types, utoffs, isdsts, p

    version, trans, types, utoffs, isdsts, end = parse_block(raw, 0, 4)
    footer = ""
    if version != b"\x00":
        # v2+: a second, 64-bit data block follows, then the footer TZ string.
        _, trans, types, utoffs, isdsts, end = parse_block(raw, end, 8)
        nl1 = raw.index(b"\n", end)
        nl2 = raw.index(b"\n", nl1 + 1)
        footer = raw[nl1 + 1:nl2].decode("ascii", errors="replace")
    return _TzifData(trans, types, utoffs, isdsts, footer)


def _zone_is_supported(tz: _TzifData) -> bool:
    """Reference supported-set rule (GpuTimeZoneDB.java:236-240): fixed
    offset, or rules with no *recurring* transition rule.  A TZif footer with
    a ',' carries a recurring DST rule; without one the zone is frozen."""
    return "," not in tz.footer


def _build_transition_rows(tz: _TzifData) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Build (utc_instants, tz_instants, offsets) per GpuTimeZoneDB.loadData."""
    utc, loc, off = [INT64_MIN], [INT64_MIN], []
    if not tz.trans_times:
        # fixed-offset zone: single sentinel row with the lone offset
        # (GpuTimeZoneDB.java:284-288)
        off.append(tz.utoffs[0] if tz.utoffs else 0)
    else:
        # Offset in force before the first transition: first standard
        # (non-DST) type, falling back to type 0 — the tzfile(5) convention,
        # which matches java.time's initial standard offset.
        before = next((u for u, d in zip(tz.utoffs, tz.isdsts) if not d),
                      tz.utoffs[0])
        off.append(before)
        for t, ty in zip(tz.trans_times, tz.trans_types):
            after = tz.utoffs[ty]
            if after > before:   # gap (clocks jump forward) — java isGap()
                utc.append(t)
                loc.append(t + after)
            else:                # overlap: compare against instant+offsetBefore
                utc.append(t)
                loc.append(t + before)
            off.append(after)
            before = after
    return (np.array(utc, dtype=np.int64), np.array(loc, dtype=np.int64),
            np.array(off, dtype=np.int32))


# ---------------------------------------------------------------------------
# Zone-id resolution (Spark/java.time surface)
# ---------------------------------------------------------------------------

_OFFSET_RE = re.compile(
    r"^(?P<sign>[+-])(?P<h>\d{1,2})(?::?(?P<m>\d{2})(?::?(?P<s>\d{2}))?)?$")


def normalize_zone_id(tz_str: str) -> str:
    """Spark's pre-3.0 zone-id fixups (GpuTimeZoneDB.getZoneId :251-258)."""
    tz_str = re.sub(r"(\+|\-)(\d):", r"\g<1>0\g<2>:", tz_str, count=1)
    tz_str = re.sub(r"(\+|\-)(\d\d):(\d)$", r"\g<1>\g<2>:0\g<3>", tz_str, count=1)
    return tz_str


def _resolve_zone(tz_str: str):
    """Return ('fixed', offset_seconds) or ('region', canonical_path_id)."""
    s = normalize_zone_id(tz_str.strip())
    s = SHORT_IDS.get(s, s)
    if s in ("Z", "UTC", "GMT", "UT", "Etc/UTC", "Etc/GMT"):
        return ("fixed", 0)
    for prefix in ("UTC", "GMT", "UT"):
        if s.startswith(prefix) and len(s) > len(prefix):
            s = s[len(prefix):]
            break
    m = _OFFSET_RE.match(s)
    if m:
        mins, secs = int(m.group("m") or 0), int(m.group("s") or 0)
        if mins > 59 or secs > 59:  # ZoneOffset.of rejects +08:99 etc.
            raise ValueError(f"invalid zone offset: {tz_str}")
        total = int(m.group("h")) * 3600 + mins * 60 + secs
        if total > 18 * 3600:  # java.time limit: +/-18:00 total
            raise ValueError(f"zone offset out of range: {tz_str}")
        return ("fixed", -total if m.group("sign") == "-" else total)
    for root in _TZPATHS:
        path = os.path.join(root, s)
        if os.path.isfile(path):
            return ("region", s)
    raise ValueError(f"unknown time zone: {tz_str}")


# ---------------------------------------------------------------------------
# The database singleton
# ---------------------------------------------------------------------------

class TimeZoneDB:
    """Cached transition database (reference's GpuTimeZoneDB singleton,
    GpuTimeZoneDB.java:60-202: idempotent cache, async load, shutdown)."""

    _instance: Optional["TimeZoneDB"] = None
    _lock = threading.Lock()

    def __init__(self):
        # zone id -> (utc_instants, tz_instants, offsets) numpy triple
        self._tables: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
        # per-zone device-resident padded arrays, keyed by resolved id
        self._device: Dict[str, Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]] = {}
        self._table_lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------
    @classmethod
    def instance(cls) -> "TimeZoneDB":
        with cls._lock:
            if cls._instance is None:
                cls._instance = TimeZoneDB()
            return cls._instance

    @classmethod
    def cache_database(cls) -> "TimeZoneDB":
        return cls.instance()

    @classmethod
    def cache_database_async(cls) -> threading.Thread:
        t = threading.Thread(target=cls.cache_database, daemon=True,
                             name="tpu-tzdb-loader")
        t.start()
        return t

    @classmethod
    def shutdown(cls):
        """Drop the cached database; a later cache_database() reloads it
        (reference shutdown/restart protocol, GpuTimeZoneDB.java:161-176)."""
        with cls._lock:
            cls._instance = None

    # -- table access -------------------------------------------------------
    def _table_for(self, tz_str: str):
        kind, key = _resolve_zone(tz_str)
        cache_key = f"fixed:{key}" if kind == "fixed" else key
        with self._table_lock:
            if cache_key in self._tables:
                return cache_key, self._tables[cache_key]
            if kind == "fixed":
                rows = (np.array([INT64_MIN], np.int64),
                        np.array([INT64_MIN], np.int64),
                        np.array([key], np.int32))
            else:
                path = next(os.path.join(r, key) for r in _TZPATHS
                            if os.path.isfile(os.path.join(r, key)))
                tz = _parse_tzif(path)
                if not _zone_is_supported(tz):
                    raise ValueError(f"Unsupported timezone: {tz_str}")
                rows = _build_transition_rows(tz)
            self._tables[cache_key] = rows
            return cache_key, rows

    def _device_table_for(self, tz_str: str):
        key, (utc, loc, off) = self._table_for(tz_str)
        with self._table_lock:
            if key not in self._device:
                # pad to power-of-two bucket so jit shapes are bounded
                pad = _round_bucket(len(off)) - len(off)
                utc_p = np.concatenate([utc, np.full(pad, INT64_MAX, np.int64)])
                loc_p = np.concatenate([loc, np.full(pad, INT64_MAX, np.int64)])
                off_p = np.concatenate([off, np.full(pad, off[-1], np.int32)])
                self._device[key] = (jnp.asarray(utc_p), jnp.asarray(loc_p),
                                     jnp.asarray(off_p))
            return self._device[key]


def is_supported_time_zone(tz_str: str) -> bool:
    try:
        TimeZoneDB.instance()._table_for(tz_str)
        return True
    except ValueError:
        return False


# ---------------------------------------------------------------------------
# Device kernel
# ---------------------------------------------------------------------------

_SCALES = {
    dtypes.Kind.TIMESTAMP_S: 1,
    dtypes.Kind.TIMESTAMP_MS: 1_000,
    dtypes.Kind.TIMESTAMP_US: 1_000_000,
}


@partial(jax.jit, static_argnames=("to_utc", "scale"))
def _convert_kernel(ts, trans_times, offsets, *, to_utc: bool, scale: int):
    ts = ts.astype(jnp.int64)
    # epoch seconds with C++ duration_cast truncation-toward-zero
    # (timezones.cu:74-76)
    q = ts // scale
    r = ts - q * scale
    epoch_s = q + jnp.where((ts < 0) & (r != 0), jnp.int64(1), jnp.int64(0))
    idx = jnp.searchsorted(trans_times, epoch_s, side="right")
    off = offsets[idx - 1].astype(jnp.int64) * scale
    return ts - off if to_utc else ts + off


def _convert(column: Column, tz_str: str, to_utc: bool) -> Column:
    if column.dtype.kind not in _SCALES:
        raise TypeError(f"expected a timestamp column, got {column.dtype}")
    db = TimeZoneDB.cache_database()
    utc_i, tz_i, offs = db._device_table_for(tz_str)
    keys = tz_i if to_utc else utc_i
    out = _convert_kernel(column.data, keys, offs, to_utc=to_utc,
                          scale=_SCALES[column.dtype.kind])
    return Column(dtype=column.dtype, length=column.length, data=out,
                  validity=column.validity)


def from_timestamp_to_utc_timestamp(column: Column, tz_str: str) -> Column:
    """Interpret `column` as wall-clock time in `tz_str`; return UTC instants
    (GpuTimeZoneDB.fromTimestampToUtcTimestamp :204-217)."""
    return _convert(column, tz_str, to_utc=True)


def from_utc_timestamp_to_timestamp(column: Column, tz_str: str) -> Column:
    """Convert UTC instants to wall-clock time in `tz_str`
    (GpuTimeZoneDB.fromUtcTimestampToTimestamp :219-232)."""
    return _convert(column, tz_str, to_utc=False)
